"""Fused train-step builder: the TPU-native fast path.

Where the reference's hot loop is Python driving kernels (SURVEY.md §3.2),
here the entire iteration — forward, backward, unscale + overflow check,
conditional skip, optimizer update, loss-scale update, BN running stats —
compiles into ONE XLA executable with zero host round-trips.  The stateful
facade (model/optimizer/scaler objects) is synchronized from the returned
device state, so the imperative API and the fused path are interchangeable.

This is the path ``bench.py``, the examples and DistributedDataParallel use;
``amp.scale_loss`` + ``loss.backward()`` (apex_tpu.autograd) is the
API-parity path.
"""
from __future__ import annotations

import functools
import itertools
import time
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..amp.scaler import ScalerState, update_scale_state
from ..compat import axis_size as _axis_size
from ..nn.modules import Ctx
from ..nn.parameter import Parameter
from ..observe import spans as _obs_spans
from ..observe import telemetry as _obs_telemetry
from ..observe import watchdog as _obs_watchdog

#: per-make_train_step token in the step_cache static key — two step
#: programs with identical signatures but different closures (model /
#: optimizer / loss_fn objects) must never share a cache entry
_STEP_TOKENS = itertools.count()


class StepState(NamedTuple):
    """Device-side training state for the fused step."""
    master_params: list          # fp32 masters (or the params themselves)
    model_params: list           # half copies fed to forward (may be same)
    opt_state: dict              # optimizer slots, name -> list
    scaler: ScalerState
    stats: list                  # module buffer values (BN running stats)
    step: jax.Array              # i32
    #: observe.StepTelemetry accumulator, or None (telemetry off).  None
    #: flattens to an empty subtree, so the leaf signature — and every
    #: checkpoint saved before this field existed — is unchanged when off.
    telem: Optional[object] = None


class TrainStep:
    """Built by :func:`make_train_step`; owns the compiled step and the
    object<->state synchronization."""

    def __init__(self, model, optimizer, loss_fn, step_fn, params, buffers,
                 init_state):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self._step_fn = step_fn
        self._params = params
        self._buffers = buffers
        self.state = init_state
        #: wall seconds of the first call (≈ trace + XLA compile: jit
        #: compilation is synchronous at dispatch, execution is async).
        #: Round-1 lesson: compile cost was invisible until it timed out.
        self.compile_s = None
        #: 0-based count of dispatched calls (chaos `at=` indices key on it)
        self.calls = 0
        #: resilience.BadStepGuard attached via guard.attach(step), or None
        self._guard = None
        #: the parallel.auto.Plan that built this step (parallel=), or None
        self.plan = None
        #: the PlanReport behind parallel="auto", or None
        self.plan_report = None
        #: on-device telemetry accumulation (make_train_step telemetry=)
        self._telemetry = False
        #: windows between host drains of the on-device accumulator
        self._drain_every = 1
        #: True when _step_fn submits through runtime.executor, which
        #: then owns the dispatch span + watchdog heartbeat; False for
        #: steps dispatched by other wrappers (pipeline, manual
        #: shard_map), where this facade emits them itself
        self._via_executor = False

    def __call__(self, *batch):
        from ..runtime import chaos as _chaos
        if _chaos.active():
            batch = _chaos_taint(self, batch)
        t0 = time.perf_counter() if self.compile_s is None else None
        if self._via_executor:
            self.state, loss = self._step_fn(self.state, *batch)
        else:
            with _obs_spans.span("dispatch"):
                self.state, loss = self._step_fn(self.state, *batch)
        if t0 is not None:
            self.compile_s = time.perf_counter() - t0
        self.calls += 1
        if not self._via_executor:
            # dispatch returned == the host made forward progress
            # (execution is async; a heartbeat after enqueue is exactly
            # the liveness signal the stall watchdog wants — a wedged
            # backend blocks the dispatch).  The executor path emits
            # this itself at submit time.
            _obs_watchdog.heartbeat(step=self.calls)
        if self._guard is not None:
            # the on-device skip flag apply_fused_update carried out in
            # scaler.overflow — handing the array over costs nothing; the
            # guard reads it lazily (is_ready polling)
            self._guard.observe(self.state.scaler.overflow)
        if self._telemetry and self.calls % self._drain_every == 0:
            self.drain_telemetry()
        return loss

    def drain_telemetry(self):
        """Host-sync the on-device telemetry accumulator and reset it.

        The drain lives in :func:`apex_tpu.runtime.executor.
        drain_telemetry` — the carry-drain shared by every step kind —
        and stays eager code outside jit, so the HOST-SYNC invariant
        holds and the compiled window program stays 1 compile +
        1 dispatch.  Emits a ``train.telemetry`` event and returns the
        record (None when telemetry is off or no window has completed
        since the last drain).
        """
        from ..runtime import executor as _executor
        return _executor.drain_telemetry(self)

    @property
    def last_step_skipped(self):
        """Device i32 scalar: 1 when the most recent call overflow-skipped
        (reading it as ``int(...)`` is a host sync)."""
        return self.state.scaler.overflow

    def sync_to_objects(self):
        """Write device state back into the model/scaler objects.

        The optimizer's param_groups reference the SAME Parameter objects as
        the model (make_train_step never swaps masters in), so each param
        gets its model-dtype value (half where cast, else the fp32 master);
        the fp32 masters live in ``self.state.master_params``.
        """
        st = self.state
        meta = getattr(self, "_flat_meta", None)
        if meta is not None:
            for i, (bid, j) in enumerate(meta.pos):
                half = st.model_params[bid]
                src_buf = st.master_params[bid] if half is None else half
                self._params[i].data = _row(src_buf, j, meta.shapes[i])
        else:
            for i, (p, v) in enumerate(zip(self._params, st.model_params)):
                p.data = st.master_params[i] if v is None else v
        for b, v in zip(self._buffers, st.stats):
            b.data = v
        from ..amp._amp_state import _amp_state
        if _amp_state.loss_scalers:
            _amp_state.loss_scalers[0].state = st.scaler

    def load_state(self, host_state):
        """Re-device a host checkpoint state into this step, laying each
        leaf out under its CURRENT placement (the elastic cross-plan
        restore entry; ``runtime.resilience.reshard_state`` holds the
        validation contract — typed ``CheckpointReshardError`` on a
        structural mismatch, values never touched by arithmetic)."""
        from ..runtime.resilience import reshard_state
        self.state = reshard_state(host_state, self.state)
        return self


def _chaos_taint(train_step, batch):
    """``train.step`` chaos hook: ``"nonfinite_grads"`` multiplies every
    floating batch leaf by NaN, so the scaled loss — and therefore every
    gradient — goes non-finite and the fused step's own overflow machinery
    (flag → skip → scale halving) fires exactly as it would in a real
    overflow storm.  ``"kill"``/``"fail"`` raise from the hook itself."""
    from ..runtime import chaos as _chaos

    action = _chaos.hook("train.step", step=train_step.calls)
    if action != "nonfinite_grads":
        return batch

    def taint(x):
        if hasattr(x, "dtype") and jnp.issubdtype(
                jnp.asarray(x).dtype, jnp.floating):
            return jnp.asarray(x) * jnp.asarray(float("nan"),
                                                jnp.asarray(x).dtype)
        return x
    return tuple(jax.tree_util.tree_map(taint, b) for b in batch)


def match_param_groups(optimizer, params, caller="make_train_step"):
    """Match optimizer param_groups to ``params`` by identity → per-group
    index lists.  Hyperparameters come from each param's own group; model
    params held by no group are frozen (torch semantics)."""
    id2idx = {id(p): i for i, p in enumerate(params)}
    group_idxs: list = []
    for gi, group in enumerate(optimizer.param_groups):
        idxs = []
        for p in group["params"]:
            if id(p) not in id2idx:
                raise ValueError(
                    f"{caller}: optimizer param_groups[{gi}] holds a "
                    f"parameter (shape {tuple(p.shape)}) that is not one of "
                    f"model.parameters(); the fused step requires the "
                    f"optimizer to optimize the model's own parameters")
            idxs.append(id2idx[id(p)])
        group_idxs.append(idxs)
    return group_idxs


def _gather(lst, idxs):
    return [lst[i] for i in idxs]


def _scatter(dst, idxs, new):
    for i, v in zip(idxs, new):
        dst[i] = v


def _model_dtypes(model, params, half_dtype, keep_batchnorm_fp32):
    from ..nn.modules import _BatchNorm

    bn_param_ids = set()
    if keep_batchnorm_fp32:
        for m in model.modules():
            if isinstance(m, _BatchNorm):
                for p in m._parameters.values():
                    if p is not None:
                        bn_param_ids.add(id(p))
    if half_dtype is None:
        return [p.data.dtype for p in params]
    return [jnp.float32 if id(p) in bn_param_ids else jnp.dtype(half_dtype)
            for p in params]


def apply_fused_update(sub: StepState, grads, opt_update, model_dtypes, *,
                       dynamic, init_scale, scale_window,
                       min_loss_scale, max_loss_scale, lr_schedule=None,
                       loss=None, telem_axes=()):
    """The post-gradient half of a fused step: unscale into fp32 master
    grads + overflow flag, fused optimizer update, skip-on-overflow
    (lax.select keeps it fused), model-dtype re-cast, loss-scale update.
    Returns the new sub-state with ``sub.stats`` passed through.

    bf16-style runs (static scale 1.0) skip the non-finite reduction: no
    scaling means no scaled-overflow to detect, and the extra full pass over
    every gradient costs real step time (the reference likewise early-outs
    in unscale for scale==1.0 non-dynamic, apex/amp/scaler.py:102-103).
    """
    check_overflow = dynamic or init_scale != 1.0
    flag = jnp.zeros((), jnp.int32)
    master_grads = []
    if check_overflow:
        inv = 1.0 / sub.scaler.loss_scale
    for g in grads:
        gf = g.astype(jnp.float32)
        if check_overflow:
            gf = gf * inv
            flag = jnp.maximum(flag, (~jnp.isfinite(gf)).any()
                               .astype(jnp.int32))
        master_grads.append(gf)

    step_count = sub.step + 1
    if lr_schedule is None:
        new_masters, new_slots = opt_update(
            flag, master_grads, sub.master_params, sub.opt_state, step_count)
    else:
        # schedules see the 1-based step as a traced scalar and return a
        # multiplier on each group's base lr — on-device, no recompiles
        new_masters, new_slots = opt_update(
            flag, master_grads, sub.master_params, sub.opt_state, step_count,
            lr_scale=lr_schedule(step_count))

    skip = flag > 0
    sel = functools.partial(jnp.where, skip)
    masters = [sel(o, n) for o, n in zip(sub.master_params, new_masters)]
    slots = {k: [sel(o, n) for o, n in zip(sub.opt_state[k], new_slots[k])]
             for k in new_slots}
    model_params = [
        None if jnp.dtype(d) == jnp.dtype(jnp.float32) else m.astype(d)
        for m, d in zip(masters, model_dtypes)]
    step_count = jnp.where(skip, sub.step, step_count)

    scaler_state = ScalerState(sub.scaler.loss_scale, sub.scaler.unskipped,
                               flag)
    new_scaler, _ = update_scale_state(
        scaler_state, dynamic=dynamic, scale_window=scale_window,
        min_loss_scale=min_loss_scale, max_loss_scale=max_loss_scale)
    # carry THIS step's skip flag out in the returned scaler state: the
    # fused path never reads `overflow` on entry (the flag is recomputed
    # from the gradients each step), so the slot is free to make "did the
    # step skip" observable on device — BadStepGuard consumes it without
    # adding a host sync to the step
    new_scaler = new_scaler._replace(overflow=flag)
    telem = sub.telem
    if telem is not None:
        # fold this window's observables into the donated carry — pure
        # jnp, stays inside the one compiled program, drained by
        # TrainStep.drain_telemetry from eager code
        telem = _obs_telemetry.accumulate(
            telem, loss=loss, master_grads=master_grads, flag=flag,
            loss_scale=new_scaler.loss_scale, mean_axes=telem_axes)
    return StepState(masters, model_params, slots, new_scaler, sub.stats,
                     step_count, telem)


def init_step_state(params, buffers, model_dtypes, opt_init, init_scale):
    """Initial device state for a fused step.  copy=True: .astype is a
    no-op view for already-fp32 params, and the state is donated — without
    the copy the first step would delete the live Parameter.data /
    Buffer.data arrays out from under the model."""
    from ..inference.quant import QuantTensor
    for p in params:
        if isinstance(p.data, QuantTensor):
            raise ValueError(
                "this model has int8-quantized weights "
                "(apex_tpu.inference.quantize_int8) — quantized models "
                "are inference-only; rebuild/reload the model to train")
    masters0 = [jnp.array(p.data, dtype=jnp.float32, copy=True)
                for p in params]
    return StepState(
        master_params=masters0,
        model_params=[
            None if jnp.dtype(d) == jnp.dtype(jnp.float32)
            else m.astype(d) for m, d in zip(masters0, model_dtypes)],
        opt_state=opt_init(),
        scaler=ScalerState(jnp.asarray(init_scale, jnp.float32),
                           jnp.zeros((), jnp.int32),
                           jnp.zeros((), jnp.int32)),
        stats=[jnp.array(b.data, copy=True) for b in buffers],
        step=jnp.zeros((), jnp.int32))


def model_vals_of(sub: StepState):
    """Forward-pass param values: the half copy where cast, else the fp32
    master (model_params holds None where no cast is needed — sharing the
    master buffer would double-donate under buffer donation)."""
    return [sub.master_params[i] if mp is None else mp
            for i, mp in enumerate(sub.model_params)]


def build_opt_update(optimizer, params, group_idxs,
                     caller="make_train_step"):
    """Map a fused optimizer instance to a pure update over flat lists,
    applied per group (hyperparameters are read at trace time;
    mutate-and-recompile to change them mid-training, as with any jitted
    step).  Returns ``(opt_update, opt_init)``."""
    from ..optimizers import FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD
    from .. import ops

    opt = optimizer
    if isinstance(opt, FusedSGD):
        def opt_update(flag, grads, masters, slots, step, lr_scale=1.0):
            new_p, new_m = list(masters), list(slots["momentum"])
            for group, idxs in zip(opt.param_groups, group_idxs):
                if not idxs:
                    continue
                flag, g_p, g_m = ops.multi_tensor_sgd(
                    flag, [_gather(grads, idxs), _gather(new_p, idxs),
                           _gather(new_m, idxs)],
                    group["weight_decay"], group["momentum"],
                    group["dampening"], group["lr"] * lr_scale,
                    group["nesterov"],
                    False, opt.wd_after_momentum, 1.0)
                _scatter(new_p, idxs, g_p)
                _scatter(new_m, idxs, g_m)
            return new_p, {"momentum": new_m}

        def opt_init():
            return {"momentum": [jnp.zeros(p.shape, jnp.float32)
                                 for p in params]}
    elif isinstance(opt, FusedAdam):
        def opt_update(flag, grads, masters, slots, step, lr_scale=1.0):
            new_p = list(masters)
            new_m, new_v = list(slots["m"]), list(slots["v"])
            for group, idxs in zip(opt.param_groups, group_idxs):
                if not idxs:
                    continue
                b1, b2 = group["betas"]
                _, g_p, g_m, g_v = ops.multi_tensor_adam(
                    flag, [_gather(grads, idxs), _gather(new_p, idxs),
                           _gather(new_m, idxs), _gather(new_v, idxs)],
                    group["lr"] * lr_scale, b1, b2, group["eps"], step,
                    opt.adam_w_mode, bool(group["bias_correction"]),
                    group["weight_decay"])
                _scatter(new_p, idxs, g_p)
                _scatter(new_m, idxs, g_m)
                _scatter(new_v, idxs, g_v)
            return new_p, {"m": new_m, "v": new_v}

        def opt_init():
            z = [jnp.zeros(p.shape, jnp.float32) for p in params]
            return {"m": z, "v": [jnp.zeros(p.shape, jnp.float32)
                                  for p in params]}
    elif isinstance(opt, FusedLAMB):
        def opt_update(flag, grads, masters, slots, step, lr_scale=1.0):
            new_p = list(masters)
            new_m, new_v = list(slots["m"]), list(slots["v"])
            for group, idxs in zip(opt.param_groups, group_idxs):
                if not idxs:
                    continue
                b1, b2 = group["betas"]
                # per-group global grad norm, matching the eager
                # FusedLAMB.step's per-dtype-bucket l2norm (fused_lamb.py:26)
                _, gnorm, _ = ops.multi_tensor_l2norm(
                    flag, [_gather(grads, idxs)])
                _, g_p, g_m, g_v = ops.multi_tensor_lamb(
                    flag, [_gather(grads, idxs), _gather(new_p, idxs),
                           _gather(new_m, idxs), _gather(new_v, idxs)],
                    group["lr"] * lr_scale, b1, b2, group["eps"], step,
                    bool(group["bias_correction"]), group["weight_decay"],
                    1 if group["grad_averaging"] else 0, opt.adam_w_mode,
                    gnorm, group["max_grad_norm"])
                _scatter(new_p, idxs, g_p)
                _scatter(new_m, idxs, g_m)
                _scatter(new_v, idxs, g_v)
            return new_p, {"m": new_m, "v": new_v}

        def opt_init():
            z = [jnp.zeros(p.shape, jnp.float32) for p in params]
            return {"m": z, "v": [jnp.zeros(p.shape, jnp.float32)
                                  for p in params]}
    elif isinstance(opt, FusedNovoGrad):
        def opt_update(flag, grads, masters, slots, step, lr_scale=1.0):
            new_p = list(masters)
            new_m, new_n = list(slots["m"]), list(slots["grad_norms"])
            for group, idxs in zip(opt.param_groups, group_idxs):
                if not idxs:
                    continue
                b1, b2 = group["betas"]
                norm_type = group["norm_type"]
                g_grads = _gather(grads, idxs)
                # first-step norm init (reference fused_novograd.py:158-174):
                # seed the running norm with ||g|| so the first blend is a
                # no-op, unless init_zero
                norms_in = _gather(new_n, idxs)
                if not group["init_zero"]:
                    def _local_norm(g):
                        gf = g.astype(jnp.float32)
                        return (jnp.max(jnp.abs(gf)) if norm_type == 0
                                else jnp.sqrt(jnp.sum(gf * gf)))
                    norms_in = [
                        jnp.where(step == 1, _local_norm(g), n)
                        for g, n in zip(g_grads, norms_in)]
                _, g_p, g_m, g_n = ops.multi_tensor_novograd(
                    flag, [g_grads, _gather(new_p, idxs),
                           _gather(new_m, idxs), norms_in],
                    group["lr"] * lr_scale, b1, b2, group["eps"], step,
                    bool(group["bias_correction"]), group["weight_decay"],
                    1 if group["grad_averaging"] else 0, opt.moment_mode,
                    norm_type)
                _scatter(new_p, idxs, g_p)
                _scatter(new_m, idxs, g_m)
                _scatter(new_n, idxs, g_n)
            return new_p, {"m": new_m, "grad_norms": new_n}

        def opt_init():
            return {"m": [jnp.zeros(p.shape, jnp.float32) for p in params],
                    "grad_norms": [jnp.zeros((), jnp.float32)
                                   for _ in params]}
    else:
        raise TypeError(
            f"{caller} does not support {type(opt).__name__}; "
            f"supported: FusedSGD, FusedAdam, FusedLAMB, FusedNovoGrad")
    return opt_update, opt_init


class FlatMeta(NamedTuple):
    """Layout of the shape-bucketed master/slot buffers.

    One buffer per (param group, shape, model dtype) bucket: the
    bucket's tensors STACK on a new leading axis, so each keeps its
    native TPU tiling — a truly flat 1-D buffer measurably lost 24%
    ResNet step time to 1-D→tiled relayouts (convert+reshape ~17 ms,
    BENCH round 5); leading-axis stacking keeps slices and casts
    layout-preserving and nearly free while the update still runs as
    one fused op per bucket (~2 dozen) instead of one per param
    (~161)."""
    buckets: list    # [(group_index, shape, dtype, [param indices])]
    pos: list        # per PARAM: (bucket_id, index within bucket)
    shapes: list     # per PARAM: original shape


def build_flat_meta(params, group_idxs, model_dtypes):
    buckets, pos = [], [None] * len(params)
    key2bid = {}
    for gi, idxs in enumerate(group_idxs):
        for i in idxs:
            key = (gi, tuple(params[i].data.shape),
                   jnp.dtype(model_dtypes[i]).name)
            if key not in key2bid:
                key2bid[key] = len(buckets)
                buckets.append((gi, tuple(params[i].data.shape),
                                jnp.dtype(model_dtypes[i]).name, []))
            bid = key2bid[key]
            pos[i] = (bid, len(buckets[bid][3]))
            buckets[bid][3].append(i)
    return FlatMeta(buckets, pos, [tuple(p.data.shape) for p in params])


def _row(stacked, j, shape):
    # static leading-axis slice: layout-preserving, folds into consumers
    return jax.lax.index_in_dim(stacked, j, axis=0, keepdims=False)


def flat_param_values(meta: FlatMeta, masters, model_params,
                      model_dtypes):
    """Per-param forward values: half params take a row of the
    bucket's one half-cast stack, fp32 params (BN under
    keep_batchnorm_fp32) a row of the f32 master stack."""
    out = [None] * len(meta.shapes)
    for i, (bid, j) in enumerate(meta.pos):
        src = masters[bid] if model_params[bid] is None else \
            model_params[bid]
        out[i] = _row(src, j, meta.shapes[i])
    return out


def flat_model_params(meta: FlatMeta, masters, model_dtypes):
    """Per-BUCKET half copy — one full-stack cast per bucket per step;
    None for fp32 buckets (their forward values read the master)."""
    out = []
    for bid, (gi, shape, dname, idxs) in enumerate(meta.buckets):
        d = jnp.dtype(dname)
        out.append(None if d == jnp.dtype(jnp.float32)
                   else masters[bid].astype(d))
    return out


def build_opt_update_flat(optimizer, meta: FlatMeta,
                          caller="make_train_step"):
    """Per-BUCKET stacked update: each bucket's (grad, master, slots)
    are single stacked arrays, so the multi-tensor op runs once per
    bucket (a couple dozen fused ops) with its group's hyperparams.
    Only elementwise-per-parameter optimizers are eligible — LAMB's
    trust ratio and NovoGrad's running norms are per-tensor quantities
    a stacked update would silently compute per bucket instead."""
    from ..optimizers import FusedAdam, FusedSGD
    from .. import ops

    opt = optimizer
    bucket_groups = [b[0] for b in meta.buckets]
    if isinstance(opt, FusedSGD):
        def opt_update(flag, grads, masters, slots, step, lr_scale=1.0):
            new_p, new_m = [], []
            for bid, gi in enumerate(bucket_groups):
                group = opt.param_groups[gi]
                flag, g_p, g_m = ops.multi_tensor_sgd(
                    flag, [[grads[bid]], [masters[bid]],
                           [slots["momentum"][bid]]],
                    group["weight_decay"], group["momentum"],
                    group["dampening"], group["lr"] * lr_scale,
                    group["nesterov"],
                    False, opt.wd_after_momentum, 1.0)
                new_p.append(g_p[0])
                new_m.append(g_m[0])
            return new_p, {"momentum": new_m}

        def opt_init(bucket_shapes):
            return {"momentum": [jnp.zeros(s, jnp.float32)
                                 for s in bucket_shapes]}
    elif isinstance(opt, FusedAdam):
        def opt_update(flag, grads, masters, slots, step, lr_scale=1.0):
            new_p, new_m, new_v = [], [], []
            for bid, gi in enumerate(bucket_groups):
                group = opt.param_groups[gi]
                b1, b2 = group["betas"]
                _, g_p, g_m, g_v = ops.multi_tensor_adam(
                    flag, [[grads[bid]], [masters[bid]], [slots["m"][bid]],
                           [slots["v"][bid]]],
                    group["lr"] * lr_scale, b1, b2, group["eps"], step,
                    opt.adam_w_mode, bool(group["bias_correction"]),
                    group["weight_decay"])
                new_p.append(g_p[0])
                new_m.append(g_m[0])
                new_v.append(g_v[0])
            return new_p, {"m": new_m, "v": new_v}

        def opt_init(bucket_shapes):
            return {"m": [jnp.zeros(s, jnp.float32) for s in bucket_shapes],
                    "v": [jnp.zeros(s, jnp.float32)
                          for s in bucket_shapes]}
    else:
        raise TypeError(
            f"{caller}: flat_master=True supports the elementwise "
            f"optimizers (FusedSGD, FusedAdam); {type(opt).__name__} "
            f"updates depend on per-tensor norms (LAMB trust ratio, "
            f"NovoGrad running norms) that stacked buffers would "
            f"change — use flat_master=False")
    return opt_update, opt_init


def apply_fused_update_flat(sub: StepState, grads, meta: FlatMeta,
                            opt_update, model_dtypes, *,
                            dynamic, init_scale, scale_window,
                            min_loss_scale, max_loss_scale,
                            lr_schedule=None, loss=None, telem_axes=()):
    """Stacked twin of :func:`apply_fused_update`: per-tensor grads
    stack once per shape bucket (layout-preserving leading-axis
    concat), then unscale/overflow, update, and the skip select each
    run as one full-stack op per bucket."""
    check_overflow = dynamic or init_scale != 1.0
    flag = jnp.zeros((), jnp.int32)
    flat_grads = []
    inv = 1.0 / sub.scaler.loss_scale if check_overflow else None
    for bid, (gi, shape, dname, idxs) in enumerate(meta.buckets):
        fg = jnp.stack([grads[i].astype(jnp.float32) for i in idxs])
        if check_overflow:
            fg = fg * inv
            flag = jnp.maximum(flag, (~jnp.isfinite(fg)).any()
                               .astype(jnp.int32))
        flat_grads.append(fg)

    step_count = sub.step + 1
    kw = {} if lr_schedule is None else \
        {"lr_scale": lr_schedule(step_count)}
    new_masters, new_slots = opt_update(
        flag, flat_grads, sub.master_params, sub.opt_state, step_count,
        **kw)

    skip = flag > 0
    sel = functools.partial(jnp.where, skip)
    masters = [sel(o, n) for o, n in zip(sub.master_params, new_masters)]
    slots = {k: [sel(o, n) for o, n in zip(sub.opt_state[k], new_slots[k])]
             for k in new_slots}
    step_count = jnp.where(skip, sub.step, step_count)

    scaler_state = ScalerState(sub.scaler.loss_scale, sub.scaler.unskipped,
                               flag)
    new_scaler, _ = update_scale_state(
        scaler_state, dynamic=dynamic, scale_window=scale_window,
        min_loss_scale=min_loss_scale, max_loss_scale=max_loss_scale)
    # skip-flag carry-out, as in apply_fused_update
    new_scaler = new_scaler._replace(overflow=flag)
    telem = sub.telem
    if telem is not None:
        # the stacked buckets cover every master grad exactly once, so the
        # sum-of-squares over buckets IS the global norm
        telem = _obs_telemetry.accumulate(
            telem, loss=loss, master_grads=flat_grads, flag=flag,
            loss_scale=new_scaler.loss_scale, mean_axes=telem_axes)
    return StepState(masters, flat_model_params(meta, masters, model_dtypes),
                     slots, new_scaler, sub.stats, step_count, telem)


def init_step_state_flat(params, buffers, meta: FlatMeta, model_dtypes,
                         opt_init, init_scale):
    from ..inference.quant import QuantTensor
    for p in params:
        if isinstance(p.data, QuantTensor):
            raise ValueError(
                "this model has int8-quantized weights "
                "(apex_tpu.inference.quantize_int8) — quantized models "
                "are inference-only; rebuild/reload the model to train")
    masters0 = [
        jnp.stack([jnp.asarray(params[i].data, jnp.float32)
                   for i in idxs])
        for (gi, shape, dname, idxs) in meta.buckets]
    return StepState(
        master_params=masters0,
        model_params=flat_model_params(meta, masters0, model_dtypes),
        opt_state=opt_init([m.shape for m in masters0]),
        scaler=ScalerState(jnp.asarray(init_scale, jnp.float32),
                           jnp.zeros((), jnp.int32),
                           jnp.zeros((), jnp.int32)),
        stats=[jnp.array(b.data, copy=True) for b in buffers],
        step=jnp.zeros((), jnp.int32))


def _default_zero_mesh(zero_axis):
    """Default ZeRO mesh: the ambient mesh context when one is active
    (a step built inside ``with Mesh(...):`` must not silently rebuild a
    1-D mesh over ALL ``jax.devices()`` — on a dp×tp submesh that would
    shard masters across devices the step never runs on), else a 1-D
    mesh over every device."""
    ambient = None
    try:
        from jax._src import mesh as _mesh_lib
        m = _mesh_lib.thread_resources.env.physical_mesh
        if m is not None and not m.empty:
            ambient = m
    except Exception:       # private surface moved: fall back to global
        ambient = None
    if ambient is not None:
        if zero_axis in ambient.shape:
            return ambient
        raise ValueError(
            f"zero_sharding=True inside an active mesh context whose axes "
            f"{tuple(ambient.shape)} do not include zero_axis="
            f"{zero_axis!r} — pass zero_mesh= (and zero_axis=) explicitly; "
            f"the default no longer rebuilds a 1-D mesh over all "
            f"jax.devices() when the step already runs on a submesh")
    import numpy as _np
    from jax.sharding import Mesh as _Mesh
    return _Mesh(_np.array(jax.devices()), (zero_axis,))


def make_train_step(model, optimizer, loss_fn: Callable,
                    half_dtype=None,
                    keep_batchnorm_fp32: bool = True,
                    dynamic_loss_scale: bool = True,
                    scale_window: int = 2000,
                    min_loss_scale: Optional[float] = None,
                    max_loss_scale: float = 2.0 ** 24,
                    loss_scale: float | str = "dynamic",
                    axis_name: Optional[str] = None,
                    tp_axis: Optional[str] = None,
                    gradient_predivide_factor: float = 1.0,
                    allreduce_always_fp32: bool = False,
                    donate_state="auto",
                    grad_accum_steps: int = 1,
                    accum_steps: Optional[int] = None,
                    accum_stacked: bool = False,
                    lr_schedule: Optional[Callable] = None,
                    rng_seed: int = 0,
                    zero_sharding: bool = False,
                    zero_mesh=None,
                    zero_axis: str = "data",
                    zero_stage: int = 1,
                    flat_master: bool = False,
                    parallel=None,
                    example_batch=None,
                    devices=None,
                    auto_tune: int = 0,
                    plan_options=None,
                    telemetry: bool = False,
                    drain_every: int = 1,
                    overlap="auto",
                    _plan=None,
                    _gather_prefetch_mesh=None,
                    _gather_prefetch_axis="data",
                    _gather_prefetch_sharded=True,
                    _gather_prefetch_on=False):
    """Build a fully-fused O2-style train step.

    ``loss_fn(outputs..., *batch_tail) -> scalar``: called with the model
    output.  The step signature is ``step(state, *batch) -> (state, loss)``
    where ``batch[0]`` feeds the model and the full batch feeds ``loss_fn``.

    ``accum_steps=K`` (preferred name; ``grad_accum_steps`` is the
    original spelling and stays accepted) runs the batch as K sequential
    microbatches inside the SAME compiled step (a ``lax.scan``),
    accumulating gradients in fp32 and applying one optimizer update —
    peak activation memory is that of one microbatch.  By default the
    step splits a flat ``(K*B, ...)`` batch itself; with
    ``accum_stacked=True`` it consumes pre-stacked ``(K, B, ...)``
    microbatch blocks (what ``runtime.DataPrefetcher(accum_steps=K)``
    delivers) with no reshape.  Everything that follows the window —
    optimizer update, master→half cast, dynamic-scale update, and the
    DP/TP gradient exchange — happens exactly once at the window
    boundary, and an overflow in ANY microbatch skips the whole window
    (the flag ORs across microbatches through the fp32 accumulator: a
    non-finite microbatch gradient keeps the sum non-finite).  Reported
    loss is the microbatch mean.  Batch
    elements sharing the model input's leading dim are split; anything
    else (scalars, per-step constants, custom containers) is broadcast to
    every microbatch.  The step matches the full-batch step up to
    summation order PROVIDED ``loss_fn`` computes a per-sample mean (the
    default reductions): gradients are (1/K)·Σ microbatch grads.  A
    sum-reduction or weight-normalized loss does not decompose that way —
    its accumulated gradients are 1/K of the full-batch run's, exactly as
    when a torch user accumulates ``loss / K`` manually.  (BatchNorm
    normalizes within each microbatch, as everywhere.)  Under DP the
    gradient all-reduce happens once per step, after accumulation — the
    reference's ``delay_unscale=True`` grad-accumulation pattern
    (docs/advanced.md), fused.

    When ``axis_name`` is given the step is meant to run under
    ``shard_map``/``pjit`` over that mesh axis: gradients are psum-averaged
    with the reference DDP's knobs honored (``gradient_predivide_factor``
    splits the averaging before/after the all-reduce,
    apex/parallel/distributed.py:445-454; ``allreduce_always_fp32`` casts
    grads to fp32 for the collective, :417-421).

    ``tp_axis``: the model was built with Megatron tensor parallelism over
    this mesh axis (``tp_axis=`` on the GPT/BERT families).  Each TP
    device's gradient for a sharded parameter is block-sparse — only its
    own head/feature block is nonzero — so those gradients are psum'd
    (NOT averaged: the blocks are disjoint, the psum assembles the full
    gradient) over the axis, keeping the replicated full parameters and
    optimizer state consistent across TP devices.  The model must expose
    ``tp_sharded_params()``; all other gradients are already identical
    across the axis (the row-parallel psums replicate every activation
    the replicated parameters touch) and are left alone.  Composes with
    ``axis_name`` for DP×TP meshes — batch sharded over ``axis_name``,
    replicated over ``tp_axis``.

    ``flat_master=True``: the reference amp_C design
    (csrc/multi_tensor_apply.cuh chunks many tensors into one kernel
    sweep), TPU-style — fp32 masters and optimizer slots live STACKED
    per (param group, shape, dtype) bucket, the per-step unscale +
    update + skip select run as one fused op per bucket (~2 dozen)
    instead of one per param (~161), and the forward reads
    layout-preserving leading-axis rows.  Supported for the
    elementwise optimizers (FusedSGD, FusedAdam); FusedLAMB and
    FusedNovoGrad have per-TENSOR norm semantics (trust ratio /
    per-tensor running norms) that a stacked update would silently
    change, so they refuse.  Composes with axis_name/tp_axis (grad
    collectives are per-tensor, pre-stack) and grad_accum; excludes
    zero_sharding (its per-param shardings are the point there).

    MEASURED VERDICT (v5e, BENCH_HISTORY round 5): a NEGATIVE result,
    kept as the reference design's receipt.  ResNet-50 b128: 2256
    img/s stacked vs 2355 per-tensor (a truly flat 1-D layout was far
    worse, 1806 — the 1-D→tiled relayouts cost ~17 ms/step).  The
    profile shows why there was nothing to win: the presumed
    "optimizer adds" tail (~4.5 ms op:add) is identical in every arm —
    it is the residual-join gradient adds of the conv backward, not
    optimizer work — and XLA already runs the per-tensor update well.
    Default stays per-tensor; ``bench.py --flat-optim`` re-measures.

    ``zero_sharding=True``: ZeRO sharding — fp32 masters and optimizer
    slots shard over ``zero_axis`` of ``zero_mesh`` (default: a 1-D mesh
    over all devices) and XLA's GSPMD partitioner derives the
    reduce-scatter (gradients into master shards) / all-gather (updated
    masters back out) pair itself.  Returns a
    :class:`~apex_tpu.parallel.zero.ZeroTrainStep` (same calling
    surface: ``step(x, y) -> loss``, ``.state``, ``.sync_to_objects()``).
    Data parallelism is implicit — the batch shards over the axis in the
    global-view program — so ``axis_name`` must not also be given.
    ``zero_stage`` picks the scope: 1 (default) keeps the half model
    copies replicated (the win is optimizer+master memory, ~1/n per
    shardable tensor); 3 shards the half copies too (FSDP-style: each
    parameter is all-gathered just ahead of use and never stored whole —
    activation-sized gather traffic traded for O(P/n) parameter
    residency).  There is no stage 2 switch: the fused step holds no
    persistent gradient buffer — gradients are intermediates of the one
    jitted program and already land reduce-scattered into master shards.
    ``zero_stage=0`` keeps the whole state replicated and only shards the
    batch — pure GSPMD data parallelism through the same wrapper (what a
    ``parallel.auto`` plan with ``dp>1, zero=0`` threads).

    ``parallel``: ``"auto"`` or a :class:`apex_tpu.parallel.auto.Plan` —
    the analytical parallelism planner picks (or the given plan fixes)
    dp × sp × tp, ZeRO stage, accumulation K, and threads exactly the
    knobs above; ``parallel="auto"`` needs ``example_batch=`` (one global
    batch of arrays or ShapeDtypeStructs) so the planner knows the batch/
    sequence geometry, and ``auto_tune=k`` compiles and times the top-k
    predicted plans and re-ranks by measurement.  See
    ``docs/auto_parallel.md``.

    ``telemetry=True``: accumulate per-window loss, global master-grad
    L2 norm, loss scale, and overflow count ON DEVICE inside the same
    compiled program (5 extra scalar slots in the donated carry — the
    PR 3 skip-flag discipline), drained to host by
    ``TrainStep.drain_telemetry`` every ``drain_every`` windows from
    eager code.  The window program stays 1 compile + 1 dispatch; the
    drain is the one (amortized) host sync.  See ``docs/observability.md``.
    Works on every kind: under ``axis_name``/``tp_axis`` the accumulator
    pmeans the per-shard loss over the batch axes inside the step (the
    exchanged gradients are already replicated, so the grad norm needs
    no extra collective); under ``zero_sharding`` the global-view
    program carries the scalars replicated; ``parallel=`` threads it
    through whichever kind the plan picks.

    ``overlap``: True/False/"auto" — ZeRO all-gather prefetch inside the
    scanned accumulation window (the replicated parameter view for
    microbatch i+1 is issued under microbatch i's compute, the
    weight-update-sharding overlap of arXiv:2004.13336).  "auto" defers
    to :func:`apex_tpu.runtime.executor.overlap_enabled` — on for
    backends with async collectives, off on cpu, where XLA runs
    collectives synchronously (forcing it on there is bitwise-identical,
    just not faster; the parity tests do exactly that).  Only meaningful
    with ``zero_sharding`` (stage 1/3) and ``accum_steps > 1``.

    ``donate_state``: "auto" (default) follows the executor's
    :class:`~apex_tpu.runtime.executor.DonationPolicy` — donate on
    tpu/gpu (in-place buffer reuse), skip on cpu, where XLA degrades
    donation to defensive copies (measured 2x step time, and jax 0.4.x's
    persistently-cached CPU executables resolve the input→output
    aliasing of deserialized donated programs incorrectly — stale
    outputs on cache hits).  Pass True/False to force.
    """
    from ..runtime import executor as _executor

    donate_state = _executor.donation.resolve(donate_state)
    if telemetry and drain_every < 1:
        raise ValueError(f"drain_every must be >= 1, got {drain_every}")
    if parallel is not None:
        if axis_name is not None or tp_axis is not None or zero_sharding:
            raise ValueError(
                "parallel= owns the parallelism knobs — do not also pass "
                "axis_name / tp_axis / zero_sharding (the plan threads "
                "them; spell the config fully by hand instead if you "
                "want manual control)")
        if accum_steps is not None or grad_accum_steps != 1:
            raise ValueError(
                "parallel= owns gradient accumulation — the plan's K is "
                "threaded as accum_steps; drop accum_steps/"
                "grad_accum_steps")
        from ..parallel import auto as _auto
        return _auto.build_planned_step(
            model, optimizer, loss_fn, parallel,
            example_batch=example_batch, devices=devices,
            auto_tune=auto_tune, plan_options=plan_options,
            half_dtype=half_dtype,
            keep_batchnorm_fp32=keep_batchnorm_fp32,
            dynamic_loss_scale=dynamic_loss_scale,
            scale_window=scale_window, min_loss_scale=min_loss_scale,
            max_loss_scale=max_loss_scale, loss_scale=loss_scale,
            gradient_predivide_factor=gradient_predivide_factor,
            allreduce_always_fp32=allreduce_always_fp32,
            donate_state=donate_state, accum_stacked=accum_stacked,
            lr_schedule=lr_schedule, rng_seed=rng_seed,
            zero_axis=zero_axis, flat_master=flat_master,
            telemetry=telemetry, drain_every=drain_every,
            overlap=overlap)
    if accum_steps is not None:
        if grad_accum_steps not in (1, accum_steps):
            raise ValueError(
                f"accum_steps={accum_steps} conflicts with "
                f"grad_accum_steps={grad_accum_steps} — they are the same "
                f"knob (accum_steps is the preferred spelling); pass one")
        grad_accum_steps = int(accum_steps)
    if accum_stacked and grad_accum_steps == 1:
        raise ValueError(
            "accum_stacked=True requires accum_steps > 1 — stacked "
            "(K, B, ...) blocks only exist under accumulation")
    if flat_master and zero_sharding:
        raise ValueError(
            "flat_master=True excludes zero_sharding: ZeRO's win is "
            "per-parameter sharding of exactly the buffers flat_master "
            "concatenates")
    if zero_sharding:
        if zero_stage not in (0, 1, 3):
            raise ValueError(
                f"zero_stage must be 1 (optimizer-state sharding), 3 "
                f"(+ parameter sharding), or 0 (replicated state — pure "
                f"GSPMD data parallelism); got {zero_stage!r}.  Stage 2 "
                f"has no separate switch: the fused step never holds a "
                f"persistent gradient buffer, so sharded masters already "
                f"imply reduce-scattered gradients")
        if axis_name is not None or tp_axis is not None:
            raise ValueError(
                "zero_sharding=True excludes axis_name/tp_axis — ZeRO "
                "data parallelism is implicit in the global-view jitted "
                "program (no shard_map/psum); TP's explicit mesh axes "
                "belong to the shard_map path")
        from ..parallel.zero import ZeroTrainStep
        if zero_mesh is None:
            zero_mesh = _default_zero_mesh(zero_axis)
        elif zero_axis not in zero_mesh.shape:
            raise ValueError(
                f"zero_axis {zero_axis!r} is not an axis of zero_mesh "
                f"(axes: {tuple(zero_mesh.shape)})")
        # ZeRO all-gather prefetch: resolved here (the one place that
        # knows mesh + stage + K) and threaded into the recursive base
        # build.  The base always gathers the replicated parameter view
        # explicitly per microbatch; the executor's overlap knob only
        # moves where the gather is issued (inline at use vs pipelined
        # one iteration early through the scan carry), so overlap on/off
        # is bitwise-identical.  Stage 0 keeps everything replicated —
        # there is no gather to prefetch.
        prefetch_mesh = zero_mesh if (
            zero_stage in (1, 3) and grad_accum_steps > 1) else None
        base = make_train_step(
            model, optimizer, loss_fn, half_dtype=half_dtype,
            keep_batchnorm_fp32=keep_batchnorm_fp32,
            dynamic_loss_scale=dynamic_loss_scale,
            scale_window=scale_window, min_loss_scale=min_loss_scale,
            max_loss_scale=max_loss_scale, loss_scale=loss_scale,
            donate_state=False,
            grad_accum_steps=grad_accum_steps, accum_stacked=accum_stacked,
            lr_schedule=lr_schedule,
            rng_seed=rng_seed,
            telemetry=telemetry, drain_every=drain_every,
            _gather_prefetch_mesh=prefetch_mesh,
            _gather_prefetch_axis=zero_axis,
            # the model-consumed values travel sharded when they ARE the
            # sharded buffers (stage 3 copies, or the masters themselves
            # when half_dtype is None); stage-1 half copies replicate
            _gather_prefetch_sharded=(zero_stage == 3
                                      or half_dtype is None),
            _gather_prefetch_on=_executor.overlap_enabled("gather",
                                                          overlap))
        return ZeroTrainStep(base, zero_mesh, zero_axis,
                             donate=donate_state,
                             stage=zero_stage, plan=_plan)
    params = [p for p in model.parameters() if p is not None]
    buffers = [b for b in model.buffers()]
    group_idxs = match_param_groups(optimizer, params)
    model_dtypes = _model_dtypes(model, params, half_dtype,
                                 keep_batchnorm_fp32)
    flat_meta = None
    if flat_master:
        grouped = {i for idxs in group_idxs for i in idxs}
        if len(grouped) != len(params):
            raise ValueError(
                "flat_master=True requires every model parameter to be "
                "in an optimizer param_group (frozen params have no "
                "slot in the flat master buffers)")
        flat_meta = build_flat_meta(params, group_idxs, model_dtypes)
        opt_update, opt_init = build_opt_update_flat(optimizer, flat_meta)
    else:
        opt_update, opt_init = build_opt_update(optimizer, params,
                                                group_idxs)

    dynamic = loss_scale == "dynamic"
    init_scale = (min(max_loss_scale, 2.0 ** 16) if dynamic
                  else float(loss_scale))

    if grad_accum_steps < 1:
        raise ValueError(f"grad_accum_steps must be >= 1, "
                         f"got {grad_accum_steps}")

    tp_ids = frozenset()
    if tp_axis is not None:
        getter = getattr(model, "tp_sharded_params", None)
        if getter is None:
            raise ValueError(
                "tp_axis given but the model has no tp_sharded_params() — "
                "build the model with its tp_axis= option (models/gpt.py, "
                "models/bert.py) so the step knows which gradients are "
                "block-sparse")
        tp_ids = frozenset(id(p) for p in getter())

    # telemetry loss reduction: under shard_map the per-device loss is
    # the local shard mean, so the accumulator pmeans it over the batch
    # axes (the exchanged gradients are already replicated across every
    # axis — the grad norm needs no extra collective)
    telem_axes = ()
    if telemetry and axis_name is not None:
        telem_axes = (tuple(axis_name)
                      if isinstance(axis_name, (tuple, list))
                      else (axis_name,))

    def step_fn(state: StepState, *batch):
        model_vals = (flat_param_values(flat_meta, state.master_params,
                                        state.model_params, model_dtypes)
                      if flat_master else model_vals_of(state))

        prefetch = None
        prefetch_on = False
        if _gather_prefetch_mesh is not None and grad_accum_steps > 1:
            # ZeRO gather prefetch (executor overlap knob): the scanned
            # window consumes an EXPLICIT replicated view of the
            # (sharded) parameters each microbatch.  With the knob off
            # the gather is issued inline at the point of use; with it
            # on the view travels in the scan carry, gathered one
            # iteration EARLIER — the all-gather overlaps compute
            # instead of stalling the forward.  Both arms compile the
            # same math DAG (gather → forward → backward →
            # reduce-scattered grads); only the issue slot moves, so
            # overlap on/off is bitwise-identical — the parity the
            # executor tests pin by forcing the knob on under cpu.
            rep = jax.sharding.NamedSharding(
                _gather_prefetch_mesh, jax.sharding.PartitionSpec())
            _n_ax = _gather_prefetch_mesh.shape[_gather_prefetch_axis]
            _shd = jax.sharding.NamedSharding(
                _gather_prefetch_mesh,
                jax.sharding.PartitionSpec(_gather_prefetch_axis))
            prefetch_on = bool(_gather_prefetch_on)

            def prefetch(vals):
                return [jax.lax.with_sharding_constraint(v, rep)
                        for v in vals]

            def reshard_grads(grads):
                # pin each microbatch gradient back to the consumed
                # buffer's OWN zero sharding (dim-0 where divisible, the
                # zero_state_sharding rule): the backward of the gathered
                # view stays a reduce-scatter into a sharded
                # accumulator, not an all-reduce into a replicated one —
                # deterministic reduction order on both arms and no
                # full-gradient replica (the ZeRO memory win)
                if not _gather_prefetch_sharded:
                    return grads
                return [jax.lax.with_sharding_constraint(
                            g, _shd if (getattr(g, "ndim", 0) >= 1
                                        and g.shape[0] % _n_ax == 0)
                            else rep)
                        for g in grads]

        def forward(model_vals_in, stats_in, mb_idx, *b):
            env = {id(p): v for p, v in zip(params, model_vals_in)}
            stats_env = {id(bf): v for bf, v in zip(buffers, stats_in)}
            stats_out = {}
            # per-step dropout randomness, derived from the step counter so
            # the state shape stays fixed (and steps are reproducible);
            # under DP also fold in the replica index so shards draw
            # independent masks (matching per-device RNG in the reference);
            # under accumulation fold in the microbatch index likewise
            key = jax.random.fold_in(jax.random.PRNGKey(rng_seed), state.step)
            if axis_name is not None:
                # fold each mesh axis EXCEPT the model's own sp_axis:
                # the SP model families fold that one themselves
                # (fold_shard_into_key), stashing the pre-fold key as
                # Ctx.shared_key — the replicated seed ring-attention
                # dropout hashes for its cross-shard-consistent mask.
                # Folding sp here too would leave no sp-replicated key
                # anywhere in the step.
                sp = getattr(model, "sp_axis", None)
                axes = (axis_name if isinstance(axis_name, (tuple, list))
                        else (axis_name,))
                for ax in axes:
                    if ax != sp:
                        key = jax.random.fold_in(key,
                                                 jax.lax.axis_index(ax))
            if grad_accum_steps > 1:
                key = jax.random.fold_in(key, mb_idx)
            ctx = Ctx(env={**env, **stats_env}, stats_out=stats_out,
                      training=True, key=key)
            x = b[0]
            if half_dtype is not None:
                # O2 input cast (reference patches model.forward to cast
                # incoming data, _initialize.py:194-201); tree-mapped so
                # multi-input models (tuples/dicts of arrays, e.g. a
                # seq2seq's (src, tgt) pair) cast every floating leaf
                from ..amp.policy import _cast_tree
                x = _cast_tree(x, jnp.dtype(half_dtype))
            out = model.forward(ctx, x)
            loss = loss_fn(out, *b[1:])
            # auxiliary objectives modules recorded during forward (e.g.
            # the Switch-MoE load-balancing loss, models/gpt.py): part of
            # the optimized (and reported) loss, scaled with it
            if ctx.aux_losses:
                loss = loss + sum(ctx.aux_losses)
            new_stats = [stats_out.get(id(bf), sv)
                         for bf, sv in zip(buffers, stats_in)]
            return loss.astype(jnp.float32) * state.scaler.loss_scale, \
                (loss, new_stats)

        if grad_accum_steps == 1:
            (_, (loss, new_stats)), grads = jax.value_and_grad(
                forward, has_aux=True)(
                    model_vals, list(state.stats), jnp.zeros((), jnp.int32),
                    *batch)
        else:
            def split(b):
                def leaf(a):
                    n = a.shape[0]
                    if accum_stacked:
                        # (K, B, ...) blocks from the data pipeline: the
                        # microbatch axis already leads, scan consumes it
                        if n != grad_accum_steps:
                            raise ValueError(
                                f"accum_stacked=True with accum_steps="
                                f"{grad_accum_steps}: batch leading dim "
                                f"{n} is not the microbatch count — "
                                f"expected (K, B, ...) stacked blocks")
                        return a
                    if n % grad_accum_steps:
                        raise ValueError(
                            f"grad_accum_steps={grad_accum_steps}: batch "
                            f"leading dim {n} is not divisible "
                            f"into microbatches")
                    return a.reshape(
                        (grad_accum_steps, n // grad_accum_steps)
                        + a.shape[1:])
                return jax.tree.map(leaf, b)

            leaves0 = [a for a in jax.tree.leaves(batch[0])
                       if getattr(a, "ndim", 0) >= 1]
            if not leaves0:
                raise ValueError(
                    f"grad_accum_steps={grad_accum_steps}: the model input "
                    f"(batch[0]) has no leading batch dimension to split")
            n0 = leaves0[0].shape[0]

            def splittable(b):
                leaves = jax.tree.leaves(b)
                return bool(leaves) and all(
                    getattr(a, "ndim", 0) >= 1 and a.shape[0] == n0
                    for a in leaves)

            # elements (pytrees) whose every leaf shares the model
            # input's batch dim split into microbatches; anything else
            # (scalars, per-step constants) is broadcast
            splits = [i == 0 or splittable(b)
                      for i, b in enumerate(batch)]
            micro = tuple(split(b) for b, s in zip(batch, splits) if s)

            def micro_step(carry, mb):
                if prefetch is not None and prefetch_on:
                    acc, stats_in, loss_sum, i, vals = carry
                elif prefetch is not None:
                    # overlap off: same explicit gather, issued inline
                    # at the point of use — stalls the forward, but the
                    # math DAG is identical to the pipelined arm
                    acc, stats_in, loss_sum, i = carry
                    vals = prefetch(model_vals)
                else:
                    acc, stats_in, loss_sum, i = carry
                    vals = model_vals
                mb_it = iter(mb)
                full = tuple(next(mb_it) if s else b
                             for b, s in zip(batch, splits))
                (_, (l, ns)), g = jax.value_and_grad(
                    forward, has_aux=True)(vals, stats_in, i, *full)
                if prefetch is not None:
                    g = reshard_grads(g)
                if prefetch is not None and prefetch_on:
                    # issue the gather for microbatch i+1's view NOW,
                    # pinned after this microbatch's grads by the
                    # barrier (no CSE with the view just consumed, no
                    # hoist out of the scan) — the async collective
                    # overlaps the accumulate below and the next
                    # iteration's early compute
                    next_vals, g = jax.lax.optimization_barrier(
                        (prefetch(model_vals), g))
                acc = [a + gi.astype(jnp.float32)
                       for a, gi in zip(acc, g)]
                out = (acc, ns, loss_sum + l.astype(jnp.float32), i + 1)
                if prefetch is not None and prefetch_on:
                    out = out + (next_vals,)
                return out, None

            carry0 = ([jnp.zeros(v.shape, jnp.float32)
                       for v in model_vals],
                      list(state.stats),
                      jnp.zeros((), jnp.float32),
                      jnp.zeros((), jnp.int32))
            if prefetch is not None and prefetch_on:
                # prologue gather: microbatch 0's view rides in the
                # initial carry
                carry0 = carry0 + (prefetch(model_vals),)
            final_carry, _ = jax.lax.scan(micro_step, carry0, micro)
            acc, new_stats, loss_sum = final_carry[:3]
            grads = [a / grad_accum_steps for a in acc]
            loss = loss_sum / grad_accum_steps

        # DP gradient exchange (psum over the mapped axis), with DDP knobs
        if axis_name is not None:
            n = _axis_size(axis_name)
            pre = gradient_predivide_factor
            post = n / gradient_predivide_factor

            def exchange(g):
                gc = g.astype(jnp.float32) if allreduce_always_fp32 else g
                gc = gc / pre if pre != 1.0 else gc
                gc = jax.lax.psum(gc, axis_name)
                gc = gc / post
                return gc.astype(g.dtype) if allreduce_always_fp32 else gc
            grads = [exchange(g) for g in grads]

        # TP gradient assembly: sharded params' grads are block-sparse per
        # device (disjoint blocks), psum = the full gradient; everything
        # else is already replicated across the axis
        if tp_axis is not None:
            grads = [jax.lax.psum(g, tp_axis) if id(p) in tp_ids else g
                     for p, g in zip(params, grads)]

        if flat_master:
            new_state = apply_fused_update_flat(
                state._replace(stats=new_stats), grads, flat_meta,
                opt_update, model_dtypes,
                dynamic=dynamic, init_scale=init_scale,
                scale_window=scale_window, min_loss_scale=min_loss_scale,
                max_loss_scale=max_loss_scale, lr_schedule=lr_schedule,
                loss=loss, telem_axes=telem_axes)
        else:
            new_state = apply_fused_update(
                state._replace(stats=new_stats), grads, opt_update,
                model_dtypes,
                dynamic=dynamic, init_scale=init_scale,
                scale_window=scale_window, min_loss_scale=min_loss_scale,
                max_loss_scale=max_loss_scale, lr_schedule=lr_schedule,
                loss=loss, telem_axes=telem_axes)
        return new_state, loss

    if flat_master:
        init_state = init_step_state_flat(params, buffers, flat_meta,
                                          model_dtypes, opt_init,
                                          init_scale)
    else:
        init_state = init_step_state(params, buffers, model_dtypes,
                                     opt_init, init_scale)
    if telemetry:
        init_state = init_state._replace(
            telem=_obs_telemetry.init_telemetry())

    via_executor = axis_name is None and tp_axis is None
    if via_executor:
        # submit through the runtime executor (which compiles via the
        # step-program cache): the compiled window program is keyed on
        # (per-builder token, K, stacking, donation) plus the argument
        # signature, so step_cache.stats() pins exactly 1 compile and
        # 1 dispatch per accumulation window — K is part of the STATIC
        # key (a K=4 and a K=16 window are different executables), and
        # the donated state means the scan's fp32 gradient accumulator
        # and the carried masters/slots update in place across windows
        from ..runtime import step_cache as _step_cache

        token = next(_STEP_TOKENS)
        # the plan (when this step was built by parallel.auto) is part of
        # the STATIC key: compiled executables stay per-plan observables
        static_key = (token, grad_accum_steps, accum_stacked,
                      bool(donate_state), bool(telemetry),
                      _step_cache.static_plan_key(_plan))
        program = _executor.Program(
            "train_step", static_key, step_fn,
            donate_argnums=(0,) if donate_state else ())
        dispatch_no = itertools.count(1)

        def jit_step(state, *batch):
            return _executor.executor.submit(
                program, (state,) + batch, step=next(dispatch_no))
    else:
        jit_step = step_fn  # caller wraps in shard_map/pjit

    ts = TrainStep(model, optimizer, loss_fn, jit_step, params, buffers,
                   init_state)
    ts._via_executor = via_executor
    # the un-jitted step for wrappers that jit with their own shardings /
    # donation (parallel/zero.py)
    ts._raw_step_fn = step_fn
    ts._donate_state = donate_state and axis_name is None and tp_axis is None
    ts._flat_meta = flat_meta
    ts._flat_dtypes = model_dtypes
    ts._telemetry = bool(telemetry)
    ts._drain_every = int(drain_every)
    return ts
