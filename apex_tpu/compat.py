"""jax version compatibility shims.

The codebase targets the modern jax surface (``jax.shard_map`` with the
``check_vma`` knob, ``jax.lax.axis_size``); the oldest supported runtime is
jax 0.4.x, where ``shard_map`` still lives in ``jax.experimental.shard_map``
(with the knob spelled ``check_rep``) and ``axis_size`` does not exist.
Everything in apex_tpu goes through this module — ``tests/test_compat.py``
lints that no source file calls ``jax.shard_map`` directly — and
:func:`install` additionally polyfills the modern names onto the ``jax``
module itself so user code (and the test suite) written against the modern
API runs unchanged on 0.4.x.
"""
from __future__ import annotations

import functools

import jax

_SENTINEL = object()

try:
    _NATIVE_SHARD_MAP = jax.shard_map   # jax >= 0.5
except AttributeError:
    _NATIVE_SHARD_MAP = None

#: True when this jax exposes jax.shard_map natively (>= 0.5)
HAS_NATIVE_SHARD_MAP = _NATIVE_SHARD_MAP is not None


def _legacy_shard_map():
    from jax.experimental.shard_map import shard_map as sm
    return sm


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=_SENTINEL, **kw):
    """``jax.shard_map`` on every supported jax.

    Accepts the modern keyword surface; on jax 0.4.x the call is forwarded
    to ``jax.experimental.shard_map.shard_map`` with ``check_vma``
    translated to its old spelling ``check_rep`` (same meaning: verify the
    per-device values are consistent with the declared replication).
    """
    if HAS_NATIVE_SHARD_MAP:
        if check_vma is not _SENTINEL:
            kw["check_vma"] = check_vma
        return _NATIVE_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)
    if check_vma is not _SENTINEL:
        kw["check_rep"] = check_vma
    return _legacy_shard_map()(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, **kw)


_NATIVE_AXIS_SIZE = getattr(jax.lax, "axis_size", None)


def axis_size(axis_name):
    """``jax.lax.axis_size`` on every supported jax.

    On 0.4.x the idiom is ``lax.psum(1, axis)``: psum of the literal 1 is
    constant-folded to the mapped axis size without emitting a collective.
    """
    if _NATIVE_AXIS_SIZE is not None:
        return _NATIVE_AXIS_SIZE(axis_name)
    return jax.lax.psum(1, axis_name)


def _polyfill_shard_map(f=None, **kw):
    """The function installed AS ``jax.shard_map`` on 0.4.x: the compat
    wrapper above, usable both directly and (defensively) curried."""
    if f is None:
        return functools.partial(_polyfill_shard_map, **kw)
    return shard_map(f, **kw)


def install():
    """Polyfill the modern names onto ``jax`` where missing (idempotent).

    Called from ``apex_tpu.__init__`` so that importing apex_tpu is enough
    to make ``jax.shard_map(..., check_vma=False)`` and
    ``jax.lax.axis_size`` work on jax 0.4.x.  No-op on modern jax.
    """
    if not HAS_NATIVE_SHARD_MAP:
        jax.shard_map = _polyfill_shard_map
    if _NATIVE_AXIS_SIZE is None:
        jax.lax.axis_size = axis_size


install()
