"""GPT-style causal decoder family — the autoregressive counterpart to
models/bert.py, built from the same fused components.

The reference repo carries no language models of its own (SURVEY.md §2 —
its fused pieces were consumed by external scripts); this standalone
decoder completes the transformer story: pre-LN blocks, causal Pallas
flash attention (``SelfMultiheadAttn`` with a time mask), FusedLayerNorm,
GELU FFN, weight-tied LM head.

Layout: public API is batch-first ``(B, S)`` token ids; internally the
decoder runs ``(S, B, E)`` for the attention module's reference layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..normalization import FusedLayerNorm
from ..contrib.multihead_attn import SelfMultiheadAttn
from ..nn.modules import fold_shard_into_key as _fold_shard_into_key


class GptBlock(nn.Module):
    """Pre-LN decoder block: LN → causal MHA → residual, LN → GELU FFN →
    residual."""

    def __init__(self, hidden, heads, intermediate, dropout=0.1,
                 attn_dropout=0.1, sp_axis=None, tp_axis=None,
                 attn_bias=False, _dense_ffn=True):
        super().__init__()
        self.ln1 = FusedLayerNorm(hidden)
        # causal=True: the flash path masks the triangle in-kernel with
        # no O(S^2) mask operand.  Attention dropout ALSO rides the
        # kernel (counter-based hash mask regenerated in the backward,
        # ops/pallas/attention.py) — no (S, S) dropout mask tensor in
        # HBM; composes with tp_axis (per-shard seed streams) and
        # sp_axis (ring: bit-consistent global hash mask).
        # attn_bias=True (GPT-2 checkpoints carry QKV/out-proj biases)
        # selects the reference's 'default' impl, which is the one that
        # supports biases (reference contrib/multihead_attn/
        # self_multihead_attn.py fast-impl assert) — the materializing
        # attention path, priced in docs/models.md
        self.attn = SelfMultiheadAttn(hidden, heads, dropout=attn_dropout,
                                      bias=attn_bias,
                                      impl="default" if attn_bias
                                      else "fast", causal=True,
                                      seq_parallel_axis=sp_axis,
                                      tensor_parallel_axis=tp_axis)
        self.ln2 = FusedLayerNorm(hidden)
        if _dense_ffn:
            self.fc1 = nn.Linear(hidden, intermediate)
            self.fc2 = nn.Linear(intermediate, hidden)
        else:
            # MoeGptBlock supplies its own routed FFN (the LlamaBlock
            # convention): skip drawing dense matrices it would discard
            self.fc1 = self.fc2 = None
        self.dropout = nn.Dropout(dropout)
        self.tp_axis = tp_axis
        self.sp_axis = sp_axis

    def _ffn(self, ctx, h):
        """The feed-forward on the LN2 output — one hook for the dense,
        Megatron-TP, and (in MoeGptBlock) expert-routed variants, shared
        by the training forward and every cached decode path."""
        if self.tp_axis is not None:
            # Megatron MLP: fc1 column-parallel, gelu on the sharded
            # hidden, fc2 row-parallel — one psum for the pair; weights
            # stay full, the shard slice happens at trace time
            from ..parallel.tensor_parallel import tp_ffn
            return tp_ffn(h,
                          ctx.value(self.fc1.weight),
                          ctx.value(self.fc1.bias),
                          ctx.value(self.fc2.weight),
                          ctx.value(self.fc2.bias),
                          self.tp_axis, activation=F.gelu)
        return self.fc2.forward(ctx, F.gelu(self.fc1.forward(ctx, h)))

    def forward(self, ctx, x):
        h, _ = self.attn.forward(ctx, self.ln1.forward(ctx, x))
        x = x + self.dropout.forward(ctx, h)
        h = self._ffn(ctx, self.ln2.forward(ctx, x))
        return x + self.dropout.forward(ctx, h)

    def tp_sharded_params(self):
        """Parameters whose per-device gradients are block-sparse under
        ``tp_axis`` (each device's slice sees only its block): their grads
        must be psum'd over the TP axis to keep the replicated full
        parameters consistent (training/step.py handles this when built
        with ``tp_axis``).  The attention subset lives on the attention
        module itself; this block adds its column/row MLP entries."""
        return self.attn.tp_sharded_params() + [
            self.fc1.weight, self.fc1.bias, self.fc2.weight]

    def _chunk_qkv(self, ctx, x):
        """(B, S_c, E) -> q/k/v (B, H, S_c, D) via the training
        projection (the interleaved QKV layout of
        attn_funcs._split_interleaved_qkv), so caches filled here
        reproduce the training forward's attention.  Under ``tp_axis``
        the interleaved layout is head-major (3·D contiguous rows per
        head), so a contiguous row slice of the in-projection IS a head
        block — decode shards heads exactly like the training path —
        and the returned H is the LOCAL head count."""
        attn = self.attn
        heads, d = attn.num_heads, attn.head_dim
        b, s_c, _ = x.shape
        h = self.ln1.forward(ctx, x)
        wi = ctx.value(attn.in_proj_weight)
        bi = ctx.value(attn.in_proj_bias) if attn.bias else None
        if self.tp_axis is not None:
            from ..parallel.tensor_parallel import (copy_to_tp_region,
                                                    _shard_rows)
            n = jax.lax.psum(1, self.tp_axis)
            if heads % n:
                raise ValueError(
                    f"tensor parallelism: heads ({heads}) not divisible "
                    f"by the '{self.tp_axis}' axis size ({n})")
            h = copy_to_tp_region(h, self.tp_axis)
            wi = _shard_rows(wi, self.tp_axis)
            if bi is not None:
                bi = _shard_rows(bi, self.tp_axis)
            heads //= n
        qkv = jnp.matmul(h, wi.T.astype(h.dtype))
        if bi is not None:
            qkv = qkv + bi.astype(qkv.dtype)
        qkv = qkv.reshape(b, s_c, heads, 3, d)
        to_bh = lambda y: jnp.swapaxes(y, 1, 2)       # (B, H, S_c, D)
        return (to_bh(qkv[:, :, :, 0]), to_bh(qkv[:, :, :, 1]),
                to_bh(qkv[:, :, :, 2]))

    def _attn_mlp_tail(self, ctx, x, o):
        """Shared residual tail after attention combine: out projection
        + GELU MLP (one body for prefill/decode_chunk/decode).  Under
        ``tp_axis`` ``o`` carries LOCAL head features: the out
        projection is row-parallel (its psum exits the attention
        region; the bias is added once, post-reduction) and the MLP is
        the column→row pair."""
        attn = self.attn
        wo = ctx.value(attn.out_proj_weight)
        bo = ctx.value(attn.out_proj_bias) if attn.bias else None
        if self.tp_axis is not None:
            from ..parallel.tensor_parallel import (row_parallel_linear,
                                                    _shard_cols)
            x = x + row_parallel_linear(
                o, _shard_cols(wo, self.tp_axis), bo, self.tp_axis)
        else:
            o = jnp.matmul(o, wo.T.astype(o.dtype))
            if attn.bias:
                o = o + bo.astype(o.dtype)
            x = x + o
        return x + self._ffn(ctx, self.ln2.forward(ctx, x))

    def prefill(self, ctx, x, kcache, vcache):
        """Cache-filling forward from position 0: flash causal attention
        over the chunk (the caches are empty) + KV writes — one pass for
        a whole prompt instead of S_p decode steps."""
        b, s_c, _ = x.shape
        d = self.attn.head_dim
        from ..inference.quant import kv_write
        q, k_new, v_new = self._chunk_qkv(ctx, x)     # H is LOCAL under tp
        kcache = kv_write(kcache, k_new, (0, 0, 0, 0))
        vcache = kv_write(vcache, v_new, (0, 0, 0, 0))
        from ..contrib.multihead_attn.attn_funcs import flash_attention
        o = flash_attention(q, k_new, v_new, causal=True,
                            scale=self.attn.scaling)
        o = jnp.swapaxes(o, 1, 2).reshape(b, s_c, q.shape[1] * d)
        return self._attn_mlp_tail(ctx, x, o), kcache, vcache

    def decode_chunk(self, ctx, x, kcache, vcache, t0):
        """Cached forward over a chunk ``x (B, S_c, E)`` at positions
        ``t0 ..`` — each query attends the cache with the shifted-causal
        mask.  Meant for SHORT verification windows (scores are
        (S_c, S_max) per head); prompts go through :meth:`prefill`."""
        attn = self.attn
        d = attn.head_dim
        b, s_c, _ = x.shape
        pos = t0 + jnp.arange(s_c, dtype=jnp.int32)
        from ..inference.quant import kv_value, kv_write
        q, k_new, v_new = self._chunk_qkv(ctx, x)     # H is LOCAL under tp
        if self.sp_axis is not None:
            # sequence-parallel decode: this device's cache block holds
            # positions sp_slot_positions(...); the chunk's KV rows land
            # on their owners, scores run against the LOCAL block only,
            # and the partials lse-merge over the axis
            # (parallel/context_parallel.py)
            from ..parallel.context_parallel import (
                sp_kv_write, sp_slot_positions, sp_softmax_combine)
            kcache = sp_kv_write(kcache, k_new, t0, self.sp_axis)
            vcache = sp_kv_write(vcache, v_new, t0, self.sp_axis)
            slots = sp_slot_positions(kcache.shape[2], self.sp_axis)
        else:
            kcache = kv_write(kcache, k_new, (0, 0, t0, 0))
            vcache = kv_write(vcache, v_new, (0, 0, t0, 0))
            slots = jnp.arange(kcache.shape[2], dtype=jnp.int32)
        scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(jnp.float32),
                            kv_value(kcache)) * attn.scaling
        # cache slots beyond each position are unwritten (or stale)
        valid = slots[None, :] <= pos[:, None]
        scores = jnp.where(valid[None, None, :, :], scores, -1e30)
        if self.sp_axis is not None:
            o = sp_softmax_combine(
                scores, self.sp_axis,
                lambda p: jnp.einsum("bhqs,bhsd->bhqd", p,
                                     kv_value(vcache))).astype(x.dtype)
        else:
            probs = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("bhqs,bhsd->bhqd", probs,
                           kv_value(vcache)).astype(x.dtype)
        o = jnp.swapaxes(o, 1, 2).reshape(b, s_c, q.shape[1] * d)
        return self._attn_mlp_tail(ctx, x, o), kcache, vcache

    def decode(self, ctx, x, kcache, vcache, t):
        """One-token decode with a KV cache: ``x (B, E)`` at global
        position ``t`` (traced i32), caches ``(B, H, S_max, D)``.  The
        ``S_c = 1`` case of :meth:`decode_chunk` — one body, so the
        single-token and chunked programs cannot drift apart."""
        y, kcache, vcache = self.decode_chunk(
            ctx, x[:, None, :], kcache, vcache, t)
        return y[:, 0], kcache, vcache


class MoeGptBlock(GptBlock):
    """Pre-LN decoder block with a Switch-MoE feed-forward: LN → causal
    MHA → residual, LN → top-k routed expert FFN → residual.

    One expert per device along ``moe_axis`` (which the model typically
    shares with the data axis — experts then ride the same mesh dimension
    the batch shards over, the canonical Switch/GShard layout).  Expert
    weights are held STACKED and full-size ``(E, ...)`` on every device —
    same philosophy as the TP families: checkpoints are mesh-independent,
    each device slices its expert at trace time.  Their gradients are
    exact under the train step's psum-MEAN over the axis: device ``i``'s
    grad is nonzero only in its expert's slice and the global loss is the
    mean of per-device means, so mean-of-blocks IS the true gradient —
    no extra collectives needed (contrast parallel/tensor_parallel.py's
    f/g pair).

    The Switch load-balancing aux loss (weighted by ``aux_weight``) is
    recorded via ``Ctx.add_aux_loss``; ``make_train_step`` folds it into
    the optimized loss.  Tokens over capacity are dropped by the MoE —
    the residual connection carries them through unchanged.
    """

    def __init__(self, hidden, heads, intermediate, num_experts,
                 dropout=0.1, attn_dropout=0.1, sp_axis=None,
                 moe_axis="data", capacity_factor=1.25, top_k=1,
                 aux_weight=0.01):
        from ..nn.parameter import Parameter
        super().__init__(hidden, heads, intermediate, dropout,
                         attn_dropout, sp_axis=sp_axis, _dense_ffn=False)
        self.moe_axis = moe_axis
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.top_k = top_k
        self.aux_weight = aux_weight
        # router: (H, E), Switch init — small scale keeps early routing
        # near-uniform so the aux loss can act before collapse
        self.router = nn.Linear(hidden, num_experts, bias=False)
        self.router.weight.data = self.router.weight.data * 0.1
        # stacked per-expert FFN weights, nn.Linear layout (out, in) per
        # expert; drawn through throwaway Linears so each expert gets the
        # standard init distribution
        w1, b1, w2, b2 = [], [], [], []
        for _ in range(num_experts):
            l1 = nn.Linear(hidden, intermediate)
            l2 = nn.Linear(intermediate, hidden)
            w1.append(l1.weight.data)
            b1.append(l1.bias.data)
            w2.append(l2.weight.data)
            b2.append(l2.bias.data)
        self.w1 = Parameter(jnp.stack(w1))    # (E, I, H)
        self.b1 = Parameter(jnp.stack(b1))    # (E, I)
        self.w2 = Parameter(jnp.stack(w2))    # (E, H, I)
        self.b2 = Parameter(jnp.stack(b2))    # (E, H)

    def _ffn(self, ctx, h):
        """Routed expert mixture on the LN2 output (overrides the dense
        hook, so the training forward AND the cached decode paths route
        identically — tokens flatten over whatever leading layout the
        caller uses: (S, B, E) in forward, (B, S_c, E) in decode)."""
        from ..parallel.expert_parallel import switch_moe

        shape = h.shape
        toks = h.reshape(-1, shape[-1])
        i = jax.lax.axis_index(self.moe_axis)
        params = tuple(
            jax.lax.dynamic_index_in_dim(ctx.value(p), i, 0,
                                         keepdims=False)
            for p in (self.w1, self.b1, self.w2, self.b2))

        def expert_fn(params, xe):
            w1l, b1l, w2l, b2l = params
            hh = F.gelu(jnp.matmul(xe, w1l.T.astype(xe.dtype))
                        + b1l.astype(xe.dtype))
            return jnp.matmul(hh, w2l.T.astype(xe.dtype)) \
                + b2l.astype(xe.dtype)

        y, aux = switch_moe(toks, ctx.value(self.router.weight).T,
                            params, expert_fn, self.moe_axis,
                            capacity_factor=self.capacity_factor,
                            top_k=self.top_k)
        ctx.add_aux_loss(self.aux_weight * aux)
        return y.reshape(shape)

    def tp_sharded_params(self):
        return []    # MoE blocks carry no TP-sharded params


class GptModel(nn.Module):
    """Token+position embeddings → N pre-LN causal blocks → final LN →
    weight-tied LM head.  ``forward(input_ids[B,S]) -> logits (B,S,V)``."""

    def __init__(self, vocab_size=50257, hidden=768, layers=12, heads=12,
                 intermediate=None, max_positions=1024, dropout=0.1,
                 attn_dropout=0.1, remat=False, sp_axis=None, tp_axis=None,
                 tp_vocab=False, moe_axis=None, moe_num_experts=None,
                 moe_every=2, moe_capacity_factor=1.25, moe_top_k=1,
                 moe_aux_weight=0.01, attn_bias=False,
                 pad_vocab_multiple=None, output_hidden=False):
        super().__init__()
        intermediate = intermediate or 4 * hidden
        # pad_vocab_multiple: the Megatron --make-vocab-size-divisible-by
        # convention — the embedding table and tied head round the vocab
        # up to a lane-aligned multiple (GPT-2's 50257 is not).  logits
        # come back with padded width; pad columns are masked to -1e30,
        # so softmax / cross-entropy / argmax over them are EXACT w.r.t.
        # the logical vocab (labels never change).  That includes
        # label-smoothed losses THROUGH THIS PACKAGE — F.cross_entropy
        # and contrib.xentropy exclude <=-1e29-masked columns from the
        # smoothing term (mask-aware smoothing) — but a third-party
        # smoothed loss that spreads s/C over all columns would average
        # the -1e30 pads into the loss; slice logits[..., :vocab_size]
        # before such a loss.  Pad table rows are
        # never looked up and receive zero gradient through the masked
        # columns.  Measured on v5e (BENCH_HISTORY round 4): a WASH on
        # the GPT headlines (912 vs 921 seq/s at seq-128) — XLA pads
        # unaligned contraction dims internally — so this is a
        # divisibility/parity convenience (e.g. for tp sharding), not a
        # perf lever on this backend.
        self.vocab_size = vocab_size
        self.padded_vocab = vocab_size
        if pad_vocab_multiple:
            self.padded_vocab = -(-vocab_size // pad_vocab_multiple) \
                * pad_vocab_multiple
        if tp_vocab and self.padded_vocab != vocab_size:
            raise ValueError(
                "pad_vocab_multiple with tp_vocab is not supported: the "
                "vocab-parallel loss would see unmasked pad columns in "
                "the last shard")
        # attn_bias: QKV/out-proj biases on every block's attention (what
        # GPT-2 checkpoints carry — models/hf.py loads into this config);
        # selects the bias-capable 'default' attention impl per block
        if attn_bias and moe_axis is not None:
            raise ValueError(
                "attn_bias is not supported with moe_axis (MoE blocks "
                "are this framework's own architecture; imported "
                "checkpoints are dense)")
        self.hidden = hidden
        self.max_positions = max_positions
        # moe_axis: Switch-MoE — every ``moe_every``-th block (Switch's
        # every-other-layer default) swaps its dense FFN for a top-k
        # routed expert FFN with one expert per device along this mesh
        # axis (usually the data axis).  ``moe_num_experts`` must equal
        # that axis's size at run time (validated by switch_moe).
        self.moe_axis = moe_axis
        if moe_axis is not None:
            if moe_num_experts is None:
                raise ValueError(
                    "moe_axis requires moe_num_experts (= the mesh axis "
                    "size: one expert per device)")
            if tp_axis is not None:
                raise ValueError(
                    "moe_axis and tp_axis are mutually exclusive for now "
                    "(the MoE FFN replaces the dense FFN that TP shards)")
            if not 1 <= moe_every <= layers:
                raise ValueError(
                    f"moe_every={moe_every} with layers={layers}: must "
                    f"be in [1, layers] or no block would be MoE (block "
                    f"moe_every-1 is the first routed one)")
        # tp_axis: Megatron tensor parallelism — forward must run inside
        # shard_map over a mesh with this axis; attention heads and the
        # MLP hidden shard over it, embeddings/LNs/head stay replicated.
        # Composes with sp_axis (TP shards heads, SP shards time) and
        # with a data axis for 2-D/3-D meshes.
        self.tp_axis = tp_axis
        # attention dropout composes with tp_axis on the flash path:
        # each head-shard folds its axis index into the in-kernel mask
        # seed (attn_funcs._dropout_seed).  The 'default' impl
        # (attn_bias=True) cannot decorrelate — fail where the config
        # is written, not deep inside shard_map tracing
        if tp_axis is not None and attn_dropout > 0.0 and attn_bias:
            raise ValueError(
                "tp_axis with attn_dropout > 0 requires the flash impl; "
                "attn_bias=True selects the materializing 'default' "
                "impl, which draws from one shared key — set "
                "attn_dropout=0.0 or attn_bias=False")
        # tp_vocab: Megatron vocab parallelism — the tied embedding table
        # row-shards over tp_axis, the input lookup combines partial rows,
        # and forward returns VOCAB-SHARDED logits (B, S, V/n_tp): the
        # full logits tensor (the largest activation of an LM step) never
        # materializes.  Train with
        # parallel.vocab_parallel_cross_entropy(logits, targets, tp_axis)
        # as the loss.
        self.tp_vocab = tp_vocab
        if tp_vocab and tp_axis is None:
            raise ValueError("tp_vocab requires tp_axis")
        # output_hidden: training-time option — forward returns
        # (hidden, table) instead of logits so a chunked/fused loss can
        # own the vocab chain (see forward).  Decode paths apply the
        # head themselves and are unaffected.
        self.output_hidden = output_hidden
        if output_hidden and tp_vocab:
            raise ValueError(
                "output_hidden with tp_vocab is redundant: vocab-parallel "
                "logits already never materialize whole — use "
                "vocab_parallel_cross_entropy as the loss instead")
        # remat: rematerialize each block's activations in backward
        # (jax.checkpoint) — HBM drops from O(layers * S * E) residuals to
        # O(layers) block boundaries, the long-sequence enabler
        self.remat = remat
        # sp_axis: sequence parallelism — forward must run inside
        # shard_map with input_ids sharded on dim 1 over this mesh axis;
        # attention rides the ring (parallel/ring_attention.py), position
        # embeddings use global offsets, everything else is local.
        # max_positions caps the GLOBAL sequence length.  Composes with
        # remat for the long-context recipe.
        self.sp_axis = sp_axis
        # attention dropout composes with sp_axis: the ring hashes
        # GLOBAL coordinates under the replicated pre-shard key, so the
        # dropped positions are bit-identical to the unsharded run
        # (attn_funcs.self_attn_func; ulysses decorrelates per shard)
        self.tok_emb = nn.Embedding(self.padded_vocab, hidden)
        self.pos_emb = nn.Embedding(max_positions, hidden)
        # GPT initializer_range=0.02 (nn.Embedding draws std-1 normals; the
        # tied head would otherwise see logits of std ~sqrt(hidden))
        for emb in (self.tok_emb, self.pos_emb):
            emb.weight.data = emb.weight.data * 0.02
        self.drop = nn.Dropout(dropout)
        def _block(idx):
            if moe_axis is not None and idx % moe_every == moe_every - 1:
                return MoeGptBlock(
                    hidden, heads, intermediate, moe_num_experts,
                    dropout, attn_dropout, sp_axis=sp_axis,
                    moe_axis=moe_axis,
                    capacity_factor=moe_capacity_factor,
                    top_k=moe_top_k, aux_weight=moe_aux_weight)
            return GptBlock(hidden, heads, intermediate, dropout,
                            attn_dropout, sp_axis=sp_axis, tp_axis=tp_axis,
                            attn_bias=attn_bias)

        self.blocks = nn.ModuleList([_block(i) for i in range(layers)])
        self.ln_f = FusedLayerNorm(hidden)

    def tp_sharded_params(self):
        """All blocks' TP-block-sparse parameters (see GptBlock), plus
        the vocab-sharded embedding table under ``tp_vocab`` (its
        gradient is a scatter into the device's own vocab rows)."""
        ps = [p for blk in self.blocks for p in blk.tp_sharded_params()]
        if self.tp_vocab:
            ps.append(self.tok_emb.weight)
        return ps

    def forward(self, ctx, input_ids):
        b, s = input_ids.shape
        if self.sp_axis is not None:
            ctx = _fold_shard_into_key(ctx, self.sp_axis)
            # s is the LOCAL shard; global position = shard offset + local
            from ..compat import axis_size as _axis_size
            n = _axis_size(self.sp_axis)
            if s * n > self.max_positions:
                raise ValueError(
                    f"global sequence length {s * n} exceeds "
                    f"max_positions {self.max_positions}")
            off = jax.lax.axis_index(self.sp_axis) * s
            pos = (off + jnp.arange(s, dtype=jnp.int32))[None, :]
        elif s > self.max_positions:
            # jax gather clamps out-of-range indices, so oversized inputs
            # would silently reuse the last position embedding (torch
            # errors here)
            raise ValueError(
                f"sequence length {s} exceeds max_positions "
                f"{self.max_positions}")
        else:
            pos = jnp.arange(s, dtype=jnp.int32)[None, :]
        if self.tp_vocab:
            from ..parallel.tensor_parallel import vocab_parallel_embedding
            x = vocab_parallel_embedding(
                input_ids, ctx.value(self.tok_emb.weight), self.tp_axis) \
                + self.pos_emb.forward(ctx, pos)
        else:
            x = self.tok_emb.forward(ctx, input_ids) \
                + self.pos_emb.forward(ctx, pos)
        x = self.drop.forward(ctx, x)
        x = jnp.swapaxes(x, 0, 1)          # (S, B, E)
        for blk in self.blocks:
            if self.remat:
                x = nn.checkpoint_forward(blk, ctx, x)
            else:
                x = blk.forward(ctx, x)
        x = self.ln_f.forward(ctx, x)
        x = jnp.swapaxes(x, 0, 1)          # (B, S, E)
        emb = ctx.value(self.tok_emb.weight)
        if self.output_hidden:
            # head deferred to the loss: (hidden (B,S,E), table (V,E)) —
            # the chunked/fused vocab-chain losses (contrib.xentropy.
            # chunked_lm_head_loss, ops.pallas.fused_lm_head_xent) apply
            # the tied head themselves so (B,S,V) logits never have to
            # materialize whole
            return x, emb
        if self.tp_vocab:
            from ..parallel.tensor_parallel import vocab_parallel_logits
            return vocab_parallel_logits(x, emb, self.tp_axis)
        return self._mask_pad_logits(
            jnp.matmul(x, jnp.swapaxes(emb, 0, 1).astype(x.dtype)))


    def _mask_pad_logits(self, logits):
        """-1e30 on vocab-pad columns: softmax/argmax/cross-entropy over
        the padded width equal the logical-vocab results exactly."""
        if self.padded_vocab == self.vocab_size:
            return logits
        cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape,
                                        logits.ndim - 1)
        return jnp.where(cols < self.vocab_size, logits,
                         jnp.asarray(-1e30, logits.dtype))

    def init_caches(self, batch, s_max, dtype=jnp.float32):
        """Per-layer (k, v) caches of shape (B, H, S_max, D).  Under
        ``tp_axis`` H is the LOCAL head count (call inside shard_map —
        generate does): each device caches only its own head shard.
        Under ``sp_axis`` S is the LOCAL sequence block (ceil(S_max/n),
        rounded up so every position has an owner): per-device cache HBM
        shrinks with the mesh — the context-length scaling lever."""
        blk0 = self.blocks[0]
        h, d = blk0.attn.num_heads, blk0.attn.head_dim
        if self.tp_axis is not None:
            try:
                n = jax.lax.psum(1, self.tp_axis)   # static axis size
            except NameError:
                raise ValueError(
                    f"init_caches on a tp_axis='{self.tp_axis}' model "
                    f"must run inside shard_map over a mesh with that "
                    f"axis — generate(..., mesh=...) wraps the whole "
                    f"decode; direct callers must shard_map themselves"
                ) from None
            if h % n:
                raise ValueError(
                    f"init_caches: heads ({h}) must divide by the "
                    f"'{self.tp_axis}' axis size ({n})")
            h //= n
        if self.sp_axis is not None:
            from ..parallel.context_parallel import sp_axis_size
            s_max = -(-s_max // sp_axis_size(self.sp_axis))
        from ..inference.quant import make_kv_cache
        return [(make_kv_cache((batch, h, s_max, d), dtype),
                 make_kv_cache((batch, h, s_max, d), dtype))
                for _ in self.blocks]

    def _cache_capacity(self, caches):
        """Global position capacity of the caches (under ``sp_axis`` the
        per-device block times the axis size)."""
        cap = caches[0][0].shape[2]
        if self.sp_axis is not None:
            from ..parallel.context_parallel import sp_axis_size
            cap *= sp_axis_size(self.sp_axis)
        return cap

    def _decode_guard(self, what):
        """Cached decode supports single-shard, tensor-parallel
        (``tp_axis``), expert-parallel (``moe_axis``), and
        sequence-parallel (``sp_axis``) execution — the sharded flavors
        run inside shard_map (generate(mesh=...) wraps it): TP shards
        heads with psum-replicated logits; MoE keeps caches replicated
        and routes each decoded chunk through the training forward's
        all_to_all; SP shards the KV cache's TIME axis with lse-merged
        partial attention (parallel/context_parallel.py).  SP×TP
        composes (heads and time shard independently); SP×MoE does not
        (untested collective interleaving) — refuse loudly."""
        if self.sp_axis is not None and self.moe_axis is not None:
            raise NotImplementedError(
                f"{what}: sp_axis does not compose with moe_axis for "
                f"cached decode; build the model with one or the other "
                f"for inference")

    def _run_blocks(self, ctx, toks, caches, pos_of, blk_fn):
        """Embed ``toks`` + positions (``pos_of(pos_table)``), thread the
        caches through ``blk_fn`` per block, final-LN + tied head — the
        shared body of every cached decode entry point.  The token
        gather is int8-aware (only selected rows dequantize); the tied
        HEAD matmul still reads the full table, which ctx.value
        dequantizes fused into the matmul."""
        from ..inference.quant import gather_rows
        emb = ctx.value(self.tok_emb.weight)
        x = gather_rows(ctx, self.tok_emb.weight, toks) \
            + pos_of(ctx.value(self.pos_emb.weight))
        new_caches = []
        for blk, (kc, vc) in zip(self.blocks, caches):
            x, kc, vc = blk_fn(blk, x, kc, vc)
            new_caches.append((kc, vc))
        x = self.ln_f.forward(ctx, x)
        return self._mask_pad_logits(
            jnp.matmul(x, jnp.swapaxes(emb, 0, 1).astype(x.dtype))), \
            new_caches

    def prefill(self, ctx, toks, caches):
        """Consume a PROMPT ``toks (B, S_p)`` from position 0 in one
        flash-attention pass, filling the KV caches: returns
        ``(logits (B, S_p, V), new_caches)`` — O(1) calls instead of
        S_p decode steps.  Under ``sp_axis`` the prompt runs in cache-
        block-bounded chunks instead (cross-chunk attention rides the
        sharded cache; parallel/context_parallel.py)."""
        self._decode_guard("prefill")
        if self.sp_axis is not None:
            from ..parallel.context_parallel import sp_chunked_prefill
            return sp_chunked_prefill(self, ctx, toks, caches)
        s_p = toks.shape[1]
        return self._run_blocks(
            ctx, toks, caches, lambda pos: pos[:s_p][None, :, :],
            lambda blk, x, kc, vc: blk.prefill(ctx, x, kc, vc))

    def decode_chunk(self, ctx, toks, caches, t0):
        """Logits for a token CHUNK ``toks (B, S_c)`` at positions
        ``t0 ..`` against the caches (the speculative-verification
        primitive; same contract as LlamaModel.decode_chunk).

        ``t0 + S_c`` must be ``<= max_positions``: the position table is
        read with ``lax.dynamic_slice``, which CLAMPS an out-of-range
        start instead of failing — silently wrong position embeddings.
        A concrete (Python int) ``t0`` is checked here; traced callers
        (generate / speculative_generate) enforce the bound on the whole
        generation up front, so the clamp is unreachable through them."""
        self._decode_guard("decode_chunk")
        s_c = toks.shape[1]
        if not isinstance(t0, jax.core.Tracer):
            bound = min(self.max_positions, self._cache_capacity(caches))
            if int(t0) < 0 or int(t0) + s_c > bound:
                raise ValueError(
                    f"decode_chunk: positions {int(t0)}..{int(t0) + s_c} "
                    f"out of range for max_positions {self.max_positions} "
                    f"/ cache capacity {self._cache_capacity(caches)} — "
                    f"dynamic_slice would clamp and return wrong position "
                    f"embeddings / corrupt the cache")
        return self._run_blocks(
            ctx, toks, caches,
            lambda pos: jax.lax.dynamic_slice(
                pos, (t0, 0), (s_c, pos.shape[1]))[None, :, :],
            lambda blk, x, kc, vc: blk.decode_chunk(ctx, x, kc, vc, t0))

    def decode_step(self, ctx, tok, caches, t):
        """Logits for one token: ``tok (B,)`` ids at global position
        ``t`` (traced i32).  Returns ``(logits (B, V), new_caches)``."""
        self._decode_guard("decode_step")
        return self._run_blocks(
            ctx, tok, caches,
            lambda pos: jax.lax.dynamic_index_in_dim(pos, t,
                                                     keepdims=False),
            lambda blk, x, kc, vc: blk.decode(ctx, x, kc, vc, t))


def _sharded_decode_axes(model):
    """The mesh axes a model's decode needs: tp (head-sharded), moe
    (expert dispatch), and/or sp (time-sharded KV cache).  Callers run
    the model's own ``_decode_guard`` FIRST, so a composition a family
    refuses (sp×moe) never reaches the mesh demands here."""
    axes = []
    for attr in ("tp_axis", "moe_axis", "sp_axis"):
        ax = getattr(model, attr, None)
        if ax is not None:
            axes.append((attr, ax))
    return axes


def _check_decode_mesh(model, mesh, what="generate", who="model"):
    """Shared mesh validation for the decode drivers: a model with any
    sharded decode axis needs a mesh carrying ALL of them; a mesh with
    nothing to shard is a caller error.  ``who`` names the model in the
    errors (speculative decoding passes "target"/"draft" so a mismatch
    says which of its two models to fix).  Call the model's
    ``_decode_guard`` before this — an unsupported-composition refusal
    must win over a 'pass mesh=' demand."""
    axes = _sharded_decode_axes(model)
    if axes and mesh is None:
        names = ", ".join(f"{a}='{v}'" for a, v in axes)
        raise ValueError(
            f"{who} was built with {names}: decode runs inside "
            f"shard_map — pass {what}(..., mesh=<Mesh with the axes>)")
    if mesh is not None:
        for attr, ax in axes:
            if ax not in mesh.axis_names:
                raise ValueError(
                    f"mesh axes {mesh.axis_names} do not include "
                    f"{who}'s {attr} '{ax}'")


def nucleus_filter(logits, top_p):
    """Top-p (nucleus) logit filter, static shapes: keep the smallest
    prefix of the probability-sorted vocab whose cumulative probability
    reaches ``top_p`` (the first token always survives), set the rest
    to -1e30.  ``logits (..., V)``."""
    if top_p >= 1.0:
        # exact no-op: f32 cumsum rounding can push the tail's prefix
        # mass a few ulps past 1.0 and mask valid tokens otherwise
        return logits
    srt = jnp.sort(logits, axis=-1)[..., ::-1]          # descending
    probs = jax.nn.softmax(srt.astype(jnp.float32), axis=-1)
    # token i is OUTSIDE the nucleus iff the mass strictly before it
    # already reached top_p
    before = jnp.cumsum(probs, axis=-1) - probs
    kept = before < top_p                               # (..., V) sorted
    # per-row threshold = smallest kept logit
    thresh = jnp.min(jnp.where(kept, srt, jnp.inf), axis=-1,
                     keepdims=True).astype(logits.dtype)
    return jnp.where(logits < thresh, -1e30, logits)


def make_sampler(temperature, top_k, top_p, vocab):
    """Validate the sampling knobs and return ``sample(logits, key)``
    — ONE implementation of the greedy/temperature/top-k/top-p
    composition, shared by ``generate`` and ``inference.DecodeSession``
    so the two paths cannot drift."""
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None and not 1 <= top_k <= vocab:
        raise ValueError(
            f"top_k must be in [1, vocab={vocab}], got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")

    def sample(logits, k):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1)
        logits = logits / temperature
        if top_k is not None:
            kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits < kth, -1e30, logits)
        if top_p is not None:
            logits = nucleus_filter(logits, top_p)
        return jax.random.categorical(k, logits, axis=-1)

    return sample


def generate(model: GptModel, prompt_ids, max_new_tokens, temperature=0.0,
             top_k=None, key=None, cache_dtype=None, mesh=None,
             top_p=None):
    """Autoregressive sampling with a KV cache: models with the chunk
    protocol (GPT, Llama) consume the prompt in ONE ``model.prefill``
    flash pass, then generation runs a ``lax.scan`` of per-token decode
    steps; models without it run the whole sequence through the scan,
    teacher-forced inside the prompt.  Either way everything compiles
    into one jitted program, cached per model instance and config, so
    repeated calls pay compile once.

    ``prompt_ids (B, P)``; returns ``(B, P + max_new_tokens)``.
    ``temperature=0`` is greedy; ``top_k`` keeps the k highest logits
    and ``top_p`` the probability nucleus (applied after top_k, the
    usual composition); ``cache_dtype`` defaults to the token-embedding
    dtype (use
    ``jnp.bfloat16`` to halve cache HBM for fp32 checkpoints, or the
    string ``"int8"`` for a quantized KV cache — per-position absmax,
    half of bf16's traffic again; long-context decode re-reads the
    whole cache every token, so cache bytes are the lever there).  The
    reference has no inference path (it is a training-side library); this
    is the decode half of the GPT family.

    Tensor-parallel decode: a model built with ``tp_axis`` needs
    ``mesh`` (a ``jax.sharding.Mesh`` carrying that axis) — the whole
    decode program runs inside ``shard_map`` with weights, tokens, and
    the PRNG key replicated: each device projects only its own head
    blocks (KV caches are head-sharded, HBM/device shrinks with the
    mesh), the row-parallel psums make the logits replicated, and
    sampling therefore emits bit-identical tokens on every device —
    the output equals the single-shard decode of the same weights.

    Note on sampled reproducibility: the prefill fast path consumes ONE
    key split for the prompt where the legacy per-token path consumed
    ``P - 1``, so sampled (temperature > 0) streams differ from runs of
    this function before prefill existed (and from models without the
    chunk protocol).  Greedy output is unaffected.
    """
    from ..nn.modules import Ctx

    b, p = prompt_ids.shape
    s_total = p + max_new_tokens
    if s_total > model.max_positions:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_positions {model.max_positions}")
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if key is None:
        key = jax.random.PRNGKey(0)
    vocab = getattr(model, 'vocab_size', None) \
        or model.tok_emb.weight.shape[0]
    sample = make_sampler(temperature, top_k, top_p, vocab)
    # unsupported-composition refusal (sp) wins over mesh demands;
    # then validate the mesh against the sharded axes
    model._decode_guard("generate")
    _check_decode_mesh(model, mesh)
    if mesh is not None and not _sharded_decode_axes(model):
        raise ValueError(
            "mesh was passed but the model has no tp_axis/moe_axis/"
            "sp_axis — single-shard decode needs no mesh")

    params = [q for q in model.parameters()]
    buffers = list(model.buffers())
    vals = [q.data for q in params] + [bu.data for bu in buffers]
    if cache_dtype is None:
        cache_dtype = model.tok_emb.weight.data.dtype

    prompt_padded = jnp.concatenate(
        [prompt_ids, jnp.zeros((b, max_new_tokens), prompt_ids.dtype)],
        axis=1)

    # models exposing prefill (the GPT and Llama families; the dispatch
    # condition is the method itself) consume the whole prompt in ONE
    # flash-attention cached forward instead of p sequential decode
    # steps; max_new_tokens == 0 keeps the legacy path (the prefill
    # path's first sampled token would be unrequested)
    chunk_prefill = hasattr(model, "prefill") and p > 1 \
        and max_new_tokens >= 1

    def run(vals, prompt_padded, key):
        env = {id(o): v for o, v in zip(params + buffers, vals)}
        ctx = Ctx(env=env, stats_out={}, training=False)
        caches = model.init_caches(b, s_total, dtype=cache_dtype)

        def step(carry, t):
            tok, caches, key = carry
            logits, caches = model.decode_step(ctx, tok, caches, t)
            key, sub = jax.random.split(key)
            sampled = sample(logits, sub)
            # teacher-force inside the prompt, sample past it (the scan
            # covers t < s_total - 1, so t + 1 is always in bounds)
            nxt = jnp.where(t + 1 < p, prompt_padded[:, t + 1], sampled)
            return (nxt, caches, key), nxt

        if chunk_prefill:
            logits, caches = model.prefill(
                ctx, prompt_padded[:, :p], caches)
            key, sub = jax.random.split(key)
            first_new = sample(logits[:, -1], sub)
            (_, _, _), toks = jax.lax.scan(
                step, (first_new, caches, key),
                jnp.arange(p, s_total - 1))
            return jnp.concatenate(
                [prompt_padded[:, :p], first_new[:, None],
                 jnp.swapaxes(toks, 0, 1)], axis=1)

        (_, _, _), toks = jax.lax.scan(
            step, (prompt_padded[:, 0], caches, key),
            jnp.arange(s_total - 1))
        return jnp.concatenate(
            [prompt_padded[:, :1], jnp.swapaxes(toks, 0, 1)], axis=1)

    # per-model compiled-run cache (see utils/jit_cache.py for the
    # parameter-identity/LRU invariants — LoRA apply/merge must miss)
    from ..utils.jit_cache import compiled_run_cache

    def build():
        if mesh is not None:
            # everything replicated in and out; the TP sharding lives in
            # the trace-time head-block slices inside the blocks
            from jax.sharding import PartitionSpec as _P
            from ..compat import shard_map as _shard_map
            return jax.jit(_shard_map(
                run, mesh=mesh, in_specs=(_P(), _P(), _P()),
                out_specs=_P(), check_vma=False))
        return jax.jit(run)

    fn = compiled_run_cache(
        model, "_generate_jit_cache",
        (b, p, max_new_tokens, float(temperature), top_k,
         None if top_p is None else float(top_p),
         cache_dtype if isinstance(cache_dtype, str)
         else jnp.dtype(cache_dtype).name, mesh),
        params + buffers, build)
    return fn(vals, prompt_padded, key)


def gpt2_small(**kw):
    """GPT-2 small geometry: 12 layers, hidden 768, 12 heads (124M)."""
    return GptModel(**{**dict(hidden=768, layers=12, heads=12), **kw})


def gpt2_medium(**kw):
    """GPT-2 medium geometry: 24 layers, hidden 1024, 16 heads (350M)."""
    return GptModel(**{**dict(hidden=1024, layers=24, heads=16), **kw})


def gpt2_large(**kw):
    """GPT-2 large geometry: 36 layers, hidden 1280, 20 heads (774M)."""
    return GptModel(**{**dict(hidden=1280, layers=36, heads=20), **kw})


def gpt2_xl(**kw):
    """GPT-2 XL geometry: 48 layers, hidden 1600, 25 heads (1.5B)."""
    return GptModel(**{**dict(hidden=1600, layers=48, heads=25), **kw})
