"""GPT-style causal decoder family — the autoregressive counterpart to
models/bert.py, built from the same fused components.

The reference repo carries no language models of its own (SURVEY.md §2 —
its fused pieces were consumed by external scripts); this standalone
decoder completes the transformer story: pre-LN blocks, causal Pallas
flash attention (``SelfMultiheadAttn`` with a time mask), FusedLayerNorm,
GELU FFN, weight-tied LM head.

Layout: public API is batch-first ``(B, S)`` token ids; internally the
decoder runs ``(S, B, E)`` for the attention module's reference layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..normalization import FusedLayerNorm
from ..contrib.multihead_attn import SelfMultiheadAttn


class GptBlock(nn.Module):
    """Pre-LN decoder block: LN → causal MHA → residual, LN → GELU FFN →
    residual."""

    def __init__(self, hidden, heads, intermediate, dropout=0.1,
                 attn_dropout=0.1, sp_axis=None):
        super().__init__()
        self.ln1 = FusedLayerNorm(hidden)
        # causal=True: when the flash path applies (attn_dropout == 0 in
        # training, or eval) the kernel masks the triangle in-kernel with
        # no O(S^2) mask operand; with attention dropout active the
        # materializing fallback runs (the Pallas kernel has no dropout)
        self.attn = SelfMultiheadAttn(hidden, heads, dropout=attn_dropout,
                                      impl="fast", causal=True,
                                      seq_parallel_axis=sp_axis)
        self.ln2 = FusedLayerNorm(hidden)
        self.fc1 = nn.Linear(hidden, intermediate)
        self.fc2 = nn.Linear(intermediate, hidden)
        self.dropout = nn.Dropout(dropout)

    def forward(self, ctx, x):
        h, _ = self.attn.forward(ctx, self.ln1.forward(ctx, x))
        x = x + self.dropout.forward(ctx, h)
        h = F.gelu(self.fc1.forward(ctx, self.ln2.forward(ctx, x)))
        h = self.fc2.forward(ctx, h)
        return x + self.dropout.forward(ctx, h)


class GptModel(nn.Module):
    """Token+position embeddings → N pre-LN causal blocks → final LN →
    weight-tied LM head.  ``forward(input_ids[B,S]) -> logits (B,S,V)``."""

    def __init__(self, vocab_size=50257, hidden=768, layers=12, heads=12,
                 intermediate=None, max_positions=1024, dropout=0.1,
                 attn_dropout=0.1, remat=False, sp_axis=None):
        super().__init__()
        intermediate = intermediate or 4 * hidden
        self.hidden = hidden
        self.max_positions = max_positions
        # remat: rematerialize each block's activations in backward
        # (jax.checkpoint) — HBM drops from O(layers * S * E) residuals to
        # O(layers) block boundaries, the long-sequence enabler
        self.remat = remat
        # sp_axis: sequence parallelism — forward must run inside
        # shard_map with input_ids sharded on dim 1 over this mesh axis;
        # attention rides the ring (parallel/ring_attention.py), position
        # embeddings use global offsets, everything else is local.
        # max_positions caps the GLOBAL sequence length.  Composes with
        # remat for the long-context recipe.
        self.sp_axis = sp_axis
        if sp_axis is not None and attn_dropout > 0.0:
            # fail where the config is written, not deep inside
            # shard_map tracing on the first training step
            raise ValueError(
                "sp_axis requires attn_dropout=0.0 — the sequence-"
                "parallel kernels have no attention dropout (like flash)")
        self.tok_emb = nn.Embedding(vocab_size, hidden)
        self.pos_emb = nn.Embedding(max_positions, hidden)
        # GPT initializer_range=0.02 (nn.Embedding draws std-1 normals; the
        # tied head would otherwise see logits of std ~sqrt(hidden))
        for emb in (self.tok_emb, self.pos_emb):
            emb.weight.data = emb.weight.data * 0.02
        self.drop = nn.Dropout(dropout)
        self.blocks = nn.ModuleList([
            GptBlock(hidden, heads, intermediate, dropout, attn_dropout,
                     sp_axis=sp_axis)
            for _ in range(layers)])
        self.ln_f = FusedLayerNorm(hidden)

    def forward(self, ctx, input_ids):
        b, s = input_ids.shape
        if self.sp_axis is not None:
            # s is the LOCAL shard; global position = shard offset + local
            n = jax.lax.axis_size(self.sp_axis)
            if s * n > self.max_positions:
                raise ValueError(
                    f"global sequence length {s * n} exceeds "
                    f"max_positions {self.max_positions}")
            off = jax.lax.axis_index(self.sp_axis) * s
            pos = (off + jnp.arange(s, dtype=jnp.int32))[None, :]
        elif s > self.max_positions:
            # jax gather clamps out-of-range indices, so oversized inputs
            # would silently reuse the last position embedding (torch
            # errors here)
            raise ValueError(
                f"sequence length {s} exceeds max_positions "
                f"{self.max_positions}")
        else:
            pos = jnp.arange(s, dtype=jnp.int32)[None, :]
        x = self.tok_emb.forward(ctx, input_ids) \
            + self.pos_emb.forward(ctx, pos)
        x = self.drop.forward(ctx, x)
        x = jnp.swapaxes(x, 0, 1)          # (S, B, E)
        for blk in self.blocks:
            if self.remat:
                x = nn.checkpoint_forward(blk, ctx, x)
            else:
                x = blk.forward(ctx, x)
        x = self.ln_f.forward(ctx, x)
        x = jnp.swapaxes(x, 0, 1)          # (B, S, E)
        emb = ctx.value(self.tok_emb.weight)
        return jnp.matmul(x, jnp.swapaxes(emb, 0, 1).astype(x.dtype))


def gpt2_small(**kw):
    """GPT-2 small geometry: 12 layers, hidden 768, 12 heads (124M)."""
    return GptModel(**{**dict(hidden=768, layers=12, heads=12), **kw})


def gpt2_medium(**kw):
    """GPT-2 medium geometry: 24 layers, hidden 1024, 16 heads (350M)."""
    return GptModel(**{**dict(hidden=1024, layers=24, heads=16), **kw})
