"""Vision Transformer family — patch embedding + pre-LN encoder on the
same fused substrate as the language families (SelfMultiheadAttn,
FusedLayerNorm, fused train step, remat).

The reference repo carries no vision transformer (its imagenet example
is ResNet, SURVEY.md §2); this rounds out the zoo with the standard
ViT shape (Dosovitskiy et al.): conv patchify, learned positions, a
prepended CLS token, pre-LN blocks, classification off the CLS state.
At ViT sequence lengths (197 tokens for 224/16) the shape-aware
dispatch routes attention to XLA's own path — exactly the regime the
kernel A/B measured it faster in.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..normalization import FusedLayerNorm
from ..contrib.multihead_attn import SelfMultiheadAttn


class VitBlock(nn.Module):
    """Pre-LN encoder block: LN → MHA → residual, LN → GELU FFN →
    residual (contrast BertLayer's post-LN)."""

    def __init__(self, hidden, heads, intermediate, dropout=0.0,
                 attn_dropout=0.0):
        super().__init__()
        self.ln1 = FusedLayerNorm(hidden)
        self.attn = SelfMultiheadAttn(hidden, heads, dropout=attn_dropout,
                                      impl="fast")
        self.ln2 = FusedLayerNorm(hidden)
        self.fc1 = nn.Linear(hidden, intermediate)
        self.fc2 = nn.Linear(intermediate, hidden)
        self.dropout = nn.Dropout(dropout)

    def forward(self, ctx, x):
        h, _ = self.attn.forward(ctx, self.ln1.forward(ctx, x))
        x = x + self.dropout.forward(ctx, h)
        h = F.gelu(self.fc1.forward(ctx, self.ln2.forward(ctx, x)))
        return x + self.dropout.forward(ctx, self.fc2.forward(ctx, h))


class VitModel(nn.Module):
    """``forward(images (B, 3, H, W)) -> logits (B, num_classes)``."""

    def __init__(self, image_size=224, patch_size=16, hidden=384,
                 layers=12, heads=6, num_classes=1000, intermediate=None,
                 dropout=0.0, attn_dropout=0.0, remat=False):
        super().__init__()
        if image_size % patch_size:
            raise ValueError(
                f"image_size {image_size} not divisible by patch_size "
                f"{patch_size}")
        self.patch_size = patch_size
        self.remat = remat
        n_patches = (image_size // patch_size) ** 2
        intermediate = intermediate or 4 * hidden
        self.patch_embed = nn.Conv2d(3, hidden, patch_size,
                                     stride=patch_size)
        from ..nn.modules import _next_key
        from ..nn.parameter import Parameter
        self.cls_token = Parameter(0.02 * jax.random.normal(
            _next_key(), (1, 1, hidden), jnp.float32))
        self.pos_emb = Parameter(0.02 * jax.random.normal(
            _next_key(), (n_patches + 1, hidden), jnp.float32))
        self.dropout = nn.Dropout(dropout)
        self.blocks = nn.ModuleList([
            VitBlock(hidden, heads, intermediate, dropout=dropout,
                     attn_dropout=attn_dropout)
            for _ in range(layers)])
        self.ln_f = FusedLayerNorm(hidden)
        self.head = nn.Linear(hidden, num_classes)

    def forward(self, ctx, x):
        b = x.shape[0]
        p = self.patch_embed.forward(ctx, x)          # (B, E, H', W')
        e = p.shape[1]
        p = p.reshape(b, e, -1)
        p = jnp.swapaxes(p, 1, 2)                     # (B, N, E)
        cls = jnp.broadcast_to(ctx.value(self.cls_token).astype(p.dtype),
                               (b, 1, e))
        x = jnp.concatenate([cls, p], axis=1)         # (B, N+1, E)
        pos = ctx.value(self.pos_emb).astype(x.dtype)
        if pos.shape[0] != x.shape[1]:
            raise ValueError(
                f"ViT built for {pos.shape[0] - 1} patches, got "
                f"{x.shape[1] - 1} (input spatial size mismatch)")
        x = self.dropout.forward(ctx, x + pos[None, :, :])
        x = jnp.swapaxes(x, 0, 1)                     # (S, B, E) for MHA
        for blk in self.blocks:
            if self.remat:
                x = nn.checkpoint_forward(blk, ctx, x)
            else:
                x = blk.forward(ctx, x)
        x = self.ln_f.forward(ctx, x[0])              # CLS state (B, E)
        return self.head.forward(ctx, x)


def vit_small(**kw):
    """ViT-S/16: 12 layers, hidden 384, 6 heads (~22M)."""
    return VitModel(**{**dict(hidden=384, layers=12, heads=6), **kw})


def vit_base(**kw):
    """ViT-B/16: 12 layers, hidden 768, 12 heads (~86M)."""
    return VitModel(**{**dict(hidden=768, layers=12, heads=12), **kw})
