"""BERT encoder family — the transformer model for BASELINE.md config 4
(BERT-base pretrain with FusedLAMB + FusedLayerNorm under amp O2).

The reference repo carries no BERT model of its own (it provides the pieces —
FusedLAMB, FusedLayerNorm, fast_self_multihead_attn — that NVIDIA's BERT
scripts consume), so this is the standalone equivalent: a post-LN BERT
encoder built from this framework's fused components:

* ``SelfMultiheadAttn(impl="fast")`` — the Pallas flash-attention path
  (apex_tpu/contrib/multihead_attn/), the fast_* extension analogue;
* ``FusedLayerNorm`` — Pallas LN with fp32 statistics;
* GELU feed-forward sized ``4*hidden`` (XLA fuses matmul+bias+gelu).

Layout: the public API is batch-first ``(B, S)`` token ids like BERT
checkpoints expect; internally the encoder runs ``(S, B, E)`` to feed the
attention module's reference layout.  The masked-LM head ties its decoder to
the token embedding matrix.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..normalization import FusedLayerNorm
from ..contrib.multihead_attn import SelfMultiheadAttn
from ..nn.modules import fold_shard_into_key as _fold_shard_into_key


class BertLayer(nn.Module):
    """One post-LN encoder block: MHA + residual + LN, GELU FFN + residual
    + LN."""

    def __init__(self, hidden, heads, intermediate, dropout=0.1,
                 attn_dropout=0.1, sp_axis=None, tp_axis=None):
        super().__init__()
        # encoder SP uses the Ulysses (all-to-all) impl: non-causal
        # attention with a key-padding mask needs the gathered global
        # sequence per device (the ring carries no mask operand)
        self.attn = SelfMultiheadAttn(hidden, heads, dropout=attn_dropout,
                                      impl="fast", seq_parallel_axis=sp_axis,
                                      seq_parallel_impl="ulysses",
                                      tensor_parallel_axis=tp_axis)
        self.attn_ln = FusedLayerNorm(hidden)
        self.fc1 = nn.Linear(hidden, intermediate)
        self.fc2 = nn.Linear(intermediate, hidden)
        self.out_ln = FusedLayerNorm(hidden)
        self.dropout = nn.Dropout(dropout)
        self.tp_axis = tp_axis

    def forward(self, ctx, x, key_padding_mask=None):
        h, _ = self.attn.forward(ctx, x, key_padding_mask=key_padding_mask)
        x = self.attn_ln.forward(ctx, x + self.dropout.forward(ctx, h))
        if self.tp_axis is not None:
            # Megatron MLP: column → gelu → row, one psum per pair
            from ..parallel.tensor_parallel import tp_ffn
            h = tp_ffn(x, ctx.value(self.fc1.weight),
                       ctx.value(self.fc1.bias),
                       ctx.value(self.fc2.weight),
                       ctx.value(self.fc2.bias),
                       self.tp_axis, activation=F.gelu)
        else:
            h = F.gelu(self.fc1.forward(ctx, x))
            h = self.fc2.forward(ctx, h)
        x = self.out_ln.forward(ctx, x + self.dropout.forward(ctx, h))
        return x

    def tp_sharded_params(self):
        """Parameters with TP-block-sparse gradients (models/gpt.py has
        the full story); the train step psums these over ``tp_axis``.
        The attention subset comes from the module itself."""
        return self.attn.tp_sharded_params() + [
            self.fc1.weight, self.fc1.bias, self.fc2.weight]


class BertModel(nn.Module):
    """Token/position/segment embeddings + N encoder layers.

    ``forward(input_ids[B,S], token_type_ids=None, attention_mask=None)``
    returns the sequence output ``(B, S, H)``.  ``attention_mask`` follows
    the BERT convention: 1 for real tokens, 0 for padding.
    """

    def __init__(self, vocab_size=30522, hidden=768, layers=12, heads=12,
                 intermediate=3072, max_positions=512, type_vocab=2,
                 dropout=0.1, attn_dropout=0.1, remat=False, sp_axis=None,
                 tp_axis=None):
        super().__init__()
        self.hidden = hidden
        self.max_positions = max_positions
        # tp_axis: Megatron tensor parallelism (see models/gpt.py — same
        # design: heads + MLP hidden shard, everything else replicated,
        # full weights sliced at trace time); composes with sp_axis
        self.tp_axis = tp_axis
        # attention dropout composes with tp_axis: each head-shard
        # folds its axis index into the in-kernel mask seed (decorrelated
        # per-rank streams, attn_funcs._dropout_seed)
        # remat: rematerialize each layer's activations in backward
        # (jax.checkpoint via nn.checkpoint_forward) — the long-sequence
        # HBM saver
        self.remat = remat
        # sp_axis: Ulysses sequence parallelism — forward must run inside
        # shard_map with input_ids sharded on dim 1 over this mesh axis
        # and heads divisible by the axis size; the attention_mask stays
        # GLOBAL (B, S_global) and replicated.  Position embeddings use
        # global shard offsets; max_positions caps the GLOBAL length.
        self.sp_axis = sp_axis
        # attention dropout composes with sp_axis (ring: bit-consistent
        # global hash mask; ulysses: per-shard streams — see
        # attn_funcs.self_attn_func)
        self.tok_emb = nn.Embedding(vocab_size, hidden)
        self.pos_emb = nn.Embedding(max_positions, hidden)
        self.type_emb = nn.Embedding(type_vocab, hidden)
        # BERT initializer_range=0.02; nn.Embedding draws std-1 normals, and
        # through the tied MLM decoder std-1 embeddings give logits of std
        # ~sqrt(hidden) (useless initial loss)
        for emb in (self.tok_emb, self.pos_emb, self.type_emb):
            emb.weight.data = emb.weight.data * 0.02
        self.emb_ln = FusedLayerNorm(hidden)
        self.emb_drop = nn.Dropout(dropout)
        self.layers = nn.ModuleList([
            BertLayer(hidden, heads, intermediate, dropout, attn_dropout,
                      sp_axis=sp_axis, tp_axis=tp_axis)
            for _ in range(layers)])

    def tp_sharded_params(self):
        """All layers' TP-block-sparse parameters (see BertLayer)."""
        return [p for ly in self.layers for p in ly.tp_sharded_params()]

    def forward(self, ctx, input_ids, token_type_ids=None,
                attention_mask=None):
        b, s = input_ids.shape
        if self.sp_axis is not None:
            ctx = _fold_shard_into_key(ctx, self.sp_axis)
            # s is the LOCAL shard; guard the GLOBAL length — jax gather
            # clamps out-of-range indices, so an oversized sequence would
            # silently reuse the last position embedding (mirrors gpt.py)
            from ..compat import axis_size as _axis_size
            n = _axis_size(self.sp_axis)
            if s * n > self.max_positions:
                raise ValueError(
                    f"global sequence length {s * n} exceeds "
                    f"max_positions {self.max_positions}")
            off = jax.lax.axis_index(self.sp_axis) * s
            pos = (off + jnp.arange(s, dtype=jnp.int32))[None, :]
        elif s > self.max_positions:
            raise ValueError(
                f"sequence length {s} exceeds max_positions "
                f"{self.max_positions}")
        else:
            pos = jnp.arange(s, dtype=jnp.int32)[None, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (self.tok_emb.forward(ctx, input_ids)
             + self.pos_emb.forward(ctx, pos)
             + self.type_emb.forward(ctx, token_type_ids))
        x = self.emb_drop.forward(ctx, self.emb_ln.forward(ctx, x))
        # encoder runs (S, B, E); attention's key_padding_mask is (B, S)
        # additive-bool with True = masked, so invert the BERT convention
        x = jnp.swapaxes(x, 0, 1)
        kpm = None
        if attention_mask is not None:
            kpm = (attention_mask == 0)
        for layer in self.layers:
            if self.remat:
                x = nn.checkpoint_forward(layer, ctx, x, kpm)
            else:
                x = layer.forward(ctx, x, key_padding_mask=kpm)
        return jnp.swapaxes(x, 0, 1)


class BertForMaskedLM(nn.Module):
    """BertModel + MLM transform head with the decoder tied to the token
    embedding (standard BERT pretraining head)."""

    def __init__(self, **kw):
        super().__init__()
        self.bert = BertModel(**kw)
        hidden = self.bert.hidden
        self.transform = nn.Linear(hidden, hidden)
        self.transform_ln = FusedLayerNorm(hidden)
        vocab = self.bert.tok_emb.weight.shape[0]
        self.decoder_bias = nn.Parameter(jnp.zeros((vocab,), jnp.float32))

    def tp_sharded_params(self):
        """The encoder's TP-block-sparse parameters (the MLM head stays
        replicated)."""
        return self.bert.tp_sharded_params()

    def forward(self, ctx, input_ids, token_type_ids=None,
                attention_mask=None, mlm_positions=None):
        """``mlm_positions (B, P)`` — the reference BERT pretraining
        convention (TF-BERT ``masked_lm_positions`` /
        ``max_predictions_per_seq``): the MLM head (transform + GELU +
        LN + tied decoder) runs ONLY on the gathered positions and
        logits come back ``(B, P, V)``.  The head is per-position, so
        gather-then-head equals head-then-gather exactly — but the
        head's matmuls shrink by S/P (~6x at the canonical 15%/seq-128
        recipe), which is most of the MLM head's FLOPs.  May also
        arrive as ``input_ids=(ids, mlm_positions)`` (the fused train
        step's single-input convention, as the seq2seq family does)."""
        if mlm_positions is None and isinstance(input_ids, (tuple, list)):
            input_ids, mlm_positions = input_ids
        seq = self.bert.forward(ctx, input_ids, token_type_ids,
                                attention_mask)
        if mlm_positions is not None:
            seq = jnp.take_along_axis(
                seq, mlm_positions[..., None].astype(jnp.int32), axis=1)
        h = F.gelu(self.transform.forward(ctx, seq))
        h = self.transform_ln.forward(ctx, h)
        emb = ctx.value(self.bert.tok_emb.weight)
        logits = jnp.matmul(h, jnp.swapaxes(emb, 0, 1).astype(h.dtype))
        return logits + ctx.value(self.decoder_bias).astype(logits.dtype)


def bert_base(**kw):
    """BERT-base: 12 layers, hidden 768, 12 heads (110M params)."""
    return BertForMaskedLM(**{**dict(hidden=768, layers=12, heads=12,
                                     intermediate=3072), **kw})


def bert_large(**kw):
    """BERT-large: 24 layers, hidden 1024, 16 heads (340M params)."""
    return BertForMaskedLM(**{**dict(hidden=1024, layers=24, heads=16,
                                     intermediate=4096), **kw})
