from .resnet import (ResNet, BasicBlock, Bottleneck, resnet18, resnet34,
                     resnet50, resnet101)  # noqa: F401
from .bert import (BertForMaskedLM, BertLayer, BertModel, bert_base,
                   bert_large)  # noqa: F401
from .gpt import (  # noqa: F401
    GptBlock, GptModel, generate, gpt2_small, gpt2_medium,
    gpt2_large, gpt2_xl)
from .llama import (  # noqa: F401
    LlamaBlock, LlamaModel, llama_1b, llama_7b, llama_tiny)
from .vit import VitBlock, VitModel, vit_base, vit_small  # noqa: F401
from .hf import (gpt2_from_hf, gpt2_to_hf_state_dict,  # noqa: F401
                 llama_from_hf, llama_to_hf_state_dict,
                 mixtral_from_hf, resnet_from_torch,
                 resnet18_from_torch, resnet50_from_torch)
from .seq2seq import (  # noqa: F401
    Seq2SeqDecoderLayer, TransformerSeq2Seq, seq2seq_generate,
    transformer_seq2seq)
