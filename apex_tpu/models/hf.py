"""HuggingFace checkpoint interop: load ``transformers`` GPT-2 weights
into the :mod:`apex_tpu.models.gpt` family and Llama/Mistral weights
into :mod:`apex_tpu.models.llama`.

The reference repo has no model zoo of its own — its users bring
torch models (BERT/GPT scripts) and apply the fused pieces.  The
equivalent migration story here is loading the checkpoints those users
already have.  ``gpt2_from_hf`` accepts a ``transformers``
``GPT2LMHeadModel`` (or its ``state_dict()``) and returns a
:class:`~apex_tpu.models.gpt.GptModel` with identical logits;
``llama_from_hf`` does the same for ``LlamaForCausalLM``-shaped
checkpoints (Llama, Mistral, and friends — anything with RoPE +
RMSNorm + SwiGLU + optional GQA).

Layout notes (why the permutations below exist):

* HF GPT-2 linears are ``Conv1D``: weight ``(in, out)``, ``y = x W + b``
  — transposed relative to this framework's torch-layout
  ``Linear.weight (out, in)``.
* HF packs QKV type-major: ``c_attn`` columns are ``[Q(E) | K(E) | V(E)]``
  with head-major features inside each.  The attention module here uses
  the reference's INTERLEAVED head-major layout — rows grouped
  ``[q_h | k_h | v_h]`` per head (contrib/multihead_attn/
  attn_funcs._split_interleaved_qkv; reference
  self_multihead_attn_func.py:35-38) — so the loaded tensor is
  ``W.T`` reshaped ``(3, H, D, E)`` → transposed to ``(H, 3, D, E)``.
* GPT-2 architecture facts that already match this family 1:1: pre-LN
  blocks, learned positions, tanh-approximate GELU (``gelu_new`` ==
  ``jax.nn.gelu(approximate=True)``), LayerNorm eps 1e-5, weight-tied
  LM head.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def _to_numpy(t):
    """torch tensor / numpy array -> float32 numpy (no torch import
    required unless the value is a torch tensor).  Torch tensors go
    through .float() first: numpy has no bf16, and bf16 is the default
    distribution dtype of the checkpoints these loaders target."""
    if hasattr(t, "detach"):
        t = t.detach().float().cpu().numpy()
    return np.asarray(t, np.float32)


def _interleave_qkv(w_t, heads, head_dim):
    """HF type-major ``(3E, E)`` (already transposed from Conv1D) ->
    reference interleaved head-major ``(3E, E)``."""
    e = heads * head_dim
    return w_t.reshape(3, heads, head_dim, e).transpose(1, 0, 2, 3) \
              .reshape(3 * e, e)


def _interleave_qkv_bias(b, heads, head_dim):
    return b.reshape(3, heads, head_dim).transpose(1, 0, 2).reshape(-1)


def _put(param, value):
    """Load a checkpoint tensor into a Parameter, shape-checked."""
    value = np.asarray(value, np.float32)
    if tuple(param.data.shape) != value.shape:
        raise ValueError(
            f"shape mismatch loading HF weights: model "
            f"{tuple(param.data.shape)} vs checkpoint {value.shape}")
    param.data = jnp.asarray(value)


def gpt2_from_hf(src, dropout=0.1, attn_dropout=0.0, **model_kw):
    """Build a :class:`GptModel` carrying the weights of an HF GPT-2.

    ``src``: a ``transformers.GPT2LMHeadModel`` (or any module whose
    ``state_dict()`` matches it), or a ready state-dict mapping.  Keys
    may carry the ``transformer.`` prefix or not.  Geometry (vocab,
    hidden, layers, heads, max positions) is inferred from the tensors.
    Dropout probabilities are training-time knobs, not weights — they
    default to GPT-2's 0.1 residual/embedding dropout with attention
    dropout OFF (attention biases already force the materializing
    attention path; see ``attn_bias`` in models/gpt.py).

    Returns the model in ``eval()`` mode; call ``.train()`` to
    fine-tune.
    """
    from .gpt import GptModel

    sd = src.state_dict() if hasattr(src, "state_dict") else dict(src)
    # normalize: strip "transformer.", drop the causal-mask buffers
    # ("attn.bias" is HF's triangle constant, not a parameter); hold the
    # head weight aside for the tie check below
    norm, lm_head = {}, None
    for k, v in sd.items():
        if k.startswith("transformer."):
            k = k[len("transformer."):]
        if k == "lm_head.weight":
            lm_head = _to_numpy(v)
            continue
        if k.endswith(".attn.bias") or k.endswith(".attn.masked_bias"):
            continue
        norm[k] = _to_numpy(v)

    wte = norm["wte.weight"]
    wpe = norm["wpe.weight"]
    if lm_head is not None and (lm_head.shape != wte.shape
                                or not np.array_equal(lm_head, wte)):
        # this family's head is weight-tied (as GPT-2's is); silently
        # dropping a genuinely untied head would change every logit
        raise ValueError(
            "checkpoint's lm_head.weight is not tied to wte.weight — "
            "this GPT family has a weight-tied head and cannot represent "
            "an untied checkpoint")
    vocab, hidden = wte.shape
    layers = 1 + max(int(k.split(".")[1]) for k in norm if k.startswith("h."))
    inter = norm["h.0.mlp.c_fc.weight"].shape[1]
    # head count is not recoverable from the tensors alone: read it from
    # the module's config when given one, else accept an override, else
    # GPT-2's hidden/64 rule (all published GPT-2 sizes use head_dim 64)
    heads = model_kw.pop("heads", None)
    if heads is None:
        heads = getattr(getattr(src, "config", None), "n_head", None)
    if heads is None:
        heads = hidden // 64
    head_dim = hidden // heads

    model = GptModel(vocab_size=vocab, hidden=hidden, layers=layers,
                     heads=heads, intermediate=inter,
                     max_positions=wpe.shape[0], dropout=dropout,
                     attn_dropout=attn_dropout, attn_bias=True,
                     **model_kw)

    _put(model.tok_emb.weight, wte)
    _put(model.pos_emb.weight, wpe)
    _put(model.ln_f.weight, norm["ln_f.weight"])
    _put(model.ln_f.bias, norm["ln_f.bias"])
    for i, blk in enumerate(model.blocks):
        p = f"h.{i}."
        _put(blk.ln1.weight, norm[p + "ln_1.weight"])
        _put(blk.ln1.bias, norm[p + "ln_1.bias"])
        _put(blk.ln2.weight, norm[p + "ln_2.weight"])
        _put(blk.ln2.bias, norm[p + "ln_2.bias"])
        _put(blk.attn.in_proj_weight,
            _interleave_qkv(norm[p + "attn.c_attn.weight"].T, heads,
                            head_dim))
        _put(blk.attn.in_proj_bias,
            _interleave_qkv_bias(norm[p + "attn.c_attn.bias"], heads,
                                 head_dim))
        _put(blk.attn.out_proj_weight, norm[p + "attn.c_proj.weight"].T)
        _put(blk.attn.out_proj_bias, norm[p + "attn.c_proj.bias"])
        _put(blk.fc1.weight, norm[p + "mlp.c_fc.weight"].T)
        _put(blk.fc1.bias, norm[p + "mlp.c_fc.bias"])
        _put(blk.fc2.weight, norm[p + "mlp.c_proj.weight"].T)
        _put(blk.fc2.bias, norm[p + "mlp.c_proj.bias"])
    model.eval()
    return model


def llama_from_hf(src, **model_kw):
    """Build a :class:`~apex_tpu.models.llama.LlamaModel` carrying the
    weights of an HF ``LlamaForCausalLM`` / ``MistralForCausalLM``.

    ``src``: the transformers module (geometry read from ``.config``) or
    a bare state-dict — head counts are not recoverable from the tensors
    then, so pass ``heads=`` (and ``kv_heads=`` if grouped) along with
    any of ``rope_theta``/``eps``/``max_positions`` that differ from the
    Llama defaults.  All linears are plain ``nn.Linear`` (out, in) on
    both sides — no transposition, unlike GPT-2's Conv1D.  A tied
    checkpoint (``tie_word_embeddings``, no ``lm_head.weight`` in the
    dict) loads the embedding into the (untied here) head, which is
    exactly the tied forward.
    """
    norm, emb, geom, dflt = _llama_prelude(src, model_kw)
    inter = norm["layers.0.mlp.gate_proj.weight"].shape[0]

    from .llama import LlamaModel
    model = LlamaModel(
        intermediate=inter,
        max_positions=dflt("max_positions", "max_position_embeddings",
                           2048),
        rope_theta=dflt("rope_theta", "rope_theta", 10000.0),
        eps=dflt("eps", "rms_norm_eps", 1e-6),
        sliding_window=dflt("sliding_window", "sliding_window", None),
        **geom, **model_kw)

    _load_llama_trunk(model, norm, emb)
    for i, blk in enumerate(model.blocks):
        p = f"layers.{i}."
        for name in ("gate_proj", "up_proj", "down_proj"):
            _put(getattr(blk, name).weight,
                norm[p + "mlp." + name + ".weight"])
    model.eval()
    return model


def _llama_prelude(src, model_kw):
    """Shared loader front half for Llama-family checkpoints: normalize
    keys (strip ``model.``, drop rotary buffers), recover geometry with
    the heads/kv_heads divisibility diagnostics, and build the
    config-with-override resolver.  Returns ``(norm, emb, geometry
    kwargs, dflt)``; mutates ``model_kw`` (pops the override keys)."""
    sd = src.state_dict() if hasattr(src, "state_dict") else dict(src)
    norm = {}
    for k, v in sd.items():
        if k.startswith("model."):
            k = k[len("model."):]
        if k.endswith("rotary_emb.inv_freq"):
            continue
        norm[k] = _to_numpy(v)

    emb = norm["embed_tokens.weight"]
    vocab, hidden = emb.shape
    layers = 1 + max(int(k.split(".")[1]) for k in norm
                     if k.startswith("layers."))

    cfg = getattr(src, "config", None)
    heads = model_kw.pop("heads", None) \
        or getattr(cfg, "num_attention_heads", None)
    if heads is None:
        raise ValueError(
            "head count is not recoverable from a bare state dict — "
            "pass heads= (and kv_heads= for GQA checkpoints)")
    # head_dim IS recoverable from the tensors: q_proj has heads*head_dim
    # rows (decoupled from hidden/heads in e.g. Mistral-Nemo)
    q_rows = norm["layers.0.self_attn.q_proj.weight"].shape[0]
    if q_rows % heads:
        raise ValueError(
            f"q_proj rows {q_rows} are not divisible by heads={heads} — "
            f"wrong heads=?")
    head_dim = q_rows // heads
    kv_rows = norm["layers.0.self_attn.k_proj.weight"].shape[0]
    kv_heads = model_kw.pop("kv_heads", None) or kv_rows // head_dim
    if kv_heads * head_dim != kv_rows:
        raise ValueError(
            f"k_proj rows {kv_rows} are not kv_heads*head_dim with "
            f"heads={heads} (head_dim {head_dim}) — wrong heads=?")

    def dflt(key, attr, fallback):
        v = model_kw.pop(key, None)
        if v is None:
            v = getattr(cfg, attr, None)
        return fallback if v is None else v

    geom = dict(vocab_size=vocab, hidden=hidden, layers=layers,
                heads=heads, kv_heads=kv_heads, head_dim=head_dim)
    return norm, emb, geom, dflt


def _load_llama_trunk(model, norm, emb):
    """Embedding, final norm, (possibly tied) head, and every block's
    norms + attention projections — the layout both Llama-family
    loaders share."""
    _put(model.tok_emb.weight, emb)
    _put(model.norm.weight, norm["norm.weight"])
    _put(model.lm_head.weight, norm.get("lm_head.weight", emb))
    for i, blk in enumerate(model.blocks):
        p = f"layers.{i}."
        _put(blk.ln1.weight, norm[p + "input_layernorm.weight"])
        _put(blk.ln2.weight, norm[p + "post_attention_layernorm.weight"])
        for name in ("q_proj", "k_proj", "v_proj", "o_proj"):
            _put(getattr(blk, name).weight,
                norm[p + "self_attn." + name + ".weight"])


# ---------------------------------------------------------------------------
# export: the inverse direction (train here, serve anywhere)
# ---------------------------------------------------------------------------

def _deinterleave_qkv(w, heads, head_dim):
    """Reference interleaved head-major ``(3E, E)`` -> HF type-major
    ``(3E, E)`` (inverse of :func:`_interleave_qkv`)."""
    e = heads * head_dim
    return w.reshape(heads, 3, head_dim, e).transpose(1, 0, 2, 3) \
            .reshape(3 * e, e)


def _deinterleave_qkv_bias(b, heads, head_dim):
    return b.reshape(heads, 3, head_dim).transpose(1, 0, 2).reshape(-1)


def gpt2_to_hf_state_dict(model):
    """Export a :class:`GptModel` as an HF ``GPT2LMHeadModel`` state
    dict (numpy float32 values, ``transformer.``-prefixed keys plus the
    tied ``lm_head.weight``).  Inverse of :func:`gpt2_from_hf` — load
    with ``strict=False`` (HF's causal-mask buffers are constants the
    dict omits) and the torch forward reproduces this model's logits
    (tests/test_hf_interop.py round-trip).
    """
    if getattr(model, "moe_axis", None) is not None:
        raise ValueError(
            "gpt2_to_hf_state_dict: MoE models have no GPT2LMHeadModel "
            "layout (export the dense family, or the experts separately)")
    heads = model.blocks[0].attn.num_heads
    head_dim = model.blocks[0].attn.head_dim
    attn0 = model.blocks[0].attn
    if attn0.in_proj_bias is None or attn0.out_proj_bias is None:
        # a model-wide constructor property: check once, before any work
        raise ValueError(
            "gpt2_to_hf_state_dict requires attention biases (HF "
            "GPT-2's Conv1D projections always carry them) — build "
            "the model with attn_bias=True, as gpt2_from_hf does")
    sd = {}

    def np32(p):
        return _to_numpy(p.data)

    # a pad_vocab_multiple model stores a lane-padded table; checkpoints
    # carry the logical vocab (pad rows are framework-internal)
    sd["transformer.wte.weight"] = np32(
        model.tok_emb.weight)[:model.vocab_size]
    sd["transformer.wpe.weight"] = np32(model.pos_emb.weight)
    sd["transformer.ln_f.weight"] = np32(model.ln_f.weight)
    sd["transformer.ln_f.bias"] = np32(model.ln_f.bias)
    sd["lm_head.weight"] = sd["transformer.wte.weight"]
    for i, blk in enumerate(model.blocks):
        p = f"transformer.h.{i}."
        sd[p + "ln_1.weight"] = np32(blk.ln1.weight)
        sd[p + "ln_1.bias"] = np32(blk.ln1.bias)
        sd[p + "ln_2.weight"] = np32(blk.ln2.weight)
        sd[p + "ln_2.bias"] = np32(blk.ln2.bias)
        sd[p + "attn.c_attn.weight"] = _deinterleave_qkv(
            np32(blk.attn.in_proj_weight), heads, head_dim).T
        sd[p + "attn.c_attn.bias"] = _deinterleave_qkv_bias(
            np32(blk.attn.in_proj_bias), heads, head_dim)
        sd[p + "attn.c_proj.weight"] = np32(blk.attn.out_proj_weight).T
        sd[p + "attn.c_proj.bias"] = np32(blk.attn.out_proj_bias)
        sd[p + "mlp.c_fc.weight"] = np32(blk.fc1.weight).T
        sd[p + "mlp.c_fc.bias"] = np32(blk.fc1.bias)
        sd[p + "mlp.c_proj.weight"] = np32(blk.fc2.weight).T
        sd[p + "mlp.c_proj.bias"] = np32(blk.fc2.bias)
    return sd


def llama_to_hf_state_dict(model):
    """Export a :class:`LlamaModel` as an HF ``LlamaForCausalLM`` state
    dict (numpy float32; plain ``(out, in)`` linears both sides, no
    permutations).  Inverse of :func:`llama_from_hf`; round-trip logit
    parity in tests/test_hf_interop.py.  MoE models (`moe_axis`) have
    no HF Llama equivalent and are refused.
    """
    if getattr(model, "moe_axis", None) is not None:
        raise ValueError(
            "llama_to_hf_state_dict: MoE models have no LlamaForCausalLM "
            "layout (export the dense family, or the experts separately)")
    sd = {}

    def np32(p):
        return _to_numpy(p.data)

    sd["model.embed_tokens.weight"] = np32(model.tok_emb.weight)
    sd["model.norm.weight"] = np32(model.norm.weight)
    sd["lm_head.weight"] = np32(model.lm_head.weight)
    for i, blk in enumerate(model.blocks):
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = np32(blk.ln1.weight)
        sd[p + "post_attention_layernorm.weight"] = np32(blk.ln2.weight)
        for name in ("q_proj", "k_proj", "v_proj", "o_proj"):
            sd[p + "self_attn." + name + ".weight"] = \
                np32(getattr(blk, name).weight)
        for name in ("gate_proj", "up_proj", "down_proj"):
            sd[p + "mlp." + name + ".weight"] = \
                np32(getattr(blk, name).weight)
    return sd


def mixtral_from_hf(src, moe_axis="data", capacity_factor=8.0,
                    aux_weight=0.0, **model_kw):
    """Build a Mixtral-shape :class:`LlamaModel` (every block MoE)
    carrying the weights of an HF ``MixtralForCausalLM``.

    Gating semantics match exactly: softmax over all experts, top-2,
    normalized over the selected pair (transformers
    modeling_mixtral.py:111-113 == ``switch_moe(top_k=2)``).  The ONE
    semantic divergence is capacity: Mixtral dispatches densely (every
    routed token computes), while this framework's Switch/GShard
    machinery drops tokens beyond ``ceil(T_local/E * capacity_factor)``
    per expert.  The default factor 8.0 makes drops rare; raise it
    (2*E guarantees none, at dispatch-buffer memory cost) for exact
    parity, lower it to trade fidelity for memory.

    ``aux_weight`` defaults to 0 (inference/fine-tune from a trained
    checkpoint needs no balance pressure; set >0 to re-enable the
    Switch aux loss for continued pretraining).  ``moe_top_k`` can be
    overridden by keyword (bare state dicts carry no config; the
    default is Mixtral's 2).  The model's forward
    must run inside ``shard_map`` over ``moe_axis`` with one expert per
    device (``moe_num_experts`` = the axis size = the checkpoint's
    expert count).
    """
    from .llama import LlamaModel

    norm, emb, geom, dflt = _llama_prelude(src, model_kw)
    n_exp = 1 + max(
        int(k.split(".")[4]) for k in norm
        if ".block_sparse_moe.experts." in k)
    inter = norm["layers.0.block_sparse_moe.experts.0.w1.weight"].shape[0]
    top_k = dflt("moe_top_k", "num_experts_per_tok", 2)

    model = LlamaModel(
        intermediate=inter,
        max_positions=dflt("max_positions", "max_position_embeddings",
                           2048),
        rope_theta=dflt("rope_theta", "rope_theta", 10000.0),
        eps=dflt("eps", "rms_norm_eps", 1e-6),
        sliding_window=dflt("sliding_window", "sliding_window", None),
        moe_axis=moe_axis, moe_num_experts=n_exp, moe_every=1,
        moe_top_k=top_k, moe_capacity_factor=capacity_factor,
        moe_aux_weight=aux_weight, **geom, **model_kw)

    _load_llama_trunk(model, norm, emb)
    for i, blk in enumerate(model.blocks):
        p = f"layers.{i}."
        _put(blk.router.weight, norm[p + "block_sparse_moe.gate.weight"])
        ep = p + "block_sparse_moe.experts."
        # HF per-expert w1=gate, w3=up, w2=down -> stacked wg/wu/wd
        _put(blk.wg, np.stack(
            [norm[f"{ep}{e}.w1.weight"] for e in range(n_exp)]))
        _put(blk.wu, np.stack(
            [norm[f"{ep}{e}.w3.weight"] for e in range(n_exp)]))
        _put(blk.wd, np.stack(
            [norm[f"{ep}{e}.w2.weight"] for e in range(n_exp)]))
    model.eval()
    return model


def resnet_from_torch(src, **model_kw):
    """Build a :class:`~apex_tpu.models.resnet.ResNet` carrying the
    weights of a torch/torchvision ResNet (18/34/50/101 and friends).

    The north-star clause asks for the reference's examples to consume
    existing torch checkpoints (the imagenet example's ``--resume``,
    reference examples/imagenet/main_amp.py:180-195); torch-xla is not
    available here, so the interop story is checkpoint-level — mirror of
    :func:`gpt2_from_hf` for the vision path.  ``src``: a torch module
    (``torchvision.models.resnet50()``), a ``state_dict()`` mapping, or
    a ``torch.load`` result (``state_dict``/``model`` wrapper keys and
    DDP ``module.`` prefixes are unwrapped).  Geometry — block type
    (Basic vs Bottleneck), stage depths, class count, CIFAR-vs-ImageNet
    stem — is inferred from the tensors; this framework's module tree
    uses torchvision's exact attribute names, so the load is
    name-matched with shape checks, and missing/unexpected keys raise.

    Returns the model in ``eval()`` mode with BN running stats loaded
    (``num_batches_tracked`` included when present — absent in very old
    torch checkpoints, tolerated).
    """
    from .resnet import BasicBlock, Bottleneck, ResNet

    sd = src.state_dict() if hasattr(src, "state_dict") else dict(src)
    # torch.load checkpoint wrappers (examples/imagenet resume format)
    for wrap in ("state_dict", "model"):
        if wrap in sd and not hasattr(sd[wrap], "shape"):
            sd = dict(sd[wrap])
    sd = {(k[len("module."):] if k.startswith("module.") else k): v
          for k, v in sd.items()}

    for needed in ("conv1.weight", "fc.weight", "layer1.0.conv1.weight"):
        if needed not in sd:
            raise ValueError(
                f"state dict does not look like a torchvision ResNet: "
                f"missing '{needed}'")
    depths = [1 + max(int(k.split(".")[1]) for k in sd
                      if k.startswith(f"layer{i}."))
              for i in range(1, 5)]
    block = Bottleneck if "layer1.0.conv3.weight" in sd else BasicBlock
    num_classes = sd["fc.weight"].shape[0]
    small_input = sd["conv1.weight"].shape[-1] == 3
    model = ResNet(block, depths, num_classes=num_classes,
                   small_input=small_input, **model_kw)

    used = set()
    for name, p in model.named_parameters():
        if name not in sd:
            raise ValueError(f"checkpoint is missing parameter '{name}'")
        _put(p, _to_numpy(sd[name]))
        used.add(name)
    for name, b in model.named_buffers():
        if name not in sd:
            if name.endswith("num_batches_tracked"):
                continue    # pre-0.4-era checkpoints lack the counter
            raise ValueError(f"checkpoint is missing buffer '{name}'")
        used.add(name)
        if name.endswith("num_batches_tracked"):
            b.data = jnp.asarray(np.asarray(sd[name]).item(), jnp.int32)
            continue
        v = _to_numpy(sd[name])
        if tuple(b.data.shape) != v.shape:
            raise ValueError(
                f"shape mismatch loading torch weights: buffer {name} "
                f"{tuple(b.data.shape)} vs checkpoint {v.shape}")
        b.data = jnp.asarray(v)
    unexpected = sorted(set(sd) - used)
    if unexpected:
        raise ValueError(
            f"checkpoint carries keys this ResNet has no slot for: "
            f"{unexpected[:5]}{'...' if len(unexpected) > 5 else ''}")
    model.eval()
    return model


# the flagship alias the migration guide points at; the generic loader
# already infers the depth/block geometry, so all named variants share it
resnet50_from_torch = resnet_from_torch
resnet18_from_torch = resnet_from_torch
