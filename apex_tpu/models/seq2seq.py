"""Transformer encoder-decoder (seq2seq) family — completes the
transformer trio (BERT encoder, GPT decoder, this cross-attending pair)
and is the model-level consumer of ``EncdecMultiheadAttn`` (reference
apex/contrib/multihead_attn/encdec_multihead_attn.py, which the reference
only ever shipped as a bare module).

The encoder reuses ``BertLayer`` (post-LN, the BERT convention — each
layer ends normalized, so no extra final LN); the decoder is pre-LN:
causal self-attention → cross-attention over the encoder memory → GELU
FFN, with a final LN before the head.  Layout: public API is batch-first
``(B, S)`` ids; internals run ``(S, B, E)``.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..normalization import FusedLayerNorm
from ..contrib.multihead_attn import EncdecMultiheadAttn, SelfMultiheadAttn
from .bert import BertLayer


class Seq2SeqDecoderLayer(nn.Module):
    """LN → causal self-MHA → residual, LN → cross-MHA(memory) →
    residual, LN → GELU FFN → residual."""

    def __init__(self, hidden, heads, intermediate, dropout=0.1,
                 attn_dropout=0.1, tp_axis=None):
        super().__init__()
        self.ln1 = FusedLayerNorm(hidden)
        self.self_attn = SelfMultiheadAttn(
            hidden, heads, dropout=attn_dropout, impl="fast", causal=True,
            tensor_parallel_axis=tp_axis)
        self.ln2 = FusedLayerNorm(hidden)
        self.cross_attn = EncdecMultiheadAttn(
            hidden, heads, dropout=attn_dropout, impl="fast",
            tensor_parallel_axis=tp_axis)
        self.ln3 = FusedLayerNorm(hidden)
        self.fc1 = nn.Linear(hidden, intermediate)
        self.fc2 = nn.Linear(intermediate, hidden)
        self.dropout = nn.Dropout(dropout)
        self.tp_axis = tp_axis

    def forward(self, ctx, x, memory, memory_kpm=None):
        h, _ = self.self_attn.forward(ctx, self.ln1.forward(ctx, x))
        x = x + self.dropout.forward(ctx, h)
        h, _ = self.cross_attn.forward(ctx, self.ln2.forward(ctx, x),
                                       memory, key_padding_mask=memory_kpm)
        x = x + self.dropout.forward(ctx, h)
        if self.tp_axis is not None:
            from ..parallel.tensor_parallel import tp_ffn
            h = tp_ffn(self.ln3.forward(ctx, x),
                       ctx.value(self.fc1.weight), ctx.value(self.fc1.bias),
                       ctx.value(self.fc2.weight), ctx.value(self.fc2.bias),
                       self.tp_axis, activation=F.gelu)
        else:
            h = F.gelu(self.fc1.forward(ctx, self.ln3.forward(ctx, x)))
            h = self.fc2.forward(ctx, h)
        return x + self.dropout.forward(ctx, h)

    def tp_sharded_params(self):
        """Self + cross attention head blocks and the column/row MLP
        entries (the contract make_train_step(tp_axis=...) assembles)."""
        return (self.self_attn.tp_sharded_params()
                + self.cross_attn.tp_sharded_params()
                + [self.fc1.weight, self.fc1.bias, self.fc2.weight])


class TransformerSeq2Seq(nn.Module):
    """Shared-vocab encoder-decoder with a weight-tied output head.

    ``forward(src_ids (B, S_src), tgt_ids (B, S_tgt),
    src_attention_mask=None) -> logits (B, S_tgt, V)``.
    ``src_attention_mask`` follows the BERT convention (1 = real token,
    0 = padding) and masks encoder self-attention AND decoder
    cross-attention.
    """

    def __init__(self, vocab_size=32000, hidden=512, enc_layers=6,
                 dec_layers=6, heads=8, intermediate=None,
                 max_positions=512, dropout=0.1, attn_dropout=0.1,
                 tp_axis=None, output_hidden=False):
        super().__init__()
        # output_hidden: training-time option — forward returns
        # (decoder hidden, tied table) instead of logits so a
        # chunked/fused loss can own the vocab chain (the GptModel
        # convention)
        self.output_hidden = output_hidden
        intermediate = intermediate or 4 * hidden
        self.hidden = hidden
        self.max_positions = max_positions
        # tp_axis: Megatron tensor parallelism across BOTH stacks (see
        # models/gpt.py — same full-weight/trace-time-slice design)
        self.tp_axis = tp_axis
        # attention dropout composes with tp_axis: each head-shard
        # folds its axis index into the in-kernel mask seed (decorrelated
        # per-rank streams, attn_funcs._dropout_seed)
        self.tok_emb = nn.Embedding(vocab_size, hidden)
        self.pos_emb = nn.Embedding(max_positions, hidden)
        for emb in (self.tok_emb, self.pos_emb):
            emb.weight.data = emb.weight.data * 0.02
        self.drop = nn.Dropout(dropout)
        self.enc_layers = nn.ModuleList([
            BertLayer(hidden, heads, intermediate, dropout, attn_dropout,
                      tp_axis=tp_axis)
            for _ in range(enc_layers)])
        self.dec_layers = nn.ModuleList([
            Seq2SeqDecoderLayer(hidden, heads, intermediate, dropout,
                                attn_dropout, tp_axis=tp_axis)
            for _ in range(dec_layers)])
        self.dec_ln = FusedLayerNorm(hidden)

    def tp_sharded_params(self):
        """Both stacks' TP-block-sparse parameters."""
        return [p for ly in list(self.enc_layers) + list(self.dec_layers)
                for p in ly.tp_sharded_params()]

    def _embed(self, ctx, ids):
        s = ids.shape[1]
        if s > self.max_positions:
            raise ValueError(
                f"sequence length {s} exceeds max_positions "
                f"{self.max_positions}")
        pos = jnp.arange(s, dtype=jnp.int32)[None, :]
        x = self.tok_emb.forward(ctx, ids) + self.pos_emb.forward(ctx, pos)
        x = self.drop.forward(ctx, x)
        return jnp.swapaxes(x, 0, 1)            # (S, B, E)

    def forward(self, ctx, src_ids, tgt_ids=None, src_attention_mask=None):
        # packed form: forward(ctx, (src_ids, tgt_ids[, mask])) — lets the
        # fused step feed both streams as batch[0] (training/step.py casts
        # and microbatches pytree inputs)
        if tgt_ids is None:
            if not isinstance(src_ids, (tuple, list)) or \
                    len(src_ids) not in (2, 3):
                raise TypeError(
                    "seq2seq forward needs (src_ids, tgt_ids[, mask]) — "
                    "either as positional args or packed in one tuple")
            src_ids, tgt_ids, *rest = src_ids
            if rest:
                src_attention_mask = rest[0]
        kpm = None
        if src_attention_mask is not None:
            kpm = (src_attention_mask == 0)
        mem = self._embed(ctx, src_ids)
        for layer in self.enc_layers:
            mem = layer.forward(ctx, mem, key_padding_mask=kpm)
        # BertLayer is post-LN: the last layer's output is already
        # normalized, no extra encoder LN needed

        x = self._embed(ctx, tgt_ids)
        for layer in self.dec_layers:
            x = layer.forward(ctx, x, mem, memory_kpm=kpm)
        x = self.dec_ln.forward(ctx, x)
        x = jnp.swapaxes(x, 0, 1)               # (B, S_tgt, E)
        emb = ctx.value(self.tok_emb.weight)
        if self.output_hidden:
            return x, emb
        return jnp.matmul(x, jnp.swapaxes(emb, 0, 1).astype(x.dtype))


def transformer_seq2seq(**kw):
    """Base geometry: 6+6 layers, hidden 512, 8 heads (transformer-base
    shape)."""
    return TransformerSeq2Seq(**{**dict(hidden=512, enc_layers=6,
                                        dec_layers=6, heads=8), **kw})


def seq2seq_generate(model: TransformerSeq2Seq, src_ids, max_new_tokens,
                     bos_id=0, src_attention_mask=None, temperature=0.0,
                     top_k=None, key=None, mesh=None):
    """Decoding: encode the source once, then extend the target one token
    per step.  The decoder runs over a fixed-size padded target buffer
    every step (causal attention makes positions > t inert), so the whole
    loop is ONE compiled ``lax.scan`` — simple and compile-once; a
    decoder KV cache (as in ``gpt.generate``) is the next optimization if
    decode throughput ever matters here.

    ``temperature=0`` (default) is greedy; ``top_k`` restricts sampling —
    the same sampling surface as ``gpt.generate``.  ``src_ids (B, S_src)``
    → ``(B, max_new_tokens)`` generated ids (BOS not included).  Compiled
    programs are cached per model + shapes + sampling config.

    A model built with ``tp_axis`` needs ``mesh`` (the gpt.generate TP
    convention): the whole encode+decode program runs inside shard_map
    with everything replicated except the trace-time head/FFN block
    slices the decoder layers already perform — logits come out
    psum-replicated, so the emitted tokens match the single-shard
    decode of the same weights.
    """
    if model.tp_axis is not None and mesh is None:
        raise ValueError(
            f"model was built with tp_axis='{model.tp_axis}': decode "
            f"runs inside shard_map — pass seq2seq_generate(..., "
            f"mesh=<Mesh with '{model.tp_axis}'>)")
    if mesh is not None and model.tp_axis is None:
        raise ValueError(
            "mesh was passed but the model has no tp_axis — single-"
            "shard decode needs no mesh")
    if mesh is not None and model.tp_axis not in mesh.axis_names:
        raise ValueError(
            f"mesh axes {mesh.axis_names} do not include the model's "
            f"tp_axis '{model.tp_axis}'")
    import jax

    from ..nn.modules import Ctx

    b, _ = src_ids.shape
    if max_new_tokens + 1 > model.max_positions:
        raise ValueError(
            f"max_new_tokens {max_new_tokens} exceeds max_positions "
            f"{model.max_positions} - 1")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if key is None:
        key = jax.random.PRNGKey(0)
    vocab = model.tok_emb.weight.shape[0]
    if top_k is not None and not 1 <= top_k <= vocab:
        raise ValueError(
            f"top_k must be in [1, vocab={vocab}], got {top_k}")

    def sample(logits, k):
        if temperature == 0.0:
            return logits.argmax(axis=-1)
        logits = logits / temperature
        if top_k is not None:
            kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits < kth, -1e30, logits)
        return jax.random.categorical(k, logits, axis=-1)

    params = [q for q in model.parameters()]
    buffers = list(model.buffers())
    vals = [q.data for q in params] + [bu.data for bu in buffers]

    def run(vals, src_ids, mask, key):
        env = {id(o): v for o, v in zip(params + buffers, vals)}
        ctx = Ctx(env=env, stats_out={}, training=False)
        kpm = None if mask is None else (mask == 0)

        mem = model._embed(ctx, src_ids)
        for layer in model.enc_layers:
            mem = layer.forward(ctx, mem, key_padding_mask=kpm)

        def decode(tgt_buf):
            x = model._embed(ctx, tgt_buf)
            for layer in model.dec_layers:
                x = layer.forward(ctx, x, mem, memory_kpm=kpm)
            x = model.dec_ln.forward(ctx, x)
            x = jnp.swapaxes(x, 0, 1)
            emb = ctx.value(model.tok_emb.weight)
            return jnp.matmul(x, jnp.swapaxes(emb, 0, 1).astype(x.dtype))

        buf0 = jnp.full((b, max_new_tokens + 1), bos_id, src_ids.dtype)

        def step(carry, t):
            buf, k = carry
            logits = decode(buf)
            # causal decoder: position t's logits depend only on <= t
            row = jax.lax.dynamic_index_in_dim(logits, t, axis=1,
                                               keepdims=False)
            k, sub = jax.random.split(k)
            tok_t = sample(row, sub).astype(buf.dtype)
            buf = jax.lax.dynamic_update_slice(
                buf, tok_t[:, None], (0, t + 1))
            return (buf, k), tok_t

        (_, _), toks = jax.lax.scan(step, (buf0, key),
                                    jnp.arange(max_new_tokens))
        return jnp.swapaxes(toks, 0, 1)

    # per-model compiled-run cache (see utils/jit_cache.py for the
    # parameter-identity/LRU invariants — LoRA apply/merge must miss)
    from ..utils.jit_cache import compiled_run_cache

    def build():
        if mesh is not None:
            from jax.sharding import PartitionSpec as _P
            from ..compat import shard_map as _shard_map
            return jax.jit(_shard_map(
                run, mesh=mesh, in_specs=(_P(), _P(), _P(), _P()),
                out_specs=_P(), check_vma=False))
        return jax.jit(run)

    fn = compiled_run_cache(
        model, "_s2s_gen_cache",
        (b, src_ids.shape[1], max_new_tokens, int(bos_id),
         src_attention_mask is not None, float(temperature), top_k,
         mesh),
        params + buffers, build)
    return fn(vals, src_ids, src_attention_mask, key)
