"""ResNet family (torchvision-compatible architecture), the flagship model
for the ImageNet benchmark config (reference: examples/imagenet/main_amp.py
uses torchvision resnet50; we are standalone so the architecture lives here).

NCHW layout to match the reference's data pipeline; XLA lays out for TPU
internally.
"""
from __future__ import annotations

from .. import nn


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, in_planes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(in_planes, planes, 3, stride=stride,
                               padding=1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2d(planes, planes, 3, padding=1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.downsample = downsample

    def forward(self, ctx, x):
        identity = x
        out = self.bn1.forward(ctx, self.conv1.forward(ctx, x))
        out = self.relu.forward(ctx, out)
        out = self.bn2.forward(ctx, self.conv2.forward(ctx, out))
        if self.downsample is not None:
            identity = self.downsample.forward(ctx, x)
        return self.relu.forward(ctx, out + identity)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, in_planes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(in_planes, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride=stride, padding=1,
                               bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, ctx, x):
        identity = x
        out = self.relu.forward(ctx, self.bn1.forward(
            ctx, self.conv1.forward(ctx, x)))
        out = self.relu.forward(ctx, self.bn2.forward(
            ctx, self.conv2.forward(ctx, out)))
        out = self.bn3.forward(ctx, self.conv3.forward(ctx, out))
        if self.downsample is not None:
            identity = self.downsample.forward(ctx, x)
        return self.relu.forward(ctx, out + identity)


class ResNet(nn.Module):
    def __init__(self, block, layers, num_classes=1000, small_input=False):
        """``small_input`` uses the CIFAR stem (3x3 conv, no maxpool)."""
        super().__init__()
        self.in_planes = 64
        if small_input:
            self.conv1 = nn.Conv2d(3, 64, 3, stride=1, padding=1, bias=False)
            self.maxpool = nn.Identity()
        else:
            self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False)
            self.maxpool = nn.MaxPool2d(3, stride=2, padding=1)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU()
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        self.avgpool = nn.AdaptiveAvgPool2d((1, 1))
        self.flatten = nn.Flatten()
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.in_planes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2d(self.in_planes, planes * block.expansion, 1,
                          stride=stride, bias=False),
                nn.BatchNorm2d(planes * block.expansion))
        layers = [block(self.in_planes, planes, stride, downsample)]
        self.in_planes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.in_planes, planes))
        return nn.Sequential(*layers)

    def forward(self, ctx, x):
        x = self.relu.forward(ctx, self.bn1.forward(
            ctx, self.conv1.forward(ctx, x)))
        x = self.maxpool.forward(ctx, x)
        x = self.layer1.forward(ctx, x)
        x = self.layer2.forward(ctx, x)
        x = self.layer3.forward(ctx, x)
        x = self.layer4.forward(ctx, x)
        x = self.avgpool.forward(ctx, x)
        x = self.flatten.forward(ctx, x)
        return self.fc.forward(ctx, x)


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(Bottleneck, [3, 4, 6, 3], num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(Bottleneck, [3, 4, 23, 3], num_classes, **kw)
