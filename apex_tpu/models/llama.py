"""Llama-style decoder family: RoPE + RMSNorm + SwiGLU + grouped-query
attention — the modern long-context LM shape, on the same fused
substrate as the GPT family (Pallas flash attention, FusedRMSNorm,
fused step, remat, KV-cache decode).

The reference repo carries no language models (SURVEY.md §2); the GPT
family covers the GPT-2-era architecture, this one covers the
Llama/Mistral era: no biases anywhere, rotary position embeddings
instead of learned positions (so ``max_positions`` only sizes caches,
not a table), RMSNorm pre-norm, gated SiLU FFN, optional
``kv_heads < heads`` (GQA — K/V heads shared across query-head groups,
the standard KV-cache shrink), and an UNTIED LM head (Llama convention;
contrast GptModel's tied head).

Layout: public API is batch-first ``(B, S)`` ids; attention runs the
flash kernel directly in its native ``(B, H, S, D)`` layout (the GPT
family's ``(S, B, E)`` interior exists for reference-parity with the
torch MHA module; nothing here has a reference analogue, so the model
keeps the kernel's own layout throughout).

``llama_from_hf`` (models/hf.py) loads ``transformers`` Llama/Mistral
checkpoints with logit parity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..normalization import FusedRMSNorm
from ..contrib.multihead_attn.attn_funcs import flash_attention


def rope_tables(positions, head_dim, theta=10000.0):
    """cos/sin tables for rotary embeddings, HF half-rotation convention:
    ``positions (...,)`` int32 → ``(cos, sin)`` of shape
    ``(..., head_dim)`` fp32, frequencies duplicated over both halves."""
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32)
                                * (2.0 / head_dim)))
    ang = positions.astype(jnp.float32)[..., None] * inv_freq  # (..., half)
    ang = jnp.concatenate([ang, ang], axis=-1)
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """Rotate ``x (..., S, D)`` by tables ``(S, D)`` (broadcast over
    leading dims).  rotate_half: the second half holds the negated
    quadrature component (HF modeling_llama.rotate_half)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x.astype(jnp.float32) * cos
            + rotated.astype(jnp.float32) * sin).astype(x.dtype)


class LlamaBlock(nn.Module):
    """Pre-norm decoder block: RMSNorm → RoPE-GQA causal attention →
    residual, RMSNorm → SwiGLU FFN → residual.  No biases (Llama
    convention)."""

    def __init__(self, hidden, heads, kv_heads, intermediate,
                 rope_theta=10000.0, eps=1e-6, head_dim=None,
                 tp_axis=None, sp_axis=None, sliding_window=None,
                 _dense_ffn=True):
        super().__init__()
        # sliding_window: Mistral-style banded causal attention —
        # position t sees keys in (t - window, t].  Exact EVERYWHERE:
        # the cached decode paths band-mask their scores, and the
        # full-sequence forward/prefill ride the banded flash kernel
        # (out-of-band blocks skipped, O(S·window) compute)
        self.sliding_window = sliding_window
        # sp_axis: ring sequence parallelism — the sequence dim is
        # sharded over this mesh axis and attention runs the ring
        # (parallel/ring_attention.py); the MODEL supplies global-offset
        # RoPE tables so each shard rotates by its absolute positions
        self.sp_axis = sp_axis
        # tp_axis: Megatron tensor parallelism — forward must run inside
        # shard_map over a mesh with this axis.  Q heads AND KV heads
        # shard over it (both row-major head blocks in the projection
        # weights), o_proj/down_proj are row-parallel; weights stay FULL
        # (replicated) and each device slices its block at trace time,
        # exactly the GPT/BERT families' convention (models/gpt.py).
        self.tp_axis = tp_axis
        if head_dim is None:
            # some checkpoints (Mistral-Nemo etc.) decouple head_dim from
            # hidden/heads; the default is the usual coupling
            if hidden % heads:
                raise ValueError(
                    f"hidden {hidden} not divisible by {heads} — pass "
                    f"head_dim explicitly")
            head_dim = hidden // heads
        if heads % kv_heads:
            raise ValueError(
                f"heads {heads} not divisible by kv_heads {kv_heads} "
                f"(GQA shares each K/V head over an equal group)")
        self.heads = heads
        self.kv_heads = kv_heads
        self.head_dim = head_dim
        self.rope_theta = rope_theta
        self.ln1 = FusedRMSNorm(hidden, eps=eps)
        self.q_proj = nn.Linear(hidden, heads * head_dim, bias=False)
        self.k_proj = nn.Linear(hidden, kv_heads * head_dim, bias=False)
        self.v_proj = nn.Linear(hidden, kv_heads * head_dim, bias=False)
        self.o_proj = nn.Linear(heads * head_dim, hidden, bias=False)
        self.ln2 = FusedRMSNorm(hidden, eps=eps)
        if _dense_ffn:
            self.gate_proj = nn.Linear(hidden, intermediate, bias=False)
            self.up_proj = nn.Linear(hidden, intermediate, bias=False)
            self.down_proj = nn.Linear(intermediate, hidden, bias=False)
        else:
            # MoeLlamaBlock supplies its own routed FFN: skip drawing
            # (and then discarding) three dense matrices that can be
            # hundreds of MB at Mixtral scale
            self.gate_proj = self.up_proj = self.down_proj = None

    def _qkv(self, ctx, h):
        """(B, S, E) → q (B, H, S, D), k/v (B, KVH, S, D).  Under
        ``tp_axis`` the returned head dims are the LOCAL head counts and
        the entry f operator has been applied to ``h``'s stream."""
        b, s, _ = h.shape
        d = self.head_dim
        heads, kv_heads = self.heads, self.kv_heads
        wq = ctx.value(self.q_proj.weight)
        wk = ctx.value(self.k_proj.weight)
        wv = ctx.value(self.v_proj.weight)
        if self.tp_axis is not None:
            # head-major row blocks: a contiguous row slice IS a head
            # block, for Q and for KV alike — so _shard_rows shards heads
            from ..parallel.tensor_parallel import (copy_to_tp_region,
                                                    _shard_rows)
            n = jax.lax.psum(1, self.tp_axis)
            if heads % n or kv_heads % n:
                raise ValueError(
                    f"tensor parallelism: heads ({heads}) and kv_heads "
                    f"({kv_heads}) must both divide by the "
                    f"'{self.tp_axis}' axis size ({n})")
            h = copy_to_tp_region(h, self.tp_axis)
            wq = _shard_rows(wq, self.tp_axis)
            wk = _shard_rows(wk, self.tp_axis)
            wv = _shard_rows(wv, self.tp_axis)
            heads, kv_heads = heads // n, kv_heads // n
        to_heads = lambda y, nh: jnp.swapaxes(
            y.reshape(b, s, nh, d), 1, 2)
        q = to_heads(jnp.matmul(h, wq.T.astype(h.dtype)), heads)
        k = to_heads(jnp.matmul(h, wk.T.astype(h.dtype)), kv_heads)
        v = to_heads(jnp.matmul(h, wv.T.astype(h.dtype)), kv_heads)
        return q, k, v

    def forward(self, ctx, x, cos, sin):
        b, s, e = x.shape
        h = self.ln1.forward(ctx, x)
        q, k, v = self._qkv(ctx, h)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if self.sp_axis is not None:
            # the ring is GQA-aware: KVH-wide chunks rotate (H/KVH x
            # fewer ICI bytes per hop), expansion happens at use
            from ..parallel.ring_attention import ring_attention
            o = ring_attention(q, k, v, self.sp_axis, causal=True)
        else:
            if q.shape[1] != k.shape[1]:
                # GQA: repeat each KV head over its query group (the
                # local ratio equals the global one under TP — both
                # divide by n).  Trace-time expansion is exact and XLA
                # folds it into the attention matmul's layout; flash
                # already streams the expanded operand blockwise
                rep = q.shape[1] // k.shape[1]
                k = jnp.repeat(k, rep, axis=1)
                v = jnp.repeat(v, rep, axis=1)
            # the Mistral band rides the kernel (banded blocks skipped:
            # O(S·window) compute), so the full-sequence forward is
            # exact at ANY length              (B, H_loc, S, D)
            o = flash_attention(q, k, v, causal=True,
                                sliding_window=self.sliding_window)
        o = jnp.swapaxes(o, 1, 2).reshape(b, s, q.shape[1] * self.head_dim)
        return self._mlp_tail(ctx, x, o)

    def _tp_swiglu(self, ctx, h):
        """SwiGLU as the Megatron column→row pair: gate and up are both
        column-parallel consumers of the same f-entered stream (one
        backward psum covers both), the gating product happens on the
        feature shard, and down_proj's row-parallel psum is the pair's
        single forward collective."""
        from ..parallel.tensor_parallel import (copy_to_tp_region,
                                                row_parallel_linear,
                                                _shard_rows, _shard_cols)
        h = copy_to_tp_region(h, self.tp_axis)
        wg = _shard_rows(ctx.value(self.gate_proj.weight), self.tp_axis)
        wu = _shard_rows(ctx.value(self.up_proj.weight), self.tp_axis)
        wd = _shard_cols(ctx.value(self.down_proj.weight), self.tp_axis)
        gated = F.silu(jnp.matmul(h, wg.T.astype(h.dtype))) \
            * jnp.matmul(h, wu.T.astype(h.dtype))
        return row_parallel_linear(gated, wd, None, self.tp_axis)

    def tp_sharded_params(self):
        """Parameters whose per-device gradients are block-sparse under
        ``tp_axis`` (make_train_step(tp_axis=...) psum-assembles them):
        the head-sharded Q/K/V rows, the column-sharded o_proj, and the
        SwiGLU pair's sharded dims."""
        return [self.q_proj.weight, self.k_proj.weight,
                self.v_proj.weight, self.o_proj.weight,
                self.gate_proj.weight, self.up_proj.weight,
                self.down_proj.weight]

    def _ffn(self, ctx, h):
        """Dense SwiGLU — MoeLlamaBlock overrides this with the routed
        expert mixture."""
        gated = F.silu(self.gate_proj.forward(ctx, h)) \
            * self.up_proj.forward(ctx, h)
        return self.down_proj.forward(ctx, gated)

    def _mlp_tail(self, ctx, x, o):
        """Shared residual tail: attention output projection + FFN (one
        body for the training forward and every cached decode path).
        Under ``tp_axis`` the attention combine ``o`` carries the LOCAL
        head features: o_proj is row-parallel (its psum is the exit g
        operator of the attention region) and the FFN runs the
        column→row SwiGLU pair."""
        if self.tp_axis is not None:
            from ..parallel.tensor_parallel import (row_parallel_linear,
                                                    _shard_cols)
            wo = _shard_cols(ctx.value(self.o_proj.weight), self.tp_axis)
            x = x + row_parallel_linear(o, wo, None, self.tp_axis)
            h = self.ln2.forward(ctx, x)
            return x + self._tp_swiglu(ctx, h)
        x = x + self.o_proj.forward(ctx, o)
        h = self.ln2.forward(ctx, x)
        return x + self._ffn(ctx, h)

    def _chunk_qkv(self, ctx, x, pos):
        """(B, S_c, E) -> rotated q (B, H, S_c, D), k/v (B, KVH, S_c, D)
        at absolute positions ``pos (S_c,)`` — the cached-decode
        projection.  Routed through :meth:`_qkv`, so under ``tp_axis``
        the head dims are LOCAL and decode shards exactly like the
        training forward (one projection body, no drift)."""
        h = self.ln1.forward(ctx, x)
        q, k, v = self._qkv(ctx, h)
        cos, sin = rope_tables(pos, self.head_dim, self.rope_theta)
        return apply_rope(q, cos, sin), apply_rope(k, cos, sin), v

    def prefill(self, ctx, x, kcache, vcache):
        """Cache-filling forward from position 0: flash causal attention
        over the chunk itself (the caches are empty — nothing earlier
        exists to attend) + KV writes.  Use for prompts; decode_chunk's
        whole-cache attention is for SHORT chunks against a long cache —
        on a prompt it would materialize (S_p, S_max) scores per head."""
        b, s_c, _ = x.shape
        from ..inference.quant import kv_write
        q, k_new, v_new = self._chunk_qkv(
            ctx, x, jnp.arange(s_c, dtype=jnp.int32))
        kcache = kv_write(kcache, k_new, (0, 0, 0, 0))
        vcache = kv_write(vcache, v_new, (0, 0, 0, 0))
        # LOCAL head counts (== global ones single-shard; both divide by
        # the axis size under tp, so the GQA ratio is shard-invariant)
        rep = q.shape[1] // k_new.shape[1]
        if rep > 1:
            k_new = jnp.repeat(k_new, rep, axis=1)
            v_new = jnp.repeat(v_new, rep, axis=1)
        o = flash_attention(q, k_new, v_new, causal=True,
                            sliding_window=self.sliding_window)
        o = jnp.swapaxes(o, 1, 2).reshape(b, s_c,
                                          q.shape[1] * self.head_dim)
        return self._mlp_tail(ctx, x, o), kcache, vcache

    def decode_chunk(self, ctx, x, kcache, vcache, t0):
        """Cached forward over a CHUNK: ``x (B, S_c, E)`` at positions
        ``t0 .. t0+S_c-1`` (``t0`` traced i32).  Writes the chunk's KV
        into the caches and attends each query over the cache with the
        shifted-causal mask (position ``t0+i`` sees keys ``<= t0+i``).
        One matmul-shaped pass instead of ``S_c`` single-token steps —
        the speculative-scoring primitive.  Scores materialize
        (S_c, S_max) per head: meant for SHORT chunks against the cache;
        prefill a prompt with :meth:`prefill` instead."""
        b, s_c, _ = x.shape
        d = self.head_dim
        pos = t0 + jnp.arange(s_c, dtype=jnp.int32)
        q, k_new, v_new = self._chunk_qkv(ctx, x, pos)
        # LOCAL head counts: under tp_axis the caches are KVH/n-wide and
        # q carries H/n heads (the GQA group ratio is shard-invariant)
        from ..inference.quant import kv_value, kv_write
        h_loc, kvh = q.shape[1], k_new.shape[1]
        if self.sp_axis is not None:
            # sequence-parallel decode (parallel/context_parallel.py):
            # time-sharded caches, windowed owner writes, lse-merged
            # partial attention.  sliding_window never reaches here —
            # the model constructor refuses that composition.
            from ..parallel.context_parallel import (
                sp_kv_write, sp_slot_positions, sp_softmax_combine)
            kcache = sp_kv_write(kcache, k_new, t0, self.sp_axis)
            vcache = sp_kv_write(vcache, v_new, t0, self.sp_axis)
            slots = sp_slot_positions(kcache.shape[2], self.sp_axis)
        elif self.sliding_window is not None:
            # rolling window cache (inference/rolling.py): W slots, slot
            # = position mod W.
            from ..inference.rolling import (rolling_kv_write,
                                             rolling_slot_positions)
            if s_c == 1:
                # hot decode path: write first (one O(1) slot write),
                # attend the cache in place — safe because the one
                # evicted position is >= a full window behind the query
                # (n_slots >= window + slack, or the cache never wraps)
                kcache = rolling_kv_write(kcache, k_new, t0)
                vcache = rolling_kv_write(vcache, v_new, t0)
                keys = kv_value(kcache)
                vals = kv_value(vcache)
                slots = rolling_slot_positions(kcache.shape[2], t0 + 1)
            else:
                # chunks attend [pre-write cache | fresh rows]: the
                # PRE-write cache holds exactly the band prefix
                # (t0-W, t0) every chunk query can reach, while writing
                # first would evict band keys the chunk's early queries
                # still need; the fresh rows cover in-chunk attention
                # (so chunks of ANY length work — the band mask
                # prunes).  The write lands after, for later calls.
                keys = jnp.concatenate(
                    [kv_value(kcache), k_new.astype(jnp.float32)],
                    axis=2)
                vals = jnp.concatenate(
                    [kv_value(vcache), v_new.astype(jnp.float32)],
                    axis=2)
                slots = jnp.concatenate(
                    [rolling_slot_positions(kcache.shape[2], t0), pos])
                kcache = rolling_kv_write(kcache, k_new, t0)
                vcache = rolling_kv_write(vcache, v_new, t0)
        else:
            kcache = kv_write(kcache, k_new, (0, 0, t0, 0))
            vcache = kv_write(vcache, v_new, (0, 0, t0, 0))
            slots = jnp.arange(kcache.shape[2], dtype=jnp.int32)
        if self.sliding_window is None or self.sp_axis is not None:
            keys, vals = kv_value(kcache), kv_value(vcache)
        group = h_loc // kvh
        qg = q.reshape(b, kvh, group, s_c, d)
        scores = jnp.einsum("bkgqd,bksd->bkgqs", qg.astype(jnp.float32),
                            keys) * (d ** -0.5)
        valid = slots[None, :] <= pos[:, None]          # (S_c, S_keys)
        if self.sliding_window is not None:
            # banded: key j visible from position t iff t-w < j <= t;
            # negative slot positions are never-written rolling slots
            valid = valid & (slots[None, :]
                             > pos[:, None] - self.sliding_window) \
                & (slots[None, :] >= 0)
        scores = jnp.where(valid[None, None, None, :, :], scores, -1e30)
        if self.sp_axis is not None:
            o = sp_softmax_combine(
                scores, self.sp_axis,
                lambda p: jnp.einsum("bkgqs,bksd->bkgqd", p,
                                     vals)).astype(x.dtype)
        else:
            probs = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("bkgqs,bksd->bkgqd", probs,
                           vals).astype(x.dtype)
        o = jnp.swapaxes(o.reshape(b, h_loc, s_c, d), 1, 2) \
            .reshape(b, s_c, h_loc * d)
        return self._mlp_tail(ctx, x, o), kcache, vcache

    def decode(self, ctx, x, kcache, vcache, t):
        """One-token decode, ``x (B, E)`` at position ``t`` (traced i32);
        caches ``(B, KVH, S_max, D)`` hold UN-repeated KV heads (the GQA
        memory win is exactly that the cache stays KVH-wide).  The
        ``S_c = 1`` case of :meth:`decode_chunk` — one body, so the
        single-token and chunked programs cannot drift apart."""
        y, kcache, vcache = self.decode_chunk(
            ctx, x[:, None, :], kcache, vcache, t)
        return y[:, 0], kcache, vcache


class MoeLlamaBlock(LlamaBlock):
    """Mixtral-shape block: the Llama attention (RoPE + GQA + flash)
    with the dense SwiGLU replaced by a top-k routed mixture of SwiGLU
    experts — one expert per device along ``moe_axis``, dispatch and
    combine via the Switch/GShard ``all_to_all`` machinery
    (parallel/expert_parallel.py), load-balancing aux loss through
    ``Ctx.add_aux_loss``.

    Expert weights are stacked full-size ``(E, ...)`` and replicated
    (mesh-independent checkpoints, exact grads under the step's
    psum-mean — the MoeGptBlock convention, models/gpt.py).  Unlike
    Mixtral's softmax-over-top-k, gates follow the framework-wide
    Switch/GShard semantics of ``switch_moe`` (top-1: the chosen
    expert's softmax probability; top-2: normalized over the pair).
    """

    def __init__(self, hidden, heads, kv_heads, intermediate,
                 num_experts, rope_theta=10000.0, eps=1e-6,
                 head_dim=None, moe_axis="data", capacity_factor=1.25,
                 top_k=1, aux_weight=0.01, sp_axis=None,
                 sliding_window=None):
        from ..nn.parameter import Parameter

        super().__init__(hidden, heads, kv_heads, intermediate,
                         rope_theta=rope_theta, eps=eps,
                         head_dim=head_dim, sp_axis=sp_axis,
                         sliding_window=sliding_window,
                         _dense_ffn=False)
        self.moe_axis = moe_axis
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.top_k = top_k
        self.aux_weight = aux_weight
        self.router = nn.Linear(hidden, num_experts, bias=False)
        self.router.weight.data = self.router.weight.data * 0.1
        wg, wu, wd = [], [], []
        for _ in range(num_experts):
            lg = nn.Linear(hidden, intermediate, bias=False)
            lu = nn.Linear(hidden, intermediate, bias=False)
            ld = nn.Linear(intermediate, hidden, bias=False)
            wg.append(lg.weight.data)
            wu.append(lu.weight.data)
            wd.append(ld.weight.data)
        self.wg = Parameter(jnp.stack(wg))    # (E, I, H)
        self.wu = Parameter(jnp.stack(wu))    # (E, I, H)
        self.wd = Parameter(jnp.stack(wd))    # (E, H, I)

    def _ffn(self, ctx, h):
        from ..parallel.expert_parallel import switch_moe

        b, s, e = h.shape
        toks = h.reshape(b * s, e)
        i = jax.lax.axis_index(self.moe_axis)
        params = tuple(
            jax.lax.dynamic_index_in_dim(ctx.value(p), i, 0,
                                         keepdims=False)
            for p in (self.wg, self.wu, self.wd))

        def expert_fn(params, xe):
            wgl, wul, wdl = params
            gated = F.silu(jnp.matmul(xe, wgl.T.astype(xe.dtype))) \
                * jnp.matmul(xe, wul.T.astype(xe.dtype))
            return jnp.matmul(gated, wdl.T.astype(xe.dtype))

        y, aux = switch_moe(toks, ctx.value(self.router.weight).T,
                            params, expert_fn, self.moe_axis,
                            capacity_factor=self.capacity_factor,
                            top_k=self.top_k)
        ctx.add_aux_loss(self.aux_weight * aux)
        return y.reshape(b, s, e)

    def tp_sharded_params(self):
        raise NotImplementedError(
            "MoeLlamaBlock does not compose with tensor parallelism")


class LlamaModel(nn.Module):
    """Embeddings → N Llama blocks → final RMSNorm → untied LM head.
    ``forward(input_ids[B,S]) -> logits (B, S, V)``."""

    def __init__(self, vocab_size=32000, hidden=512, layers=8, heads=8,
                 kv_heads=None, intermediate=None, max_positions=2048,
                 rope_theta=10000.0, eps=1e-6, remat=False,
                 head_dim=None, tp_axis=None, sp_axis=None, moe_axis=None,
                 moe_num_experts=None, moe_every=2,
                 moe_capacity_factor=1.25, moe_top_k=1,
                 moe_aux_weight=0.01, sliding_window=None,
                 output_hidden=False):
        super().__init__()
        # output_hidden: training-time option — forward returns
        # (hidden, head_weight) instead of logits so a chunked/fused
        # loss can own the vocab chain (the GptModel convention; decode
        # paths apply the head themselves and are unaffected)
        self.output_hidden = output_hidden
        self.hidden = hidden
        self.max_positions = max_positions
        self.rope_theta = rope_theta
        self.remat = remat
        self.tp_axis = tp_axis
        # sp_axis: ring sequence parallelism — forward must run inside
        # shard_map with the sequence dim sharded rank-contiguously over
        # this axis (device i holds global rows [i*S_loc, (i+1)*S_loc));
        # RoPE rotates by global positions, attention runs the ring.
        # Composes with tp_axis (heads shard, the ring passes local-head
        # KV shards) and a data axis, exactly as the GPT family.
        self.sp_axis = sp_axis
        # sliding_window: Mistral-style banded causal attention (see
        # LlamaBlock); exact in the cached decode paths AND the
        # full-sequence forward/prefill (banded flash kernel)
        self.sliding_window = sliding_window
        if sliding_window is not None:
            if sliding_window < 1:
                raise ValueError(
                    f"sliding_window must be >= 1, got {sliding_window}")
            if sp_axis is not None:
                raise ValueError(
                    "sliding_window with sp_axis is not supported (the "
                    "ring's chunk bias is causal, not banded)")
        # moe_axis: Mixtral-shape MoE — every ``moe_every``-th block
        # routes its SwiGLU over experts along the axis (the GptModel
        # convention; one expert per device, moe_num_experts = axis size)
        self.moe_axis = moe_axis
        if moe_axis is not None:
            if moe_num_experts is None:
                raise ValueError(
                    "moe_axis requires moe_num_experts (= the mesh axis "
                    "size: one expert per device)")
            if tp_axis is not None:
                raise ValueError(
                    "moe_axis and tp_axis are mutually exclusive for now "
                    "(expert FFNs are not tensor-sharded)")
            if not 1 <= moe_every <= layers:
                raise ValueError(
                    f"moe_every={moe_every} with layers={layers}: must "
                    f"be in [1, layers] or no block would be MoE (block "
                    f"moe_every-1 is the first routed one)")
        kv_heads = kv_heads or heads
        # Llama's FFN width: 2/3 * 4E rounded up to a multiple of 256
        # (only the default — checkpoints carry their own)
        if intermediate is None:
            intermediate = -(-(8 * hidden // 3) // 256) * 256
        self.tok_emb = nn.Embedding(vocab_size, hidden)
        self.tok_emb.weight.data = self.tok_emb.weight.data * 0.02

        def build_block(idx):
            if moe_axis is not None and idx % moe_every == moe_every - 1:
                return MoeLlamaBlock(
                    hidden, heads, kv_heads, intermediate,
                    moe_num_experts, rope_theta=rope_theta, eps=eps,
                    head_dim=head_dim, moe_axis=moe_axis,
                    capacity_factor=moe_capacity_factor,
                    top_k=moe_top_k, aux_weight=moe_aux_weight,
                    sp_axis=sp_axis, sliding_window=sliding_window)
            return LlamaBlock(hidden, heads, kv_heads, intermediate,
                              rope_theta=rope_theta, eps=eps,
                              head_dim=head_dim, tp_axis=tp_axis,
                              sp_axis=sp_axis,
                              sliding_window=sliding_window)

        self.blocks = nn.ModuleList([build_block(i)
                                     for i in range(layers)])
        self.norm = FusedRMSNorm(hidden, eps=eps)
        self.lm_head = nn.Linear(hidden, vocab_size, bias=False)
        # untied head initialized like the embedding, N(0, 0.02) (the
        # Llama initializer_range) — replacing, not scaling, the Linear
        # default kaiming draw
        from ..nn.modules import _next_key
        self.lm_head.weight.data = 0.02 * jax.random.normal(
            _next_key(), (vocab_size, hidden), jnp.float32)

    def forward(self, ctx, input_ids):
        b, s = input_ids.shape
        head_dim = self.blocks[0].head_dim
        if self.sp_axis is not None:
            # ``s`` is the LOCAL shard; RoPE rotates by global positions
            from ..compat import axis_size as _axis_size
            n = _axis_size(self.sp_axis)
            if s * n > self.max_positions:
                raise ValueError(
                    f"global sequence {s} x {n} shards exceeds "
                    f"max_positions {self.max_positions}")
            pos = jax.lax.axis_index(self.sp_axis) * s \
                + jnp.arange(s, dtype=jnp.int32)
        else:
            if s > self.max_positions:
                raise ValueError(
                    f"sequence length {s} exceeds max_positions "
                    f"{self.max_positions}")
            pos = jnp.arange(s, dtype=jnp.int32)
        cos, sin = rope_tables(pos, head_dim, self.rope_theta)
        x = self.tok_emb.forward(ctx, input_ids)
        for blk in self.blocks:
            if self.remat:
                x = nn.checkpoint_forward(blk, ctx, x, cos, sin)
            else:
                x = blk.forward(ctx, x, cos, sin)
        x = self.norm.forward(ctx, x)
        if self.output_hidden:
            return x, ctx.value(self.lm_head.weight)
        return self.lm_head.forward(ctx, x)

    def init_caches(self, batch, s_max, dtype=jnp.float32):
        """Per-layer (k, v) caches, (B, KVH, S_max, D) — KVH-wide, the
        GQA cache saving.  Under ``tp_axis`` the caches are LOCAL
        (KVH/n-wide, each device caching only its own KV head shard —
        the per-device cache HBM shrinks with the mesh) and this must be
        called inside ``shard_map`` (generate does)."""
        n = 1
        if self.tp_axis is not None:
            try:
                n = jax.lax.psum(1, self.tp_axis)   # static axis size
            except NameError:
                raise ValueError(
                    f"init_caches on a tp_axis='{self.tp_axis}' model "
                    f"must run inside shard_map over a mesh with that "
                    f"axis — generate(..., mesh=...) wraps the whole "
                    f"decode; direct callers must shard_map themselves"
                ) from None
            if any(blk.kv_heads % n for blk in self.blocks):
                raise ValueError(
                    f"init_caches: kv_heads must divide by the "
                    f"'{self.tp_axis}' axis size ({n})")
        if self.sp_axis is not None:
            # LOCAL time block (the GptModel convention): per-device
            # cache HBM shrinks with the axis — context-length scaling
            from ..parallel.context_parallel import sp_axis_size
            s_max = -(-s_max // sp_axis_size(self.sp_axis))
        if self.sliding_window is not None:
            # rolling cache: the band can only attend the last `window`
            # positions, so the cache needs that many slots plus a
            # rewind-safety margin (slot = position mod n_slots;
            # inference/rolling.py, ROLLING_SLACK) — decode cache HBM
            # is O(window), not O(context)
            from ..inference.rolling import ROLLING_SLACK
            s_max = min(s_max, self.sliding_window + ROLLING_SLACK)
        from ..inference.quant import make_kv_cache
        return [(make_kv_cache((batch, blk.kv_heads // n, s_max,
                                blk.head_dim), dtype),
                 make_kv_cache((batch, blk.kv_heads // n, s_max,
                                blk.head_dim), dtype))
                for blk in self.blocks]

    def _cache_capacity(self, caches):
        """Global position capacity of the caches (under ``sp_axis`` the
        per-device block times the axis size).  A FULL-SIZE rolling
        sliding-window cache never bounds positions — old slots are
        overwritten as they fall out of the band — so capacity is the
        position-table-free family's only position limit,
        ``max_positions``; a cache allocated SMALLER than the rolling
        size (init_caches clamps to the caller's declared s_max) must
        not wrap — wrapping would evict in-band keys — so it keeps its
        slot count as the capacity."""
        if self.sliding_window is not None:
            from ..inference.rolling import ROLLING_SLACK
            n = caches[0][0].shape[2]
            if n >= min(self.max_positions,
                        self.sliding_window + ROLLING_SLACK):
                return self.max_positions
            return n
        cap = caches[0][0].shape[2]
        if self.sp_axis is not None:
            from ..parallel.context_parallel import sp_axis_size
            cap *= sp_axis_size(self.sp_axis)
        return cap

    def tp_sharded_params(self):
        """All blocks' TP-block-sparse parameters (see LlamaBlock) — the
        contract make_train_step(tp_axis=...) assembles by psum."""
        return [p for blk in self.blocks for p in blk.tp_sharded_params()]

    def _head(self, ctx, x):
        return jnp.matmul(
            x, ctx.value(self.lm_head.weight).T.astype(x.dtype))

    def _decode_guard(self, what):
        """Cached decode supports single-shard, tensor-parallel
        (``tp_axis``), AND expert-parallel (``moe_axis``) execution —
        the sharded flavors run inside shard_map (generate(mesh=...)
        wraps it): TP shards KV heads with psum-replicated logits; MoE
        keeps caches replicated and routes each decoded chunk's tokens
        through the expert all_to_all exactly like the training
        forward (the Mixtral serving path — mixtral_from_hf builds this
        model).  Sequence parallelism (``sp_axis``) decodes with a
        TIME-sharded KV cache and lse-merged partial attention
        (parallel/context_parallel.py) — the serving mirror of the
        training ring; it composes with tp_axis but not with moe_axis
        (untested collective interleaving) — refuse that loudly."""
        if self.sp_axis is not None and self.moe_axis is not None:
            raise NotImplementedError(
                f"{what}: sp_axis does not compose with moe_axis for "
                f"cached decode; build the model with one or the other "
                f"for inference")

    def _run_blocks(self, ctx, toks, caches, blk_fn):
        """Embed ``toks``, thread the caches through ``blk_fn`` per
        block, final-norm + head — the shared body of every cached
        decode entry point.  The embedding gather is int8-aware: under
        quantize_int8 only the selected rows dequantize."""
        from ..inference.quant import gather_rows
        x = gather_rows(ctx, self.tok_emb.weight, toks)
        new_caches = []
        for blk, (kc, vc) in zip(self.blocks, caches):
            x, kc, vc = blk_fn(blk, x, kc, vc)
            new_caches.append((kc, vc))
        return self._head(ctx, self.norm.forward(ctx, x)), new_caches

    def prefill(self, ctx, toks, caches):
        """Consume a PROMPT ``toks (B, S_p)`` from position 0 in one
        flash-attention pass, filling the KV caches: returns
        ``(logits (B, S_p, V), new_caches)``.  O(1) calls instead of
        ``S_p`` decode steps, with no (S_p, S_max) score tensor (the
        caches are empty, so the chunk attends only itself).  Under
        ``sliding_window`` the kernel applies the band exactly at any
        prompt length (banded blocks skipped, O(S·window)).  Under
        ``sp_axis`` OR a rolling ``sliding_window`` cache, the prompt
        runs in cache-bounded chunks through ``decode_chunk`` instead
        (the chunk loop is layout-generic: it splits to the per-device
        block / the window respectively)."""
        self._decode_guard("prefill")
        if self.sp_axis is not None or self.sliding_window is not None:
            from ..parallel.context_parallel import sp_chunked_prefill
            return sp_chunked_prefill(
                self, ctx, toks, caches,
                bound_by_cache=self.sp_axis is not None)
        return self._run_blocks(
            ctx, toks, caches,
            lambda blk, x, kc, vc: blk.prefill(ctx, x, kc, vc))

    def decode_chunk(self, ctx, toks, caches, t0):
        """Logits for a token CHUNK ``toks (B, S_c)`` at positions
        ``t0 .. t0+S_c-1``, attending the KV caches: returns
        ``(logits (B, S_c, V), new_caches)``.  ``logits[:, i]`` is the
        next-token distribution after consuming ``toks[:, :i+1]`` (and
        everything already in the caches) — the speculative-verification
        primitive (inference/speculative.py scores draft tokens with it;
        prompts go through :meth:`prefill`).

        Same bounds contract as GptModel.decode_chunk: a concrete
        (Python int) ``t0`` is validated against the cache length here —
        ``lax.dynamic_update_slice`` CLAMPS an out-of-range write start,
        which would silently overwrite prefix KV entries while RoPE
        rotates by the unclamped positions.  Traced callers (generate /
        speculative_generate) enforce the bound up front."""
        self._decode_guard("decode_chunk")
        if not isinstance(t0, jax.core.Tracer):
            s_c = toks.shape[1]
            bound = min(self.max_positions, self._cache_capacity(caches))
            if int(t0) < 0 or int(t0) + s_c > bound:
                raise ValueError(
                    f"decode_chunk: positions {int(t0)}..{int(t0) + s_c} "
                    f"out of range for max_positions "
                    f"{self.max_positions} / cache capacity "
                    f"{self._cache_capacity(caches)} — "
                    f"dynamic_update_slice would clamp and corrupt the "
                    f"cache")
        return self._run_blocks(
            ctx, toks, caches,
            lambda blk, x, kc, vc: blk.decode_chunk(ctx, x, kc, vc, t0))

    def decode_step(self, ctx, tok, caches, t):
        """Logits for one token (same decode protocol as GptModel, so
        :func:`~apex_tpu.models.gpt.generate` drives this family too)."""
        self._decode_guard("decode_step")
        return self._run_blocks(
            ctx, tok, caches,
            lambda blk, x, kc, vc: blk.decode(ctx, x, kc, vc, t))


def llama_tiny(**kw):
    """Test-scale geometry (for suites and examples)."""
    return LlamaModel(**{**dict(vocab_size=1000, hidden=128, layers=2,
                                heads=4, kv_heads=2, max_positions=128),
                         **kw})


def llama_1b(**kw):
    """~1.2B geometry (Llama-3.2-1B-like: 16 layers, hidden 2048,
    32q/8kv heads, FFN 8192; vocab comes from the caller/checkpoint).
    ``max_positions`` defaults to 8192 — raise it (cache/HBM cost only,
    RoPE has no table) for the checkpoint's full 128k window."""
    return LlamaModel(**{**dict(hidden=2048, layers=16, heads=32,
                                kv_heads=8, intermediate=8192,
                                rope_theta=500000.0,
                                max_positions=8192), **kw})


def llama_7b(**kw):
    """Llama-2-7B geometry: 32 layers, hidden 4096, 32 MHA heads,
    FFN 11008, the checkpoint's 4096 context window."""
    return LlamaModel(**{**dict(hidden=4096, layers=32, heads=32,
                                intermediate=11008,
                                max_positions=4096), **kw})
