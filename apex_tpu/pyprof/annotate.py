"""Trace-time op annotation — the NVTX-marker analogue.

The reference's ``pyprof.nvtx.init()`` (apex/pyprof/nvtx/nvmarker.py)
monkey-patches every torch/Tensor/F entrypoint to push an NVTX range whose
payload is a JSON dict {module, op, args shapes/dtypes, call trace}; nvprof
later attributes GPU kernels to those ranges.  The TPU analogue exploits
XLA's trace-once model: patching ``apex_tpu.nn.functional`` records each op
exactly once per compiled trace — shapes, dtypes, layer params, call site,
module scope — and simultaneously wraps the op in ``jax.named_scope`` so the
same labels appear in ``jax.profiler`` traces (the XLA-side join the
reference needed a SQL database for happens in the HLO metadata for free).

``init()`` is idempotent; events accumulate in a global log drained by
``apex_tpu.pyprof.capture()`` / ``save()``.
"""
from __future__ import annotations

import functools
import inspect
import threading

import jax
import numpy as np

_state = threading.local()
_installed = False


def _log():
    if not hasattr(_state, "events"):
        _state.events = []
        _state.enabled = False
        _state.scopes = []
    return _state


def events():
    return _log().events


def enabled() -> bool:
    return getattr(_log(), "enabled", False)


def set_enabled(flag: bool):
    _log().enabled = flag


def clear():
    _log().events.clear()


def _shape_of(x):
    try:
        s = np.shape(x)
        return list(s) if s or hasattr(x, "dtype") else None
    except Exception:
        return None


def _dtype_of(x):
    try:
        return str(x.dtype) if hasattr(x, "dtype") else None
    except Exception:
        return None


def _jsonable(v):
    if isinstance(v, (int, float, bool, str)) or v is None:
        return v
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    return repr(v)


def _callsite():
    """First stack frame outside apex_tpu/jax — the user line that issued
    the op (reference nvmarker records the full call trace; one frame is
    what its prof stage actually uses).  Walks raw frames — no
    inspect.stack(), which materializes every FrameInfo + source context on
    every recorded event."""
    import os
    import sys
    sep = os.sep
    # match whole path components, not substrings: a user script under
    # ~/jax-experiments/train.py must still be attributed
    skip = (f"{sep}apex_tpu{sep}", f"{sep}jax{sep}", f"{sep}jaxlib{sep}")
    f = sys._getframe(2)
    for _ in range(12):
        if f is None:
            break
        fn = f.f_code.co_filename
        if not any(s in fn for s in skip) and "<" not in fn:
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return None


def _is_tensor(v):
    return hasattr(v, "shape") and hasattr(v, "dtype")


def _effective_dtypes(op, dtypes):
    """Dtypes as the op will actually run them: _record fires before the
    wrapped fn applies the amp cast policy, so consult the active policy
    (amp/policy.py) — otherwise every op under O1/O2 reports its pre-cast
    fp32 inputs and the MXU/roofline columns are wrong."""
    try:
        from ..amp.policy import current_policy
        pol = current_policy()
        if pol is None or not getattr(pol, "enabled", False):
            return dtypes
        cat = pol.category_of(op)
    except Exception:
        return dtypes
    import jax.numpy as jnp
    floats = {"float16", "bfloat16", "float32", "float64"}
    if cat == "half":
        tgt = str(jnp.dtype(pol.half_dtype))
        return [tgt if d in floats else d for d in dtypes]
    if cat == "float":
        return ["float32" if d in floats else d for d in dtypes]
    if cat in ("promote", "sequence"):
        present = [d for d in dtypes if d in floats]
        if present:
            widest = "float32" if len(set(present)) > 1 else present[0]
            return [widest if d in floats else d for d in dtypes]
    return dtypes


def _record(op, sig, args, kwargs):
    """Bind args to the op's signature so positional layer params (a
    positional kernel_size, tuple strides) land in ``params`` by name
    instead of being dropped; tensors (anything with shape+dtype) feed the
    shapes/dtypes lists in signature order."""
    st = _log()
    shapes, dtypes, params, tensors = [], [], {}, {}
    if sig is not None:
        try:
            items = sig.bind(*args, **kwargs).arguments.items()
        except TypeError:
            items = [(f"arg{i}", a) for i, a in enumerate(args)] + \
                list(kwargs.items())
    else:
        items = [(f"arg{i}", a) for i, a in enumerate(args)] + \
            list(kwargs.items())
    for name, v in items:
        if _is_tensor(v):
            shapes.append(_shape_of(v))
            dtypes.append(_dtype_of(v))
            tensors[name] = {"shape": _shape_of(v), "dtype": _dtype_of(v)}
        elif v is not None:
            params[name] = _jsonable(v)
    eff = _effective_dtypes(op, dtypes)
    if eff is not dtypes:
        # keep the per-tensor dict consistent with the policy-adjusted flat
        # list — otherwise JSON/CSV rows report contradictory dtypes under O1
        for name, d in zip(tensors, eff):
            tensors[name]["dtype"] = d
    st.events.append({
        "seq": len(st.events),
        "op": op,
        "dir": "fwd",
        "scope": "/".join(st.scopes) if st.scopes else "",
        "shapes": shapes,
        "dtypes": eff,
        "tensors": tensors,
        "params": params,
        "callsite": _callsite(),
    })


def _wrap_fn(op_name, fn):
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        sig = None

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        st = _log()
        if not st.enabled:
            return fn(*args, **kwargs)
        seq = len(st.events)
        _record(op_name, sig, args, kwargs)
        # unique per-event label: survives into HLO metadata op_name
        # (fwd "jvp(ppN_op)", bwd "transpose(jvp(ppN_op))"), which is what
        # parse/trace.py joins measured thunk timings against — the
        # nvvp.py:91-199 marker<->kernel correlation, done through HLO
        # metadata instead of an NVTX SQL table
        with jax.named_scope(f"pp{seq}_{op_name}"):
            return fn(*args, **kwargs)
    wrapper.__wrapped_pyprof__ = fn
    return wrapper


def _wrap_forward(cls):
    """Scope tracking wraps ``forward`` because the module tree executes
    through ``child.forward(ctx, x)`` (tape re-execution path), not
    ``__call__`` (nn/modules.py Sequential.forward)."""
    orig = vars(cls).get("forward")
    if orig is None or getattr(orig, "__wrapped_pyprof__", None) is not None:
        return

    @functools.wraps(orig)
    def forward(self, *args, **kwargs):
        st = _log()
        if not st.enabled:
            return orig(self, *args, **kwargs)
        label = type(self).__name__
        st.scopes.append(label)
        try:
            with jax.named_scope(label):
                return orig(self, *args, **kwargs)
        finally:
            st.scopes.pop()

    forward.__wrapped_pyprof__ = orig
    cls.forward = forward


def _instrument_module_tree():
    """Wrap forward on every Module subclass seen so far; re-run on each
    init() so classes defined after the first call get covered too."""
    from ..nn.modules import Module

    def walk(cls):
        _wrap_forward(cls)
        for sub in cls.__subclasses__():
            walk(sub)

    walk(Module)


def init():
    """Install the annotator (idempotent) and enable recording — the
    ``pyprof.nvtx.init()`` analogue (nvmarker.py docstring)."""
    global _installed
    if not _installed:
        from ..nn import functional as F
        from ..nn import modules as M

        wrapped = {}
        for name, fn in vars(F).items():
            if callable(fn) and not name.startswith("_") and \
                    inspect.isfunction(fn) and fn.__module__ == F.__name__:
                w = _wrap_fn(name, fn)
                setattr(F, name, w)
                wrapped[fn] = w
        # conv modules bind F.conv* as staticmethods at class-definition
        # time; rebind any captured originals to the wrappers
        for cls in vars(M).values():
            if inspect.isclass(cls) and "_fn" in vars(cls):
                raw = inspect.getattr_static(cls, "_fn")
                orig = getattr(raw, "__func__", None)
                if orig in wrapped:
                    cls._fn = staticmethod(wrapped[orig])

        # fused custom-vjp ops live outside nn.functional (contrib flash
        # attention, FusedLayerNorm, xentropy) — wrap their defining-module
        # bindings (which the module classes call) and the package
        # re-exports, so the profile sees the fused ops a TPU user most
        # wants to find (the reference gives each its own prof/ handler)
        import importlib

        from ..contrib import multihead_attn as _attn_pkg
        from ..contrib.multihead_attn import attn_funcs as _attn
        from ..contrib import xentropy as _sx_pkg
        from ..contrib.xentropy import chunked as _cx
        from ..contrib.xentropy import softmax_xentropy as _sx
        from .. import normalization as _norm_pkg
        # the package re-exports a function named like the submodule, so a
        # plain "from ..normalization import fused_layer_norm" would grab
        # the function — resolve the module itself
        _fln = importlib.import_module(
            _norm_pkg.__name__ + ".fused_layer_norm")
        _frn = importlib.import_module(
            _norm_pkg.__name__ + ".rms_norm")
        # NOTE: the named_scope label carries into the *forward* HLO only;
        # a custom_vjp's backward is traced outside the scope, so measured-
        # mode bwd durations for these ops stay unattributed (their bwd
        # rows keep the analytic estimate) — same limitation as tape ops
        for mods, name in (
                ((_attn, _attn_pkg), "flash_attention"),
                ((_fln, _norm_pkg), "fused_layer_norm_affine"),
                ((_fln, _norm_pkg), "fused_layer_norm"),
                ((_frn, _norm_pkg), "fused_rms_norm_affine"),
                ((_frn, _norm_pkg), "fused_rms_norm"),
                ((_sx, _sx_pkg), "softmax_cross_entropy_loss"),
                ((_cx, _sx_pkg), "chunked_lm_head_loss")):
            fn = getattr(mods[0], name)
            if not hasattr(fn, "__wrapped_pyprof__"):
                w = _wrap_fn(name, fn)
                for mod in mods:
                    setattr(mod, name, w)

        # tensor-method ops (the reference wraps torch.Tensor methods via
        # tensor_overrides, nvmarker.py): the tape analogue is one hook on
        # autograd.record_op, through which every Tensor arithmetic /
        # reduction / view op flows exactly once per trace.  The ppN scope
        # labels the *forward* dispatch only; the tape's backward replay
        # calls _OPS[name] directly, so tape-op bwd rows stay analytic in
        # measured mode (unlike the F.* wrappers, whose jvp/transpose
        # metadata carries the label into the compiled backward).
        from .. import autograd as _ag
        if not hasattr(_ag.record_op, "__wrapped_pyprof__"):
            _orig_record_op = _ag.record_op

            @functools.wraps(_orig_record_op)
            def _record_op(name, array_args, static_kwargs):
                st = _log()
                if not st.enabled:
                    return _orig_record_op(name, array_args, static_kwargs)
                ev_idx = len(st.events)
                _record(name, None, tuple(array_args), dict(static_kwargs))
                with jax.named_scope(f"pp{ev_idx}_{name}"):
                    out = _orig_record_op(name, array_args, static_kwargs)
                # the output shape sizes data-movement ops (a getitem of
                # one row moves the row, not the whole input)
                st.events[ev_idx]["out_shape"] = _shape_of(out)
                return out

            _record_op.__wrapped_pyprof__ = _orig_record_op
            _ag.record_op = _record_op

        # optimizer step annotation (pyprof's wrap_fused_adam analogue):
        # record one event per step() with the total param element count
        from .. import optimizers as opt_pkg
        for cls in vars(opt_pkg).values():
            if inspect.isclass(cls) and hasattr(cls, "step") and \
                    not hasattr(cls.step, "__wrapped_pyprof__"):
                cls.step = _wrap_opt_step(cls.__name__, cls.step)
        _installed = True
    _instrument_module_tree()
    set_enabled(True)


def _wrap_opt_step(name, step):
    @functools.wraps(step)
    def wrapper(self, *args, **kwargs):
        st = _log()
        if st.enabled:
            numel = sum(int(np.prod(np.shape(p.data)))
                        for g in getattr(self, "param_groups", [])
                        for p in g["params"])
            st.events.append({
                "seq": len(st.events), "op": f"optimizer.{name}.step",
                "dir": "fwd", "scope": "", "shapes": [[numel]],
                "dtypes": ["float32"], "tensors": {}, "params": {},
                "callsite": _callsite(),
            })
            with jax.named_scope(f"{name}.step"):
                return step(self, *args, **kwargs)
        return step(self, *args, **kwargs)
    wrapper.__wrapped_pyprof__ = step
    return wrapper
