"""Per-op FLOP / byte / MXU models (reference: apex/pyprof/prof/*.py — one
file per op family: blas.py, conv.py, pointwise.py, normalization.py,
softmax.py, loss.py, optim.py, pooling.py, embedding.py ... collapsed here
into one registry since the op metadata arrives uniformly from the trace).

Each model maps an enriched row (shapes/dtypes/params) to
(flops, bytes, mxu_info).  The Tensor-Core-eligibility column of the
reference becomes MXU eligibility: matmul-shaped ops qualify, with a
utilization estimate from padding the operand dims up to the (8, 128)
sublane×lane tile and 128-deep MXU contraction.
"""
from __future__ import annotations

import math

_DSIZE = {"float32": 4, "bfloat16": 2, "float16": 2, "float64": 8,
          "int32": 4, "int64": 8, "uint8": 1, "int8": 1, "bool": 1}


def _ds(dtype):
    return _DSIZE.get(dtype, 4)


def _numel(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return list(v) + [v[-1]] * (n - len(v))
    return [v] * n


def _mxu(m, k, n, dtype):
    """MXU tiling model: operands padded to (8,128) tiles, contraction to
    128.  util = useful MACs / padded MACs; 'eligible' mirrors the
    reference's TC dtype gate (prof/blas.py) with bf16 in place of fp16."""
    pm = max(8, math.ceil(m / 8) * 8)
    pk = max(128, math.ceil(k / 128) * 128)
    pn = max(128, math.ceil(n / 128) * 128)
    util = (m * k * n) / (pm * pk * pn)
    return {"eligible": dtype in ("bfloat16", "float16"),
            "util": round(util, 3)}


def _gemm_family(row):
    shapes = row["shapes"]
    dtype = (row["dtypes"] or ["float32"])[0]
    op = row["op"]
    if len(shapes) < 2:
        # tape matmul against a non-array operand (plain list rhs lifted by
        # the tape): the rhs shape was not recorded, so no gemm dims exist
        # — degrade to a coarse elementwise estimate instead of crashing
        return _elemwise(row, 2)
    if op == "linear":
        x, w = shapes[0], shapes[1]
        m = _numel(x[:-1])
        k = x[-1]
        n = w[0]
        flops = 2 * m * k * n + (m * n if len(shapes) > 2 else 0)
        bytes_ = (m * k + k * n + m * n) * _ds(dtype)
        return flops, bytes_, _mxu(m, k, n, dtype)
    # matmul: (..., M, K) @ (..., K, N), with jnp.matmul's 1-D promotion
    # rules (vector operands gain/drop a unit dim) — reachable with
    # arbitrary ranks now that Tensor.__matmul__ flows through the hook
    a, b = list(shapes[0]), list(shapes[1])
    if len(a) == 1:
        a = [1] + a
    if len(b) == 1:
        b = b + [1]
    # batch dims broadcast between the operands; either side may carry them
    try:
        import numpy as _np
        batch = _numel(_np.broadcast_shapes(tuple(a[:-2]), tuple(b[:-2])))
    except ValueError:
        batch = max(_numel(a[:-2]), _numel(b[:-2]))
    m, k, n = a[-2], a[-1], b[-1]
    flops = 2 * batch * m * k * n
    bytes_ = batch * (m * k + k * n + m * n) * _ds(dtype)
    return flops, bytes_, _mxu(m, k, n, dtype)


def _conv_out(sz, k, s, p, d):
    return (sz + 2 * p - d * (k - 1) - 1) // s + 1


def _conv_family(row):
    shapes = row["shapes"]
    dtype = (row["dtypes"] or ["float32"])[0]
    x, w = shapes[0], shapes[1]
    nd = len(x) - 2
    params = row.get("params", {})
    stride = _pair(params.get("stride", 1), nd)
    pad = _pair(params.get("padding", 0), nd)
    dil = _pair(params.get("dilation", 1), nd)
    groups = int(params.get("groups", 1))
    n = x[0]
    if row["op"] == "conv_transpose2d":
        cin, cout_g = w[0], w[1]
        cout = cout_g * groups
        spatial_out = [s_ * st for s_, st in zip(x[2:], stride)]
        kprod = _numel(w[2:])
        macs = n * cin * _numel(x[2:]) * cout_g * kprod
    else:
        cout, cin_g = w[0], w[1]
        spatial_out = [_conv_out(s_, k_, st, p_, d_) for s_, k_, st, p_, d_
                       in zip(x[2:], w[2:], stride, pad, dil)]
        kprod = _numel(w[2:])
        macs = n * cout * _numel(spatial_out) * cin_g * kprod
        cout_g = cout // groups
        cin = cin_g * groups
    flops = 2 * macs
    out_elems = n * cout * _numel(spatial_out)
    bytes_ = (_numel(x) + _numel(w) + out_elems) * _ds(dtype)
    # im2col view: M = N·prod(out), K = Cin/g·prod(kernel), N = Cout/g
    k_dim = (cin_g if row["op"] != "conv_transpose2d" else cin) * kprod
    n_dim = cout_g if row["op"] == "conv_transpose2d" else cout // groups
    mxu = _mxu(n * _numel(spatial_out), k_dim, n_dim, dtype)
    return flops, bytes_, mxu


_POINTWISE_COST = {"relu": 1, "leaky_relu": 2, "tanh": 4, "sigmoid": 4,
                   "gelu": 8, "dropout": 2, "pad": 1, "flatten": 0,
                   "silu": 5}
_NORM_COST = {"batch_norm": 8, "layer_norm": 8, "group_norm": 8,
              "instance_norm": 8, "fused_layer_norm": 8,
              "fused_layer_norm_affine": 8,
              "fused_rms_norm": 6, "fused_rms_norm_affine": 6}
_SOFTMAX_COST = {"softmax": 5, "log_softmax": 6}
_LOSS_COST = {"cross_entropy": 7, "nll_loss": 2, "mse_loss": 3,
              "l1_loss": 3, "binary_cross_entropy": 6,
              "binary_cross_entropy_with_logits": 8,
              "softmax_cross_entropy_loss": 7}
_OPT_COST = {"FusedAdam": 12, "FusedLAMB": 16, "FusedNovoGrad": 12,
             "FusedSGD": 4, "LARC": 6}

# tape-level Tensor ops (reference prof/{pointwise,reduction,convert,
# index_slice_join_mutate}.py): elementwise arithmetic by cost, reductions
# read-dominated, views free under XLA, data movement at two passes
_ARITH_COST = {"add": 1, "sub": 1, "rsub": 1, "mul": 1, "div": 1,
               "rdiv": 1, "neg": 1, "abs": 1, "pow": 10, "exp": 8,
               "log": 8, "sqrt": 2}
_REDUCTION_OPS = ("sum", "mean", "max", "min")
_VIEW_OPS = ("reshape", "squeeze")              # XLA bitcast: free
_MOVE_OPS = ("transpose", "getitem", "getitem_dyn", "astype")


def _broadcast_shape(row):
    """Elementwise broadcast of all recorded operand shapes — the result
    (and the work) follows the broadcast, not either single operand
    (outer-product-style [N,1]*[1,M] produces N*M elements).  Scalars
    broadcast to shape ()."""
    import numpy as _np
    shapes = [tuple(s) for s in row["shapes"] if s is not None]
    if not shapes:
        return []
    try:
        return list(_np.broadcast_shapes(*shapes))
    except ValueError:           # incompatible (shouldn't happen): max side
        return max(shapes, key=_numel)


def _binary_elemwise(row, cost, passes=3):
    n = _numel(_broadcast_shape(row))
    dtype = (row["dtypes"] or ["float32"])[0]
    return cost * n, passes * n * _ds(dtype), None


def _movement(row):
    # bytes follow what actually moves: the output when recorded (getitem
    # of one row out of a big tensor moves the row, not the tensor)
    dtype = (row["dtypes"] or ["float32"])[0]
    out = row.get("out_shape")
    n = _numel(out) if out is not None else _numel(_first_shape(row))
    if row["op"] == "astype":
        ds_out = _ds(row.get("params", {}).get("dtype", dtype))
        return 0, n * (_ds(dtype) + ds_out), None
    return 0, 2 * n * _ds(dtype), None


def _first_shape(row):
    return row["shapes"][0] if row["shapes"] else [0]


def _elemwise(row, cost, passes=2):
    x = _first_shape(row)
    dtype = (row["dtypes"] or ["float32"])[0]
    n = _numel(x)
    return cost * n, passes * n * _ds(dtype), None


def _pool_family(row):
    x = _first_shape(row)
    dtype = (row["dtypes"] or ["float32"])[0]
    k = _pair(row.get("params", {}).get("kernel_size", 2), 2)
    n = _numel(x)
    return _numel(k) * n, 2 * n * _ds(dtype), None


def _embedding(row):
    ids, w = row["shapes"][0], row["shapes"][1]
    dtype = (row["dtypes"] or [None, "float32"])[-1]
    out = _numel(ids) * w[-1]
    return 0, out * _ds(dtype) * 2, None


def _attention_family(row):
    """Fused (flash) attention, (B, H, S, D) operands: QK^T and PV matmuls
    dominate; causal halves the useful area.  Bytes model the flash
    property — q/k/v/out move through HBM, the S^2 score matrix never
    does (ops/pallas/attention.py streams it through VMEM)."""
    q, k = row["shapes"][0], row["shapes"][1]
    dtype = (row["dtypes"] or ["float32"])[0]
    b, h, sq, d = q[-4], q[-3], q[-2], q[-1]
    sk = k[-2]
    area = b * h * sq * sk * (0.5 if row.get("params", {}).get("causal")
                              else 1.0)
    flops = 2 * 2 * area * d + 5 * area          # two matmuls + softmax
    bytes_ = b * h * (2 * sq + 2 * sk) * d * _ds(dtype)
    return flops, bytes_, _mxu(sq, d, sk, dtype)


def _optimizer(row):
    name = row["op"].split(".")[1] if "." in row["op"] else row["op"]
    cost = _OPT_COST.get(name, 10)
    numel = _numel(_first_shape(row))
    # read p/g/m(/v), write p/m(/v): ~5 array passes fp32
    return cost * numel, 5 * numel * 4, None


def model_row(row):
    """→ (flops, bytes, mxu_info|None).  Backward rows get the family
    factor: matmul/conv backward = dgrad + wgrad ≈ 2× forward."""
    op = row["op"]
    if op.startswith("optimizer."):
        f, b, m = _optimizer(row)
    elif op in ("linear", "matmul"):
        f, b, m = _gemm_family(row)
    elif op == "flash_attention":
        f, b, m = _attention_family(row)
    elif op.startswith("conv"):
        f, b, m = _conv_family(row)
    elif op in _POINTWISE_COST:
        f, b, m = _elemwise(row, _POINTWISE_COST[op])
    elif op in _NORM_COST:
        f, b, m = _elemwise(row, _NORM_COST[op], passes=3)
    elif op in _SOFTMAX_COST:
        f, b, m = _elemwise(row, _SOFTMAX_COST[op], passes=3)
    elif op in _LOSS_COST:
        f, b, m = _elemwise(row, _LOSS_COST[op], passes=2)
    elif op.endswith("pool2d"):
        f, b, m = _pool_family(row)
    elif op == "embedding":
        f, b, m = _embedding(row)
    elif op in _ARITH_COST:
        f, b, m = _binary_elemwise(row, _ARITH_COST[op])
    elif op in _REDUCTION_OPS:
        f, b, m = _elemwise(row, 1, passes=1)
    elif op in _VIEW_OPS:
        f, b, m = 0, 0, None
    elif op in _MOVE_OPS:
        f, b, m = _movement(row)
    else:
        f, b, m = _elemwise(row, 1)
    if row.get("dir") == "bwd":
        if op == "flash_attention":
            # dq + dk + dv plus the in-kernel score recompute
            factor = 2.5
        elif op in ("linear", "matmul") or op.startswith("conv"):
            factor = 2
        else:
            factor = 1
        f, b = f * factor, b * factor
    return f, b, m
