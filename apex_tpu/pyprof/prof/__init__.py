from .prof import analyze_rows  # noqa: F401
