"""Columnar writer (reference: apex/pyprof/prof/output.py)."""
from __future__ import annotations

import sys


class Table:
    def __init__(self, headers, file=None):
        self.headers = [str(h) for h in headers]
        self.rows = []
        self.file = file or sys.stdout

    def row(self, cells):
        self.rows.append([str(c) for c in cells])

    def flush(self):
        widths = [len(h) for h in self.headers]
        for r in self.rows:
            for i, c in enumerate(r):
                widths[i] = max(widths[i], len(c))
        fmt = "  ".join(f"{{:<{w}}}" for w in widths)
        print(fmt.format(*self.headers), file=self.file)
        print("  ".join("-" * w for w in widths), file=self.file)
        for r in self.rows:
            print(fmt.format(*r), file=self.file)
