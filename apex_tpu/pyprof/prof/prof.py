"""Prof stage: enriched rows → per-op metrics + roofline estimate
(reference: apex/pyprof/prof/prof.py driving the per-family handlers, with
output.py's columnar/CSV writer).

Adds the TPU-specific columns: MXU eligibility/utilization (the reference's
Tensor-Core column) and a roofline time estimate
``max(flops/peak, bytes/bw)`` from configurable chip numbers (defaults:
v5e — 197 bf16 TFLOP/s, 819 GB/s HBM).
"""
from __future__ import annotations

import json

from .models import model_row

V5E_BF16_TFLOPS = 197.0
V5E_HBM_GBS = 819.0


def analyze_rows(rows, peak_tflops: float = V5E_BF16_TFLOPS,
                 hbm_gbs: float = V5E_HBM_GBS):
    out = []
    for row in rows:
        flops, bytes_, mxu = model_row(row)
        dtype = (row.get("dtypes") or ["float32"])[0]
        peak = peak_tflops * 1e12
        if dtype == "float32":
            peak = peak / 2  # MXU f32 throughput is half of bf16
        t_compute = flops / peak
        t_memory = bytes_ / (hbm_gbs * 1e9)
        est_us = max(t_compute, t_memory) * 1e6
        dur = row.get("dur_us")
        out.append({
            **row,
            "flops": flops,
            "bytes": bytes_,
            "ai": round(flops / bytes_, 2) if bytes_ else 0.0,
            "mxu": mxu,
            "bound": "compute" if t_compute >= t_memory else "memory",
            "est_us": round(est_us, 3),
            # measured columns (present when parse joined a profiler trace):
            # achieved TFLOP/s and fraction of the roofline estimate
            "meas_us": dur,
            "tflops": (round(flops / (dur * 1e-6) / 1e12, 3)
                       if dur else None),
            "eff": (round(est_us / dur, 3) if dur else None),
        })
    return out


def _shapes_str(row):
    return ";".join("x".join(str(d) for d in s) for s in row["shapes"][:3])


def write_columnar(rows, file, top=None):
    from .output import Table
    measured = any(r.get("meas_us") is not None for r in rows)
    cols = ["seq", "dir", "op", "scope", "shapes", "dtype", "flops",
            "bytes", "AI", "MXU", "bound", "est_us"]
    if measured:
        cols += ["meas_us", "TFLOP/s"]
    t = Table(cols, file=file)
    total_f = total_b = total_t = total_m = 0.0
    body = rows if top is None else sorted(
        rows, key=lambda r: -(r["meas_us"] if measured and r.get("meas_us")
                              else r["est_us"]))[:top]
    for r in body:
        mxu = r["mxu"]
        vals = [r["seq"], r["dir"], r["op"], r.get("scope", ""),
                _shapes_str(r), (r.get("dtypes") or ["-"])[0],
                _human(r["flops"]), _human(r["bytes"]), r["ai"],
                "-" if mxu is None else
                f"{'Y' if mxu['eligible'] else 'n'}:{mxu['util']:.2f}",
                r["bound"], r["est_us"]]
        if measured:
            vals += [r.get("meas_us") if r.get("meas_us") is not None
                     else "-",
                     r.get("tflops") if r.get("tflops") is not None else "-"]
        t.row(vals)
    n_meas = 0
    for r in rows:
        total_f += r["flops"]
        total_b += r["bytes"]
        total_t += r["est_us"]
        if r.get("meas_us") is not None:
            total_m += r["meas_us"]
            n_meas += 1
    totals = ["", "", "TOTAL", "", "", "", _human(total_f), _human(total_b),
              round(total_f / total_b, 2) if total_b else 0, "", "",
              round(total_t, 1)]
    if measured:
        # mark coverage so a partial join isn't read as "faster than
        # roofline": meas total only spans the measured rows
        cov = "" if n_meas == len(rows) else f" ({n_meas}/{len(rows)} rows)"
        totals += [f"{round(total_m, 1)}{cov}", ""]
    t.row(totals)
    t.flush()


def _human(n):
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(n) < 1000:
            return f"{n:.1f}{unit}" if unit else f"{int(n)}"
        n /= 1000.0
    return f"{n:.1f}E"


def write_csv(rows, file):
    import csv
    w = csv.writer(file)
    w.writerow(["seq", "dir", "op", "scope", "shapes", "dtype", "flops",
                "bytes", "ai", "mxu_eligible", "mxu_util", "bound",
                "est_us", "meas_us", "tflops", "eff", "callsite"])
    for r in rows:
        mxu = r["mxu"] or {}
        w.writerow([r["seq"], r["dir"], r["op"], r.get("scope", ""),
                    _shapes_str(r), (r.get("dtypes") or ["-"])[0],
                    r["flops"], r["bytes"], r["ai"],
                    mxu.get("eligible", ""), mxu.get("util", ""),
                    r["bound"], r["est_us"], r.get("meas_us", ""),
                    r.get("tflops", ""), r.get("eff", ""),
                    r.get("callsite") or ""])


def main(argv=None):
    import argparse
    import sys
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.pyprof.prof",
        description="enriched op dict -> FLOP/byte/MXU/roofline analysis")
    p.add_argument("file", help="output of python -m apex_tpu.pyprof.parse")
    p.add_argument("--csv", action="store_true")
    p.add_argument("--top", type=int, default=None,
                   help="only the N most expensive ops")
    p.add_argument("--peak-tflops", type=float, default=V5E_BF16_TFLOPS)
    p.add_argument("--hbm-gbs", type=float, default=V5E_HBM_GBS)
    args = p.parse_args(argv)
    with open(args.file) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    rows = analyze_rows(rows, args.peak_tflops, args.hbm_gbs)
    if args.csv:
        write_csv(rows, sys.stdout)
    else:
        write_columnar(rows, sys.stdout, top=args.top)


if __name__ == "__main__":
    main()
