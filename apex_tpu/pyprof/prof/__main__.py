from .prof import main

main()
