"""Measured-profile ingestion: join ``jax.profiler`` trace events against the
annotate-stage op log.

The reference's parse stage reads an nvprof SQL database and correlates GPU
kernel rows to the NVTX marker ranges that enclose them, using autograd
seq-ids for forward<->backward correlation
(/root/reference/apex/pyprof/parse/nvvp.py:91-199).  The TPU-native
equivalent has three measured inputs:

1. the annotate op log (trace-time shapes/dtypes, one ``ppN_<op>`` named
   scope per event — annotate.py);
2. the compiled program's HLO text, whose per-instruction
   ``metadata={op_name="jit(f)/jvp(ppN_op)/..."}`` carries those scopes
   through XLA's optimizer (fusion instructions keep their root's metadata);
3. a ``jax.profiler.trace`` dump, whose device/runtime lanes carry one
   complete event per executed thunk/kernel, named by HLO instruction.

The join is therefore: thunk event name -> HLO instruction -> metadata
op_name -> ``ppN`` seq id, with direction read off the ``transpose(...)``
wrapper jax puts around reverse-mode ops — the seq-id correlation of
nvvp.py:149-173 expressed in XLA metadata instead of an SQL table.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import re

# host-runtime bookkeeping events on the device lanes that are not kernels
_INFRA = ("ThreadpoolListener", "ThunkExecutor", "end: ")
# whole-program span events: "jit_step(2360695404505296586)" etc.
_PROGRAM_RE = re.compile(r"^jit_?[\w$.\-]*\(-?\d+\)$")

_SCOPE_RE = re.compile(r"pp(\d+)_")
_INSTR_RE = re.compile(
    r"%?([\w.\-]+)\s*=\s*[^\n]*metadata=\{[^}]*op_name=\"([^\"]+)\"")


def find_trace_json(path: str) -> str:
    """Locate the ``*.trace.json.gz`` under a ``jax.profiler.trace`` output
    directory (``<dir>/plugins/profile/<run>/<host>.trace.json.gz``), or
    pass a direct file path through."""
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(
        os.path.join(path, "**", "*.trace.json*"), recursive=True))
    if not hits:
        raise FileNotFoundError(
            f"no *.trace.json(.gz) found under {path!r}; pass the directory "
            f"given to jax.profiler.trace()")
    return hits[-1]  # newest run


def load_thunk_events(path: str):
    """All complete ("ph":"X") events from the trace's device/runtime lanes
    as ``{"name", "dur_us", "ts_us"}`` dicts.

    Lane selection: anything that is NOT the python host thread — TPU device
    processes are named "/device:TPU:N", the CPU backend's thunk executor
    thread "tf_XLAPjRtCpuClient/..."; python host events are prefixed "$" or
    carry python frame names and live on the thread named "python".
    """
    f = find_trace_json(path)
    opener = gzip.open if f.endswith(".gz") else open
    with opener(f, "rt") as fh:
        data = json.load(fh)
    events = data.get("traceEvents", [])

    # lane selection is positive, not negative: only device-process lanes
    # ("/device:TPU:N" on hardware) and the CPU backend's thunk-executor
    # thread count as kernel lanes.  Host TraceMe spans (PjRt execute /
    # transfer bookkeeping on arbitrary threads) would otherwise inflate
    # the unattributed total and make the join statistic meaningless.
    proc_names = {}
    thread_names = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")

    def is_kernel_lane(pid, tid):
        if proc_names.get(pid, "").startswith("/device:"):
            return True
        return "XLAPjRtCpuClient" in thread_names.get((pid, tid), "")

    out = []
    for e in events:
        if e.get("ph") != "X":
            continue
        if not is_kernel_lane(e.get("pid"), e.get("tid")):
            continue
        name = e.get("name", "")
        if name.startswith("$") or any(s in name for s in _INFRA):
            continue
        if _PROGRAM_RE.match(name) or name.isdigit():
            # whole-program umbrella spans on the device lane: named
            # "jit_step(<fingerprint>)" on one lane and by bare
            # per-execution run index ("0", "1", ...) on another — each
            # covers every thunk beneath it, so counting them
            # double-counts the entire execution as unattributed time
            # (round 4: 104ms of a 54ms resnet step)
            continue
        out.append({"name": name, "dur_us": float(e.get("dur", 0.0)),
                    "ts_us": float(e.get("ts", 0.0))})
    return out


def scope_map(hlo_text: str):
    """HLO instruction name -> metadata op_name path, for every instruction
    that carries one (fusions keep their root op's metadata, so fused
    kernels still attribute to an annotate scope)."""
    return {m.group(1): m.group(2)
            for m in _INSTR_RE.finditer(hlo_text)}


_THUNK_KIND_RE = re.compile(r"[A-Za-z_][\w\-]*?(?=[.\d]|$)")


def _thunk_kind(t, op_name):
    """Coarse category for an unattributed thunk: the HLO instruction-name
    stem ("fusion", "copy", "transpose", "convolution", "all-reduce", ...)
    or, when the instruction DID carry scope-less metadata, the last
    component of its op_name path prefixed "op:" — enough to tell layout
    transposes and copies apart from real compute in the unmatched bucket."""
    if op_name is not None:
        return "op:" + op_name.rsplit("/", 1)[-1]
    m = _THUNK_KIND_RE.match(t["name"].lstrip("%"))
    return m.group(0) if m else "other"


def correlate(thunks, smap):
    """-> (per-seq measurements, unattributed, unattributed_by) where
    measurements is ``{seq: {"fwd_us", "bwd_us", "fwd_n", "bwd_n"}}``
    summed over every execution captured in the trace and
    ``unattributed_by`` buckets the unmatched time by thunk category."""
    per_seq = {}
    unattributed_us = 0.0
    unattributed_by = {}

    def _miss(t, op_name):
        nonlocal unattributed_us
        unattributed_us += t["dur_us"]
        k = _thunk_kind(t, op_name)
        unattributed_by[k] = unattributed_by.get(k, 0.0) + t["dur_us"]

    for t in thunks:
        op_name = smap.get(t["name"])
        if op_name is None:
            _miss(t, None)
            continue
        m = _SCOPE_RE.search(op_name)
        if m is None:
            _miss(t, op_name)
            continue
        seq = int(m.group(1))
        d = per_seq.setdefault(
            seq, {"fwd_us": 0.0, "bwd_us": 0.0, "fwd_n": 0, "bwd_n": 0})
        if "transpose(" in op_name:
            d["bwd_us"] += t["dur_us"]
            d["bwd_n"] += 1
        else:
            d["fwd_us"] += t["dur_us"]
            d["fwd_n"] += 1
    return per_seq, unattributed_us, unattributed_by


def merge_measurements(rows, per_seq, executions: int = 1):
    """Attach measured per-execution durations to enriched rows (parse.py
    ``enrich`` output): fwd rows get ``dur_us`` from their seq's fwd sum,
    synthesized bwd rows from the bwd sum of the row they correlate to
    (``corr``).  Rows with no measurement keep ``dur_us=None`` (the analytic
    roofline estimate in the prof stage remains their only timing)."""
    n = max(1, executions)
    out = []
    for r in rows:
        r = dict(r)
        m = per_seq.get(r.get("corr", r.get("seq")))
        if m is None:
            r["dur_us"] = None
        elif r.get("dir") == "bwd":
            r["dur_us"] = round(m["bwd_us"] / n, 3) if m["bwd_n"] else None
        else:
            r["dur_us"] = round(m["fwd_us"] / n, 3) if m["fwd_n"] else None
        out.append(r)
    return out


def profile_step(fn, *args, trace_dir=None, executions: int = 3,
                 with_backward: bool = True):
    """One-stop measured profile of a jittable step: the TPU-native
    ``nvprof + parse`` run.

    Annotates ``fn``'s ops (annotate.init must have patched the op layer
    before ``fn``'s model/functional calls are bound), AOT-compiles it to
    capture the HLO metadata, executes it ``executions`` times under
    ``jax.profiler.trace``, and returns enriched rows carrying measured
    ``dur_us`` alongside the analytic columns.

    Returns ``(rows, report)`` where report carries the join statistics
    (matched/unmatched thunk time) — the visibility the reference gets from
    nvvp.py's per-kernel table.
    """
    import tempfile

    import jax

    from .. import annotate
    from .parse import enrich

    annotate.init()
    annotate.clear()
    annotate.set_enabled(True)
    try:
        jitted = jax.jit(fn)
        lowered = jitted.lower(*args)
    finally:
        annotate.set_enabled(False)
    events = [dict(e) for e in annotate.events()]
    compiled = lowered.compile()
    smap = scope_map(compiled.as_text())

    tmp = trace_dir or tempfile.mkdtemp(prefix="apex_tpu_pyprof_")
    try:
        with jax.profiler.trace(tmp):
            for _ in range(executions):
                out = compiled(*args)
            for leaf in jax.tree_util.tree_leaves(out):
                if hasattr(leaf, "block_until_ready"):
                    # a device->host fetch, not block_until_ready: the axon
                    # TPU platform treats block_until_ready as a no-op
                    np_leaf = leaf if leaf.size < 1e7 else leaf.ravel()[0]
                    _ = jax.device_get(np_leaf)

        thunks = load_thunk_events(tmp)
    finally:
        if trace_dir is None:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
            tmp = None
    per_seq, unattributed_us, unattributed_by = correlate(thunks, smap)
    rows = merge_measurements(
        enrich(events, with_backward=with_backward), per_seq,
        executions=executions)

    matched_us = sum(m["fwd_us"] + m["bwd_us"] for m in per_seq.values())
    report = {
        "trace_dir": tmp,
        "thunks": len(thunks),
        "matched_seqs": len(per_seq),
        "matched_us": round(matched_us, 3),
        "unattributed_us": round(unattributed_us, 3),
        "unattributed_by": {
            k: round(v, 3)
            for k, v in sorted(unattributed_by.items(),
                               key=lambda kv: -kv[1])},
        "executions": executions,
    }
    return rows, report
