from .parse import main

main()
