from .parse import enrich  # noqa: F401
