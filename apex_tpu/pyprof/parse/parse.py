"""Parse stage: raw trace events → enriched op dict (reference:
apex/pyprof/parse/{parse,nvvp}.py).

The reference joins nvprof's kernel table with enclosing NVTX ranges and
correlates backward kernels to forward ops through autograd seq ids
(nvvp.py:149-173).  Here the forward op list *is* the trace, so the
backward is synthesized analytically: every differentiable forward op
contributes its reverse-mode ops in reverse program order, with the
standard cost structure (matmul/conv → dgrad + wgrad, i.e. ~2× forward
FLOPs; pointwise/norm → ~1×).  Ops are tagged with a ``corr`` id linking
each bwd row to its fwd row — the seq-id correlation made explicit.
"""
from __future__ import annotations

import json

# ops with no gradient path (or none worth modeling); the per-family
# backward cost factors live in prof/models.py model_row
_NO_BWD = {"flatten", "pad"}


def enrich(events, with_backward: bool = True):
    """→ list of row dicts: fwd rows (trace order) then synthesized bwd rows
    (reverse order), each carrying seq/dir/corr."""
    rows = []
    for i, e in enumerate(events):
        r = dict(e)
        r["seq"] = i
        r["dir"] = "fwd"
        r["corr"] = i
        rows.append(r)
    if with_backward:
        nxt = len(rows)
        for e in reversed(rows[:]):
            op = e["op"]
            if op in _NO_BWD or op.startswith("optimizer."):
                continue
            b = dict(e)
            b["seq"] = nxt
            b["dir"] = "bwd"
            b["corr"] = e["seq"]
            b["op"] = op
            nxt += 1
            rows.append(b)
    return rows


def main(argv=None):
    import argparse
    import sys
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.pyprof.parse",
        description="raw capture (.jsonl) -> enriched op dict on stdout")
    p.add_argument("file", help="event log written by apex_tpu.pyprof.save")
    p.add_argument("--no-backward", action="store_true",
                   help="forward ops only")
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="jax.profiler.trace output dir: join measured thunk "
                        "durations onto the rows (requires --hlo)")
    p.add_argument("--hlo", default=None, metavar="FILE",
                   help="compiled HLO text (jitted.lower(...).compile()"
                        ".as_text()) for the scope<->instruction join")
    p.add_argument("--executions", type=int, default=1,
                   help="how many step executions the trace covers "
                        "(durations are reported per execution)")
    args = p.parse_args(argv)
    with open(args.file) as f:
        events = [json.loads(line) for line in f if line.strip()]
    rows = enrich(events, with_backward=not args.no_backward)
    if args.trace:
        if not args.hlo:
            p.error("--trace requires --hlo (the compiled program whose "
                    "metadata carries the annotate scopes)")
        from .trace import (correlate, load_thunk_events, merge_measurements,
                            scope_map)
        with open(args.hlo) as f:
            smap = scope_map(f.read())
        per_seq, unattributed, _ = correlate(load_thunk_events(args.trace),
                                             smap)
        rows = merge_measurements(rows, per_seq, executions=args.executions)
        print(f"# matched {len(per_seq)} ops, "
              f"unattributed {unattributed:.1f}us", file=sys.stderr)
    for row in rows:
        sys.stdout.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
