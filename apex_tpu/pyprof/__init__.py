"""apex_tpu.pyprof — profiling & op-level performance analysis.

TPU re-design of apex/pyprof (4981 LoC; SURVEY.md §3.5/§5).  The reference's
three-process pipeline — (1) NVTX-annotated run under nvprof, (2)
``python -m apex.pyprof.parse`` joining kernels to markers from the SQL
dump, (3) ``python -m apex.pyprof.prof`` applying per-op FLOP/byte models —
maps onto XLA's trace-once model as:

1. ``pyprof.nvtx.init()`` + ``pyprof.capture()`` — annotate
   apex_tpu.nn.functional at trace time (annotate.py); each op records
   shapes/dtypes/params/callsite once per compiled trace and tags the HLO
   with ``jax.named_scope`` so ``jax.profiler`` traces carry the same
   labels (no SQL join needed — the correlation the reference reconstructs
   from seq ids ships inside the HLO metadata).
2. ``python -m apex_tpu.pyprof.parse run.jsonl > net.dict`` — enrich the
   raw event log: stable seq ids, synthesized backward ops per autograd
   rules (the reference recovers bwd kernels from nvprof; under jax.grad
   the backward is derivable from the forward trace).
3. ``python -m apex_tpu.pyprof.prof net.dict`` — per-op FLOPs / bytes /
   arithmetic intensity / MXU-eligibility models and a roofline time
   estimate (prof/models.py), columnar or CSV output.

Programmatic one-shot: ``pyprof.analyze(events)`` → list of measured rows.
"""
from __future__ import annotations

import contextlib
import json

from . import annotate
from . import nvtx  # noqa: F401


@contextlib.contextmanager
def capture(clear: bool = True):
    """Enable recording for a scope; yields the (live) event list."""
    annotate.init()
    if clear:
        annotate.clear()
    annotate.set_enabled(True)
    try:
        yield annotate.events()
    finally:
        annotate.set_enabled(False)


def save(path: str, events=None):
    """Write captured events as JSON lines (the 'nvprof sql dump' stand-in
    consumed by ``python -m apex_tpu.pyprof.parse``)."""
    events = events if events is not None else annotate.events()
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return path


def profile_step(fn, *args, trace_dir=None, executions: int = 3,
                 with_backward: bool = True, analyze_output: bool = True):
    """Measured profile of a jittable step: annotate, compile, execute under
    ``jax.profiler.trace``, join thunk timings to ops through the HLO
    metadata (parse/trace.py), and run the prof-stage models.

    Returns ``(rows, report)``: rows carry both the analytic columns
    (flops/bytes/roofline est_us) and measured ``meas_us``/achieved TFLOP/s;
    report holds the join statistics."""
    from .parse.trace import profile_step as _ps
    rows, report = _ps(fn, *args, trace_dir=trace_dir,
                       executions=executions, with_backward=with_backward)
    if analyze_output:
        from .prof.prof import analyze_rows
        rows = analyze_rows(rows)
    return rows, report


def analyze(events=None, with_backward: bool = True):
    """events → analyzed rows (parse + prof stages fused, in process)."""
    from .parse.parse import enrich
    from .prof.prof import analyze_rows
    events = events if events is not None else annotate.events()
    return analyze_rows(enrich(events, with_backward=with_backward))


_thunk_capability = None


def thunk_events_available() -> bool:
    """One-shot runtime probe: does ``jax.profiler.trace`` on THIS
    backend/jaxlib emit per-thunk duration events?

    CPU jaxlib (0.4.x) writes the trace plugin's metadata but no thunk
    timings, which left the measured-profile pipeline dead behind two
    xfail'd tests.  The probe runs one trivial jitted function under a
    trace into a tempdir and checks whether ``parse.trace`` can extract
    any duration-carrying thunk events — callers (and the test suite)
    gate the measured path on the answer instead of guessing from
    platform names.  Result is cached for the process; any probe failure
    (no profiler, no writable tmp) counts as "not available".
    """
    global _thunk_capability
    if _thunk_capability is None:
        _thunk_capability = _probe_thunk_events()
    return _thunk_capability


def _probe_thunk_events() -> bool:
    import tempfile

    from .parse.trace import find_trace_json, load_thunk_events
    try:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def _probe(x):
            return (x * x).sum()

        _probe(jnp.ones((8, 8))).block_until_ready()
        with tempfile.TemporaryDirectory() as d:
            with jax.profiler.trace(d):
                _probe(jnp.ones((8, 8))).block_until_ready()
            # find_trace_json raises FileNotFoundError when the trace
            # plugin wrote nothing — caught below as "not available"
            thunks = load_thunk_events(find_trace_json(d))
            return any(t.get("dur_us", 0) > 0 for t in thunks)
    except Exception:
        return False


__all__ = ["annotate", "nvtx", "capture", "save", "analyze",
           "thunk_events_available"]
