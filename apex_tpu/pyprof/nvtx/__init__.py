"""Reference-API shim: ``pyprof.nvtx.init()`` (apex/pyprof/nvtx/nvmarker.py).

The name is kept for drop-in parity; on TPU the "marker" is the trace-time
annotator + ``jax.named_scope`` HLO tagging (see ..annotate).
"""
from ..annotate import init, set_enabled, events, clear  # noqa: F401

__all__ = ["init", "set_enabled", "events", "clear"]
