"""Reference-API shim: ``pyprof.nvtx.init()`` (apex/pyprof/nvtx/nvmarker.py).

The name is kept for drop-in parity; on TPU the "marker" is the trace-time
annotator + ``jax.named_scope`` HLO tagging (see ..annotate).

``nvtx.annotate("region")`` — the reference's range-marker context manager
(torch.cuda.nvtx.range_push/pop) — delegates to
:func:`apex_tpu.observe.span`: one runtime span surface feeds both the
structured event log and ``jax.profiler.TraceAnnotation``, so pyprof
markers and the rest of the library's telemetry land in the same stream.
"""
from ..annotate import init, set_enabled, events, clear  # noqa: F401


def annotate(name: str, **fields):
    """Range marker context manager: ``with nvtx.annotate("fwd"): ...``.

    Delegates to ``apex_tpu.observe.span`` — emits a ``span`` event into
    the observe registry and a profiler TraceAnnotation.  Host-side only
    (OBS-IN-JIT applies), like the nvtx ranges it mimics.
    """
    from ...observe import span as _span
    return _span(name, **fields)


__all__ = ["init", "set_enabled", "events", "clear", "annotate"]
