"""Policy-aware functional ops — the framework's op vocabulary.

Every op consults the active amp cast policy (apex_tpu.amp.policy) at trace
time, giving the reference's O1 behavior (whitelist→half, blacklist→fp32,
promote, banned — apex/amp/lists/) without monkey-patching.  All ops are pure
jnp/lax and jit-friendly; convs and matmuls rely on the MXU's native
fp32 accumulation for half-precision inputs (XLA's default on TPU;
``preferred_element_type`` is deliberately NOT used because its fp32 outputs
break the conv transpose rule under autodiff with half weights).
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..amp.policy import apply_op_policy

Array = jax.Array


def _policied(op_name):
    """Decorator: run the op with args cast per the active amp policy."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            args, kwargs = apply_op_policy(op_name, args, kwargs)
            return fn(*args, **kwargs)
        wrapper._op_name = op_name
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# MXU ops (half list)
# ---------------------------------------------------------------------------

@_policied("linear")
def linear(x: Array, weight: Array, bias: Optional[Array] = None) -> Array:
    """x @ W^T + b with torch Linear weight layout (out, in)."""
    y = jnp.matmul(x, weight.T)
    if bias is not None:
        y = y + bias
    return y


@_policied("matmul")
def matmul(a: Array, b: Array) -> Array:
    return jnp.matmul(a, b)


def _conv_dn(ndim):
    # torch layout: input NCHW, kernel OIHW
    if ndim == 1:
        return ("NCH", "OIH", "NCH")
    if ndim == 2:
        return ("NCHW", "OIHW", "NCHW")
    return ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, ndim,
          transposed=False, output_padding=0, channels_last=False):
    if isinstance(stride, int):
        stride = (stride,) * ndim
    if isinstance(dilation, int):
        dilation = (dilation,) * ndim
    if isinstance(padding, int):
        padding = ((padding, padding),) * ndim
    elif isinstance(padding, (tuple, list)) and padding and \
            isinstance(padding[0], int):
        padding = tuple((p, p) for p in padding)
    if channels_last:
        # NHWC activations with the torch OIHW kernel: the MXU wants
        # channels on the minor (lane) dimension, and NHWC keeps them
        # there end-to-end with no layout transposes between ops (the
        # reference's channel-last path, apex/contrib/groupbn).  Kernels
        # stay OIHW so checkpoints are layout-independent — XLA picks
        # its own internal kernel layout either way.
        if transposed or ndim != 2:
            raise ValueError(
                "channels_last is supported for 2-d forward convs")
        spec = ("NHWC", "OIHW", "NHWC")
    else:
        spec = _conv_dn(ndim)
    if transposed:
        # expressed as an input-dilated forward conv (lhs_dilation=stride),
        # which unlike lax.conv_transpose supports feature groups.  torch
        # transposed-conv weight is (C_in, C_out/g, *k); the equivalent
        # forward conv needs (C_out, C_in/g, *k) with spatial flip: regroup
        # (g, C_in/g, C_out/g) -> (g, C_out/g, C_in/g).
        if isinstance(output_padding, int):
            output_padding = (output_padding,) * ndim
        c_in, c_out_g = weight.shape[:2]
        k = weight.shape[2:]
        w = weight.reshape((groups, c_in // groups, c_out_g) + k)
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((groups * c_out_g, c_in // groups) + k)
        w = jnp.flip(w, axis=tuple(range(2, 2 + ndim)))
        pads = []
        for i in range(ndim):
            eff_k = (k[i] - 1) * dilation[i] + 1
            lo = eff_k - 1 - padding[i][0]
            hi = eff_k - 1 - padding[i][1] + output_padding[i]
            pads.append((lo, hi))
        dn = lax.conv_dimension_numbers(x.shape, w.shape, spec)
        y = lax.conv_general_dilated(
            x, w, window_strides=(1,) * ndim, padding=pads,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
    else:
        dn = lax.conv_dimension_numbers(x.shape, weight.shape, spec)
        y = lax.conv_general_dilated(
            x, weight, window_strides=stride, padding=padding,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
    if bias is not None:
        y = y + (bias if channels_last
                 else bias.reshape((1, -1) + (1,) * ndim))
    return y


@_policied("conv1d")
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1)


@_policied("conv2d")
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           channels_last=False):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 channels_last=channels_last)


@_policied("conv3d")
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3)


@_policied("conv_transpose2d")
def conv_transpose2d(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1):
    # torch transposed-conv kernel layout (in, out/g, kH, kW); _conv
    # regroups/flips it into the equivalent input-dilated forward conv
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 transposed=True, output_padding=output_padding)


# ---------------------------------------------------------------------------
# Normalization (float list)
# ---------------------------------------------------------------------------

_warned_bn_axes = set()


def _warn_unbound_bn_axis(axis_name):
    if axis_name not in _warned_bn_axes:
        _warned_bn_axes.add(axis_name)
        import warnings
        warnings.warn(
            f"SyncBatchNorm: mesh axis {axis_name!r} is not bound; falling "
            "back to local-batch statistics. This is expected (and correct) "
            "under jit with a sharded batch, but if you are inside shard_map "
            "with a differently-named axis, pass that name via "
            "SyncBatchNorm(axis_name=...).")

@_policied("batch_norm")
def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.1, eps=1e-5,
               axis_name=None, axis_index_groups=None, return_stats=False,
               channel_axis=1):
    """torch-semantics batch norm over ``channel_axis`` (default 1,
    NC...; pass -1 for channel-last NHWC activations — the reference's
    channel_last groupbn/syncbn layout).

    When ``axis_name`` is given and we are inside a mapped axis, batch
    statistics are averaged across that mesh axis — this is the SyncBatchNorm
    collective path (reference: apex/parallel/optimized_sync_batchnorm_kernel.py:30-45,
    all_gather + welford merge; here a psum of (sum, sqsum, count) is the
    TPU-native equivalent).  Returns (y, new_running_mean, new_running_var).
    """
    channel_axis = channel_axis % x.ndim
    reduce_axes = tuple(a for a in range(x.ndim) if a != channel_axis)
    shape = tuple(x.shape[a] if a == channel_axis else 1
                  for a in range(x.ndim))
    xf = x.astype(jnp.float32)
    if training:
        local_count = 1
        for a in reduce_axes:
            local_count *= x.shape[a]
        # shifted two-pass locally (E[x^2]-mean^2 cancels catastrophically
        # for large-mean activations), then a Welford merge of per-replica
        # (mean, M2) — the same scheme as the reference's welford_parallel
        # (csrc/welford.cu, optimized_sync_batchnorm_kernel.py:32-45)
        mean = jnp.mean(xf, axis=reduce_axes)
        m2 = jnp.sum(jnp.square(xf - mean.reshape(shape)),
                     axis=reduce_axes)
        count = jnp.asarray(local_count, jnp.float32)
        if axis_name is not None:
            try:
                # per-replica counts are equal under SPMD (same local
                # shapes), so the uniform-count merge is exact
                means = lax.all_gather(mean, axis_name,
                                       axis_index_groups=axis_index_groups)
                m2s = lax.all_gather(m2, axis_name,
                                     axis_index_groups=axis_index_groups)
                group = means.shape[0]
                mean = jnp.mean(means, axis=0)
                m2 = jnp.sum(m2s, axis=0) + local_count * jnp.sum(
                    jnp.square(means - mean), axis=0)
                count = count * group
            except NameError:
                # Axis not bound: not running under shard_map/pmap.  Under
                # automatic SPMD (jit + sharded batch) local stats already
                # ARE global-batch stats, so degrading is correct there —
                # but under shard_map with a differently-named axis it would
                # silently break sync, so say something.
                _warn_unbound_bn_axis(axis_name)
        var = m2 / count  # biased, used for normalization
        # unbiased variance feeds the running stats (reference
        # sync_batchnorm.py:114-121)
        unbiased = var * (count / jnp.maximum(count - 1.0, 1.0))
        new_rm = (1 - momentum) * running_mean + momentum * mean \
            if running_mean is not None else None
        new_rv = (1 - momentum) * running_var + momentum * unbiased \
            if running_var is not None else None
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    y = (xf - mean.reshape(shape)) * inv.reshape(shape)
    if weight is not None:
        y = y * weight.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    if return_stats:
        # (group-)minibatch mean and 1/sqrt(var+eps), as the reference's
        # groupbn kernels expose via minibatch_mean/minibatch_riv
        return y.astype(x.dtype), new_rm, new_rv, mean, inv
    return y.astype(x.dtype), new_rm, new_rv


@_policied("group_norm")
def group_norm(x, num_groups, weight=None, bias=None, eps=1e-5):
    """torch.nn.functional.group_norm semantics: x (N, C, *spatial),
    statistics over each group's channels+spatial, per-channel affine."""
    n, c = x.shape[0], x.shape[1]
    if c % num_groups:
        raise ValueError(
            f"group_norm: channels ({c}) not divisible by num_groups "
            f"({num_groups})")
    xf = x.astype(jnp.float32).reshape((n, num_groups, c // num_groups)
                                       + x.shape[2:])
    axes = tuple(range(2, xf.ndim))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = ((xf - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    pshape = (1, c) + (1,) * (x.ndim - 2)
    if weight is not None:
        y = y * weight.astype(jnp.float32).reshape(pshape)
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(pshape)
    return y.astype(x.dtype)


@_policied("instance_norm")
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.1, eps=1e-5):
    """torch.nn.functional.instance_norm semantics: per-sample per-channel
    statistics over spatial dims.  Returns (y, new_rm, new_rv) — running
    stats (when tracked) average instance stats over the batch, matching
    torch's train-mode bookkeeping."""
    axes = tuple(range(2, x.ndim))
    spatial = 1
    for a in axes:
        spatial *= x.shape[a]
    if use_input_stats and spatial <= 1:
        # per-instance variance over <=1 element is 0: the output would
        # silently collapse to the bias (torch raises the same way)
        raise ValueError(
            f"instance_norm: expected more than 1 spatial element when "
            f"computing input stats, got input shape {tuple(x.shape)}")
    xf = x.astype(jnp.float32)
    if use_input_stats:
        mean = jnp.mean(xf, axis=axes, keepdims=True)       # (N, C, 1...)
        var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
        new_rm = new_rv = None
        if running_mean is not None:
            count = 1.0
            for a in axes:
                count *= x.shape[a]
            unbiased = var * (count / max(count - 1.0, 1.0))
            new_rm = (1 - momentum) * running_mean \
                + momentum * jnp.mean(mean, axis=0).reshape(-1)
            new_rv = (1 - momentum) * running_var \
                + momentum * jnp.mean(unbiased, axis=0).reshape(-1)
    else:
        shape = (1, -1) + (1,) * (x.ndim - 2)
        mean = running_mean.reshape(shape)
        var = running_var.reshape(shape)
        new_rm, new_rv = running_mean, running_var
    y = (xf - mean) * lax.rsqrt(var + eps)
    pshape = (1, -1) + (1,) * (x.ndim - 2)
    if weight is not None:
        y = y * weight.astype(jnp.float32).reshape(pshape)
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(pshape)
    return y.astype(x.dtype), new_rm, new_rv


@_policied("layer_norm")
def layer_norm(x, normalized_shape, weight=None, bias=None, eps=1e-5):
    n = len(normalized_shape) if isinstance(normalized_shape, (tuple, list)) \
        else 1
    axes = tuple(range(x.ndim - n, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# Activations (match-input unless listed)
# ---------------------------------------------------------------------------

def relu(x):
    return jnp.maximum(x, 0)


def leaky_relu(x, negative_slope=0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


@_policied("gelu")
def gelu(x, approximate="tanh"):
    return jax.nn.gelu(x, approximate=(approximate == "tanh"))


def silu(x):
    """x * sigmoid(x) (a.k.a. swish) — the Llama-family gate
    activation."""
    return jax.nn.silu(x)


def tanh(x):
    return jnp.tanh(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


@_policied("softmax")
def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@_policied("log_softmax")
def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def _dropout_impl():
    """Mask-bit source: ``rbg`` (default) uses XLA's RngBitGenerator — the
    platform's hardware generator, ~10x cheaper than threefry on TPU where
    counter-based hashing burns VPU cycles (measured: GPT-2-small spends
    ~11% of its 92ms train step on threefry masks alone).  ``threefry``
    restores jax.random.bernoulli: bit-identical masks across platforms, at
    generation cost.  Masks are deterministic per key under both."""
    import os
    impl = os.environ.get("APEX_TPU_DROPOUT_IMPL", "rbg")
    if impl not in ("rbg", "threefry"):
        raise ValueError(
            f"APEX_TPU_DROPOUT_IMPL={impl!r}: valid values are 'rbg' "
            f"(fast, per-key deterministic within a process) and "
            f"'threefry' (bit-reproducible across platforms)")
    return impl


def _rbg_seed(key):
    """128-bit RngBitGenerator state from a jax PRNG key (raw uint32[2]
    arrays and typed keys both accepted)."""
    data = key
    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        if key.shape != ():
            raise ValueError(
                f"dropout accepts a single PRNG key, got key array of "
                f"shape {key.shape}; use jax.vmap for batched masks")
        data = jax.random.key_data(key)
    if data.ndim != 1 or data.shape[0] not in (1, 2, 4):
        raise ValueError(
            f"dropout accepts a single PRNG key (1, 2 or 4 words of key "
            f"data), got shape {data.shape} — a stacked key array? "
            f"use jax.vmap for batched masks")
    data = data.astype(jnp.uint32)
    if data.shape[0] < 4:
        data = jnp.concatenate(
            [data, jnp.zeros((4 - data.shape[0],), jnp.uint32)])
    return data[:4]


def dropout_mask(key, keep, shape):
    """Boolean keep-mask with P(keep) = ``keep``.

    Deterministic per key within a process: repeated calls with the same
    key and shape return the same mask (this is what the autograd tape's
    backward replay needs, and the jitted train step computes the mask once
    — it reaches backward as a residual, so fwd/bwd consistency there is
    structural).  The rbg bit stream is NOT guaranteed stable across
    backends, compiler versions, or SPMD partitionings; for bit-exact
    reproducibility across those, set APEX_TPU_DROPOUT_IMPL=threefry.
    ``keep`` may be a python float or a traced scalar."""
    if _dropout_impl() == "threefry":
        return jax.random.bernoulli(key, keep, shape)
    _, bits = lax.rng_bit_generator(_rbg_seed(key), shape, dtype=jnp.uint32)
    if isinstance(keep, (int, float)):
        # concrete: exact threshold, P(bits < t) = t / 2^32 (keep quantized
        # to 2^-32); degenerate endpoints match bernoulli exactly
        if keep >= 1.0:
            return jnp.ones(shape, bool)
        if keep <= 0.0:
            return jnp.zeros(shape, bool)
        return bits < jnp.uint32(min(round(keep * 2 ** 32), 2 ** 32 - 1))
    # traced: float32 threshold (probability quantized to ~2^-24), clamped
    # below 2^32 so the uint32 cast cannot overflow; keep >= 1 keeps all
    keep_f = keep.astype(jnp.float32)
    tf = jnp.minimum(keep_f * jnp.float32(2 ** 32), jnp.float32(2 ** 32 - 256))
    return (bits < tf.astype(jnp.uint32)) | (keep_f >= 1.0)


def dropout(x, p=0.5, training=True, key=None):
    if not training or p == 0.0:
        return x
    if key is None:
        raise ValueError("dropout in training mode requires a PRNG key")
    keep = 1.0 - p
    mask = dropout_mask(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------

def _pool_dims(kernel_size, stride, padding, channels_last):
    """(window, strides, pads) for 2-d pooling in NCHW or NHWC."""
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    if channels_last:
        return ((1,) + tuple(kernel_size) + (1,),
                (1,) + tuple(stride) + (1,),
                ((0, 0),) + tuple(padding) + ((0, 0),),
                kernel_size)
    return ((1, 1) + tuple(kernel_size), (1, 1) + tuple(stride),
            ((0, 0), (0, 0)) + tuple(padding), kernel_size)


def max_pool2d(x, kernel_size, stride=None, padding=0, channels_last=False):
    window, strides, pads, _ = _pool_dims(kernel_size, stride, padding,
                                          channels_last)
    # init must stay a Python scalar: a traced/committed array init stops
    # JAX recognizing the max monoid, breaking reverse AD under jit
    neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, neg_inf, lax.max, window, strides, pads)


def avg_pool2d(x, kernel_size, stride=None, padding=0, channels_last=False):
    window, strides, pads, kernel_size = _pool_dims(
        kernel_size, stride, padding, channels_last)
    s = lax.reduce_window(
        x.astype(jnp.float32), 0.0, lax.add, window, strides, pads)
    return (s / (kernel_size[0] * kernel_size[1])).astype(x.dtype)


def _adaptive_pool_matrix(in_size, out_size):
    """(out, in) row-stochastic averaging matrix with torch's adaptive
    windows: bin i covers [floor(i*in/out), ceil((i+1)*in/out))."""
    import numpy as np
    m = np.zeros((out_size, in_size), np.float32)
    for i in range(out_size):
        s = (i * in_size) // out_size
        e = -((-(i + 1) * in_size) // out_size)
        m[i, s:e] = 1.0 / (e - s)
    return jnp.asarray(m)


def adaptive_avg_pool2d(x, output_size=(1, 1), channels_last=False):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    hd, wd = ((1, 2) if channels_last else (2, 3))
    h, w = x.shape[hd], x.shape[wd]
    oh = h if output_size[0] is None else output_size[0]
    ow = w if output_size[1] is None else output_size[1]
    x32 = x.astype(jnp.float32)
    if (oh, ow) == (1, 1):
        return jnp.mean(x32, axis=(hd, wd), keepdims=True).astype(x.dtype)
    # non-uniform adaptive windows as two small matmuls (static shapes,
    # MXU-friendly; uniform stride cases fuse to the same thing)
    if channels_last:
        y = jnp.einsum("nhwc,ph->npwc", x32, _adaptive_pool_matrix(h, oh))
        y = jnp.einsum("npwc,qw->npqc", y, _adaptive_pool_matrix(w, ow))
    else:
        y = jnp.einsum("nchw,ph->ncpw", x32, _adaptive_pool_matrix(h, oh))
        y = jnp.einsum("ncpw,qw->ncpq", y, _adaptive_pool_matrix(w, ow))
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# Losses (float list)
# ---------------------------------------------------------------------------

def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


@_policied("cross_entropy")
def cross_entropy(logits, target, weight=None, reduction="mean",
                  label_smoothing=0.0):
    """Softmax cross entropy with integer class targets (torch semantics:
    logits (N, C, ...), target (N, ...)).

    One traced-semantics divergence from torch: an OUT-OF-RANGE target
    (negative or >= C) cannot raise under jit — ``one_hot`` zeroes it,
    so the row contributes 0 loss (the optax convention).  A training
    loss that sits near 0 from step one usually means a class-count /
    label-range mismatch, not a converged model."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=1)
    tgt = jax.nn.one_hot(target, logits.shape[1], axis=1, dtype=logp.dtype)
    if label_smoothing > 0.0:
        # mask-aware smoothing: columns at the -1e30 masked-vocab
        # convention (pad_vocab_multiple heads, nucleus_filter) get no
        # smoothing mass and the divisor counts only valid columns —
        # otherwise q = s/C would multiply their ~-1e30 log-probs into
        # the loss.  Plain logits never reach the threshold, so
        # torch-parity semantics are unchanged for unmasked inputs.
        from ..kernels.dispatch import MASKED_LOGIT_THR
        valid = (logits > MASKED_LOGIT_THR).astype(logp.dtype)
        nv = jnp.maximum(valid.sum(axis=1, keepdims=True), 1.0)
        tgt = tgt * (1.0 - label_smoothing) \
            + (label_smoothing / nv) * valid
    nll = -(tgt * logp).sum(axis=1)
    if weight is not None:
        w = weight[target]
        nll = nll * w
        if reduction == "mean":
            return jnp.sum(nll) / jnp.sum(w)
    return _reduce(nll, reduction)


@_policied("nll_loss")
def nll_loss(logp, target, reduction="mean"):
    nll = -jnp.take_along_axis(logp, target[:, None], axis=1)[:, 0]
    return _reduce(nll, reduction)


@_policied("mse_loss")
def mse_loss(input, target, reduction="mean"):
    return _reduce(jnp.square(input - target), reduction)


@_policied("l1_loss")
def l1_loss(input, target, reduction="mean"):
    return _reduce(jnp.abs(input - target), reduction)


@_policied("binary_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(logits, target, reduction="mean"):
    logits = logits.astype(jnp.float32)
    loss = jnp.maximum(logits, 0) - logits * target + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return _reduce(loss, reduction)


@_policied("binary_cross_entropy")
def binary_cross_entropy(probs, target, reduction="mean"):
    # reaching here at all means the policy allowed it (allow_banned)
    probs = probs.astype(jnp.float32)
    eps = 1e-12
    loss = -(target * jnp.log(probs + eps)
             + (1 - target) * jnp.log(1 - probs + eps))
    return _reduce(loss, reduction)


# ---------------------------------------------------------------------------
# Misc
# ---------------------------------------------------------------------------

def embedding(ids, weight):
    return weight[ids]


def flatten(x, start_dim=1):
    return x.reshape(x.shape[:start_dim] + (-1,))


def pad(x, pad_width, value=0.0):
    return jnp.pad(x, pad_width, constant_values=value)
