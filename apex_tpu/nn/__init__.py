from . import functional  # noqa: F401
from .parameter import Parameter, is_param, param_grads, param_values  # noqa: F401
from .modules import (  # noqa: F401
    AdaptiveAvgPool2d, AvgPool2d, BatchNorm1d, BatchNorm2d, BatchNorm3d,
    BCELoss, BCEWithLogitsLoss, Buffer, Conv1d, Conv2d, Conv3d,
    ConvTranspose2d, CrossEntropyLoss, Ctx, Dropout, Embedding, Flatten,
    GELU, GroupNorm, Identity, InstanceNorm1d, InstanceNorm2d,
    InstanceNorm3d, L1Loss, LayerNorm, LeakyReLU, Linear, MaxPool2d,
    Module, ModuleList, MSELoss, NLLLoss, ReLU, Sequential, Sigmoid,
    Softmax, Tanh, _BatchNorm, checkpoint_forward, fold_shard_into_key,
    manual_seed, to_channels_last)
