from .parameter import Parameter, is_param, param_grads, param_values  # noqa: F401
