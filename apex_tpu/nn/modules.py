"""torch-shaped stateful modules over a pure functional execution core.

A Module owns Parameters/Buffers (mutable handles on jax Arrays) and defines
``forward(ctx, x)`` in terms of ``apex_tpu.nn.functional`` ops, reading every
parameter through ``ctx.value(param)``.  The Ctx indirection is what makes the
stateful API differentiable and jittable: the autograd tape (and the fused
train-step builder) re-run ``forward`` with tracer arrays substituted for the
stored values, while plain eager calls read ``param.data`` directly.

This replaces the reference's reliance on torch.nn (Apex wraps/patches torch
modules; we are standalone) — the API mirrors torch so Apex users can port
models mechanically.
"""
from __future__ import annotations

import math
from collections import OrderedDict
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp

from . import functional as F
from .parameter import Parameter
from ..inference.quant import QuantTensor

# lazy: creating a PRNGKey at import would initialize the device backend
# (and open the TPU connection) for every process that merely imports the
# package — e.g. the offline pyprof CLIs
_global_seed = [None]


def manual_seed(seed: int):
    _global_seed[0] = jax.random.PRNGKey(seed)


def _next_key():
    if _global_seed[0] is None:
        _global_seed[0] = jax.random.PRNGKey(0)
    _global_seed[0], sub = jax.random.split(_global_seed[0])
    return sub


class Buffer:
    """Non-trainable module state (e.g. BN running stats)."""
    __slots__ = ("data",)

    def __init__(self, data):
        self.data = jnp.asarray(data)

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype


class Ctx:
    """Execution context threaded through forward passes.

    env maps id(Parameter/Buffer) -> substituted array (autodiff/jit);
    stats_out, when a dict, collects new buffer values instead of writing
    them eagerly (pure mode); key supplies dropout randomness; aux_losses
    collects scalar auxiliary objectives modules add during forward (e.g.
    the Switch-MoE load-balancing loss) — the fused train step sums them
    into the optimized loss (training/step.py).
    """
    __slots__ = ("env", "stats_out", "training", "key", "_key_idx",
                 "aux_losses", "shared_key")

    def __init__(self, env=None, stats_out=None, training=False, key=None):
        self.env = env or {}
        self.stats_out = stats_out
        self.training = training
        self.key = key
        self._key_idx = 0
        self.aux_losses = []
        # the key as it was BEFORE the innermost fold_shard_into_key:
        # replicated across that shard axis.  Ring-attention dropout
        # draws from it so the mask is identical on every sequence
        # shard (bit-consistent with the single-device run), while
        # ordinary dropout keeps drawing from the folded key.
        self.shared_key = None

    def add_aux_loss(self, value):
        """Record a scalar auxiliary loss term (differentiable; gradients
        flow to whatever produced it when the step adds it to the task
        loss)."""
        self.aux_losses.append(value)

    def raw(self, p):
        """Resolve a Parameter to its RAW substituted value — env entry,
        derived recompute, or ``p.data`` — WITHOUT the QuantTensor
        dequantization.  The single resolution path shared by ``value``
        and int8-aware consumers (inference/quant.py gather_rows)."""
        v = self.env.get(id(p))
        if v is None:
            d = getattr(p, "_derived", None)
            if d is not None:
                # derived (reparameterized) parameter: compute from its
                # source parameters through this ctx so autodiff reaches
                # them
                return d(self)
            v = p.data
        return v

    def value(self, p):
        v = self.raw(p)
        if isinstance(v, QuantTensor):
            # int8-quantized weight (inference/quant.py): dequantize at
            # the point of use — XLA fuses the multiply into the
            # consuming matmul, so only int8 bytes cross HBM
            return v.dequant()
        return v

    def write_stat(self, buf: Buffer, value):
        if self.stats_out is None:
            buf.data = value
        else:
            self.stats_out[id(buf)] = value

    def next_key(self):
        if self.key is None:
            raise ValueError("this forward needs randomness (dropout); run "
                             "in training mode via the tape or pass a key")
        self._key_idx += 1
        return jax.random.fold_in(self.key, self._key_idx)


class Module:
    def __init__(self):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_modules", OrderedDict())
        object.__setattr__(self, "training", True)

    # -- registration ------------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self._parameters[name] = value
        elif isinstance(value, Buffer):
            self._buffers[name] = value
        elif isinstance(value, Module):
            self._modules[name] = value
        object.__setattr__(self, name, value)

    def register_parameter(self, name, param: Optional[Parameter]):
        if param is None:
            self._parameters.pop(name, None)
            object.__setattr__(self, name, None)
        else:
            setattr(self, name, param)

    def register_buffer(self, name, buf):
        if buf is None:
            self._buffers.pop(name, None)
            object.__setattr__(self, name, None)
        else:
            setattr(self, name, buf if isinstance(buf, Buffer) else Buffer(buf))

    # -- traversal ---------------------------------------------------------
    def named_modules(self, prefix="") -> Iterator[Tuple[str, "Module"]]:
        yield prefix, self
        for name, child in self._modules.items():
            sub = f"{prefix}.{name}" if prefix else name
            yield from child.named_modules(sub)

    def modules(self):
        for _, m in self.named_modules():
            yield m

    def children(self):
        return iter(self._modules.values())

    def named_children(self):
        return iter(self._modules.items())

    def named_parameters(self, prefix="") -> Iterator[Tuple[str, Parameter]]:
        for mod_name, mod in self.named_modules(prefix):
            for p_name, p in mod._parameters.items():
                yield (f"{mod_name}.{p_name}" if mod_name else p_name), p

    def parameters(self):
        for _, p in self.named_parameters():
            yield p

    def named_buffers(self, prefix=""):
        for mod_name, mod in self.named_modules(prefix):
            for b_name, b in mod._buffers.items():
                yield (f"{mod_name}.{b_name}" if mod_name else b_name), b

    def buffers(self):
        for _, b in self.named_buffers():
            yield b

    def apply(self, fn):
        for m in self.modules():
            fn(m)
        return self

    # -- modes / casting ---------------------------------------------------
    def train(self, mode=True):
        for m in self.modules():
            object.__setattr__(m, "training", mode)
        return self

    def eval(self):
        return self.train(False)

    def _cast_params(self, dtype, predicate=None):
        # like torch Module.to/half: float params AND float buffers are cast
        for m in self.modules():
            if predicate is not None and not predicate(m):
                continue
            for name, p in m._parameters.items():
                if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
                    p.data = p.data.astype(dtype)
            for name, b in m._buffers.items():
                if b is not None and jnp.issubdtype(b.dtype, jnp.floating):
                    b.data = b.data.astype(dtype)
        return self

    def to(self, dtype):
        return self._cast_params(dtype)

    def half(self):
        return self.to(jnp.float16)

    def bfloat16(self):
        return self.to(jnp.bfloat16)

    def float(self):
        return self.to(jnp.float32)

    # -- checkpointing -----------------------------------------------------
    def state_dict(self):
        sd = OrderedDict()
        for name, p in self.named_parameters():
            sd[name] = p.data
        for name, b in self.named_buffers():
            sd[name] = b.data
        return sd

    def load_state_dict(self, sd, strict=True):
        own = dict(self.named_parameters())
        own_buf = dict(self.named_buffers())
        missing = [k for k in list(own) + list(own_buf) if k not in sd]
        unexpected = [k for k in sd if k not in own and k not in own_buf]
        if strict and (missing or unexpected):
            raise RuntimeError(
                f"Error(s) in loading state_dict: missing {missing}, "
                f"unexpected {unexpected}")
        for k, v in sd.items():
            if k in own:
                own[k].data = jnp.asarray(v, own[k].dtype)
            elif k in own_buf:
                own_buf[k].data = jnp.asarray(v, own_buf[k].dtype)
        return self

    # -- execution ---------------------------------------------------------
    def forward(self, ctx: Ctx, *inputs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        from ..autograd import record_module_call
        return record_module_call(self, inputs, kwargs)

    def extra_repr(self):
        return ""

    def __repr__(self):
        lines = [f"{type(self).__name__}({self.extra_repr()}"]
        for name, child in self._modules.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        return "\n".join(lines) + ")" if len(lines) > 1 else lines[0] + ")"


# ---------------------------------------------------------------------------
# Leaf layers (torch init conventions: kaiming-uniform weights,
# 1/sqrt(fan_in) bias bounds)
# ---------------------------------------------------------------------------

def _kaiming_uniform(key, shape, fan_in, a=math.sqrt(5)):
    gain = math.sqrt(2.0 / (1 + a * a))
    bound = gain * math.sqrt(3.0 / fan_in)
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound)


class Linear(Module):
    def __init__(self, in_features, out_features, bias=True):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = Parameter(
            _kaiming_uniform(_next_key(), (out_features, in_features),
                             in_features))
        if bias:
            bound = 1 / math.sqrt(in_features)
            self.bias = Parameter(jax.random.uniform(
                _next_key(), (out_features,), jnp.float32, -bound, bound))
        else:
            self.register_parameter("bias", None)

    def forward(self, ctx, x):
        b = ctx.value(self.bias) if self.bias is not None else None
        return F.linear(x, ctx.value(self.weight), b)

    def extra_repr(self):
        return f"in={self.in_features}, out={self.out_features}"


class _ConvNd(Module):
    _fn = None
    _ndim = 2

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias=True):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * self._ndim
        self.in_channels, self.out_channels = in_channels, out_channels
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.dilation, self.groups = padding, dilation, groups
        fan_in = in_channels // groups
        for k in kernel_size:
            fan_in *= k
        self.weight = Parameter(_kaiming_uniform(
            _next_key(),
            (out_channels, in_channels // groups) + kernel_size, fan_in))
        if bias:
            bound = 1 / math.sqrt(fan_in)
            self.bias = Parameter(jax.random.uniform(
                _next_key(), (out_channels,), jnp.float32, -bound, bound))
        else:
            self.register_parameter("bias", None)

    # flipped to True by to_channels_last() on 2-d convs: activations
    # are NHWC, the stored OIHW kernel is layout-independent
    channels_last = False

    def forward(self, ctx, x):
        b = ctx.value(self.bias) if self.bias is not None else None
        kw = {"channels_last": True} if self.channels_last else {}
        return type(self)._fn(
            x, ctx.value(self.weight), b, stride=self.stride,
            padding=self.padding, dilation=self.dilation,
            groups=self.groups, **kw)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={self.kernel_size}, stride={self.stride}")


class Conv1d(_ConvNd):
    _fn = staticmethod(F.conv1d)
    _ndim = 1


class Conv2d(_ConvNd):
    _fn = staticmethod(F.conv2d)
    _ndim = 2


class Conv3d(_ConvNd):
    _fn = staticmethod(F.conv3d)
    _ndim = 3


class ConvTranspose2d(Module):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, bias=True,
                 dilation=1):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.stride, self.padding = stride, padding
        self.output_padding = output_padding
        self.groups, self.dilation = groups, dilation
        fan_in = in_channels * kernel_size[0] * kernel_size[1]
        self.weight = Parameter(_kaiming_uniform(
            _next_key(), (in_channels, out_channels // groups) + kernel_size,
            fan_in))
        if bias:
            bound = 1 / math.sqrt(fan_in)
            self.bias = Parameter(jax.random.uniform(
                _next_key(), (out_channels,), jnp.float32, -bound, bound))
        else:
            self.register_parameter("bias", None)

    def forward(self, ctx, x):
        b = ctx.value(self.bias) if self.bias is not None else None
        return F.conv_transpose2d(
            x, ctx.value(self.weight), b, stride=self.stride,
            padding=self.padding, output_padding=self.output_padding,
            groups=self.groups, dilation=self.dilation)


class _BatchNorm(Module):
    """Shared core of BatchNorm1d/2d/3d (reference keeps BN fp32 under O2 —
    amp's convert_network skips casting these, fp16util.py:60-70; our
    _initialize uses the same predicate on isinstance(_BatchNorm))."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True):
        super().__init__()
        self.num_features = num_features
        self.eps, self.momentum, self.affine = eps, momentum, affine
        self.track_running_stats = track_running_stats
        if affine:
            self.weight = Parameter(jnp.ones((num_features,), jnp.float32))
            self.bias = Parameter(jnp.zeros((num_features,), jnp.float32))
        else:
            self.register_parameter("weight", None)
            self.register_parameter("bias", None)
        if track_running_stats:
            self.running_mean = Buffer(jnp.zeros((num_features,), jnp.float32))
            self.running_var = Buffer(jnp.ones((num_features,), jnp.float32))
            self.num_batches_tracked = Buffer(jnp.zeros((), jnp.int32))
        else:
            self.register_buffer("running_mean", None)
            self.register_buffer("running_var", None)

    # flipped to True by to_channels_last(): stats over NHWC's last axis
    channels_last = False

    # overridden by parallel.SyncBatchNorm
    def _stats_args(self):
        return dict(axis_name=None, axis_index_groups=None)

    def forward(self, ctx, x):
        training = ctx.training and self.training
        rm = ctx.value(self.running_mean) if self.running_mean is not None \
            else None
        rv = ctx.value(self.running_var) if self.running_var is not None \
            else None
        w = ctx.value(self.weight) if self.weight is not None else None
        b = ctx.value(self.bias) if self.bias is not None else None
        y, new_rm, new_rv = F.batch_norm(
            x, rm, rv, w, b, training=training or rm is None,
            momentum=self.momentum, eps=self.eps,
            channel_axis=(-1 if self.channels_last else 1),
            **self._stats_args())
        if training and self.track_running_stats:
            ctx.write_stat(self.running_mean, new_rm)
            ctx.write_stat(self.running_var, new_rv)
            ctx.write_stat(self.num_batches_tracked,
                           ctx.value(self.num_batches_tracked) + 1)
        return y

    def extra_repr(self):
        return f"{self.num_features}, eps={self.eps}, momentum={self.momentum}"


class BatchNorm1d(_BatchNorm):
    pass


class BatchNorm2d(_BatchNorm):
    pass


class BatchNorm3d(_BatchNorm):
    pass


class LayerNorm(Module):
    def __init__(self, normalized_shape, eps=1e-5, elementwise_affine=True):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        if elementwise_affine:
            self.weight = Parameter(jnp.ones(self.normalized_shape, jnp.float32))
            self.bias = Parameter(jnp.zeros(self.normalized_shape, jnp.float32))
        else:
            self.register_parameter("weight", None)
            self.register_parameter("bias", None)

    def forward(self, ctx, x):
        w = ctx.value(self.weight) if self.weight is not None else None
        b = ctx.value(self.bias) if self.bias is not None else None
        return F.layer_norm(x, self.normalized_shape, w, b, self.eps)


class GroupNorm(Module):
    def __init__(self, num_groups, num_channels, eps=1e-5, affine=True):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.eps = eps
        if affine:
            self.weight = Parameter(jnp.ones((num_channels,), jnp.float32))
            self.bias = Parameter(jnp.zeros((num_channels,), jnp.float32))
        else:
            self.register_parameter("weight", None)
            self.register_parameter("bias", None)

    def forward(self, ctx, x):
        w = ctx.value(self.weight) if self.weight is not None else None
        b = ctx.value(self.bias) if self.bias is not None else None
        return F.group_norm(x, self.num_groups, w, b, self.eps)


class _InstanceNorm(Module):
    """torch defaults: affine=False, track_running_stats=False (unlike
    BatchNorm); eval with tracked stats normalizes by the running pair."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=False,
                 track_running_stats=False):
        super().__init__()
        self.num_features = num_features
        self.eps = eps
        self.momentum = momentum
        self.track_running_stats = track_running_stats
        if affine:
            self.weight = Parameter(jnp.ones((num_features,), jnp.float32))
            self.bias = Parameter(jnp.zeros((num_features,), jnp.float32))
        else:
            self.register_parameter("weight", None)
            self.register_parameter("bias", None)
        if track_running_stats:
            self.register_buffer("running_mean",
                                 jnp.zeros((num_features,), jnp.float32))
            self.register_buffer("running_var",
                                 jnp.ones((num_features,), jnp.float32))
        else:
            self.register_buffer("running_mean", None)
            self.register_buffer("running_var", None)

    def forward(self, ctx, x):
        training = ctx.training and self.training
        w = ctx.value(self.weight) if self.weight is not None else None
        b = ctx.value(self.bias) if self.bias is not None else None
        rm = ctx.value(self.running_mean) if self.track_running_stats \
            else None
        rv = ctx.value(self.running_var) if self.track_running_stats \
            else None
        use_input_stats = training or not self.track_running_stats
        y, new_rm, new_rv = F.instance_norm(
            x, rm, rv, w, b, use_input_stats=use_input_stats,
            momentum=self.momentum, eps=self.eps)
        if training and self.track_running_stats and new_rm is not None:
            ctx.write_stat(self.running_mean, new_rm)
            ctx.write_stat(self.running_var, new_rv)
        return y


class InstanceNorm1d(_InstanceNorm):
    pass


class InstanceNorm2d(_InstanceNorm):
    pass


class InstanceNorm3d(_InstanceNorm):
    pass


class Embedding(Module):
    def __init__(self, num_embeddings, embedding_dim):
        super().__init__()
        self.weight = Parameter(jax.random.normal(
            _next_key(), (num_embeddings, embedding_dim), jnp.float32))

    def forward(self, ctx, ids):
        return F.embedding(ids, ctx.value(self.weight))


class Dropout(Module):
    def __init__(self, p=0.5):
        super().__init__()
        self.p = p

    def forward(self, ctx, x):
        training = ctx.training and self.training
        if not training or self.p == 0.0:
            return x
        return F.dropout(x, self.p, training=True, key=ctx.next_key())


class ReLU(Module):
    def __init__(self, inplace=False):
        super().__init__()

    def forward(self, ctx, x):
        return F.relu(x)


class LeakyReLU(Module):
    def __init__(self, negative_slope=0.01, inplace=False):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, ctx, x):
        return F.leaky_relu(x, self.negative_slope)


class GELU(Module):
    def forward(self, ctx, x):
        return F.gelu(x)


class Tanh(Module):
    def forward(self, ctx, x):
        return F.tanh(x)


class Sigmoid(Module):
    def forward(self, ctx, x):
        return F.sigmoid(x)


class Softmax(Module):
    def __init__(self, dim=-1):
        super().__init__()
        self.dim = dim

    def forward(self, ctx, x):
        return F.softmax(x, axis=self.dim)


class MaxPool2d(Module):
    channels_last = False

    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def forward(self, ctx, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            channels_last=self.channels_last)


class AvgPool2d(Module):
    channels_last = False

    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def forward(self, ctx, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            channels_last=self.channels_last)


class AdaptiveAvgPool2d(Module):
    channels_last = False

    def __init__(self, output_size=(1, 1)):
        super().__init__()
        self.output_size = output_size

    def forward(self, ctx, x):
        return F.adaptive_avg_pool2d(x, self.output_size,
                                     channels_last=self.channels_last)


class Flatten(Module):
    def __init__(self, start_dim=1):
        super().__init__()
        self.start_dim = start_dim

    def forward(self, ctx, x):
        return F.flatten(x, self.start_dim)


class Identity(Module):
    def forward(self, ctx, x):
        return x


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------

class CrossEntropyLoss(Module):
    def __init__(self, weight=None, reduction="mean", label_smoothing=0.0):
        super().__init__()
        self.weight = None if weight is None else jnp.asarray(weight)
        self.reduction = reduction
        self.label_smoothing = label_smoothing

    def forward(self, ctx, logits, target):
        return F.cross_entropy(logits, target, self.weight, self.reduction,
                               self.label_smoothing)


class MSELoss(Module):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, ctx, input, target):
        return F.mse_loss(input, target, self.reduction)


class L1Loss(Module):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, ctx, input, target):
        return F.l1_loss(input, target, self.reduction)


class BCELoss(Module):
    """Banned under O1 amp, as in the reference
    (apex/amp/lists/functional_overrides.py:70-80)."""

    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, ctx, input, target):
        return F.binary_cross_entropy(input, target, self.reduction)


class BCEWithLogitsLoss(Module):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, ctx, input, target):
        return F.binary_cross_entropy_with_logits(input, target,
                                                  self.reduction)


class NLLLoss(Module):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, ctx, logp, target):
        return F.nll_loss(logp, target, self.reduction)


# ---------------------------------------------------------------------------
# Containers
# ---------------------------------------------------------------------------

class Sequential(Module):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], OrderedDict):
            for name, layer in layers[0].items():
                setattr(self, name, layer)
        else:
            for i, layer in enumerate(layers):
                setattr(self, str(i), layer)

    def forward(self, ctx, x):
        for child in self._modules.values():
            x = child.forward(ctx, x)
        return x

    def __iter__(self):
        return iter(self._modules.values())

    def __len__(self):
        return len(self._modules)

    def __getitem__(self, idx):
        return list(self._modules.values())[idx]


class ModuleList(Module):
    def __init__(self, mods=()):
        super().__init__()
        for i, m in enumerate(mods):
            setattr(self, str(i), m)

    def append(self, m):
        setattr(self, str(len(self._modules)), m)
        return self

    def __iter__(self):
        return iter(self._modules.values())

    def __len__(self):
        return len(self._modules)

    def __getitem__(self, idx):
        return list(self._modules.values())[idx]


def checkpoint_forward(module, ctx, *inputs):
    """Run ``module.forward(ctx, *inputs)`` under ``jax.checkpoint``:
    activations inside the module are rematerialized in backward instead of
    saved, trading FLOPs for HBM (the standard long-sequence recipe; the
    reference has no analogue — CUDA Apex leans on torch.utils.checkpoint).

    The module tree executes through a Ctx whose env carries substituted
    parameter values; jax.checkpoint needs a pure array->array function, so
    this bridges by passing the module's parameter values (and the dropout
    key) as explicit arguments and rebuilding a local Ctx inside.  The
    dropout key counter is snapshotted and replayed so the rematerialized
    backward trace draws identical masks, and advanced on the outer ctx so
    later modules keep drawing fresh keys.  Running-stat modules
    (BatchNorm) are rejected: their stat writes would leak tracers across
    the checkpoint boundary.
    """
    ps = [p for p in module.parameters() if p is not None]
    ps += list(module.buffers())   # buffer READS (eval BN stats,
    # env-substituted constants) must cross the boundary too, not fall
    # back to stale eager .data
    vals = [ctx.value(p) for p in ps]
    idx0 = ctx._key_idx
    consumed = [idx0]

    def fn(key, x, *vals):
        inner = Ctx(env={id(p): v for p, v in zip(ps, vals)},
                    stats_out={}, training=ctx.training, key=key)
        inner._key_idx = idx0
        out = module.forward(inner, *x)
        if inner.stats_out:
            raise ValueError(
                "checkpoint_forward: module writes running statistics "
                "(BatchNorm?) — stat updates cannot cross the remat "
                "boundary; exclude such modules from checkpointing")
        consumed[0] = inner._key_idx
        # aux losses must cross the remat boundary as an explicit output
        # (appending a traced value to the outer ctx's list would leak
        # the tracer); summed here, re-added outside
        aux = sum(inner.aux_losses) if inner.aux_losses else jnp.zeros(())
        return out, aux

    out, aux = jax.checkpoint(fn, static_argnums=())(ctx.key, inputs, *vals)
    ctx._key_idx = max(ctx._key_idx, consumed[0])
    ctx.add_aux_loss(aux)
    return out


def fold_shard_into_key(ctx, axis_name):
    """A Ctx whose dropout key differs per shard of ``axis_name`` (fold in
    the axis index) — sequence-sharded activations must draw independent
    masks, not the replicated key's identical pattern on every shard.
    Key-counter continuity is preserved; no-op when the ctx carries no
    key.  Idempotent-enough: an outer fold (e.g. make_train_step's
    axis_name fold) composes harmlessly."""
    if ctx.key is None:
        return ctx
    inner = Ctx(env=ctx.env, stats_out=ctx.stats_out,
                training=ctx.training,
                key=jax.random.fold_in(ctx.key,
                                       jax.lax.axis_index(axis_name)))
    inner._key_idx = ctx._key_idx
    inner.aux_losses = ctx.aux_losses   # shared list: aux terms propagate
    # pre-fold key: replicated across THIS axis.  Overwritten by the
    # innermost fold (data-axis then sp-axis composition leaves the
    # post-data/pre-sp key here — exactly what ring dropout needs).
    inner.shared_key = ctx.key
    return inner

def to_channels_last(module, enabled=True):
    """Flip a module tree to channels-last (NHWC) execution: 2-d convs,
    batch norms, and 2-d pools compute directly on (B, H, W, C)
    activations (the caller feeds NHWC inputs).  The TPU-native layout
    lever: the MXU wants channels on the minor (lane) dimension, and
    running the whole tree NHWC removes every inter-op layout transpose
    XLA would otherwise insert around NCHW convs.  Weights stay OIHW —
    checkpoints (incl. models.hf.resnet_from_torch imports) are
    layout-independent.  In-place tree rewrite, returns the module (the
    convert_syncbn_model convention; the reference ships channel-last
    variants of its BN kernels, apex/contrib/groupbn and
    optimized_sync_batchnorm.py:58).

    Modules with no channels-last path — 1-d/3-d convs,
    ConvTranspose2d, 1-d/3-d batch norms, GroupNorm, InstanceNorm —
    make the tree refuse rather than silently mixing layouts (their
    channel axis stays hard-coded at 1).
    """
    refuse = (Conv1d, Conv3d, ConvTranspose2d, BatchNorm1d, BatchNorm3d,
              GroupNorm, _InstanceNorm)
    # BatchNorm2d and 2-d-shaped _BatchNorm subclasses (SyncBatchNorm)
    # flip; the dimension-specific norms above refuse first
    flippable = (Conv2d, _BatchNorm, MaxPool2d, AvgPool2d,
                 AdaptiveAvgPool2d)
    for m in module.modules():
        if isinstance(m, refuse):
            raise ValueError(
                f"to_channels_last: {type(m).__name__} has no "
                f"channels-last path (2-d convs/norms/pools only)")
        if isinstance(m, flippable):
            m.channels_last = bool(enabled)
    return module
