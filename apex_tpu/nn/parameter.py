"""Parameter: a mutable handle on a jax.Array, the bridge between the
Apex-shaped stateful API (optimizers mutate ``p.data``, autograd fills
``p.grad``) and the functional JAX core.  Analogue of torch.nn.Parameter as
used throughout the reference optimizers/amp."""
from __future__ import annotations

import jax
import jax.numpy as jnp


class Parameter:
    __slots__ = ("data", "grad", "name", "requires_grad", "_derived")

    def __init__(self, data, name: str | None = None, requires_grad: bool = True):
        self.data = jnp.asarray(data)
        self.grad = None
        self.name = name
        self.requires_grad = requires_grad
        # reparameterization hook: when set, Ctx.value computes this
        # parameter from other parameters (e.g. WeightNorm g*v/||v||)
        # instead of reading .data (apex_tpu/reparameterization/)
        self._derived = None

    # -- array-ish surface -------------------------------------------------
    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def size(self):
        return self.data.size

    def numel(self) -> int:
        return int(self.data.size)

    def astype(self, dtype):
        return Parameter(self.data.astype(dtype), self.name, self.requires_grad)

    def half(self):
        return self.astype(jnp.float16)

    def bfloat16(self):
        return self.astype(jnp.bfloat16)

    def float(self):
        return self.astype(jnp.float32)

    def clone(self):
        p = Parameter(self.data, self.name, self.requires_grad)
        p.grad = self.grad
        return p

    def __array__(self, dtype=None):
        import numpy as np
        return np.asarray(self.data, dtype)

    def __jax_array__(self):
        return self.data

    def __repr__(self):
        return (f"Parameter(name={self.name!r}, shape={tuple(self.shape)}, "
                f"dtype={jnp.dtype(self.dtype).name})")


def is_param(x) -> bool:
    return isinstance(x, Parameter)


def param_values(params) -> list[jax.Array]:
    return [p.data for p in params]


def param_grads(params) -> list:
    return [p.grad for p in params]
