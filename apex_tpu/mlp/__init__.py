"""apex.mlp equivalent (reference apex/mlp/__init__.py)."""
from .mlp import MLP, mlp_function  # noqa: F401
