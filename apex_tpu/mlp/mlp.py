"""Fused MLP — TPU-native equivalent of ``apex.mlp.MLP``
(apex/mlp/mlp.py:24-71 over the ``mlp_cuda`` extension, csrc/mlp.cpp:137-138).

The CUDA version exists to fuse N cublas GEMMs with bias/ReLU epilogues and a
single reserved activation buffer.  On TPU the same chain expressed as plain
``jnp.matmul`` + bias + relu is already fused by XLA into MXU GEMMs with
elementwise epilogues — the idiomatic "fused MLP" is therefore the jitted
composition itself; what we preserve from the reference is the API (flat
weight/bias attribute list, the same init distribution, ``bias``/``relu``
constructor contract, amp half_function registration) and the numerics
(ReLU after every layer, including the last — tests/L0/run_mlp/test_mlp.py:23-32).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..amp.policy import apply_op_policy
from ..nn import functional as F
from ..nn.modules import Module, _next_key
from ..nn.parameter import Parameter


def mlp_function(x, *weights_and_biases):
    """Functional fused MLP: alternating GEMM+bias+ReLU over the flat
    ``(w0..wN-1, b0..bN-1)`` argument list, mirroring ``MlpFunction.apply``
    (mlp.py:8-22).  Registered on the amp half list, as the reference wraps
    it with ``amp.half_function`` (mlp.py:22)."""
    (x, *weights_and_biases), _ = apply_op_policy(
        "mlp", (x, *weights_and_biases), {})
    num_layers = len(weights_and_biases) // 2
    weights = weights_and_biases[:num_layers]
    biases = weights_and_biases[num_layers:]
    for w, b in zip(weights, biases):
        x = F.relu(jnp.matmul(x, w.T) + b)
    return x


class MLP(Module):
    """Multi-layer Linear+bias+ReLU block.

    Args mirror the reference (mlp.py:30-35): ``mlp_sizes`` e.g.
    ``[480, 1024, 1024, 1]`` creates 3 layers; ``bias`` and ``relu`` must both
    be True (same constraint as mlp.py:33-34).
    """

    def __init__(self, mlp_sizes, bias=True, relu=True):
        if not (bias and relu):
            raise TypeError("bias and relu must be both true.")
        super().__init__()
        self.num_layers = len(mlp_sizes) - 1
        self.mlp_sizes = list(mlp_sizes)
        self.bias, self.relu = bias, relu
        self.weights, self.biases = [], []
        for i in range(self.num_layers):
            w = Parameter(jnp.zeros((mlp_sizes[i + 1], mlp_sizes[i]),
                                    jnp.float32))
            self.weights.append(w)
            setattr(self, f"weight_{i}", w)
            b = Parameter(jnp.zeros((mlp_sizes[i + 1],), jnp.float32))
            self.biases.append(b)
            setattr(self, f"bias_{i}", b)
        self.reset_parameters()

    def reset_parameters(self):
        # same distributions as the reference (mlp.py:55-62)
        for w in self.weights:
            std = math.sqrt(2.0 / float(w.shape[0] + w.shape[1]))
            w.data = std * jax.random.normal(_next_key(), w.shape, jnp.float32)
        for b in self.biases:
            std = math.sqrt(1.0 / float(b.shape[0]))
            b.data = std * jax.random.normal(_next_key(), b.shape, jnp.float32)

    def forward(self, ctx, x):
        vals = [ctx.value(w) for w in self.weights] + \
               [ctx.value(b) for b in self.biases]
        return mlp_function(x, *vals)

    def extra_repr(self):
        return (f"MLP sizes: {self.mlp_sizes}, Bias={self.bias}, "
                f"ReLU={self.relu}")
