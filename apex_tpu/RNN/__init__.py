"""apex_tpu.RNN — pure-JAX RNN zoo (reference: apex/RNN/__init__.py).

lax.scan-based LSTM/GRU/ReLU/Tanh/mLSTM with the reference's container API
(stackedRNN, bidirectionalRNN, persistent hidden state)."""
from .models import LSTM, GRU, ReLU, Tanh, mLSTM, mLSTMRNNCell
from .RNNBackend import RNNCell, bidirectionalRNN, stackedRNN
from . import cells

__all__ = ["LSTM", "GRU", "ReLU", "Tanh", "mLSTM", "mLSTMRNNCell",
           "RNNCell", "bidirectionalRNN", "stackedRNN", "cells"]
