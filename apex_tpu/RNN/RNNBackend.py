"""RNN containers and the generic RNNCell (reference:
apex/RNN/RNNBackend.py).

TPU-first restructuring: the reference drives a Python ``for seq: for
layer:`` loop of per-timestep module calls (RNNBackend.py:122-148), which
under XLA would unroll the graph over time.  Here each layer runs its whole
sequence through ONE ``lax.scan`` (layer-major order — mathematically
identical, since layer l at time t depends only on layer l-1 at t and layer
l at t-1), so the compiled program is a compact loop whose body is two MXU
GEMMs plus fused gate math, regardless of sequence length.

Hidden-state statefulness (init_hidden/reset_hidden/detach_hidden,
RNNBackend.py:309-351) is preserved: the final states of each forward are
stored on the cells and seed the next call's carry.  Stored states are
concrete arrays, so successive forward() calls are implicitly truncated-BPTT
boundaries — equivalent to the reference with ``detach_hidden()`` between
sequences (the documented usage pattern); in-sequence backprop-through-time
is exact because the whole scan lives inside one taped forward.

All containers assume input is NOT batch_first: (seq, batch, feature).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn.modules import Ctx, Module, ModuleList, _next_key
from ..nn.parameter import Parameter


class RNNCell(Module):
    """Generic recurrent cell: holds the gate weights and the persistent
    hidden state, delegates the math to a pure ``cell`` function
    (reference RNNBackend.py:232-351).

    gate_multiplier: 4 for LSTM-like, 3 for GRU, 1 for vanilla.
    n_hidden_states: 2 for (h, c) cells, 1 for h-only.
    output_size != hidden_size adds a recurrent projection w_ho.
    """

    def __init__(self, gate_multiplier, input_size, hidden_size, cell,
                 n_hidden_states=2, bias=False, output_size=None):
        super().__init__()
        self.gate_multiplier = gate_multiplier
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.cell = cell
        self.bias = bias
        self.output_size = hidden_size if output_size is None else output_size
        self.gate_size = gate_multiplier * self.hidden_size
        self.n_hidden_states = n_hidden_states

        self.w_ih = Parameter(jnp.zeros((self.gate_size, self.input_size)))
        self.w_hh = Parameter(jnp.zeros((self.gate_size, self.output_size)))
        if self.output_size != self.hidden_size:
            self.w_ho = Parameter(
                jnp.zeros((self.output_size, self.hidden_size)))
        self.b_ih = self.b_hh = None
        if self.bias:
            self.b_ih = Parameter(jnp.zeros((self.gate_size,)))
            self.b_hh = Parameter(jnp.zeros((self.gate_size,)))

        self.hidden = [None for _ in range(self.n_hidden_states)]
        self.reset_parameters()

    def new_like(self, new_input_size=None):
        if new_input_size is None:
            new_input_size = self.input_size
        return type(self)(self.gate_multiplier, new_input_size,
                          self.hidden_size, self.cell, self.n_hidden_states,
                          self.bias, self.output_size)

    def reset_parameters(self, gain=1):
        stdev = 1.0 / math.sqrt(self.hidden_size)
        for p in self.parameters():
            p.data = jax.random.uniform(
                _next_key(), p.shape, p.dtype, -stdev, stdev)

    # -- persistent hidden state ------------------------------------------
    def _state_size(self, i):
        # state 0 is the (possibly projected) output, others are cell-internal
        return self.output_size if i == 0 else self.hidden_size

    def init_hidden(self, bsz):
        dtype = self.w_ih.dtype
        for i, h in enumerate(self.hidden):
            if h is None or h.shape[0] != bsz:
                self.hidden[i] = jnp.zeros((bsz, self._state_size(i)), dtype)

    def reset_hidden(self, bsz):
        self.hidden = [None for _ in range(self.n_hidden_states)]
        self.init_hidden(bsz)

    def detach_hidden(self):
        # states are stored as concrete arrays (already detached); the call
        # is kept for reference API parity and validates initialization
        if any(h is None for h in self.hidden):
            raise RuntimeError("Must initialize hidden state before you can "
                               "detach it")

    def init_inference(self, bsz):
        self.init_hidden(bsz)

    # -- math --------------------------------------------------------------
    def _weights(self, ctx: Ctx):
        w = {"w_ih": ctx.value(self.w_ih), "w_hh": ctx.value(self.w_hh)}
        w["b_ih"] = ctx.value(self.b_ih) if self.b_ih is not None else None
        w["b_hh"] = ctx.value(self.b_hh) if self.b_hh is not None else None
        return w

    def _step(self, ctx, w, x, hidden):
        new = list(self.cell(x, hidden, **w))
        if self.output_size != self.hidden_size:
            new[0] = F.linear(new[0], ctx.value(self.w_ho))
        return tuple(new)

    def __call__(self, x):
        # the persistent hidden state enters the tape as explicit inputs so
        # backward's re-execution sees the SAME h0 the eager forward used
        # (forward mutates self.hidden afterwards) and fresh values flow
        # into cached compiled programs on every call
        from ..autograd import record_module_call
        self.init_hidden(x.shape[0])
        return record_module_call(self, (x, *self.hidden))

    def forward(self, ctx: Ctx, x, *h0):
        """Single timestep; returns the tuple of new states
        (reference RNNBackend.py: cell forward)."""
        if not h0:
            self.init_hidden(x.shape[0])
            h0 = tuple(self.hidden)
        w = self._weights(ctx)
        new = self._step(ctx, w, x, tuple(h0))
        if ctx.stats_out is None:
            self.hidden = [jax.lax.stop_gradient(h) for h in new]
        return new

    def scan(self, ctx: Ctx, seq, h0, reverse=False):
        """Run the whole (T, B, F) sequence through one lax.scan.

        Returns (all_states, final_states): all_states[i] is (T, B, feat)
        for hidden-state i (time index is original order even when
        reverse=True), final_states is the carry after the scan.
        """
        w = self._weights(ctx)

        def body(carry, x_t):
            new = self._step(ctx, w, x_t, carry)
            return new, new

        final, ys = jax.lax.scan(body, h0, seq, reverse=reverse)
        return ys, final


class stackedRNN(Module):
    """Stack of RNNCells run layer-major over the sequence
    (reference RNNBackend.py:107-231)."""

    def __init__(self, inputRNN, num_layers=1, dropout=0):
        super().__init__()
        self.dropout = dropout
        if isinstance(inputRNN, RNNCell):
            rnns = [inputRNN]
            for _ in range(num_layers - 1):
                rnns.append(inputRNN.new_like(inputRNN.output_size))
        elif isinstance(inputRNN, list):
            assert len(inputRNN) == num_layers, \
                "RNN list length must be equal to num_layers"
            rnns = inputRNN
        else:
            raise RuntimeError()
        self.nLayers = len(rnns)
        self.rnns = ModuleList(rnns)

    def _flat_hidden(self, bsz):
        self.init_hidden(bsz)
        return [h for cell in self.rnns for h in cell.hidden]

    def __call__(self, x, collect_hidden=False, reverse=False):
        # h0 as explicit tape inputs — see RNNCell.__call__
        from ..autograd import record_module_call
        return record_module_call(
            self, (x, *self._flat_hidden(x.shape[1])),
            {"collect_hidden": collect_hidden, "reverse": reverse})

    def forward(self, ctx: Ctx, x, *flat_h0, collect_hidden=False,
                reverse=False):
        """Returns (output, hiddens).

        output: (T, B, out).  hiddens: tuple over n_hidden_states of
        (layer, B, feat) final states — or, with collect_hidden, tuple over
        n_hidden_states of per-timestep tuples of (layer, B, feat)
        (reference output contract, RNNBackend.py:155-189).
        """
        bsz = x.shape[1]
        if not flat_h0:
            flat_h0 = self._flat_hidden(bsz)
        all_states = []   # per layer: tuple of (T,B,feat) per hidden state
        finals = []       # per layer: tuple of final states
        out = x
        it = iter(flat_h0)
        for cell in self.rnns:
            h0 = tuple(next(it) for _ in range(cell.n_hidden_states))
            ys, final = cell.scan(ctx, out, h0, reverse=reverse)
            out = ys[0]
            all_states.append(ys)
            finals.append(final)

        if ctx.stats_out is None:
            for cell, final in zip(self.rnns, finals):
                cell.hidden = [jax.lax.stop_gradient(h) for h in final]

        n_hid = self.rnns[0].n_hidden_states
        if collect_hidden:
            seq_len = x.shape[0]
            # one (T, L, B, f) stack per hidden state, then cheap
            # per-timestep slices for the reference's tuple-of-(L,B,f)
            # output contract
            hiddens = tuple(
                tuple(stacked[t] for t in range(seq_len))
                for stacked in (
                    jnp.stack([all_states[l][i]
                               for l in range(self.nLayers)], axis=1)
                    for i in range(n_hid)))
        else:
            hiddens = tuple(
                jnp.stack([finals[l][i] for l in range(self.nLayers)], axis=0)
                for i in range(n_hid))
        return out, hiddens

    def reset_parameters(self):
        for rnn in self.rnns:
            rnn.reset_parameters()

    def init_hidden(self, bsz):
        for rnn in self.rnns:
            rnn.init_hidden(bsz)

    def detach_hidden(self):
        for rnn in self.rnns:
            rnn.detach_hidden()

    def reset_hidden(self, bsz):
        for rnn in self.rnns:
            rnn.reset_hidden(bsz)

    def init_inference(self, bsz):
        for rnn in self.rnns:
            rnn.init_inference(bsz)


class bidirectionalRNN(Module):
    """Forward + time-reversed stackedRNN with feature-concat outputs
    (reference RNNBackend.py:24-86)."""

    def __init__(self, inputRNN, num_layers=1, dropout=0):
        super().__init__()
        self.dropout = dropout
        self.fwd = stackedRNN(inputRNN, num_layers=num_layers,
                              dropout=dropout)
        self.bckwrd = stackedRNN(inputRNN.new_like(), num_layers=num_layers,
                                 dropout=dropout)

    def __call__(self, x, collect_hidden=False):
        from ..autograd import record_module_call
        bsz = x.shape[1]
        flat = (self.fwd._flat_hidden(bsz) + self.bckwrd._flat_hidden(bsz))
        return record_module_call(self, (x, *flat),
                                  {"collect_hidden": collect_hidden})

    def forward(self, ctx: Ctx, x, *flat_h0, collect_hidden=False):
        bsz = x.shape[1]
        if not flat_h0:
            flat_h0 = (self.fwd._flat_hidden(bsz)
                       + self.bckwrd._flat_hidden(bsz))
        k = len(flat_h0) // 2
        fwd_out, fwd_hiddens = self.fwd.forward(
            ctx, x, *flat_h0[:k], collect_hidden=collect_hidden)
        bckwrd_out, bckwrd_hiddens = self.bckwrd.forward(
            ctx, x, *flat_h0[k:], reverse=True, collect_hidden=collect_hidden)
        output = jnp.concatenate([fwd_out, bckwrd_out], axis=-1)
        if collect_hidden:
            hiddens = tuple(
                tuple(jnp.concatenate([f, b], axis=-1)
                      for f, b in zip(fseq, bseq))
                for fseq, bseq in zip(fwd_hiddens, bckwrd_hiddens))
        else:
            hiddens = tuple(jnp.concatenate([f, b], axis=-1)
                            for f, b in zip(fwd_hiddens, bckwrd_hiddens))
        return output, hiddens

    def reset_parameters(self):
        for rnn in (self.fwd, self.bckwrd):
            rnn.reset_parameters()

    def init_hidden(self, bsz):
        for rnn in (self.fwd, self.bckwrd):
            rnn.init_hidden(bsz)

    def detach_hidden(self):
        for rnn in (self.fwd, self.bckwrd):
            rnn.detach_hidden()

    def reset_hidden(self, bsz):
        for rnn in (self.fwd, self.bckwrd):
            rnn.reset_hidden(bsz)

    def init_inference(self, bsz):
        for rnn in (self.fwd, self.bckwrd):
            rnn.init_inference(bsz)
