"""Pure RNN cell functions (reference: apex/RNN/cells.py and the torch
builtin cells apex/RNN/models.py imports from torch.nn._functions.rnn).

Each cell is a pure array function ``cell(x, hidden, w_ih, w_hh, ...,
b_ih=None, b_hh=None) -> tuple(new_hidden_states)`` suitable for use as a
`lax.scan` body — the TPU-native replacement for the reference's per-timestep
fused CUDA pointwise kernels (torch ``rnnFusedPointwise``): XLA fuses the
gate elementwise math into the two GEMMs, and the MXU sees one
``(B, in) @ (in, 4H)`` matmul per step.

Gate memory layouts match torch exactly (LSTM: i,f,g,o; GRU: r,z,n) so
weights are interchangeable with torch checkpoints.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn import functional as F


def _gates(x, h, w_ih, w_hh, b_ih, b_hh):
    return F.linear(x, w_ih, b_ih) + F.linear(h, w_hh, b_hh)


def lstm_cell(x, hidden, w_ih, w_hh, b_ih=None, b_hh=None):
    """torch LSTMCell math; returns (hy, cy)."""
    hx, cx = hidden
    gates = _gates(x, hx, w_ih, w_hh, b_ih, b_hh)
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = F.sigmoid(i)
    f = F.sigmoid(f)
    g = F.tanh(g)
    o = F.sigmoid(o)
    cy = f * cx + i * g
    hy = o * F.tanh(cy)
    return hy, cy


def gru_cell(x, hidden, w_ih, w_hh, b_ih=None, b_hh=None):
    """torch GRUCell math; returns (hy,)."""
    (hx,) = hidden
    gi = F.linear(x, w_ih, b_ih)
    gh = F.linear(hx, w_hh, b_hh)
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = F.sigmoid(i_r + h_r)
    z = F.sigmoid(i_z + h_z)
    n = F.tanh(i_n + r * h_n)
    hy = n + z * (hx - n)
    return (hy,)


def rnn_relu_cell(x, hidden, w_ih, w_hh, b_ih=None, b_hh=None):
    (hx,) = hidden
    return (F.relu(_gates(x, hx, w_ih, w_hh, b_ih, b_hh)),)


def rnn_tanh_cell(x, hidden, w_ih, w_hh, b_ih=None, b_hh=None):
    (hx,) = hidden
    return (F.tanh(_gates(x, hx, w_ih, w_hh, b_ih, b_hh)),)


def mlstm_cell(x, hidden, w_ih, w_hh, w_mih, w_mhh, b_ih=None, b_hh=None):
    """Multiplicative LSTM (reference apex/RNN/cells.py:55-84): an
    input-dependent intermediate state m = (W_mih x) * (W_mhh h) replaces h
    in the recurrent gate GEMM.  Returns (hy, cy)."""
    hx, cx = hidden
    m = F.linear(x, w_mih) * F.linear(hx, w_mhh)
    gates = F.linear(x, w_ih, b_ih) + F.linear(m, w_hh, b_hh)
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = F.sigmoid(i)
    f = F.sigmoid(f)
    g = F.tanh(g)
    o = F.sigmoid(o)
    cy = f * cx + i * g
    hy = o * F.tanh(cy)
    return hy, cy
