"""RNN factory functions + mLSTM cell module (reference:
apex/RNN/models.py, apex/RNN/cells.py:12-53).

Factories return a stackedRNN (or bidirectionalRNN) whose per-layer time
loop compiles to a single lax.scan — see RNNBackend module docstring.
Input layout is (seq, batch, feature); batch_first is accepted for API
parity but, as in the reference, not implemented by the backend.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.parameter import Parameter
from . import cells
from .RNNBackend import RNNCell, bidirectionalRNN, stackedRNN


class mLSTMRNNCell(RNNCell):
    """Multiplicative-LSTM cell module (reference apex/RNN/cells.py:12-53):
    adds the m-state projections w_mih/w_mhh on top of the LSTM weights."""

    def __init__(self, input_size, hidden_size, bias=False, output_size=None):
        gate_multiplier = 4
        super().__init__(gate_multiplier, input_size, hidden_size,
                         cells.mlstm_cell, n_hidden_states=2, bias=bias,
                         output_size=output_size)
        self.w_mih = Parameter(
            jnp.zeros((self.output_size, self.input_size)))
        self.w_mhh = Parameter(
            jnp.zeros((self.output_size, self.output_size)))
        self.reset_parameters()

    def _weights(self, ctx):
        w = super()._weights(ctx)
        w["w_mih"] = ctx.value(self.w_mih)
        w["w_mhh"] = ctx.value(self.w_mhh)
        return w

    def new_like(self, new_input_size=None):
        if new_input_size is None:
            new_input_size = self.input_size
        return type(self)(new_input_size, self.hidden_size, self.bias,
                          self.output_size)


def toRNNBackend(inputRNN, num_layers, bidirectional=False, dropout=0):
    if bidirectional:
        return bidirectionalRNN(inputRNN, num_layers, dropout=dropout)
    return stackedRNN(inputRNN, num_layers, dropout=dropout)


def LSTM(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0, bidirectional=False, output_size=None):
    inputRNN = RNNCell(4, input_size, hidden_size, cells.lstm_cell, 2, bias,
                       output_size)
    return toRNNBackend(inputRNN, num_layers, bidirectional, dropout=dropout)


def GRU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
        dropout=0, bidirectional=False, output_size=None):
    inputRNN = RNNCell(3, input_size, hidden_size, cells.gru_cell, 1, bias,
                       output_size)
    return toRNNBackend(inputRNN, num_layers, bidirectional, dropout=dropout)


def ReLU(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0, bidirectional=False, output_size=None):
    inputRNN = RNNCell(1, input_size, hidden_size, cells.rnn_relu_cell, 1,
                       bias, output_size)
    return toRNNBackend(inputRNN, num_layers, bidirectional, dropout=dropout)


def Tanh(input_size, hidden_size, num_layers, bias=True, batch_first=False,
         dropout=0, bidirectional=False, output_size=None):
    inputRNN = RNNCell(1, input_size, hidden_size, cells.rnn_tanh_cell, 1,
                       bias, output_size)
    return toRNNBackend(inputRNN, num_layers, bidirectional, dropout=dropout)


def mLSTM(input_size, hidden_size, num_layers, bias=True, batch_first=False,
          dropout=0, bidirectional=False, output_size=None):
    inputRNN = mLSTMRNNCell(input_size, hidden_size, bias=bias,
                            output_size=output_size)
    return toRNNBackend(inputRNN, num_layers, bidirectional, dropout=dropout)
