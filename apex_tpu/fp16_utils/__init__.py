"""apex.fp16_utils equivalent (reference apex/fp16_utils/__init__.py)."""
from .fp16util import (  # noqa: F401
    BN_convert_float,
    FP16Model,
    clip_grad_norm,
    convert_module,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
    tofp16,
)
from .fp16_optimizer import FP16_Optimizer  # noqa: F401
from .loss_scaler import DynamicLossScaler, LossScaler  # noqa: F401
