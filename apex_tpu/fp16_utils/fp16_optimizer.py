"""FP16_Optimizer — legacy manual master-weight wrapper (reference:
apex/fp16_utils/fp16_optimizer.py:13-270; deprecated there in favor of amp,
:20-22, but still public API).

Wraps any apex_tpu optimizer: half params get fp32 master copies swapped
into the inner ``param_groups``; ``backward(loss)`` scales the loss,
``update_master_grads`` unscales model grads into the masters (with
overflow detection when dynamic), ``step`` skips on overflow then copies
masters back into the model params.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..nn.parameter import Parameter
from .fp16util import (clip_grad_norm, master_params_to_model_params,
                       model_grads_to_master_grads)
from .loss_scaler import DynamicLossScaler, LossScaler

_HALF_DTYPES = (jnp.float16, jnp.bfloat16)


def _is_half(p) -> bool:
    return any(p.dtype == d for d in _HALF_DTYPES)


class FP16_Optimizer:
    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, dynamic_loss_args=None,
                 verbose=True):
        self.optimizer = init_optimizer
        self.verbose = verbose

        # partition each group (reference fp16_optimizer.py:43-95)
        self.fp16_groups: List[List[Parameter]] = []
        self.fp32_from_fp16_groups: List[List[Parameter]] = []
        self.fp32_from_fp32_groups: List[List[Parameter]] = []
        for group in self.optimizer.param_groups:
            fp16, fp32_from_fp16, fp32 = [], [], []
            new_params = []
            for p in group["params"]:
                if _is_half(p):
                    master = Parameter(p.data.astype(jnp.float32))
                    master.requires_grad = True
                    fp16.append(p)
                    fp32_from_fp16.append(master)
                    new_params.append(master)
                    if p in self.optimizer.state:
                        self.optimizer.state[master] = \
                            self.optimizer.state.pop(p)
                else:
                    fp32.append(p)
                    new_params.append(p)
            group["params"] = new_params
            self.fp16_groups.append(fp16)
            self.fp32_from_fp16_groups.append(fp32_from_fp16)
            self.fp32_from_fp32_groups.append(fp32)

        if dynamic_loss_scale:
            self.dynamic_loss_scale = True
            args = dynamic_loss_args or {}
            self.loss_scaler = DynamicLossScaler(**args)
        else:
            self.dynamic_loss_scale = False
            self.loss_scaler = LossScaler(static_loss_scale)
        self.overflow = False
        self.first_closure_call_this_step = True

    def maybe_print(self, msg):
        if self.verbose:
            print(msg)

    # -- torch-optimizer protocol delegation -------------------------------
    @property
    def param_groups(self):
        return self.optimizer.param_groups

    @property
    def state(self):
        return self.optimizer.state

    def zero_grad(self, set_grads_to_None=False):
        for group in self.optimizer.param_groups:
            for p in group["params"]:
                p.grad = None if set_grads_to_None else (
                    jnp.zeros_like(p.grad) if p.grad is not None else None)
        for group in self.fp16_groups:
            for p in group:
                p.grad = None if set_grads_to_None else (
                    jnp.zeros_like(p.grad) if p.grad is not None else None)

    # -- the manual loop surface (reference :97-208) -----------------------
    def backward(self, loss, update_master_grads=True, retain_graph=False):
        scaled = loss * float(self.loss_scaler.loss_scale)
        scaled.backward()
        if update_master_grads:
            self.update_master_grads()

    def update_master_grads(self):
        """Unscale model grads into master grads; detect overflow
        (reference :160-185)."""
        # fp32-kept params (e.g. BN after network_to_half) can overflow too
        # (reference fp16_optimizer.py _check_overflow covers both groups)
        self.overflow = self.loss_scaler.has_overflow(
            [p for g in self.fp16_groups for p in g]
            + [p for g in self.fp32_from_fp32_groups for p in g])
        self.loss_scaler.update_scale(self.overflow)
        if self.overflow:
            return
        inv = 1.0 / float(self.loss_scaler.loss_scale)
        for fp16_group, master_group in zip(self.fp16_groups,
                                            self.fp32_from_fp16_groups):
            model_grads_to_master_grads(fp16_group, master_group)
            for m in master_group:
                if m.grad is not None:
                    m.grad = m.grad * inv
        for fp32_group in self.fp32_from_fp32_groups:
            for p in fp32_group:
                if p.grad is not None and inv != 1.0:
                    p.grad = p.grad * inv

    def clip_master_grads(self, max_norm, norm_type=2):
        """Returns the pre-clip grad norm, or -1 when this step overflowed
        (reference :187-208)."""
        if self.overflow:
            return -1
        masters = [p for g in self.optimizer.param_groups
                   for p in g["params"]]
        return clip_grad_norm(masters, max_norm, norm_type)

    def step(self, closure=None):
        if self.overflow:
            self.maybe_print(
                f"OVERFLOW! Skipping step. Attempted loss scale: "
                f"{self.loss_scaler.loss_scale}")
            return
        if closure is not None:
            raise NotImplementedError(
                "closure-based step is not supported on the TPU build")
        self.optimizer.step()
        for fp16_group, master_group in zip(self.fp16_groups,
                                            self.fp32_from_fp16_groups):
            master_params_to_model_params(fp16_group, master_group)

    # -- checkpointing (reference :209-270) --------------------------------
    def state_dict(self):
        return {
            "loss_scaler": self.loss_scaler,
            "dynamic_loss_scale": self.dynamic_loss_scale,
            "overflow": self.overflow,
            "first_closure_call_this_step":
                self.first_closure_call_this_step,
            "optimizer_state_dict": self.optimizer.state_dict(),
            "fp32_from_fp16": [[p.data for p in g]
                               for g in self.fp32_from_fp16_groups],
        }

    def load_state_dict(self, state_dict):
        self.loss_scaler = state_dict["loss_scaler"]
        self.dynamic_loss_scale = state_dict["dynamic_loss_scale"]
        self.overflow = state_dict["overflow"]
        self.first_closure_call_this_step = \
            state_dict["first_closure_call_this_step"]
        self.optimizer.load_state_dict(state_dict["optimizer_state_dict"])
        for cur, saved in zip(self.fp32_from_fp16_groups,
                              state_dict["fp32_from_fp16"]):
            for p, data in zip(cur, saved):
                p.data = jnp.asarray(data, jnp.float32)

    # -- loss scale accessors (reference :272-286) -------------------------
    def _get_loss_scale(self):
        return self.loss_scaler.loss_scale

    def _set_loss_scale(self, value):
        self.loss_scaler.cur_scale = value

    loss_scale = property(_get_loss_scale, _set_loss_scale)
