"""Legacy loss scalers (reference: apex/fp16_utils/loss_scaler.py).

``LossScaler`` is static; ``DynamicLossScaler`` starts at 2**32, halves on
overflow and doubles after ``scale_window=1000`` clean iterations
(loss_scaler.py:10,46,74-82,113-121 — note the legacy defaults differ from
amp's scaler: init 2**32 vs 2**16, window 1000 vs 2000).  Kept as a separate
small implementation because the legacy API is iteration-driven
(``update_scale(overflow)``/``has_overflow(params)``) rather than
state-threaded.
"""
from __future__ import annotations

import jax.numpy as jnp


def _params_have_overflow(params) -> bool:
    for p in params:
        if p.grad is not None and not bool(
                jnp.isfinite(p.grad.astype(jnp.float32)).all()):
            return True
    return False


class LossScaler:
    """Static loss scaler (reference loss_scaler.py:10-44)."""

    def __init__(self, scale=1.0):
        self.cur_scale = float(scale)

    def has_overflow(self, params):
        return False

    def _has_inf_or_nan(x):
        return False

    def update_scale(self, overflow):
        pass

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, module, grad_in, grad_out):
        return tuple(self.loss_scale * g for g in grad_in)

    def backward(self, loss, retain_graph=False):
        scaled_loss = loss * self.loss_scale
        scaled_loss.backward()


class DynamicLossScaler:
    """Dynamic loss scaler (reference loss_scaler.py:46-135)."""

    def __init__(self, init_scale=2 ** 32, scale_factor=2.0,
                 scale_window=1000):
        # float: a Python-int 2**32 scale overflows int32 coercion when it
        # multiplies a jax array (the reference relies on torch promotion)
        self.cur_scale = float(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window

    def has_overflow(self, params):
        return _params_have_overflow(params)

    @staticmethod
    def _has_inf_or_nan(x):
        return not bool(jnp.isfinite(
            jnp.asarray(x, jnp.float32)).all())

    def update_scale(self, overflow):
        # reference loss_scaler.py:113-121
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1)
            self.last_overflow_iter = self.cur_iter
        else:
            if (self.cur_iter - self.last_overflow_iter) % \
                    self.scale_window == 0:
                self.cur_scale *= self.scale_factor
        self.cur_iter += 1

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, module, grad_in, grad_out):
        return tuple(self.loss_scale * g for g in grad_in)

    def backward(self, loss, retain_graph=False):
        scaled_loss = loss * self.loss_scale
        scaled_loss.backward()
