"""Manual mixed-precision helpers (reference: apex/fp16_utils/fp16util.py).

These predate amp in the reference and remain public API.  Semantics kept:
``network_to_half`` casts params/buffers to half but leaves batchnorm in
fp32 (fp16util.py:35-58); ``convert_network`` is the dtype-general form
(:60-70); ``prep_param_lists`` builds fp32 master copies, optionally
flattened into one tensor (:90-134); the grad/param copy helpers move
between model and master lists (:136-172).

TPU notes: "half" defaults to bfloat16 (fp16 supported for parity testing);
the flat-master path concatenates into a single fp32 array — the layout the
fused optimizers prefer on TPU anyway.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

from ..nn.modules import Module, _BatchNorm
from ..nn.parameter import Parameter


def tofp16(network: Module, dtype=jnp.bfloat16) -> Module:
    """Cast the whole network to half (reference fp16util.py:35-43)."""
    return network.to(dtype)


def BN_convert_float(module: Module) -> Module:
    """Cast batchnorm modules back to fp32 (reference fp16util.py:46-58)."""
    for m in module.modules():
        if isinstance(m, _BatchNorm):
            m._cast_params(jnp.float32)
    return module


def network_to_half(network: Module, dtype=jnp.bfloat16) -> Module:
    """Half network with fp32 batchnorm (reference fp16util.py:35-58 —
    there a composition of tofp16 + BN_convert_float)."""
    return BN_convert_float(tofp16(network, dtype))


class FP16Model(Module):
    """Module wrapper converting a network to half in a batchnorm-safe way
    and casting its inputs to half per forward (reference
    fp16util.py:73-84; default dtype is bf16, the TPU-native half)."""

    def __init__(self, network: Module, dtype=jnp.bfloat16):
        super().__init__()
        self.dtype = jnp.dtype(dtype)
        self.network = convert_network(network, dtype)

    def forward(self, ctx, *inputs):
        cast = tuple(
            x.astype(self.dtype) if hasattr(x, "dtype")
            and jnp.issubdtype(x.dtype, jnp.floating) else x
            for x in inputs)
        return self.network.forward(ctx, *cast)


def convert_module(module: Module, dtype) -> Module:
    """Cast ONE module's own params/buffers unless it's batchnorm
    (reference fp16util.py:72-88)."""
    if isinstance(module, _BatchNorm):
        return module
    for p in module._parameters.values():
        if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
            p.data = p.data.astype(dtype)
    for b in module._buffers.values():
        if b is not None and jnp.issubdtype(b.dtype, jnp.floating):
            b.data = b.data.astype(dtype)
    return module


def convert_network(network: Module, dtype) -> Module:
    """Cast all non-BN modules to ``dtype`` (reference fp16util.py:60-70);
    the predicate amp's O2 cast shares."""
    for m in network.modules():
        convert_module(m, dtype)
    return network


def prep_param_lists(model: Module, flat_master: bool = False
                     ) -> Tuple[List[Parameter], List[Parameter]]:
    """(model_params, master_params) with fp32 master copies (reference
    fp16util.py:90-134).  ``flat_master=True`` returns a singleton list
    holding one flattened fp32 master."""
    model_params = [p for p in model.parameters()
                    if getattr(p, "requires_grad", True)]
    if flat_master:
        flat = jnp.concatenate(
            [jnp.ravel(p.data).astype(jnp.float32) for p in model_params])
        master = Parameter(flat)
        master.requires_grad = True
        return model_params, [master]
    masters = []
    for p in model_params:
        m = Parameter(p.data.astype(jnp.float32))
        m.requires_grad = True
        masters.append(m)
    return model_params, masters


def model_grads_to_master_grads(model_params, master_params,
                                flat_master: bool = False):
    """Copy model grads into master grads, upcasting (reference
    fp16util.py:136-156)."""
    if flat_master:
        grads = [jnp.ravel(p.grad).astype(jnp.float32)
                 if p.grad is not None else jnp.zeros((p.size,), jnp.float32)
                 for p in model_params]
        master_params[0].grad = jnp.concatenate(grads)
    else:
        for model, master in zip(model_params, master_params):
            master.grad = (model.grad.astype(jnp.float32)
                           if model.grad is not None else None)


def master_params_to_model_params(model_params, master_params,
                                  flat_master: bool = False):
    """Copy master params back into the model, downcasting (reference
    fp16util.py:158-172)."""
    if flat_master:
        offset = 0
        flat = master_params[0].data
        for p in model_params:
            n = p.size
            p.data = flat[offset:offset + n].reshape(p.shape).astype(p.dtype)
            offset += n
    else:
        for model, master in zip(model_params, master_params):
            model.data = master.data.astype(model.dtype)


def to_python_float(t) -> float:
    if hasattr(t, "item"):
        return float(t.item())
    return float(t)


def clip_grad_norm(parameters, max_norm: float, norm_type: float = 2.0):
    """Grad clipping over a param list; returns the pre-clip total norm
    (the torch.nn.utils.clip_grad_norm the reference re-exports,
    fp16util.py:17-33)."""
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return 0.0
    if norm_type == float("inf"):
        total = max(float(jnp.max(jnp.abs(p.grad))) for p in params)
    else:
        total = float(sum(jnp.sum(jnp.abs(p.grad.astype(jnp.float32))
                                  ** norm_type) for p in params)
                      ) ** (1.0 / norm_type)
    clip_coef = max_norm / (total + 1e-6)
    if clip_coef < 1.0:
        for p in params:
            p.grad = (p.grad.astype(jnp.float32) * clip_coef).astype(
                p.grad.dtype)
    return total
