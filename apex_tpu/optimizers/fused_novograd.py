"""FusedNovoGrad (reference: apex/optimizers/fused_novograd.py).

Layout deviation from the reference: per-tensor second-moment norms are kept
one-per-param in ``self.state[p]["exp_avg_sq"]`` (a scalar) instead of two
flat per-group tensors (``group['exp_avg_sq'][0/1]``, fused_novograd.py:158-177)
— same math, but state_dict round-trips through the standard per-param
packing and a third bf16 bucket needs no special casing.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .. import ops
from ..multi_tensor_apply import multi_tensor_applier
from .base import Optimizer, split_by_dtype


@functools.partial(
    jax.jit,
    static_argnames=("beta1", "beta2", "eps", "bias_correction",
                     "weight_decay", "grad_averaging", "moment_mode",
                     "norm_type"))
def _novograd_step(flag, lists, lr, step, beta1, beta2, eps, bias_correction,
                   weight_decay, grad_averaging, moment_mode, norm_type):
    return multi_tensor_applier(
        ops.multi_tensor_novograd, flag, lists, lr, beta1, beta2, eps, step,
        bias_correction, weight_decay, grad_averaging, moment_mode, norm_type)


class FusedNovoGrad(Optimizer):
    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.95, 0.98), eps=1e-8, weight_decay=0.0,
                 amsgrad=False, reg_inside_moment=False, grad_averaging=True,
                 norm_type=2, init_zero=False, set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad "
                               "variant.")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        grad_averaging=grad_averaging, norm_type=norm_type,
                        init_zero=init_zero)
        super().__init__(params, defaults)
        # moment_mode 0 applies weight decay inside the moment update
        # (reference fused_novograd.py:87)
        self.moment_mode = 0 if reg_inside_moment else 1
        self.set_grad_none = set_grad_none
        self._overflow_buf = ops.zero_flag()

    def zero_grad(self, set_to_none: bool = None):
        if set_to_none is None:
            set_to_none = self.set_grad_none
        super().zero_grad(set_to_none)

    def _init_norm(self, p, group):
        """First-step norm init so the first blend is a no-op, or zero
        (reference fused_novograd.py:158-174)."""
        if group["init_zero"]:
            return jnp.zeros((), jnp.float32)
        g = p.grad.astype(jnp.float32)
        if group["norm_type"] == 0:
            return jnp.max(jnp.abs(g))
        elif group["norm_type"] == 2:
            return jnp.sqrt(jnp.sum(g * g))
        raise RuntimeError("FusedNovoGrad only support l2/inf norm now.")

    def step(self, closure=None):
        loss = closure() if closure is not None else None

        for group in self.param_groups:
            bias_correction = bool(group["bias_correction"])
            beta1, beta2 = group["betas"]
            grad_averaging = 1 if group["grad_averaging"] else 0
            group["step"] = group.get("step", 0) + 1

            for dtype, plist in split_by_dtype(group["params"]).items():
                for p in plist:
                    state = self.state[p]
                    if "exp_avg" not in state:
                        state["exp_avg"] = jnp.zeros_like(p.data)
                    if "exp_avg_sq" not in state:
                        state["exp_avg_sq"] = self._init_norm(p, group)
                lists = [[p.grad for p in plist],
                         [p.data for p in plist],
                         [self.state[p]["exp_avg"] for p in plist],
                         [self.state[p]["exp_avg_sq"] for p in plist]]
                _, new_ps, new_ms, new_norms = _novograd_step(
                    self._overflow_buf, lists,
                    jnp.asarray(group["lr"], jnp.float32),
                    jnp.asarray(group["step"], jnp.int32),
                    beta1, beta2, group["eps"], bias_correction,
                    group["weight_decay"], grad_averaging, self.moment_mode,
                    group["norm_type"])
                for p, nd, nm, nv in zip(plist, new_ps, new_ms, new_norms):
                    p.data = nd
                    self.state[p]["exp_avg"] = nm
                    self.state[p]["exp_avg_sq"] = nv
        return loss
