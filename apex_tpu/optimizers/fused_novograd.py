"""FusedNovoGrad (reference: apex/optimizers/fused_novograd.py).

Layout deviation from the reference: per-tensor second-moment norms are kept
one-per-param in ``self.state[p]["exp_avg_sq"]`` (a scalar) instead of two
flat per-group tensors (``group['exp_avg_sq'][0/1]``, fused_novograd.py:158-177)
— same math, but state_dict round-trips through the standard per-param
packing and a third bf16 bucket needs no special casing.

The whole step (all groups × dtype buckets) runs as one step-cache
executable with traced hyperparameters and donated params/moments/norms;
the first-step norm seed stays eager (it happens exactly once).
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import ops
from ..multi_tensor_apply import multi_tensor_applier
from .base import (Optimizer, amp_model_copy_map, dispatch_cached_step,
                   group_buckets)

_f32 = jnp.float32


def _novograd_update(static_cfg, donated, grads, hyper, flag):
    """Pure whole-optimizer NovoGrad update across every group × bucket."""
    bucket_gis, bias_correction, grad_averaging, moment_mode, norm_type = \
        static_cfg
    new_steps = [s + 1 for s in donated["steps"]]
    new_buckets = []
    for entry, gs, gi in zip(donated["buckets"], grads, bucket_gis):
        h = hyper[gi]
        _, new_ps, new_ms, new_norms = multi_tensor_applier(
            ops.multi_tensor_novograd, flag,
            [gs, entry["p"], entry["m"], entry["v"]],
            h["lr"], h["beta1"], h["beta2"], h["eps"], new_steps[gi],
            bias_correction[gi], h["weight_decay"], grad_averaging[gi],
            moment_mode, norm_type[gi])
        out = {"p": new_ps, "m": new_ms, "v": new_norms}
        if "model" in entry:
            out["model"] = [
                None if mp is None else np_.astype(mp.dtype)
                for np_, mp in zip(new_ps, entry["model"])]
        new_buckets.append(out)
    return {"steps": new_steps, "buckets": new_buckets}


class FusedNovoGrad(Optimizer):
    _step_cache_scaler_ok = True

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.95, 0.98), eps=1e-8, weight_decay=0.0,
                 amsgrad=False, reg_inside_moment=False, grad_averaging=True,
                 norm_type=2, init_zero=False, set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad "
                               "variant.")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        grad_averaging=grad_averaging, norm_type=norm_type,
                        init_zero=init_zero)
        super().__init__(params, defaults)
        # moment_mode 0 applies weight decay inside the moment update
        # (reference fused_novograd.py:87)
        self.moment_mode = 0 if reg_inside_moment else 1
        self.set_grad_none = set_grad_none
        self._overflow_buf = ops.zero_flag()

    def _init_norm(self, p, group):
        """First-step norm init so the first blend is a no-op, or zero
        (reference fused_novograd.py:158-174)."""
        if group["init_zero"]:
            return jnp.zeros((), jnp.float32)
        g = p.grad.astype(jnp.float32)
        if group["norm_type"] == 0:
            return jnp.max(jnp.abs(g))
        elif group["norm_type"] == 2:
            return jnp.sqrt(jnp.sum(g * g))
        raise RuntimeError("FusedNovoGrad only support l2/inf norm now.")

    def step(self, closure=None):
        loss = closure() if closure is not None else None

        buckets = group_buckets(self.param_groups)
        if not buckets:
            return loss
        for gi, plist in buckets:
            group = self.param_groups[gi]
            for p in plist:
                state = self.state[p]
                if "exp_avg" not in state:
                    state["exp_avg"] = jnp.zeros_like(p.data)
                if "exp_avg_sq" not in state:
                    state["exp_avg_sq"] = self._init_norm(p, group)

        model_map = amp_model_copy_map(self)
        donated = {"steps": [jnp.asarray(g.get("step", 0), jnp.int32)
                             for g in self.param_groups],
                   "buckets": []}
        grads_tree = []
        for _, plist in buckets:
            entry = {"p": [p.data for p in plist],
                     "m": [self.state[p]["exp_avg"] for p in plist],
                     "v": [self.state[p]["exp_avg_sq"] for p in plist]}
            if model_map is not None:
                entry["model"] = [
                    None if model_map.get(id(p)) is None
                    else model_map[id(p)].data for p in plist]
            donated["buckets"].append(entry)
            grads_tree.append([p.grad for p in plist])

        hyper = []
        for group in self.param_groups:
            beta1, beta2 = group["betas"]
            hyper.append({
                "lr": jnp.asarray(group["lr"], _f32),
                "beta1": jnp.asarray(beta1, _f32),
                "beta2": jnp.asarray(beta2, _f32),
                "eps": jnp.asarray(group["eps"], _f32),
                "weight_decay": jnp.asarray(group["weight_decay"], _f32)})

        static_cfg = (tuple(gi for gi, _ in buckets),
                      tuple(bool(g["bias_correction"])
                            for g in self.param_groups),
                      tuple(1 if g["grad_averaging"] else 0
                            for g in self.param_groups),
                      self.moment_mode,
                      tuple(g["norm_type"] for g in self.param_groups))
        new = dispatch_cached_step(self, "fused_novograd", static_cfg,
                                   _novograd_update, donated, grads_tree,
                                   hyper)

        for group, s in zip(self.param_groups, new["steps"]):
            group["step"] = s
        for (_, plist), entry in zip(buckets, new["buckets"]):
            for i, p in enumerate(plist):
                p.data = entry["p"][i]
                self.state[p]["exp_avg"] = entry["m"][i]
                self.state[p]["exp_avg_sq"] = entry["v"][i]
                if model_map is not None and entry["model"][i] is not None:
                    model_map[id(p)].data = entry["model"][i]
        if model_map is not None:
            self._amp_stash._model_params_synced = True
        return loss
