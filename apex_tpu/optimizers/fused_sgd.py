"""FusedSGD (reference: apex/optimizers/fused_sgd.py).

The whole step — momentum, weight decay, nesterov, grad unscale via
``scale``, and the optional half model-copy writeback for EVERY launch set —
compiles into one step-cache executable with lr/weight_decay/dampening/scale
traced (schedules never retrace) and params/momenta donated.  ``momentum``,
``nesterov`` and ``first_run`` shape the program and stay static;
``first_run`` flips False after the first step, so an SGD instance compiles
exactly twice over its lifetime (the reference re-launches kernels every
step).
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import ops
from ..multi_tensor_apply import multi_tensor_applier
from .base import Optimizer, dispatch_cached_step, required, split_by_dtype

_f32 = jnp.float32


def _sgd_update(static_cfg, donated, grads, hyper, flag):
    """Pure whole-optimizer SGD update over every launch set."""
    set_infos, wd_after_momentum, group_static = static_cfg
    new_sets = []
    for entry, gs, (gid, first_run, has_model) in zip(
            donated["sets"], grads, set_infos):
        h = hyper["groups"][gid]
        momentum, dampening, nesterov = group_static[gid]
        lists = [gs, entry["p"], entry["m"]]
        if has_model:
            lists.append(entry["model"])
        out = multi_tensor_applier(
            ops.multi_tensor_sgd, flag, lists, h["weight_decay"], momentum,
            dampening, h["lr"], nesterov, first_run, wd_after_momentum,
            hyper["scale"])
        if has_model:
            _, new_ps, new_ms, new_model = out
            new_sets.append({"p": new_ps, "m": new_ms, "model": new_model})
        else:
            _, new_ps, new_ms = out
            new_sets.append({"p": new_ps, "m": new_ms})
    return {"sets": new_sets}


class FusedSGD(Optimizer):
    """Drop-in for torch.optim.SGD semantics with multi-tensor batching.

    amp integration (reference fused_sgd.py:95-96,139-212): when
    ``_amp_stash`` is present the 4-list launch writes both the fp32 master
    params and the half model params in one pass, and ``most_recent_scale``
    folds gradient unscaling into the kernel.
    """

    def __init__(self, params, lr=required, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False,
                 wd_after_momentum=False,
                 materialize_master_grads=True):
        if lr is not required and lr < 0.0:
            raise ValueError(f"Invalid learning rate: {lr}")
        if momentum < 0.0:
            raise ValueError(f"Invalid momentum value: {momentum}")
        if weight_decay < 0.0:
            raise ValueError(f"Invalid weight_decay value: {weight_decay}")
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        defaults = dict(lr=lr, momentum=momentum, dampening=dampening,
                        weight_decay=weight_decay, nesterov=nesterov)
        super().__init__(params, defaults)

        self.wd_after_momentum = wd_after_momentum
        self.materialize_master_grads = materialize_master_grads
        self.most_recent_scale = 1.0
        self.scale_set_by_backward = False
        self._overflow_buf = ops.zero_flag()

    def get_momentums(self, params):
        momentums = []
        first_run = True
        for p in params:
            state = self.state[p]
            if "momentum_buffer" not in state:
                first_run = True
                state["momentum_buffer"] = jnp.zeros_like(p.data,
                                                          dtype=jnp.float32)
            else:
                first_run = False
            momentums.append(state["momentum_buffer"])
        return momentums, first_run

    def step(self, closure=None):
        loss = closure() if closure is not None else None

        explicit_master_params = (
            hasattr(self, "_amp_stash")
            and hasattr(self._amp_stash, "fp32_from_fp16_groups"))

        launch_params: list = []   # parallel to launch sets
        launch_sets: list = []
        set_infos: list = []       # (group_index, first_run, has_model)
        model_param_sets: list = []

        for gid, group in enumerate(self.param_groups):
            if explicit_master_params:
                stash = self._amp_stash

                fp32_params = [p for p in stash.fp32_from_fp32_groups[gid]
                               if p.grad is not None]
                fp32_grads = [p.grad for p in fp32_params]
                fp32_mom, fr32 = self.get_momentums(fp32_params)

                if self.materialize_master_grads:
                    fp16_model = [p for i, p in enumerate(stash.fp16_groups[gid])
                                  if stash.fp32_from_fp16_groups[gid][i].grad
                                  is not None]
                    masters = [p for p in stash.fp32_from_fp16_groups[gid]
                               if p.grad is not None]
                    master_grads = [p.grad for p in masters]
                    m_mom, fr16 = self.get_momentums(masters)
                    launch_sets.append([master_grads,
                                        [p.data for p in masters], m_mom,
                                        [p.data for p in fp16_model]])
                else:
                    fp16_model = [p for p in stash.fp16_groups[gid]
                                  if p.grad is not None]
                    model_grads = [p.grad for p in fp16_model]
                    masters = [p for i, p in
                               enumerate(stash.fp32_from_fp16_groups[gid])
                               if stash.fp16_groups[gid][i].grad is not None]
                    m_mom, fr16 = self.get_momentums(masters)
                    launch_sets.append([model_grads,
                                        [p.data for p in masters], m_mom,
                                        [p.data for p in fp16_model]])
                launch_params.append(masters)
                model_param_sets.append(fp16_model)
                set_infos.append((gid, fr16, True))

                launch_sets.append([fp32_grads,
                                    [p.data for p in fp32_params], fp32_mom])
                launch_params.append(fp32_params)
                model_param_sets.append(None)
                set_infos.append((gid, fr32, False))
            else:
                for dtype, plist in split_by_dtype(group["params"]).items():
                    moms, fr = self.get_momentums(plist)
                    launch_sets.append([[p.grad for p in plist],
                                        [p.data for p in plist], moms])
                    launch_params.append(plist)
                    model_param_sets.append(None)
                    set_infos.append((gid, fr, False))

        # drop empty launch sets (their static info goes with them)
        keep = [i for i, ls in enumerate(launch_sets) if ls[0]]
        launch_sets = [launch_sets[i] for i in keep]
        launch_params = [launch_params[i] for i in keep]
        model_param_sets = [model_param_sets[i] for i in keep]
        set_infos = [set_infos[i] for i in keep]
        if not launch_sets:
            self.most_recent_scale = 1.0
            self.scale_set_by_backward = False
            return loss

        donated = {"sets": []}
        grads_tree = []
        for ls, (gid, fr, has_model) in zip(launch_sets, set_infos):
            entry = {"p": ls[1], "m": ls[2]}
            if has_model:
                entry["model"] = ls[3]
            donated["sets"].append(entry)
            grads_tree.append(ls[0])

        hyper = {"groups": [
            {"lr": jnp.asarray(g["lr"], _f32),
             "weight_decay": jnp.asarray(g["weight_decay"], _f32)}
            for g in self.param_groups],
            "scale": jnp.asarray(1.0 / self.most_recent_scale, _f32)}

        static_cfg = (tuple(set_infos), self.wd_after_momentum,
                      tuple((g["momentum"], g["dampening"], g["nesterov"])
                            for g in self.param_groups))
        new = dispatch_cached_step(self, "fused_sgd", static_cfg,
                                   _sgd_update, donated, grads_tree, hyper)

        for plist, model_plist, entry in zip(launch_params, model_param_sets,
                                             new["sets"]):
            for i, p in enumerate(plist):
                p.data = entry["p"][i]
                self.state[p]["momentum_buffer"] = entry["m"][i]
            if model_plist is not None:
                for mp, nd in zip(model_plist, entry["model"]):
                    mp.data = nd

        self.most_recent_scale = 1.0
        self.scale_set_by_backward = False
        return loss
