"""FusedSGD (reference: apex/optimizers/fused_sgd.py).

The whole per-dtype-bucket update — momentum, weight decay, nesterov, grad
unscale via ``scale``, and the optional half model-copy writeback — compiles
into one XLA executable per bucket structure (the reference batches it into
one ``multi_tensor_sgd`` launch; XLA fuses the same way).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .. import ops
from ..multi_tensor_apply import multi_tensor_applier
from .base import Optimizer, required, split_by_dtype


@functools.partial(
    jax.jit,
    static_argnames=("weight_decay", "momentum", "dampening", "nesterov",
                     "first_run", "wd_after_momentum"))
def _sgd_step(flag, lists, lr, scale, weight_decay, momentum, dampening,
              nesterov, first_run, wd_after_momentum):
    return multi_tensor_applier(
        ops.multi_tensor_sgd, flag, lists, weight_decay, momentum, dampening,
        lr, nesterov, first_run, wd_after_momentum, scale)


class FusedSGD(Optimizer):
    """Drop-in for torch.optim.SGD semantics with multi-tensor batching.

    amp integration (reference fused_sgd.py:95-96,139-212): when
    ``_amp_stash`` is present the 4-list launch writes both the fp32 master
    params and the half model params in one pass, and ``most_recent_scale``
    folds gradient unscaling into the kernel.
    """

    def __init__(self, params, lr=required, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False,
                 wd_after_momentum=False,
                 materialize_master_grads=True):
        if lr is not required and lr < 0.0:
            raise ValueError(f"Invalid learning rate: {lr}")
        if momentum < 0.0:
            raise ValueError(f"Invalid momentum value: {momentum}")
        if weight_decay < 0.0:
            raise ValueError(f"Invalid weight_decay value: {weight_decay}")
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        defaults = dict(lr=lr, momentum=momentum, dampening=dampening,
                        weight_decay=weight_decay, nesterov=nesterov)
        super().__init__(params, defaults)

        self.wd_after_momentum = wd_after_momentum
        self.materialize_master_grads = materialize_master_grads
        self.most_recent_scale = 1.0
        self.scale_set_by_backward = False
        self._overflow_buf = ops.zero_flag()

    def get_momentums(self, params):
        momentums = []
        first_run = True
        for p in params:
            state = self.state[p]
            if "momentum_buffer" not in state:
                first_run = True
                state["momentum_buffer"] = jnp.zeros_like(p.data,
                                                          dtype=jnp.float32)
            else:
                first_run = False
            momentums.append(state["momentum_buffer"])
        return momentums, first_run

    def step(self, closure=None):
        loss = closure() if closure is not None else None

        explicit_master_params = (
            hasattr(self, "_amp_stash")
            and hasattr(self._amp_stash, "fp32_from_fp16_groups"))

        for gid, group in enumerate(self.param_groups):
            wd = group["weight_decay"]
            momentum = group["momentum"]
            dampening = group["dampening"]
            nesterov = group["nesterov"]

            launch_params: list = []   # parallel to launch sets
            launch_sets: list = []
            first_runs: list = []
            model_param_sets: list = []

            if explicit_master_params:
                stash = self._amp_stash

                fp32_params = [p for p in stash.fp32_from_fp32_groups[gid]
                               if p.grad is not None]
                fp32_grads = [p.grad for p in fp32_params]
                fp32_mom, fr32 = self.get_momentums(fp32_params)

                if self.materialize_master_grads:
                    fp16_model = [p for i, p in enumerate(stash.fp16_groups[gid])
                                  if stash.fp32_from_fp16_groups[gid][i].grad
                                  is not None]
                    masters = [p for p in stash.fp32_from_fp16_groups[gid]
                               if p.grad is not None]
                    master_grads = [p.grad for p in masters]
                    m_mom, fr16 = self.get_momentums(masters)
                    launch_sets.append([master_grads,
                                        [p.data for p in masters], m_mom,
                                        [p.data for p in fp16_model]])
                else:
                    fp16_model = [p for p in stash.fp16_groups[gid]
                                  if p.grad is not None]
                    model_grads = [p.grad for p in fp16_model]
                    masters = [p for i, p in
                               enumerate(stash.fp32_from_fp16_groups[gid])
                               if stash.fp16_groups[gid][i].grad is not None]
                    m_mom, fr16 = self.get_momentums(masters)
                    launch_sets.append([model_grads,
                                        [p.data for p in masters], m_mom,
                                        [p.data for p in fp16_model]])
                launch_params.append(masters)
                model_param_sets.append(fp16_model)
                first_runs.append(fr16)

                launch_sets.append([fp32_grads,
                                    [p.data for p in fp32_params], fp32_mom])
                launch_params.append(fp32_params)
                model_param_sets.append(None)
                first_runs.append(fr32)
            else:
                for dtype, plist in split_by_dtype(group["params"]).items():
                    moms, fr = self.get_momentums(plist)
                    launch_sets.append([[p.grad for p in plist],
                                        [p.data for p in plist], moms])
                    launch_params.append(plist)
                    model_param_sets.append(None)
                    first_runs.append(fr)

            for plist, launch_set, model_plist, first_run in zip(
                    launch_params, launch_sets, model_param_sets, first_runs):
                if not launch_set[0]:
                    continue
                out = _sgd_step(
                    self._overflow_buf, launch_set,
                    jnp.asarray(group["lr"], jnp.float32),
                    jnp.asarray(1.0 / self.most_recent_scale, jnp.float32),
                    wd, momentum, dampening, nesterov, first_run,
                    self.wd_after_momentum)
                if model_plist is not None:
                    _, new_ps, new_ms, new_model = out
                    for mp, nd in zip(model_plist, new_model):
                        mp.data = nd
                else:
                    _, new_ps, new_ms = out
                for p, nd, nm in zip(plist, new_ps, new_ms):
                    p.data = nd
                    self.state[p]["momentum_buffer"] = nm

        self.most_recent_scale = 1.0
        self.scale_set_by_backward = False
        return loss
