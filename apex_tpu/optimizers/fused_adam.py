"""FusedAdam (reference: apex/optimizers/fused_adam.py) — Adam/AdamW with the
whole per-dtype-bucket update compiled into one XLA executable."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .. import ops
from ..multi_tensor_apply import multi_tensor_applier
from .base import Optimizer, split_by_dtype


@functools.partial(
    jax.jit,
    static_argnames=("beta1", "beta2", "eps", "mode", "bias_correction",
                     "weight_decay"))
def _adam_step(flag, lists, lr, step, beta1, beta2, eps, mode,
               bias_correction, weight_decay):
    return multi_tensor_applier(
        ops.multi_tensor_adam, flag, lists, lr, beta1, beta2, eps, step,
        mode, bias_correction, weight_decay)


class FusedAdam(Optimizer):
    """Drop-in replacement for torch.optim.Adam / AdamW
    (``adam_w_mode=True`` selects decoupled weight decay, reference
    fused_adam.py:52-54,75)."""

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, adam_w_mode=True,
                 weight_decay=0.0, amsgrad=False, set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad "
                               "variant.")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay)
        super().__init__(params, defaults)
        self.adam_w_mode = 1 if adam_w_mode else 0
        self.set_grad_none = set_grad_none
        self._overflow_buf = ops.zero_flag()

    def zero_grad(self, set_to_none: bool = None):
        if set_to_none is None:
            set_to_none = self.set_grad_none
        super().zero_grad(set_to_none)

    def step(self, closure=None, grads=None, output_params=None, scale=None,
             grad_norms=None):
        if any(x is not None for x in [grads, output_params, scale,
                                       grad_norms]):
            raise RuntimeError(
                "FusedAdam has been updated.  Simply initialize it "
                "identically to torch.optim.Adam, and call step() with no "
                "arguments.")
        loss = closure() if closure is not None else None

        for group in self.param_groups:
            bias_correction = bool(group["bias_correction"])
            beta1, beta2 = group["betas"]
            group["step"] = group.get("step", 0) + 1

            for dtype, plist in split_by_dtype(group["params"]).items():
                for p in plist:
                    state = self.state[p]
                    if len(state) == 0:
                        state["exp_avg"] = jnp.zeros_like(p.data)
                        state["exp_avg_sq"] = jnp.zeros_like(p.data)
                lists = [[p.grad for p in plist],
                         [p.data for p in plist],
                         [self.state[p]["exp_avg"] for p in plist],
                         [self.state[p]["exp_avg_sq"] for p in plist]]
                _, new_ps, new_ms, new_vs = _adam_step(
                    self._overflow_buf, lists,
                    jnp.asarray(group["lr"], jnp.float32),
                    jnp.asarray(group["step"], jnp.int32),
                    beta1, beta2, group["eps"], self.adam_w_mode,
                    bias_correction, group["weight_decay"])
                for p, nd, nm, nv in zip(plist, new_ps, new_ms, new_vs):
                    p.data = nd
                    self.state[p]["exp_avg"] = nm
                    self.state[p]["exp_avg_sq"] = nv
        return loss
