"""FusedAdam (reference: apex/optimizers/fused_adam.py) — Adam/AdamW with the
ENTIRE step (every param group × dtype bucket, overflow-conditional skip,
optional fused master→model half copy under amp) compiled into one XLA
executable by the step cache (``apex_tpu.runtime.step_cache``).

All scalar hyperparameters — lr, betas, eps, weight_decay, step — enter the
program as traced device scalars, so lr/wd/beta schedules never retrace;
params and both moments are donated, so steady-state stepping allocates
nothing (the reference's ``multi_tensor_adam`` launch amortisation, taken to
its XLA conclusion).
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import ops
from ..multi_tensor_apply import multi_tensor_applier
from .base import (Optimizer, amp_model_copy_map, dispatch_cached_step,
                   group_buckets)

_f32 = jnp.float32


def _adam_update(static_cfg, donated, grads, hyper, flag):
    """Pure whole-optimizer Adam/AdamW update; traced once per structure by
    the step cache, then dispatched as one executable per step."""
    mode, bucket_gis, bias_correction = static_cfg
    new_steps = [s + 1 for s in donated["steps"]]
    new_buckets = []
    for entry, gs, gi in zip(donated["buckets"], grads, bucket_gis):
        h = hyper[gi]
        _, new_ps, new_ms, new_vs = multi_tensor_applier(
            ops.multi_tensor_adam, flag,
            [gs, entry["p"], entry["m"], entry["v"]],
            h["lr"], h["beta1"], h["beta2"], h["eps"], new_steps[gi],
            mode, bias_correction[gi], h["weight_decay"])
        out = {"p": new_ps, "m": new_ms, "v": new_vs}
        if "model" in entry:
            out["model"] = [
                None if mp is None else np_.astype(mp.dtype)
                for np_, mp in zip(new_ps, entry["model"])]
        new_buckets.append(out)
    return {"steps": new_steps, "buckets": new_buckets}


class FusedAdam(Optimizer):
    """Drop-in replacement for torch.optim.Adam / AdamW
    (``adam_w_mode=True`` selects decoupled weight decay, reference
    fused_adam.py:52-54,75)."""

    # the step-cache program can fuse the deferred dynamic-scale update
    # (amp.initialize(..., defer_scale_update=True))
    _step_cache_scaler_ok = True

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, adam_w_mode=True,
                 weight_decay=0.0, amsgrad=False, set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad "
                               "variant.")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay)
        super().__init__(params, defaults)
        self.adam_w_mode = 1 if adam_w_mode else 0
        self.set_grad_none = set_grad_none
        self._overflow_buf = ops.zero_flag()

    def step(self, closure=None, grads=None, output_params=None, scale=None,
             grad_norms=None):
        if any(x is not None for x in [grads, output_params, scale,
                                       grad_norms]):
            raise RuntimeError(
                "FusedAdam has been updated.  Simply initialize it "
                "identically to torch.optim.Adam, and call step() with no "
                "arguments.")
        loss = closure() if closure is not None else None

        buckets = group_buckets(self.param_groups)
        if not buckets:
            return loss
        for _, plist in buckets:
            for p in plist:
                state = self.state[p]
                if len(state) == 0:
                    state["exp_avg"] = jnp.zeros_like(p.data)
                    state["exp_avg_sq"] = jnp.zeros_like(p.data)

        model_map = amp_model_copy_map(self)
        donated = {"steps": [jnp.asarray(g.get("step", 0), jnp.int32)
                             for g in self.param_groups],
                   "buckets": []}
        grads_tree = []
        for _, plist in buckets:
            entry = {"p": [p.data for p in plist],
                     "m": [self.state[p]["exp_avg"] for p in plist],
                     "v": [self.state[p]["exp_avg_sq"] for p in plist]}
            if model_map is not None:
                models = [model_map.get(id(p)) for p in plist]
                entry["model"] = [None if mp is None else mp.data
                                  for mp in models]
            donated["buckets"].append(entry)
            grads_tree.append([p.grad for p in plist])

        hyper = []
        for group in self.param_groups:
            beta1, beta2 = group["betas"]
            hyper.append({
                "lr": jnp.asarray(group["lr"], _f32),
                "beta1": jnp.asarray(beta1, _f32),
                "beta2": jnp.asarray(beta2, _f32),
                "eps": jnp.asarray(group["eps"], _f32),
                "weight_decay": jnp.asarray(group["weight_decay"], _f32)})

        static_cfg = (self.adam_w_mode, tuple(gi for gi, _ in buckets),
                      tuple(bool(g["bias_correction"])
                            for g in self.param_groups))
        new = dispatch_cached_step(self, "fused_adam", static_cfg,
                                   _adam_update, donated, grads_tree, hyper)

        for group, s in zip(self.param_groups, new["steps"]):
            group["step"] = s
        for (_, plist), entry in zip(buckets, new["buckets"]):
            for i, p in enumerate(plist):
                p.data = entry["p"][i]
                self.state[p]["exp_avg"] = entry["m"][i]
                self.state[p]["exp_avg_sq"] = entry["v"][i]
                if model_map is not None and entry["model"][i] is not None:
                    model_map[id(p)].data = entry["model"][i]
        if model_map is not None:
            self._amp_stash._model_params_synced = True
        return loss
