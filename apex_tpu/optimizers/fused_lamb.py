"""FusedLAMB (reference: apex/optimizers/fused_lamb.py).

As in the reference host function (csrc/multi_tensor_lamb.cu:241-247), the
gradient norm for clipping is computed over the launched list — but here the
per-bucket l2norm + stage1 + per-tensor norms + stage2 for EVERY group and
dtype bucket compile into one step-cache executable with traced
hyperparameters and donated params/moments.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import ops
from ..multi_tensor_apply import multi_tensor_applier
from .base import (Optimizer, amp_model_copy_map, dispatch_cached_step,
                   group_buckets)

_f32 = jnp.float32


def _lamb_update(static_cfg, donated, grads, hyper, flag):
    """Pure whole-optimizer LAMB update (grad-norm clip per bucket, Adam
    moments, per-tensor trust ratios) across every group × dtype bucket."""
    mode, bucket_gis, bias_correction, grad_averaging, max_grad_norm = \
        static_cfg
    new_steps = [s + 1 for s in donated["steps"]]
    new_buckets = []
    for entry, gs, gi in zip(donated["buckets"], grads, bucket_gis):
        h = hyper[gi]
        _, grad_norm, _ = ops.multi_tensor_l2norm(flag, [gs])
        _, new_ps, new_ms, new_vs = multi_tensor_applier(
            ops.multi_tensor_lamb, flag,
            [gs, entry["p"], entry["m"], entry["v"]],
            h["lr"], h["beta1"], h["beta2"], h["eps"], new_steps[gi],
            bias_correction[gi], h["weight_decay"], grad_averaging[gi],
            mode, grad_norm, max_grad_norm[gi])
        out = {"p": new_ps, "m": new_ms, "v": new_vs}
        if "model" in entry:
            out["model"] = [
                None if mp is None else np_.astype(mp.dtype)
                for np_, mp in zip(new_ps, entry["model"])]
        new_buckets.append(out)
    return {"steps": new_steps, "buckets": new_buckets}


class FusedLAMB(Optimizer):
    """LAMB with global-grad-norm clipping and per-tensor trust ratios
    (reference fused_lamb.py:4,92-175)."""

    _step_cache_scaler_ok = True

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 amsgrad=False, adam_w_mode=True, grad_averaging=True,
                 set_grad_none=True, max_grad_norm=1.0):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad "
                               "variant.")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        grad_averaging=grad_averaging,
                        max_grad_norm=max_grad_norm)
        super().__init__(params, defaults)
        self.adam_w_mode = 1 if adam_w_mode else 0
        self.set_grad_none = set_grad_none
        self._overflow_buf = ops.zero_flag()

    def step(self, closure=None):
        loss = closure() if closure is not None else None

        buckets = group_buckets(self.param_groups)
        if not buckets:
            return loss
        for _, plist in buckets:
            for p in plist:
                state = self.state[p]
                if len(state) == 0:
                    state["exp_avg"] = jnp.zeros_like(p.data)
                    state["exp_avg_sq"] = jnp.zeros_like(p.data)

        model_map = amp_model_copy_map(self)
        donated = {"steps": [jnp.asarray(g.get("step", 0), jnp.int32)
                             for g in self.param_groups],
                   "buckets": []}
        grads_tree = []
        for _, plist in buckets:
            entry = {"p": [p.data for p in plist],
                     "m": [self.state[p]["exp_avg"] for p in plist],
                     "v": [self.state[p]["exp_avg_sq"] for p in plist]}
            if model_map is not None:
                entry["model"] = [
                    None if model_map.get(id(p)) is None
                    else model_map[id(p)].data for p in plist]
            donated["buckets"].append(entry)
            grads_tree.append([p.grad for p in plist])

        hyper = []
        for group in self.param_groups:
            beta1, beta2 = group["betas"]
            hyper.append({
                "lr": jnp.asarray(group["lr"], _f32),
                "beta1": jnp.asarray(beta1, _f32),
                "beta2": jnp.asarray(beta2, _f32),
                "eps": jnp.asarray(group["eps"], _f32),
                "weight_decay": jnp.asarray(group["weight_decay"], _f32)})

        static_cfg = (self.adam_w_mode, tuple(gi for gi, _ in buckets),
                      tuple(bool(g["bias_correction"])
                            for g in self.param_groups),
                      tuple(1 if g["grad_averaging"] else 0
                            for g in self.param_groups),
                      tuple(g["max_grad_norm"] for g in self.param_groups))
        new = dispatch_cached_step(self, "fused_lamb", static_cfg,
                                   _lamb_update, donated, grads_tree, hyper)

        for group, s in zip(self.param_groups, new["steps"]):
            group["step"] = s
        for (_, plist), entry in zip(buckets, new["buckets"]):
            for i, p in enumerate(plist):
                p.data = entry["p"][i]
                self.state[p]["exp_avg"] = entry["m"][i]
                self.state[p]["exp_avg_sq"] = entry["v"][i]
                if model_map is not None and entry["model"][i] is not None:
                    model_map[id(p)].data = entry["model"][i]
        if model_map is not None:
            self._amp_stash._model_params_synced = True
        return loss
