"""FusedLAMB (reference: apex/optimizers/fused_lamb.py).

As in the reference host function (csrc/multi_tensor_lamb.cu:241-247), the
gradient norm for clipping is computed over the launched list — one fused
program per dtype bucket: l2norm + stage1 + per-tensor norms + stage2.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .. import ops
from ..multi_tensor_apply import multi_tensor_applier
from .base import Optimizer, split_by_dtype


@functools.partial(
    jax.jit,
    static_argnames=("beta1", "beta2", "eps", "bias_correction",
                     "weight_decay", "grad_averaging", "mode",
                     "max_grad_norm"))
def _lamb_step(flag, lists, lr, step, beta1, beta2, eps, bias_correction,
               weight_decay, grad_averaging, mode, max_grad_norm):
    flag, grad_norm, _ = ops.multi_tensor_l2norm(flag, [lists[0]])
    return multi_tensor_applier(
        ops.multi_tensor_lamb, flag, lists, lr, beta1, beta2, eps, step,
        bias_correction, weight_decay, grad_averaging, mode, grad_norm,
        max_grad_norm)


class FusedLAMB(Optimizer):
    """LAMB with global-grad-norm clipping and per-tensor trust ratios
    (reference fused_lamb.py:4,92-175)."""

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 amsgrad=False, adam_w_mode=True, grad_averaging=True,
                 set_grad_none=True, max_grad_norm=1.0):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad "
                               "variant.")
        defaults = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                        eps=eps, weight_decay=weight_decay,
                        grad_averaging=grad_averaging,
                        max_grad_norm=max_grad_norm)
        super().__init__(params, defaults)
        self.adam_w_mode = 1 if adam_w_mode else 0
        self.set_grad_none = set_grad_none
        self._overflow_buf = ops.zero_flag()

    def zero_grad(self, set_to_none: bool = None):
        if set_to_none is None:
            set_to_none = self.set_grad_none
        super().zero_grad(set_to_none)

    def step(self, closure=None):
        loss = closure() if closure is not None else None

        for group in self.param_groups:
            bias_correction = bool(group["bias_correction"])
            beta1, beta2 = group["betas"]
            grad_averaging = 1 if group["grad_averaging"] else 0
            group["step"] = group.get("step", 0) + 1

            for dtype, plist in split_by_dtype(group["params"]).items():
                for p in plist:
                    state = self.state[p]
                    if len(state) == 0:
                        state["exp_avg"] = jnp.zeros_like(p.data)
                        state["exp_avg_sq"] = jnp.zeros_like(p.data)
                lists = [[p.grad for p in plist],
                         [p.data for p in plist],
                         [self.state[p]["exp_avg"] for p in plist],
                         [self.state[p]["exp_avg_sq"] for p in plist]]
                _, new_ps, new_ms, new_vs = _lamb_step(
                    self._overflow_buf, lists,
                    jnp.asarray(group["lr"], jnp.float32),
                    jnp.asarray(group["step"], jnp.int32),
                    beta1, beta2, group["eps"], bias_correction,
                    group["weight_decay"], grad_averaging, self.adam_w_mode,
                    group["max_grad_norm"])
                for p, nd, nm, nv in zip(plist, new_ps, new_ms, new_vs):
                    p.data = nd
                    self.state[p]["exp_avg"] = nm
                    self.state[p]["exp_avg_sq"] = nv
        return loss
