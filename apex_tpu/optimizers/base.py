"""Optimizer base class with torch.optim semantics (param_groups / state /
zero_grad / add_param_group / state_dict), holding apex_tpu.nn.Parameter
handles whose ``.data``/``.grad`` are jax Arrays.

The reference optimizers subclass torch.optim.Optimizer; this provides the
same observable surface so the amp layer (`_process_optimizer`) can patch
instances the way apex does (reference: apex/amp/_process_optimizer.py:321).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, Iterable, List

import jax.numpy as jnp

from ..nn.parameter import Parameter

required = object()  # sentinel, as torch.optim.optimizer.required


class Optimizer:
    #: default for ``zero_grad(set_to_none=None)``.  The fused optimizers
    #: consume gradients functionally through the step cache (grads are
    #: inputs of the compiled step, never written back), so dropping them is
    #: free while ``jnp.zeros_like`` per param per step is real allocation
    #: churn — True is the effective default on the whole fused path (torch
    #: >= 2.0 semantics; subclasses may override per instance).
    set_grad_none: bool = True

    def __init__(self, params, defaults: Dict[str, Any]):
        self.defaults = defaults
        self.state: Dict[Parameter, Dict[str, Any]] = defaultdict(dict)
        self.param_groups: List[Dict[str, Any]] = []

        param_groups = list(params)
        if len(param_groups) == 0:
            raise ValueError("optimizer got an empty parameter list")
        if not isinstance(param_groups[0], dict):
            param_groups = [{"params": param_groups}]
        for group in param_groups:
            self.add_param_group(group)

    def add_param_group(self, param_group: Dict[str, Any]):
        assert isinstance(param_group, dict), "param group must be a dict"
        params = param_group["params"]
        if isinstance(params, Parameter):
            param_group["params"] = [params]
        else:
            param_group["params"] = list(params)
        for p in param_group["params"]:
            if not isinstance(p, Parameter):
                raise TypeError(
                    f"optimizer can only optimize Parameters, got {type(p)}")
        for name, default in self.defaults.items():
            if default is required and name not in param_group:
                raise ValueError(
                    f"parameter group didn't specify a value of required "
                    f"optimization parameter {name}")
            param_group.setdefault(name, default)

        seen = set()
        for group in self.param_groups:
            seen.update(id(p) for p in group["params"])
        if any(id(p) in seen for p in param_group["params"]):
            raise ValueError("some parameters appear in more than one "
                             "parameter group")
        self.param_groups.append(param_group)

    def zero_grad(self, set_to_none: bool = None):
        if set_to_none is None:
            set_to_none = self.set_grad_none
        for group in self.param_groups:
            for p in group["params"]:
                if set_to_none:
                    p.grad = None
                elif p.grad is not None:
                    p.grad = jnp.zeros_like(p.grad)

    # -- checkpointing (torch-compatible structure) ------------------------
    def _all_params(self) -> List[Parameter]:
        return [p for g in self.param_groups for p in g["params"]]

    def state_dict(self) -> Dict[str, Any]:
        param_mappings: Dict[int, int] = {}
        start = 0
        packed_groups = []
        for group in self.param_groups:
            packed = {k: v for k, v in group.items() if k != "params"}
            param_mappings.update(
                {id(p): i + start for i, p in enumerate(group["params"])})
            packed["params"] = [param_mappings[id(p)] for p in group["params"]]
            start += len(group["params"])
            packed_groups.append(packed)
        packed_state = {param_mappings[id(p)]: v for p, v in self.state.items()
                        if isinstance(p, Parameter)}
        return {"state": packed_state, "param_groups": packed_groups}

    def load_state_dict(self, state_dict: Dict[str, Any]):
        groups = self.param_groups
        saved_groups = state_dict["param_groups"]
        if len(groups) != len(saved_groups):
            raise ValueError("loaded state dict has a different number of "
                             "parameter groups")
        idx_to_param = {}
        start = 0
        for group, saved in zip(groups, saved_groups):
            if len(group["params"]) != len(saved["params"]):
                raise ValueError("loaded state dict contains a parameter "
                                 "group that doesn't match the size of "
                                 "optimizer's group")
            for i, p in enumerate(group["params"]):
                idx_to_param[saved["params"][i]] = p
            start += len(group["params"])
            for k, v in saved.items():
                if k != "params":
                    group[k] = v
        self.state = defaultdict(dict)
        for idx, s in state_dict["state"].items():
            self.state[idx_to_param[idx]] = {
                k: (jnp.asarray(v) if hasattr(v, "shape") else v)
                for k, v in s.items()}

    def step(self, closure=None):
        raise NotImplementedError


def group_buckets(param_groups):
    """Eager-order ``(group_index, [Parameter, ...])`` dtype buckets across
    ALL param groups — the unit the step-cache program compiles over (the
    reference dispatches one kernel launch per group × dtype; the step cache
    folds every bucket into one executable)."""
    out = []
    for gi, group in enumerate(param_groups):
        for plist in split_by_dtype(group["params"]).values():
            out.append((gi, plist))
    return out


def amp_model_copy_map(optimizer):
    """master-Parameter-id → half model Parameter, when ``optimizer`` has
    been processed by amp with master weights.  Lets the step cache emit the
    master→model half copies from the SAME executable as the update (the
    amp-patched ``step`` then skips its separate copyback pass).  None when
    there is nothing to sync."""
    stash = getattr(optimizer, "_amp_stash", None)
    if stash is None or not getattr(stash, "lazy_init_called", False):
        return None
    masters = getattr(stash, "all_fp32_from_fp16_params", None)
    if not masters:
        return None
    return {id(mp): hp for mp, hp in zip(masters, stash.all_fp16_params)}


def dispatch_cached_step(optimizer, kind, static_cfg, update, donated, grads,
                         hyper):
    """Route one whole-optimizer step through the runtime executor.

    When ``amp.initialize(..., defer_scale_update=True)`` handed this
    optimizer a pending scaler (``_amp_stash._deferred_scaler``), the
    overflow-conditional skip AND the dynamic-loss-scale update fuse into
    the same executable with the scaler state donated; otherwise the plain
    program conditions on the optimizer's own overflow buffer.
    Returns the new donated tree; the caller rebinds every leaf.
    """
    from ..runtime import executor

    stash = getattr(optimizer, "_amp_stash", None)
    scaler = getattr(stash, "_deferred_scaler", None) if stash is not None \
        else None
    if scaler is not None:
        scaler_cfg = (("dynamic", scaler.dynamic),
                      ("scale_factor", scaler._scale_factor),
                      ("scale_window", scaler._scale_seq_len),
                      ("min_loss_scale", scaler._min_loss_scale),
                      ("max_loss_scale", scaler._max_loss_scale))
        new_state, new_donated = executor.optimizer_step_with_scaler(
            kind, static_cfg, update, scaler.state, scaler_cfg, donated,
            grads, hyper)
        scaler.state = new_state
        stash._deferred_scaler = None
        return new_donated
    return executor.optimizer_step(
        kind, static_cfg, update, optimizer._overflow_buf, donated, grads,
        hyper)


def split_by_dtype(params: Iterable[Parameter]):
    """Group params-with-grads by storage dtype, preserving order.

    The reference splits fp16/fp32 (e.g. fused_adam.py:118-140); on TPU the
    cross-product adds bf16.  Returns dict dtype -> list[Parameter].
    """
    buckets: Dict[Any, List[Parameter]] = {}
    for p in params:
        if p.grad is None:
            continue
        buckets.setdefault(jnp.dtype(p.dtype), []).append(p)
    return buckets
