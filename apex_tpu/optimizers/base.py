"""Optimizer base class with torch.optim semantics (param_groups / state /
zero_grad / add_param_group / state_dict), holding apex_tpu.nn.Parameter
handles whose ``.data``/``.grad`` are jax Arrays.

The reference optimizers subclass torch.optim.Optimizer; this provides the
same observable surface so the amp layer (`_process_optimizer`) can patch
instances the way apex does (reference: apex/amp/_process_optimizer.py:321).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, Iterable, List

import jax.numpy as jnp

from ..nn.parameter import Parameter

required = object()  # sentinel, as torch.optim.optimizer.required


class Optimizer:
    def __init__(self, params, defaults: Dict[str, Any]):
        self.defaults = defaults
        self.state: Dict[Parameter, Dict[str, Any]] = defaultdict(dict)
        self.param_groups: List[Dict[str, Any]] = []

        param_groups = list(params)
        if len(param_groups) == 0:
            raise ValueError("optimizer got an empty parameter list")
        if not isinstance(param_groups[0], dict):
            param_groups = [{"params": param_groups}]
        for group in param_groups:
            self.add_param_group(group)

    def add_param_group(self, param_group: Dict[str, Any]):
        assert isinstance(param_group, dict), "param group must be a dict"
        params = param_group["params"]
        if isinstance(params, Parameter):
            param_group["params"] = [params]
        else:
            param_group["params"] = list(params)
        for p in param_group["params"]:
            if not isinstance(p, Parameter):
                raise TypeError(
                    f"optimizer can only optimize Parameters, got {type(p)}")
        for name, default in self.defaults.items():
            if default is required and name not in param_group:
                raise ValueError(
                    f"parameter group didn't specify a value of required "
                    f"optimization parameter {name}")
            param_group.setdefault(name, default)

        seen = set()
        for group in self.param_groups:
            seen.update(id(p) for p in group["params"])
        if any(id(p) in seen for p in param_group["params"]):
            raise ValueError("some parameters appear in more than one "
                             "parameter group")
        self.param_groups.append(param_group)

    def zero_grad(self, set_to_none: bool = False):
        for group in self.param_groups:
            for p in group["params"]:
                if set_to_none:
                    p.grad = None
                elif p.grad is not None:
                    p.grad = jnp.zeros_like(p.grad)

    # -- checkpointing (torch-compatible structure) ------------------------
    def _all_params(self) -> List[Parameter]:
        return [p for g in self.param_groups for p in g["params"]]

    def state_dict(self) -> Dict[str, Any]:
        param_mappings: Dict[int, int] = {}
        start = 0
        packed_groups = []
        for group in self.param_groups:
            packed = {k: v for k, v in group.items() if k != "params"}
            param_mappings.update(
                {id(p): i + start for i, p in enumerate(group["params"])})
            packed["params"] = [param_mappings[id(p)] for p in group["params"]]
            start += len(group["params"])
            packed_groups.append(packed)
        packed_state = {param_mappings[id(p)]: v for p, v in self.state.items()
                        if isinstance(p, Parameter)}
        return {"state": packed_state, "param_groups": packed_groups}

    def load_state_dict(self, state_dict: Dict[str, Any]):
        groups = self.param_groups
        saved_groups = state_dict["param_groups"]
        if len(groups) != len(saved_groups):
            raise ValueError("loaded state dict has a different number of "
                             "parameter groups")
        idx_to_param = {}
        start = 0
        for group, saved in zip(groups, saved_groups):
            if len(group["params"]) != len(saved["params"]):
                raise ValueError("loaded state dict contains a parameter "
                                 "group that doesn't match the size of "
                                 "optimizer's group")
            for i, p in enumerate(group["params"]):
                idx_to_param[saved["params"][i]] = p
            start += len(group["params"])
            for k, v in saved.items():
                if k != "params":
                    group[k] = v
        self.state = defaultdict(dict)
        for idx, s in state_dict["state"].items():
            self.state[idx_to_param[idx]] = {
                k: (jnp.asarray(v) if hasattr(v, "shape") else v)
                for k, v in s.items()}

    def step(self, closure=None):
        raise NotImplementedError


def split_by_dtype(params: Iterable[Parameter]):
    """Group params-with-grads by storage dtype, preserving order.

    The reference splits fp16/fp32 (e.g. fused_adam.py:118-140); on TPU the
    cross-product adds bf16.  Returns dict dtype -> list[Parameter].
    """
    buckets: Dict[Any, List[Parameter]] = {}
    for p in params:
        if p.grad is None:
            continue
        buckets.setdefault(jnp.dtype(p.dtype), []).append(p)
    return buckets
