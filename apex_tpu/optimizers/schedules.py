"""Jit-safe learning-rate schedules for the fused train step.

Each factory returns ``schedule(step) -> multiplier`` on the optimizer
groups' base lr, evaluated on-device from the traced 1-based step counter
(``make_train_step(lr_schedule=...)``) — the lr changes every step with
zero recompiles, where mutating ``group["lr"]`` (the eager torch pattern)
would re-trace.  Schedules also accept plain ints for logging/plotting.
The reference ships no schedulers (its users pulled them from torch);
these cover the standard pretraining recipes (BERT's warmup+linear-decay,
GPT/Chinchilla-style warmup+cosine).
"""
from __future__ import annotations

import jax.numpy as jnp


def _check_warmup(warmup_steps, total_steps):
    if not 0 < warmup_steps < total_steps:
        raise ValueError(
            f"need 0 < warmup_steps < total_steps, got "
            f"{warmup_steps}, {total_steps}")


def _as_f32(step):
    return jnp.asarray(step).astype(jnp.float32)


def warmup_poly(warmup_steps: int, total_steps: int, power: float = 1.0,
                min_ratio: float = 0.0):
    """Linear warmup 0→1 over ``warmup_steps``, then polynomial decay to
    ``min_ratio`` at ``total_steps`` (clamped past the end)."""
    _check_warmup(warmup_steps, total_steps)

    def schedule(step):
        s = _as_f32(step)
        warm = s / warmup_steps
        frac = jnp.clip((total_steps - s)
                        / float(total_steps - warmup_steps), 0.0, 1.0)
        decay = min_ratio + (1.0 - min_ratio) * frac ** power
        return jnp.where(s < warmup_steps, warm, decay)

    return schedule


def warmup_linear(warmup_steps: int, total_steps: int,
                  min_ratio: float = 0.0):
    """Linear warmup then linear decay (BERT pretraining shape) —
    ``warmup_poly`` with ``power=1``."""
    return warmup_poly(warmup_steps, total_steps, power=1.0,
                       min_ratio=min_ratio)


def warmup_cosine(warmup_steps: int, total_steps: int,
                  min_ratio: float = 0.0):
    """Linear warmup then cosine decay to ``min_ratio`` (GPT shape)."""
    _check_warmup(warmup_steps, total_steps)

    def schedule(step):
        s = _as_f32(step)
        warm = s / warmup_steps
        prog = jnp.clip((s - warmup_steps)
                        / float(total_steps - warmup_steps), 0.0, 1.0)
        decay = min_ratio + (1.0 - min_ratio) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * prog))
        return jnp.where(s < warmup_steps, warm, decay)

    return schedule


def step_decay(boundaries, factors):
    """Piecewise-constant multiplier: after ``boundaries[i]`` steps the
    multiplier becomes ``factors[i]`` (the classic /10-at-epoch-N imagenet
    recipe, expressed in steps).  Boundaries must ascend — the pairing
    with factors depends on it."""
    boundaries = list(boundaries)
    if len(boundaries) != len(factors):
        raise ValueError("boundaries and factors must align")
    if boundaries != sorted(boundaries):
        raise ValueError(
            f"boundaries must be ascending, got {boundaries}")
    bs = jnp.asarray(boundaries, jnp.float32)
    fs = jnp.asarray([1.0] + list(factors), jnp.float32)

    def schedule(step):
        idx = jnp.sum(_as_f32(step) >= bs)
        return fs[idx]

    return schedule
