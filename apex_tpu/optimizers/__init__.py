from .base import Optimizer, required, split_by_dtype  # noqa: F401
from .fused_adam import FusedAdam  # noqa: F401
from .fused_lamb import FusedLAMB  # noqa: F401
from .fused_novograd import FusedNovoGrad  # noqa: F401
from .fused_sgd import FusedSGD  # noqa: F401
from .schedules import (  # noqa: F401
    step_decay,
    warmup_cosine,
    warmup_linear,
    warmup_poly,
)
