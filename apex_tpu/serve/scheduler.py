"""Continuous-batching scheduler: requests -> per-tick packed batches.

Static-batch serving admits N requests, runs them in lockstep, and
returns when the LAST one finishes — short requests pay the longest
request's latency and the batch slots they vacate idle.  Continuous
batching (Orca's iteration-level scheduling, vLLM's default) re-packs
the live set every tick: a session that finishes frees its batch slot
and its KV blocks *this* tick, and a queued request can take them the
next.  This module is the host-side half of that loop — pure Python
over integers, deterministic for a given request/arrival stream (the
packing-determinism test replays a seeded Poisson trace twice and
diffs the decisions).

Three policies live here, and only here (the device programs in
serve/kernels.py are policy-free):

* **admission** — FIFO, gated on three budgets: batch slots
  (``max_batch``), KV blocks (the prompt plus one decode block of
  headroom must fit the pool *whole* — half-admitted sessions would
  deadlock), and prefill backlog (``max_prefill_backlog`` tokens not
  yet ingested across admitted sessions — the queue-depth/token-budget
  backpressure that keeps time-to-first-token bounded under load:
  admitting a 30th long prompt helps nobody's SLO).
* **packing** — every decode tick takes ALL decoding sessions (in
  admission order), padded to the next batch bucket; block tables pad
  to the next block bucket.  Buckets are powers of two, so the set of
  decode program shapes is ``O(log(max_batch) · log(max_blocks))`` —
  the recompile-free-after-warmup property the step cache pins.
* **preemption** — when a decode tick needs a block and the pool is
  dry, the LAST-admitted session is evicted (LIFO victim: it has the
  least sunk prefill work and FIFO fairness protects the oldest),
  its blocks freed, and it re-queues at the queue's FRONT in recompute
  mode: on re-admission it re-prefills prompt + tokens generated so
  far, then continues decoding — greedy decode makes the recomputed
  continuation identical to the one the eviction interrupted.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from .pool import BlockPool, NULL_BLOCK, blocks_for, chain_key, chain_keys

QUEUED, PREFILL, DECODE, DONE = "queued", "prefill", "decode", "done"


def bucket(n: int, cap: Optional[int] = None) -> int:
    """Next power of two >= n (>= 1); ``cap`` bounds it (a request that
    legitimately needs more than cap is the caller's validation bug)."""
    b = 1
    while b < n:
        b *= 2
    return b if cap is None else min(b, cap)


#: admission classes the elastic fleet routes/sheds by — "latency"
#: sessions migrate on capacity loss, "batch" sessions are re-queued
SLO_CLASSES = ("latency", "batch")


@dataclass
class Request:
    """One serving request: ``prompt`` token ids, up to
    ``max_new_tokens`` generated (greedy), optional ``eos`` stop id
    (emitted, then the session finishes).  ``slo`` is the request's
    service class (:data:`SLO_CLASSES`) — a single engine ignores it;
    the elastic fleet (serve/elastic.py) migrates latency-tier sessions
    on a shrink and sheds batch-tier ones first (re-queued, not
    dropped)."""
    rid: str
    prompt: Tuple[int, ...]
    max_new_tokens: int
    eos: Optional[int] = None
    slo: str = "latency"

    def __post_init__(self):
        self.prompt = tuple(int(t) for t in self.prompt)
        if not self.prompt:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.rid}: max_new_tokens must be >= 1, got "
                f"{self.max_new_tokens}")
        if self.slo not in SLO_CLASSES:
            raise ValueError(
                f"request {self.rid}: slo must be one of {SLO_CLASSES}, "
                f"got {self.slo!r}")


@dataclass
class Session:
    """Scheduler-side state of one admitted request.  The KV state a
    session owns is exactly ``table`` (physical block ids) plus
    ``position`` (KV rows written) — no private cache buffer; the pool
    holds the bytes."""
    request: Request
    seq: int                               # admission order (preemption)
    table: List[int] = field(default_factory=list)
    position: int = 0                      # KV rows written so far
    state: str = PREFILL
    prefill_src: Tuple[int, ...] = ()      # tokens still to ingest
    emit_on_prefill: bool = True           # fresh: 1st token from logits
    pending_tok: Optional[int] = None      # next token to ingest
    out: List[int] = field(default_factory=list)
    # speculative mode only: the draft model's own block table over the
    # SAME BlockPool free-list, and how many draft KV rows are written
    # (lags `position` when a handed-off session's draft cache is still
    # catching up on the prompt; equal once spec ticks may include it)
    draft_table: List[int] = field(default_factory=list)
    draft_position: int = 0
    # target weight epoch the session was admitted under (engine-stamped
    # at admission; epochs only grow, so this is the OLDEST weights any
    # of its tokens saw — the conservative age a staleness bound wants).
    # -1 until admission on an engine that publishes weights.
    weight_epoch: int = -1
    # lifecycle timestamps (engine-stamped, telemetry only — no
    # scheduling decision reads them, so packing stays deterministic)
    t_queued: float = 0.0
    t_first: Optional[float] = None
    t_done: Optional[float] = None
    # -- prefix cache state (tentpole: content-addressed block reuse) --
    # rolling chain keys of the session's committed full blocks, one
    # per table entry < committed_blocks (adopted keys included)
    hash_chain: List[str] = field(default_factory=list)
    committed_blocks: int = 0
    # False when the session's KV provenance is mixed (e.g. adopted
    # under a different weight epoch) — its blocks must never enter
    # the hash index
    cacheable: bool = True
    # copy-on-write forks decided at admission: (table index, shared
    # source id, exclusive destination id).  The engine dispatches the
    # paged block-copy for each, then complete_cow() releases the
    # source reference — the source stays referenced until the copy is
    # in the dispatch stream, so eviction cannot recycle it first.
    cow_pending: List[Tuple[int, int, int]] = field(default_factory=list)
    # tokens of this request's prompt that admission found cached (the
    # rows prefill will NOT recompute) — telemetry for hit-rate
    prefix_hit_tokens: int = 0

    @property
    def rid(self) -> str:
        return self.request.rid

    @property
    def prefill_remaining(self) -> int:
        return len(self.prefill_src) - self.position

    @property
    def fed_tokens(self) -> Tuple[int, ...]:
        """Every token whose target KV row is committed: the prompt
        plus all output except the last (still pending ingest) —
        exactly the recompute-mode prefill source, and the draft
        catch-up source for handed-off speculative sessions."""
        if self.out:
            return self.request.prompt + tuple(self.out[:-1])
        return self.request.prompt

    def finished(self) -> bool:
        r = self.request
        return len(self.out) >= r.max_new_tokens or \
            (r.eos is not None and self.out and self.out[-1] == r.eos)


class Scheduler:
    """The per-tick policy engine.  Owns the request queue and the live
    session set; the serve engine calls, in tick order: ``admit()``,
    ``next_prefill()``, ``decode_sessions()`` (+ ``grow()`` /
    ``preempt_for()`` when blocks run out), and ``finish()``."""

    def __init__(self, pool: BlockPool, *, max_batch: int,
                 prefill_chunk: int, max_prefill_backlog: int,
                 max_positions: int, spec_tables: bool = False,
                 pos_slack: int = 0, prefix_cache: bool = True,
                 cache_tag: str = "kv"):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.pool = pool
        self.max_batch = max_batch
        self.prefill_chunk = prefill_chunk
        self.max_prefill_backlog = max_prefill_backlog
        self.max_positions = max_positions
        # prefix cache: admission walks each request's token chain
        # through the pool's hash index and prefills only the cold
        # suffix.  cache_tag stamps the chain keys with everything KV
        # bytes depend on besides tokens (dtype/block size/window/
        # weight epoch) — the engine owns it and re-tags on publish.
        self.prefix_cache = bool(prefix_cache)
        self.cache_tag = cache_tag
        # speculative mode: every session also owns a draft block table
        # (admission doubles its block ask, finish/preempt free both),
        # and each tick may write up to `pos_slack` rows PAST the last
        # committed position (the verify chunk's rejected tail), so
        # admission budgets that headroom out of max_positions up front
        self.spec_tables = spec_tables
        self.pos_slack = int(pos_slack)
        self.queue: deque = deque()
        self.sessions: List[Session] = []      # admission order
        self._seq = 0
        self.rejected: List[str] = []

    # -- intake ------------------------------------------------------------

    def _reject_never_fit(self, request: Request) -> None:
        need = len(request.prompt) + request.max_new_tokens \
            + self.pos_slack
        blocks_need = blocks_for(need, self.pool.block_size)
        if self.spec_tables:
            blocks_need *= 2               # target + draft tables
        cap_blocks = self.pool.capacity
        if need > self.max_positions or blocks_need > cap_blocks:
            self.rejected.append(request.rid)
            raise ValueError(
                f"request {request.rid}: {need} positions exceed "
                f"max_positions {self.max_positions} / pool capacity "
                f"{cap_blocks * self.pool.block_size}")

    def submit(self, request: Request) -> None:
        """Queue a request (FIFO).  Requests that can NEVER fit — more
        positions than the model or the whole pool can hold — are
        rejected now, loudly, instead of deadlocking the queue head."""
        self._reject_never_fit(request)
        self.queue.append(Session(request, -1))

    def submit_recompute(self, request: Request, out) -> None:
        """Queue a request whose first ``len(out)`` tokens were already
        generated on ANOTHER engine (a session shed or lost during a
        fleet shrink, re-homed here).  Admission treats it exactly like
        a locally preempted session: re-prefill ``prompt + out[:-1]``
        with ``out[-1]`` pending — greedy decode makes the continuation
        bitwise the one the shrink interrupted (the preemption pin)."""
        self._reject_never_fit(request)
        s = Session(request, -1)
        s.state = QUEUED
        out = [int(t) for t in out]
        if out:
            s.out = out
            s.prefill_src = request.prompt + tuple(out[:-1])
            s.emit_on_prefill = False
            s.pending_tok = out[-1]
        else:
            s.prefill_src = request.prompt
        self.queue.append(s)

    def _backlog_tokens(self) -> int:
        return sum(s.prefill_remaining for s in self.sessions
                   if s.state == PREFILL)

    def admit(self) -> List[Session]:
        """Move queue-head sessions into the live set while every budget
        (batch slots, cold-suffix blocks + headroom, prefill backlog)
        holds.  All-or-nothing per session; FIFO order preserved.

        Prefix cache: each request's token chain is walked through the
        pool's hash index first (:meth:`BlockPool.acquire_prefix`) —
        matched full blocks are adopted shared (refcounted, immutable)
        and the session's ``position`` starts past them, so the engine
        prefills only the uncached suffix and the backlog budget counts
        only suffix tokens.  A FULL-chain hit still re-ingests the last
        prompt token (first-token logits must come from somewhere), and
        that write lands inside the last shared block — so admission
        forks it copy-on-write: a fresh block joins the table, the
        shared original stays referenced in ``cow_pending`` until the
        engine dispatches the paged block-copy.  Recompute re-admission
        (preempted or shed sessions) takes the same path and typically
        re-acquires its own just-retired blocks from the cached tier —
        preemption recovery without re-prefill."""
        admitted = []
        while self.queue:
            s = self.queue[0]
            if len(self.sessions) >= self.max_batch:
                break
            # fresh sessions ingest the prompt; preempted ones carry
            # their recompute source from preempt_for
            src = s.prefill_src if s.pending_tok is not None \
                else s.request.prompt
            bs = self.pool.block_size
            need_total = blocks_for(len(src) + 1, bs)
            shared: List[int] = []
            keys: List[str] = []
            if self.prefix_cache:
                keys = chain_keys(src, bs, self.cache_tag)
                shared = self.pool.acquire_prefix(keys)
            hit = len(shared) * bs
            fork = False
            if hit >= len(src):
                # full-chain hit (len(src) is block-aligned and every
                # block matched)
                if s.pending_tok is not None:
                    pos0 = len(src)      # recompute source fully cached
                else:
                    pos0 = len(src) - 1  # re-ingest one token -> logits
                    fork = True
            else:
                pos0 = hit
            if self._backlog_tokens() + (len(src) - pos0) \
                    > self.max_prefill_backlog and self.sessions:
                self.pool.free(shared)
                break
            cold = need_total - len(shared) + (1 if fork else 0)
            ids = self.pool.alloc(cold)
            if ids is None:
                self.pool.free(shared)
                break
            draft_ids: List[int] = []
            if self.spec_tables:
                # all-or-nothing across BOTH tables: a session holding
                # a target table but no draft table would deadlock the
                # spec tick exactly like a half-admitted prompt.  The
                # draft cache is never content-addressed (draft-model
                # KV lives under different weights) — always cold.
                draft_ids = self.pool.alloc(need_total)
                if draft_ids is None:
                    self.pool.free(ids)
                    self.pool.free(shared)
                    break
            self.queue.popleft()
            s.seq = self._seq
            self._seq += 1
            if fork:
                fsrc, fdst = shared[-1], ids[0]
                s.table = shared[:-1] + [fdst] + ids[1:]
                s.cow_pending = [(len(shared) - 1, fsrc, fdst)]
            else:
                s.table = shared + ids
                s.cow_pending = []
            s.draft_table = draft_ids
            s.position = pos0
            s.draft_position = 0
            s.prefill_src = src
            s.hash_chain = keys[:len(shared)]
            s.committed_blocks = len(shared)
            s.prefix_hit_tokens = pos0
            s.cacheable = True
            # a fully cached recompute source needs no prefill at all —
            # the pending token ingests through the next decode tick
            s.state = DECODE if pos0 >= len(src) else PREFILL
            self.sessions.append(s)
            admitted.append(s)
        return admitted

    def complete_cow(self, s: Session) -> int:
        """Release the shared source of every pending copy-on-write
        fork — the engine calls this AFTER dispatching the block-copy
        program(s), so the source's bytes cannot be recycled before the
        copy is in the dispatch stream.  Host-only harnesses (the churn
        sim) call it right after admit.  Returns the fork count."""
        n = len(s.cow_pending)
        for _idx, fsrc, _fdst in s.cow_pending:
            self.pool.free([fsrc])
        s.cow_pending = []
        return n

    def note_commit(self, s: Session) -> int:
        """Commit every newly FULL block of ``s`` into the pool's hash
        index: extend the session's rolling chain over its fed tokens
        and register each block (first writer wins — a chain another
        session committed already just leaves ours unhashed).  Called
        by the engine after every position advance; returns the number
        of blocks newly chained."""
        if not self.prefix_cache or not s.cacheable:
            return 0
        bs = self.pool.block_size
        toks = s.fed_tokens
        full = min(s.position // bs, len(s.table), len(toks) // bs)
        n = 0
        while s.committed_blocks < full:
            i = s.committed_blocks
            prev = s.hash_chain[i - 1] if i else ""
            key = chain_key(prev, toks[i * bs:(i + 1) * bs],
                            self.cache_tag)
            s.hash_chain.append(key)
            b = s.table[i]
            if b != NULL_BLOCK and not any(
                    idx == i for idx, _src, _dst in s.cow_pending):
                self.pool.commit(b, key)
            s.committed_blocks = i + 1
            n += 1
        return n

    # -- per-tick views ----------------------------------------------------

    def next_prefill(self) -> Optional[Session]:
        for s in self.sessions:
            if s.state == PREFILL:
                return s
        return None

    def decode_sessions(self) -> List[Session]:
        return [s for s in self.sessions if s.state == DECODE]

    def has_work(self) -> bool:
        return bool(self.queue) or bool(self.sessions)

    # -- block growth / preemption ----------------------------------------

    def grow(self, s: Session, n_positions: int,
             draft: bool = False) -> bool:
        """Extend ``s.table`` (or ``s.draft_table``) to cover
        ``n_positions`` KV rows; False if the pool is dry (caller
        preempts and retries)."""
        table = s.draft_table if draft else s.table
        need = blocks_for(n_positions, self.pool.block_size) \
            - len(table)
        if need <= 0:
            return True
        ids = self.pool.alloc(need)
        if ids is None:
            return False
        table.extend(ids)
        return True

    def evict(self, victim: Session) -> Session:
        """Free a live session's blocks (BOTH tables) and detach it
        from the live set in recompute mode, WITHOUT re-queueing it
        locally — local preemption (:meth:`preempt_for`) re-queues at
        the queue front; the elastic fleet instead re-homes the evicted
        session to another engine (its shed path).  Either way the
        recompute re-prefill of ``prompt + out[:-1]`` continues
        bitwise.

        Shared blocks just lose this session's reference; committed
        ones retire to the cached tier, so the re-admission (here or on
        another engine with the same chain) usually re-adopts them —
        eviction stops costing the prefix its prefill."""
        self.complete_cow(victim)
        self.pool.free(b for b in victim.table if b != NULL_BLOCK)
        self.pool.free(b for b in victim.draft_table
                       if b != NULL_BLOCK)
        self.sessions.remove(victim)
        victim.table = []
        victim.draft_table = []
        victim.position = 0
        victim.draft_position = 0
        victim.hash_chain = []
        victim.committed_blocks = 0
        victim.prefix_hit_tokens = 0
        victim.state = QUEUED
        if victim.out:
            # recompute mode: re-prefill prompt + generated-so-far
            # except the last token, which is still waiting to be
            # ingested — it becomes pending again after re-prefill
            victim.prefill_src = victim.request.prompt \
                + tuple(victim.out[:-1])
            victim.emit_on_prefill = False
            victim.pending_tok = victim.out[-1]
        else:
            victim.prefill_src = victim.request.prompt
            victim.emit_on_prefill = True
            victim.pending_tok = None
        return victim

    def preempt_for(self, needy: Session) -> Optional[Session]:
        """Evict the last-admitted live session other than ``needy``
        (or ``needy`` itself if it is alone — it re-queues with its
        progress and re-admits when blocks exist).  Freed state:
        ALL the victim's blocks; the victim re-enters the queue FRONT
        in recompute mode."""
        victims = [s for s in self.sessions if s is not needy]
        victim = max(victims, key=lambda s: s.seq) if victims else needy
        self.evict(victim)
        self.queue.appendleft(victim)
        return victim

    def finish(self, s: Session) -> None:
        self.complete_cow(s)
        self.pool.free(b for b in s.table if b != NULL_BLOCK)
        self.pool.free(b for b in s.draft_table if b != NULL_BLOCK)
        s.table = []
        s.draft_table = []
        s.state = DONE
        self.sessions.remove(s)

    def retire_window_blocks(self, s: Session, window: int) -> int:
        """Free the leading blocks of a sliding-window session that no
        future query's band can reach (rolling.py's closed form,
        block-tabled).  Retired table entries become NULL — logical
        indexing is positional, so the prefix stays, pointing at the
        zero block the band mask already excludes.  Returns the number
        of blocks returned to the pool."""
        from ..inference.rolling import window_retired_blocks
        n = window_retired_blocks(s.position, window,
                                  self.pool.block_size)
        freed = [b for b in s.table[:n] if b != NULL_BLOCK]
        if freed:
            self.pool.free(freed)
            for i in range(n):
                s.table[i] = NULL_BLOCK
        return len(freed)

    # -- packing -----------------------------------------------------------

    def pack_decode(self, sessions: List[Session]):
        """Bucketed operand arrays for one decode tick:
        ``(bucket_batch, bucket_blocks, tokens, positions, tables)``
        as host int32 lists — dead rows carry ``position = -1`` and
        all-null tables (the kernels' drop encoding)."""
        b = bucket(len(sessions), self.max_batch)
        nb = bucket(max(len(s.table) for s in sessions))
        tokens, positions, tables = [], [], []
        for s in sessions:
            tokens.append(s.pending_tok)
            positions.append(s.position)
            tables.append(s.table + [NULL_BLOCK] * (nb - len(s.table)))
        for _ in range(b - len(sessions)):
            tokens.append(0)
            positions.append(-1)
            tables.append([NULL_BLOCK] * nb)
        return b, nb, tokens, positions, tables

    def pack_spec(self, sessions: List[Session]):
        """Bucketed operands for one speculative tick:
        ``(bucket_batch, bucket_t_blocks, bucket_d_blocks, tokens,
        positions, t_tables, d_tables)`` — the decode packing plus the
        draft pool's tables, bucketed independently (the draft cache
        may cover fewer rows than the target's after a handoff)."""
        b = bucket(len(sessions), self.max_batch)
        nbt = bucket(max(len(s.table) for s in sessions))
        nbd = bucket(max(len(s.draft_table) for s in sessions))
        tokens, positions, t_tables, d_tables = [], [], [], []
        for s in sessions:
            tokens.append(s.pending_tok)
            positions.append(s.position)
            t_tables.append(s.table
                            + [NULL_BLOCK] * (nbt - len(s.table)))
            d_tables.append(s.draft_table
                            + [NULL_BLOCK] * (nbd - len(s.draft_table)))
        for _ in range(b - len(sessions)):
            tokens.append(0)
            positions.append(-1)
            t_tables.append([NULL_BLOCK] * nbt)
            d_tables.append([NULL_BLOCK] * nbd)
        return b, nbt, nbd, tokens, positions, t_tables, d_tables
