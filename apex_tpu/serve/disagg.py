"""DisaggregatedEngine: prefill/decode phase split over two engines.

Prefill and decode want different hardware: prefill is one big
compute-bound batched matmul over the prompt (MXU-limited — chips with
high sustained FLOPs win), decode re-reads the whole KV cache per
emitted token (HBM-bandwidth-limited).  A unified engine time-slices
both phases on the same chips and each phase interferes with the
other's SLO — a long prompt's prefill chunk stretches every resident
session's inter-token latency.  Disaggregated serving (DistServe,
Splitwise) dedicates one engine per phase and moves each request's KV
state from the prefill engine's pool to the decode engine's pool
exactly once, when its first token is out.

This coordinator wires two :class:`~apex_tpu.serve.engine.ServeEngine`
instances — ``phase="prefill"`` (stops before the decode stage) and
``phase="decode"`` (runs full ticks; its prefill slot serves recompute
re-admissions after local preemption, and draft catch-up in
speculative mode) — through the schema-3 KV handoff in
:mod:`apex_tpu.runtime.resilience`:

1. the prefill engine ingests prompt chunks and emits each request's
   first token (TTFT is measured THERE — the handoff is off the TTFT
   path);
2. :func:`~apex_tpu.runtime.resilience.stream_kv_handoff` streams the
   finished session's KV blocks to per-block shard files (one block's
   bytes on the host at a time — the pools never round-trip through a
   gathered buffer), manifest last;
3. the decode engine adopts the session
   (:meth:`~apex_tpu.serve.engine.ServeEngine.ingest_handoff`),
   scattering the streamed blocks into its own pool verbatim — so the
   handed-off continuation is bitwise the unified engine's
   continuation (the parity tests pin fp32 and int8 pools both).

Failure modes follow the checkpoint conventions: a chaos-injected
stream failure (:class:`~apex_tpu.runtime.chaos.ChaosInjectedFailure`)
discards the partial handoff directory and re-streams once — the
blocks are still resident on the prefill engine until ``release``;
:class:`~apex_tpu.runtime.chaos.ChaosKilled` is never caught (it IS
the simulated host loss).  A decode engine with no free slot/blocks
just leaves the handoff pending; the coordinator retries ingest every
tick while the prefill engine keeps serving.
"""
from __future__ import annotations

import itertools
import os
import re
import tempfile
from typing import Dict, List, Sequence

from ..observe import registry as _obs
from ..observe import watchdog as _watchdog
from ..runtime.chaos import ChaosInjectedFailure
from ..runtime.resilience import discard_kv_handoff, stream_kv_handoff
from .engine import ServeEngine
from .scheduler import Request

__all__ = ["DisaggregatedEngine", "PendingHandoff"]


class PendingHandoff:
    """One streamed-but-not-yet-ingested session: everything the decode
    engine needs to adopt it, plus the shard directory holding its KV
    blocks."""

    __slots__ = ("request", "out", "pending_tok", "position", "dir",
                 "t_queued", "t_first", "hash_chain", "weight_epoch")

    def __init__(self, request, out, pending_tok, position, dir_path,
                 t_queued, t_first, hash_chain=(), weight_epoch=-1):
        self.request = request
        self.out = list(out)
        self.pending_tok = pending_tok
        self.position = position
        self.dir = dir_path
        self.t_queued = t_queued
        self.t_first = t_first
        self.hash_chain = list(hash_chain)
        self.weight_epoch = weight_epoch


class DisaggregatedEngine:
    """Two-engine prefill/decode deployment with streamed KV handoff.

    ``prefill_blocks`` / ``decode_blocks`` size each engine's pool
    (default: ``num_blocks`` each — disjoint pools, as on disjoint
    mesh slices; :func:`apex_tpu.parallel.auto.plan_serve_phase_split`
    picks the chip split).  Speculative decoding (``draft=...``) is a
    decode-engine mode: the prefill engine never sees the draft.
    ``handoff_dir`` hosts the per-session shard directories (a temp
    dir by default)."""

    def __init__(self, model, *, num_blocks, block_size=16, max_batch=8,
                 prefill_chunk=32, cache_dtype=None, window=None,
                 prefill_blocks=None, decode_blocks=None,
                 handoff_dir=None, draft=None, spec_k=4,
                 draft_cache_dtype="int8", spec_policy="on",
                 prefix_cache=True):
        if window is not None:
            raise NotImplementedError(
                "disaggregated serving + sliding window: handoff after "
                "block retirement would stream a table with NULL holes "
                "— serve windowed models unified for now")
        self.prefill = ServeEngine(
            model, num_blocks=prefill_blocks or num_blocks,
            block_size=block_size, max_batch=max_batch,
            prefill_chunk=prefill_chunk, cache_dtype=cache_dtype,
            phase="prefill", prefix_cache=prefix_cache)
        self.decode = ServeEngine(
            model, num_blocks=decode_blocks or num_blocks,
            block_size=block_size, max_batch=max_batch,
            prefill_chunk=prefill_chunk, cache_dtype=cache_dtype,
            phase="decode", draft=draft, spec_k=spec_k,
            draft_cache_dtype=draft_cache_dtype,
            spec_policy=spec_policy, prefix_cache=prefix_cache)
        self.spec = self.decode.spec
        if handoff_dir is None:
            handoff_dir = tempfile.mkdtemp(prefix="apex_kv_handoff_")
        self.handoff_dir = handoff_dir
        self.pending: List[PendingHandoff] = []
        self._tick = 0
        self._handoff_no = itertools.count()
        self._handoffs = 0
        self._handoff_retries = 0
        self._handoff_peak = 0

    # -- intake ------------------------------------------------------------

    def submit(self, request: Request) -> None:
        """Queue a request on the prefill engine.  Decode-side position
        budgets (speculative slack) are validated NOW — the prefill
        engine's own budget has no slack, and a request that can never
        land on the decode engine must be rejected at the door, not
        after its prefill is paid for."""
        need = len(request.prompt) + request.max_new_tokens \
            + self.decode.scheduler.pos_slack
        if need > self.decode.scheduler.max_positions:
            raise ValueError(
                f"request {request.rid}: {need} positions (incl. "
                f"speculative slack {self.decode.scheduler.pos_slack}) "
                f"exceed decode max_positions "
                f"{self.decode.scheduler.max_positions}")
        self.prefill.submit(request)

    # -- the tick ----------------------------------------------------------

    def _stream_out(self, s) -> PendingHandoff:
        tag = re.sub(r"[^A-Za-z0-9_.-]", "_", s.rid)
        d = os.path.join(self.handoff_dir,
                         f"h{next(self._handoff_no)}_{tag}")
        try:
            _meta, peak = stream_kv_handoff(
                d, self.prefill.pool, s.table, source=f"handoff:{s.rid}")
        except ChaosInjectedFailure:
            # recoverable stream fault: the blocks are still resident on
            # the prefill engine — drop the partial directory and
            # re-stream once (a second fault propagates)
            self._handoff_retries += 1
            _obs.counter("serve.handoff.retries").inc()
            discard_kv_handoff(d)
            _meta, peak = stream_kv_handoff(
                d, self.prefill.pool, s.table, source=f"handoff:{s.rid}")
        self._handoffs += 1
        self._handoff_peak = max(self._handoff_peak, peak)
        _obs.counter("serve.handoff.count").inc()
        _obs.gauge("serve.handoff.bytes_peak_host").set(
            self._handoff_peak)
        _obs.event("serve.request", rid=s.rid, phase="handoff",
                   tick=self._tick, blocks=len(s.table), peak_bytes=peak)
        return PendingHandoff(s.request, s.out, s.pending_tok,
                              s.position, d, s.t_queued, s.t_first,
                              hash_chain=s.hash_chain,
                              weight_epoch=s.weight_epoch)

    def step(self) -> bool:
        """One coordinator tick: prefill tick → stream completed
        prefills out → ingest pending handoffs into the decode engine
        (whatever fits; the rest stay pending) → decode tick.  Returns
        True while any engine or the handoff queue has work."""
        self._tick += 1
        self.prefill.step()
        for s in self.prefill.harvest_ready():
            self.pending.append(self._stream_out(s))
            self.prefill.release_handoff(s)
        still: List[PendingHandoff] = []
        for h in self.pending:
            sess = self.decode.ingest_handoff(
                h.request, out=h.out, pending_tok=h.pending_tok,
                position=h.position, handoff_dir=h.dir,
                t_queued=h.t_queued, t_first=h.t_first,
                hash_chain=h.hash_chain, weight_epoch=h.weight_epoch)
            if sess is None:
                still.append(h)      # decode engine full: retry next tick
            else:
                discard_kv_handoff(h.dir)
        self.pending = still
        _obs.gauge("serve.handoff.pending").set(len(self.pending))
        self.decode.step()
        return self.prefill.scheduler.has_work() or bool(self.pending) \
            or self.decode.scheduler.has_work()

    def run(self, requests: Sequence[Request], arrivals=None,
            watchdog_deadline_s=None, max_ticks=None):
        """Serve ``requests`` to completion; returns ``{rid: tokens}``
        merged from both engines (a request that finishes at its first
        token never leaves the prefill engine)."""
        pending = sorted(
            zip(arrivals if arrivals is not None else [0] * len(requests),
                range(len(requests))),
            key=lambda p: (p[0], p[1]))
        wd = _watchdog.StallWatchdog(watchdog_deadline_s) \
            if watchdog_deadline_s else None
        if wd is not None:
            wd.start()
        try:
            i = 0
            while True:
                while i < len(pending) and pending[i][0] <= self._tick:
                    self.submit(requests[pending[i][1]])
                    i += 1
                more = self.step()
                if not more and i >= len(pending):
                    break
                if max_ticks is not None and self._tick >= max_ticks:
                    break
        finally:
            if wd is not None:
                wd.stop()
        return dict(self.results)

    # -- introspection -----------------------------------------------------

    @property
    def tick(self) -> int:
        return self._tick

    @property
    def results(self) -> Dict[str, List[int]]:
        merged = dict(self.prefill.results)
        merged.update(self.decode.results)
        return merged

    def metrics(self) -> dict:
        return {
            "prefill": self.prefill.metrics(),
            "decode": self.decode.metrics(),
            "handoff": {
                "count": self._handoffs,
                "retries": self._handoff_retries,
                "pending": len(self.pending),
                "bytes_peak_host": self._handoff_peak,
            },
        }
