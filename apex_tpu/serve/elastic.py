"""ServeFleet: membership-backed elastic serving with live migration.

Training already survives host loss (cluster/runtime.py's
detect→agree→replan→reshard cycle); this module gives serving the same
property.  A fleet of replicated :class:`~apex_tpu.serve.engine.
ServeEngine` instances registers in the cluster membership view — each
replica is a :class:`~apex_tpu.cluster.membership.Member` heartbeating
into the shared KV store, one :class:`~apex_tpu.cluster.coordinator.
Coordinator` condenses heartbeats into epoch-numbered views — and a
thin front-end routes, snapshots, and re-homes sessions so that a
replica dying mid-decode is a latency blip, not a lost request:

* **Session snapshots** (periodic, every ``snapshot_every`` fleet
  ticks): each live DECODE session's KV blocks stream to shared
  storage through the schema-3
  :func:`~apex_tpu.runtime.resilience.stream_kv_handoff` path — one
  block's bytes on host at a time, CRC per file, manifest commits
  LAST.  The session's host state (generated tokens, pending token,
  position, SLO class) rides in the manifest's ``meta`` record, so a
  committed manifest is a complete, adoptable session and a
  mid-snapshot kill leaves only manifest-less debris the restore path
  rejects (:class:`~apex_tpu.runtime.resilience.
  CheckpointCorruptError`) — never adopts.
* **Migration on ``host.loss``**: when the coordinator publishes a
  shrink epoch, the front-end re-homes every unfinished session of the
  lost replicas.  Latency-tier sessions restore from their newest
  committed snapshot into a survivor's pool
  (:meth:`~apex_tpu.serve.engine.ServeEngine.ingest_handoff` — blocks
  land verbatim, so the continuation is BITWISE the uninterrupted
  engine's; greedy decode regenerates any tokens emitted after the
  snapshot identically).  Sessions whose snapshot is stale or
  debris-only fall back to the recompute-mode re-prefill path —
  ``prompt + out[:-1]`` with ``out[-1]`` pending — which the
  preemption tests already pin bitwise.  In speculative mode a
  migrated session's draft cache starts empty and catches up through
  the survivor's prefill slot.
* **SLO-aware shedding**: on capacity loss, batch-tier sessions are
  shed FIRST — re-queued at the front-end (never dropped), re-admitted
  in recompute mode when headroom returns — while latency-tier
  sessions migrate; a survivor with no room evicts its own newest
  batch-tier session to make room for an incoming latency migration.
  Backpressure (fleet queue depth, pending recovery, shed counters)
  is visible in :meth:`ServeFleet.metrics`.
* **Epoch-aware routing**: new submissions route to the live replica
  with the most pool headroom under the CURRENT membership epoch; a
  submission addressed to a stale epoch is refused
  (:class:`StaleEpochError`); when the coordinator publishes a new
  view the front-end re-homes its queue.  Re-homed and requeued
  sessions are inserted into the survivor's queue in original
  admission order (fleet-wide FIFO fairness).

Process-boundary rule (cluster/runtime.py): ``ChaosKilled`` is never
caught to continue the killed operation — a felled replica's engine is
closed (its pool dies with the process; blocks return so
``check_no_leaks`` stays meaningful) and only its durable snapshots
are read afterwards.  A felled coordinator is replaced by a successor
over the same KV store; recovery state lives in the front-end, so a
coordinator loss mid-migration is completed — or cleanly abandoned to
recompute — by the successor, never half-adopted.  Chaos hook points:
``serve.session_snapshot`` (before each session snapshot),
``serve.migrate`` (before each restore attempt), plus
``serve.kv_handoff`` inside the stream itself (runtime/chaos.py).
"""
from __future__ import annotations

import bisect
import itertools
import json
import os
import re
import shutil
import tempfile
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ..cluster.coordinator import Coordinator
from ..cluster.kvstore import KVStore, MemoryKV
from ..cluster.membership import Member, MembershipView, current_view
from ..cluster.runtime import SimClock, beat_and_scan
from ..observe import registry as _obs
from ..runtime import chaos as _chaos
from ..runtime.resilience import (CheckpointCorruptError,
                                  CheckpointReshardError,
                                  discard_kv_handoff,
                                  read_kv_handoff_meta, stream_kv_handoff)
from .engine import ServeEngine
from .pool import blocks_for
from .scheduler import DECODE, Request, SLO_CLASSES

__all__ = ["ServeFleet", "FleetMember", "StaleEpochError", "SLO_CLASSES"]


class StaleEpochError(RuntimeError):
    """A submission addressed a membership epoch the fleet has moved
    past — the client's routing table predates a shrink/grow; it must
    re-resolve the current view and resubmit."""


class FleetMember:
    """One serve replica: a membership agent plus the engine it
    fronts.  ``closed`` means the replica's simulated process is gone —
    its engine was torn down (blocks returned) and only its durable
    snapshots may be read from here on."""

    __slots__ = ("member", "engine", "closed")

    def __init__(self, member: Member, engine: ServeEngine):
        self.member = member
        self.engine = engine
        self.closed = False

    @property
    def member_id(self) -> str:
        return self.member.member_id

    @property
    def alive(self) -> bool:
        return self.member.alive


class _Tracked:
    """Front-end record of one submission: routing seq (fleet-wide
    FIFO order), SLO class, current home, tokens generated as of the
    last durable observation (for recompute re-queues), and the
    session's snapshot directories, newest first — a dir is added
    BEFORE its stream starts, so a mid-snapshot kill's debris is
    found, rejected, and discarded by the restore path."""

    __slots__ = ("request", "slo", "seq", "member", "out", "snaps",
                 "snap_no")

    def __init__(self, request: Request, slo: str, seq: int):
        self.request = request
        self.slo = slo
        self.seq = seq
        self.member: Optional[str] = None
        self.out: List[int] = []
        self.snaps: List[str] = []
        self.snap_no = 0


def _tag(rid: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", rid)


class ServeFleet:
    """A membership-backed fleet of replicated serve engines.

    ``n_engines`` replicas share one ``model`` (weights are read-only
    under serving); ``num_blocks`` is an int or a per-replica sequence
    (heterogeneous pools).  ``kv``/``clock`` default to the tier-1
    simulation substrate (:class:`MemoryKV` + :class:`SimClock`);
    ``deadline_s``/``miss_threshold`` parameterize the coordinator's
    consecutive-miss failure detector.  ``snapshot_every`` is the
    session-snapshot cadence in fleet ticks (0 disables — every lost
    session then recomputes); ``snapshot_max_age_ticks`` declares
    older snapshots stale (recompute fallback; None = never stale);
    ``migrate_per_tick`` bounds restores per tick (None = drain
    everything the tick the epoch lands)."""

    def __init__(self, model, *, n_engines, num_blocks, block_size=16,
                 max_batch=8, prefill_chunk=32, cache_dtype=None,
                 draft=None, spec_k=4, draft_cache_dtype="int8",
                 spec_policy="on", kv: Optional[KVStore] = None,
                 clock: Optional[SimClock] = None, deadline_s=0.25,
                 miss_threshold=2, snapshot_every=2, snapshot_dir=None,
                 snapshot_max_age_ticks=None, migrate_per_tick=None):
        if n_engines < 1:
            raise ValueError(f"n_engines must be >= 1, got {n_engines}")
        blocks = list(num_blocks) \
            if isinstance(num_blocks, (list, tuple)) \
            else [num_blocks] * n_engines
        if len(blocks) != n_engines:
            raise ValueError(
                f"num_blocks: {len(blocks)} entries for {n_engines} "
                f"engines")
        self.kv = kv if kv is not None else MemoryKV()
        self.clock = clock if clock is not None else SimClock()
        self.deadline_s = float(deadline_s)
        self.miss_threshold = int(miss_threshold)
        self.snapshot_every = int(snapshot_every)
        self.snapshot_max_age_ticks = snapshot_max_age_ticks
        self.migrate_per_tick = migrate_per_tick
        self.block_size = int(block_size)
        self.spec = draft is not None
        self._own_snapdir = snapshot_dir is None
        if snapshot_dir is None:
            snapshot_dir = tempfile.mkdtemp(prefix="apex_serve_fleet_")
        self.snapshot_dir = snapshot_dir
        self.members: Dict[str, FleetMember] = {}
        for i in range(n_engines):
            engine = ServeEngine(
                model, num_blocks=blocks[i], block_size=block_size,
                max_batch=max_batch, prefill_chunk=prefill_chunk,
                cache_dtype=cache_dtype, draft=draft, spec_k=spec_k,
                draft_cache_dtype=draft_cache_dtype,
                spec_policy=spec_policy)
            member = Member(
                self.kv, f"serve{i}", clock=self.clock,
                spec=json.dumps({"chip": "serve",
                                 "n_blocks": int(blocks[i])}))
            self.members[member.member_id] = FleetMember(member, engine)
        self.coordinator = self._make_coordinator()
        self.view: Optional[MembershipView] = None
        self.results: Dict[str, List[int]] = {}
        self.telemetry: dict = {}
        self._tick = 0
        self._seq = itertools.count()
        self._recs: Dict[str, _Tracked] = {}
        self._queue: List[str] = []        # rids awaiting routing, by seq
        self._recovery: deque = deque()    # rids awaiting re-homing
        self._migrated = 0
        self._shed_requeued = 0
        self._recomputed = 0
        self._debris_rejected = 0
        self._snapshot_peak = 0
        self._detect_ms = 0.0
        self._migrate_ms = 0.0
        self._death_wall: Optional[float] = None

    def _make_coordinator(self) -> Coordinator:
        return Coordinator(self.kv, deadline_s=self.deadline_s,
                           miss_threshold=self.miss_threshold,
                           clock=self.clock)

    # -- membership --------------------------------------------------------

    def join(self) -> MembershipView:
        """All replicas register + first-beat; the coordinator
        publishes epoch 1 and every replica acks it."""
        if self.view is not None:
            return self.view
        for m in self.members.values():
            m.member.join()
        view = self.coordinator.scan()
        for m in self.members.values():
            if m.alive:
                m.member.ack(view)
        self.view = view
        _obs.event("serve.fleet", phase="joined", epoch=view.epoch,
                   members=list(view.members))
        return view

    def _live_members(self) -> List[FleetMember]:
        return [m for m in self.members.values()
                if m.alive and not m.closed]

    def _targets(self) -> List[FleetMember]:
        """Routing candidates: replicas in the CURRENT view that also
        answer (a dead-but-undetected replica fails its headroom probe
        exactly like a refused connection), most PROJECTED free blocks
        first — pool headroom minus what the replica's own admission
        queue will claim, so one tick's routing spreads load instead
        of piling onto a single replica."""
        vm = set(self.view.members) if self.view else set()
        live = [m for m in self._live_members() if m.member_id in vm]
        live.sort(key=lambda m: (-self._projected_free(m), m.member_id))
        return live

    def _projected_free(self, m: FleetMember) -> int:
        free = m.engine.block_pool.free_count
        mult = 2 if self.spec else 1
        for s in m.engine.scheduler.queue:
            src = s.prefill_src if s.pending_tok is not None \
                else s.request.prompt
            free -= blocks_for(len(src) + 1, self.block_size) * mult
        return free

    # -- intake ------------------------------------------------------------

    def submit(self, request: Request, *, slo: Optional[str] = None,
               epoch: Optional[int] = None) -> None:
        """Queue a request with the front-end.  ``slo`` overrides the
        request's own class (``"latency"`` migrates on shrink,
        ``"batch"`` sheds first, re-queued).  ``epoch`` asserts the
        membership epoch the client routed against — a stale epoch is
        refused with :class:`StaleEpochError` so clients re-resolve
        after a shrink instead of racing it."""
        if self.view is None:
            raise RuntimeError("join() the fleet before submitting")
        slo = slo if slo is not None else request.slo
        if slo not in SLO_CLASSES:
            raise ValueError(
                f"request {request.rid}: slo must be one of "
                f"{SLO_CLASSES}, got {slo!r}")
        published = current_view(self.kv) or self.view
        if epoch is not None and int(epoch) != published.epoch:
            raise StaleEpochError(
                f"request {request.rid}: addressed to membership epoch "
                f"{epoch}; the fleet is at epoch {published.epoch} — "
                f"re-resolve the view and resubmit")
        if request.rid in self._recs:
            raise ValueError(f"request {request.rid}: duplicate rid")
        rec = _Tracked(request, slo, next(self._seq))
        self._recs[request.rid] = rec
        self._enqueue(request.rid)
        _obs.event("serve.fleet", phase="queued", rid=request.rid,
                   slo=slo, epoch=published.epoch)

    def _enqueue(self, rid: str) -> None:
        seqs = [self._recs[r].seq for r in self._queue]
        self._queue.insert(
            bisect.bisect_left(seqs, self._recs[rid].seq), rid)

    # -- the fleet tick ----------------------------------------------------

    def step(self, advance_s: Optional[float] = None) -> bool:
        """One fleet cycle: heartbeats + coordinator scan (chaos fells
        replicas/coordinators here), adopt a new epoch if one was
        published (re-homing the lost replicas' sessions), drain
        pending recovery, route the front-end queue by headroom, tick
        every live engine, and snapshot live sessions on cadence.
        Returns True while any work remains anywhere."""
        if self.view is None:
            raise RuntimeError("join() the fleet before stepping")
        if advance_s is None:
            advance_s = self.deadline_s / 2
        self._tick += 1
        view, self.coordinator, felled = beat_and_scan(
            self.kv, self.clock,
            [m.member for m in self.members.values()],
            self.coordinator, self._make_coordinator,
            advance_s=advance_s, fallback_view=self.view)
        for mid in felled:
            self._fell(mid)
        if view is not None and view.epoch != self.view.epoch:
            self._adopt_view(view)
        self._drain_recovery()
        self._route()
        for m in self._live_members():
            m.engine.step()
            self._harvest(m)
        if self.snapshot_every and \
                self._tick % self.snapshot_every == 0:
            self._snapshot_phase()
        return self.has_work()

    def run(self, requests: Sequence[Request], *, slos=None,
            arrivals=None, max_ticks: Optional[int] = None):
        """Serve ``requests`` to completion across the fleet; returns
        ``{rid: tokens}``.  ``slos`` optionally classes each request
        (else ``request.slo``); ``arrivals`` is the open-loop trace of
        submit ticks, as in :meth:`ServeEngine.run`."""
        pending = sorted(
            zip(arrivals if arrivals is not None
                else [0] * len(requests), range(len(requests))),
            key=lambda p: (p[0], p[1]))
        i = 0
        while True:
            while i < len(pending) and pending[i][0] <= self._tick:
                idx = pending[i][1]
                self.submit(requests[idx],
                            slo=slos[idx] if slos else None)
                i += 1
            more = self.step()
            if not more and i >= len(pending):
                break
            if max_ticks is not None and self._tick >= max_ticks:
                break
            if more and not self._live_members():
                raise RuntimeError(
                    "serve fleet has no live replicas but work remains")
        return dict(self.results)

    # -- failure handling --------------------------------------------------

    def _fell(self, mid: str) -> None:
        """Convert a ``ChaosKilled`` at the replica boundary: the
        process is gone.  Results it already produced were delivered
        (tokens stream out as they are emitted); its engine is closed
        — the pool's memory dies with the process — and from here on
        only its committed snapshots are read."""
        m = self.members[mid]
        m.member.alive = False
        if m.closed:
            return
        self._harvest(m)
        m.engine.close()
        m.closed = True
        if self._death_wall is None:
            self._death_wall = time.perf_counter()
        _obs.event("serve.fleet", phase="host_lost", member=mid,
                   tick=self._tick)

    def _adopt_view(self, view: MembershipView) -> None:
        """The agree + re-home half of the cycle: survivors ack the
        epoch, replicas the view dropped are fenced (their engine is
        treated as gone even if only partitioned — real fleets fence,
        they don't split-brain), and every unfinished session homed on
        a lost replica enters the recovery queue in fleet FIFO order."""
        for m in self.members.values():
            if m.alive and not m.closed and m.member_id in view.members:
                m.member.ack(view)
        if not self.coordinator.acked(view):
            missing = [mid for mid in view.members
                       if not (mid in self.members
                               and self.members[mid].alive)]
            raise RuntimeError(
                f"serve fleet epoch {view.epoch} not agreed: members "
                f"{missing} never acked")
        if self._death_wall is not None:
            self._detect_ms = \
                (time.perf_counter() - self._death_wall) * 1e3
            self._death_wall = None
        old = self.view
        self.view = view
        lost = [mid for mid in old.members if mid not in view.members]
        for mid in lost:
            if mid in self.members:
                self._fell(mid)
        plan = sorted(
            (rid for rid, rec in self._recs.items()
             if rec.member in lost and rid not in self.results),
            key=lambda rid: self._recs[rid].seq)
        for rid in plan:
            self._recs[rid].member = None
            self._recovery.append(rid)
        self.telemetry = {
            "epoch": view.epoch,
            "members": list(view.members),
            "lost": lost,
            "to_recover": len(plan),
            "detect_ms": round(self._detect_ms, 3),
        }
        _obs.event("serve.fleet", phase="epoch", epoch=view.epoch,
                   members=list(view.members), lost=lost,
                   to_recover=len(plan))

    def _drain_recovery(self) -> None:
        """Re-home lost sessions, oldest first: batch tier is shed
        (re-queued in recompute mode — never dropped), latency tier
        migrates via its newest committed snapshot.  The queue lives in
        the front-end, not the coordinator, so a coordinator felled
        mid-migration leaves the successor to finish the drain."""
        if not self._recovery:
            return
        budget = self.migrate_per_tick or len(self._recovery)
        t0 = time.perf_counter()
        while self._recovery and budget > 0:
            budget -= 1
            rid = self._recovery.popleft()
            if rid in self.results:
                continue
            rec = self._recs[rid]
            if rec.slo == "batch":
                snap = self._usable_snapshot(rec)
                out = list((snap[1].get("meta") or {}).get("out", [])) \
                    if snap else list(rec.out)
                self._requeue(rec, out, shed=True)
                continue
            self._migrate(rid, rec)
        self._migrate_ms += (time.perf_counter() - t0) * 1e3

    def _usable_snapshot(self, rec: _Tracked):
        """Newest snapshot with a COMMITTED manifest, or None.
        Manifest-less debris (a kill mid-snapshot) is rejected —
        :func:`read_kv_handoff_meta` raises
        :class:`CheckpointCorruptError` — discarded, and the next-older
        snapshot considered; it is never adopted."""
        for d in list(rec.snaps):
            try:
                manifest = read_kv_handoff_meta(d)
            except CheckpointCorruptError:
                self._debris_rejected += 1
                _obs.event("serve.fleet", phase="debris_rejected",
                           rid=rec.request.rid, dir=d)
                discard_kv_handoff(d)
                rec.snaps.remove(d)
                continue
            return d, manifest
        return None

    def _is_stale(self, manifest: dict) -> bool:
        if self.snapshot_max_age_ticks is None:
            return False
        at = int((manifest.get("meta") or {}).get("tick", 0))
        return (self._tick - at) > int(self.snapshot_max_age_ticks)

    def _requeue(self, rec: _Tracked, out, *, shed: bool) -> None:
        """Back to the front-end queue in recompute mode, keeping the
        session's fleet FIFO seat.  ``shed`` counts batch-tier
        shedding; otherwise this is a latency-tier recompute
        fallback."""
        rec.out = [int(t) for t in out]
        rec.member = None
        for d in rec.snaps:
            discard_kv_handoff(d)
        rec.snaps = []
        self._enqueue(rec.request.rid)
        if shed:
            self._shed_requeued += 1
        else:
            self._recomputed += 1
        _obs.event("serve.fleet",
                   phase="shed" if shed else "recompute",
                   rid=rec.request.rid, generated=len(rec.out))

    def _migrate(self, rid: str, rec: _Tracked) -> None:
        """Restore a latency-tier session into a survivor's pool from
        its newest committed snapshot; fall back to recompute when no
        usable snapshot exists, it is stale, or no survivor can take
        the blocks even after shedding its batch tier."""
        snap = self._usable_snapshot(rec)
        if snap is None:
            self._requeue(rec, rec.out, shed=False)
            return
        d, manifest = snap
        meta = manifest.get("meta") or {}
        if self._is_stale(manifest) or not meta:
            self._requeue(rec, meta.get("out", rec.out), shed=False)
            return
        for target in self._targets():
            try:
                if _chaos.active():
                    _chaos.hook("serve.migrate", rid=rid,
                                member=target.member_id, dir=d)
                sess = self._adopt_with_shedding(target, rec, d,
                                                 manifest, meta)
            except _chaos.ChaosKilled:
                # the ADOPTING replica died mid-migration; its pool is
                # gone but the snapshot is durable on shared storage —
                # recovery resumes next tick on whoever survives
                self._fell(target.member_id)
                self._recovery.appendleft(rid)
                return
            except _chaos.ChaosInjectedFailure:
                self._requeue(rec, meta.get("out", rec.out),
                              shed=False)
                return
            except (CheckpointCorruptError, CheckpointReshardError):
                self._debris_rejected += 1
                discard_kv_handoff(d)
                if d in rec.snaps:
                    rec.snaps.remove(d)
                self._requeue(rec, meta.get("out", rec.out),
                              shed=False)
                return
            if sess is not None:
                rec.member = target.member_id
                rec.out = [int(t) for t in meta["out"]]
                for dd in rec.snaps:
                    discard_kv_handoff(dd)
                rec.snaps = []
                self._migrated += 1
                _obs.event("serve.fleet", phase="migrated", rid=rid,
                           member=target.member_id,
                           blocks=int(manifest["n_blocks"]),
                           generated=len(rec.out))
                return
        self._requeue(rec, meta.get("out", rec.out), shed=False)

    def _adopt_with_shedding(self, target: FleetMember, rec: _Tracked,
                             d: str, manifest: dict, meta: dict):
        """Try the restore; when the target is out of slots/blocks,
        shed its newest batch-tier session (re-queued fleet-side) and
        retry — batch sheds first so latency migrates."""
        while True:
            sess = target.engine.ingest_handoff(
                rec.request, out=list(meta["out"]),
                pending_tok=int(meta["pending_tok"]),
                position=int(meta["position"]), handoff_dir=d,
                n_blocks=int(manifest["n_blocks"]),
                hash_chain=meta.get("hash_chain"),
                weight_epoch=meta.get("weight_epoch", -1))
            if sess is not None:
                return sess
            if not self._shed_batch_for_room(target):
                return None

    def _shed_batch_for_room(self, target: FleetMember) -> bool:
        """Evict the newest live batch-tier session from ``target``
        and re-queue it fleet-side (recompute mode, exact progress —
        the replica is alive, so no snapshot round-trip).  False when
        the replica holds no batch-tier sessions to shed."""
        batch = [s for s in target.engine.scheduler.sessions
                 if s.rid in self._recs
                 and self._recs[s.rid].slo == "batch"]
        if not batch:
            return False
        victim = max(batch, key=lambda s: self._recs[s.rid].seq)
        target.engine.evict_session(victim)
        self._requeue(self._recs[victim.rid], victim.out, shed=True)
        return True

    # -- routing -----------------------------------------------------------

    def _route(self) -> None:
        """Drain the front-end queue in fleet FIFO order.  Latency
        tier routes to the most-headroom replica unconditionally (its
        admission control paces it); batch tier routes only when the
        target has real block headroom and a batch slot — during a
        shrink that is the admission backpressure the metrics show."""
        routed = []
        for rid in self._queue:
            rec = self._recs[rid]
            target = self._pick_member(rec)
            if target is None:
                continue
            self._deliver(target, rec)
            routed.append(rid)
        for rid in routed:
            self._queue.remove(rid)

    def _pick_member(self, rec: _Tracked) -> Optional[FleetMember]:
        targets = self._targets()
        if not targets:
            return None
        best = targets[0]
        if rec.slo == "batch":
            src = len(rec.request.prompt) + max(0, len(rec.out) - 1)
            need = blocks_for(src + 1, self.block_size)
            if self.spec:
                need *= 2
            sched = best.engine.scheduler
            if self._projected_free(best) < need or \
                    len(sched.sessions) + len(sched.queue) \
                    >= sched.max_batch:
                return None
        return best

    def _deliver(self, target: FleetMember, rec: _Tracked) -> None:
        if rec.out:
            target.engine.submit_recompute(rec.request, rec.out)
        else:
            target.engine.submit(rec.request)
        rec.member = target.member_id
        self._reorder_queue(target.engine)
        _obs.event("serve.fleet", phase="routed", rid=rec.request.rid,
                   member=target.member_id, epoch=self.view.epoch,
                   slo=rec.slo)

    def _reorder_queue(self, engine: ServeEngine) -> None:
        """Keep an engine's admission queue in fleet FIFO order: a
        re-homed session with an older seat slots in AHEAD of the
        survivor's younger native entries (stable for ties)."""
        q = engine.scheduler.queue
        if len(q) < 2:
            return
        big = 1 << 62
        entries = sorted(
            q, key=lambda s: self._recs[s.rid].seq
            if s.rid in self._recs else big)
        q.clear()
        q.extend(entries)

    # -- snapshots ---------------------------------------------------------

    def _snapshot_phase(self) -> None:
        for m in self._live_members():
            try:
                for s in list(m.engine.scheduler.sessions):
                    if s.state != DECODE or s.position <= 0 \
                            or s.finished():
                        continue
                    self._snapshot_session(m, s)
            except _chaos.ChaosKilled:
                # the replica died mid-snapshot: debris (no manifest)
                # stays on shared storage for the restore path to
                # reject; the previous committed snapshot stands
                self._fell(m.member_id)

    def _snapshot_session(self, m: FleetMember, s) -> None:
        rec = self._recs[s.rid]
        rec.snap_no += 1
        d = os.path.join(self.snapshot_dir, _tag(s.rid),
                         f"snap{rec.snap_no}")
        n_blocks = blocks_for(s.position, self.block_size)
        # registered before the stream starts: a kill mid-stream leaves
        # this dir as findable, rejectable debris
        rec.snaps.insert(0, d)
        try:
            if _chaos.active():
                _chaos.hook("serve.session_snapshot", rid=s.rid,
                            member=m.member_id, dir=d, tick=self._tick)
            _manifest, peak = stream_kv_handoff(
                d, m.engine.pool, s.table[:n_blocks],
                source=f"snapshot:{s.rid}",
                extra_meta={"rid": s.rid, "out": list(s.out),
                            "pending_tok": int(s.pending_tok),
                            "position": int(s.position),
                            "slo": rec.slo, "tick": self._tick,
                            "epoch": self.view.epoch,
                            "hash_chain": list(s.hash_chain),
                            "weight_epoch": int(s.weight_epoch)})
        except _chaos.ChaosInjectedFailure:
            # recoverable snapshot fault: skip this round cleanly, the
            # previous committed snapshot stays newest
            discard_kv_handoff(d)
            rec.snaps.remove(d)
            return
        self._snapshot_peak = max(self._snapshot_peak, peak)
        for old in rec.snaps[1:]:
            discard_kv_handoff(old)
        rec.snaps = [d]
        _obs.event("serve.fleet", phase="snapshot", rid=s.rid,
                   member=m.member_id, blocks=n_blocks,
                   peak_bytes=peak)

    # -- results / introspection -------------------------------------------

    def _harvest(self, m: FleetMember) -> None:
        for rid, toks in m.engine.results.items():
            if rid not in self.results:
                self.results[rid] = list(toks)
                rec = self._recs.get(rid)
                if rec is not None:
                    for d in rec.snaps:
                        discard_kv_handoff(d)
                    rec.snaps = []

    def has_work(self) -> bool:
        # every accepted request is tracked until its result lands —
        # including sessions homed on a replica that just died and
        # won't enter recovery until the coordinator publishes the
        # shrink epoch a few scans from now
        return len(self.results) < len(self._recs)

    @property
    def tick(self) -> int:
        return self._tick

    def assignments(self) -> Dict[str, Optional[str]]:
        """The front-end's routing table: ``{rid: member_id}`` (None
        while a request waits fleet-side)."""
        return {rid: rec.member for rid, rec in self._recs.items()}

    def slo_of(self, rid: str) -> str:
        return self._recs[rid].slo

    def metrics(self) -> dict:
        """Fleet SLO/backpressure snapshot: per-replica liveness and
        pool state plus the shrink counters the acceptance pins —
        shed/requeued vs migrated vs recomputed, snapshot peak bytes,
        detection and migration latency."""
        members = {}
        for mid, m in self.members.items():
            members[mid] = {
                "alive": bool(m.alive and not m.closed),
                "sessions": len(m.engine.scheduler.sessions),
                "queue_depth": len(m.engine.scheduler.queue),
                "free_blocks": m.engine.block_pool.free_count,
                "cached_blocks": m.engine.block_pool.cached_count,
                "pool_occupancy": m.engine.block_pool.occupancy,
            }
        return {
            "epoch": self.view.epoch if self.view else 0,
            "members": members,
            "queue_depth": len(self._queue),
            "pending_recovery": len(self._recovery),
            "sessions_migrated": self._migrated,
            "sessions_shed_requeued": self._shed_requeued,
            "sessions_recomputed": self._recomputed,
            "debris_rejected": self._debris_rejected,
            "snapshot_bytes_peak_host": self._snapshot_peak,
            "detect_ms": round(self._detect_ms, 3),
            "migrate_ms": round(self._migrate_ms, 3),
            "completed": len(self.results),
        }

    # -- teardown ----------------------------------------------------------

    def close(self) -> None:
        """Tear down every replica still standing (returning all
        session blocks; ``check_no_leaks`` runs per engine) and remove
        the snapshot root if the fleet created it."""
        for m in self.members.values():
            if not m.closed:
                m.engine.close()
                m.closed = True
                m.member.alive = False
        if self._own_snapdir:
            shutil.rmtree(self.snapshot_dir, ignore_errors=True)

    def __enter__(self) -> "ServeFleet":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False
