"""Paged KV block pool: one preallocated HBM buffer for every session.

The single-session decode paths (inference/session.py, models.gpt
generate) each allocate private ``(B, H, S_max, D)`` caches sized for
their own worst case — at serving concurrency that is the classic
fragmentation failure: a thousand mostly-short sessions reserve a
thousand full-context caches.  vLLM's paged-attention observation is
that KV state is append-only and block-granular, so sessions can share
ONE fixed pool of ``block_size``-position blocks and hold only an
integer block table (logical block i -> physical block id).  HBM for
the serving tier becomes a single static allocation; admission control
is an integer free-list; and — the property the whole serve engine is
built around — the decode program's operand shapes depend only on the
POOL geometry and the bucket dims, never on which sessions are resident,
so session churn cannot force a recompile.

Layout: ``(layers, 2, num_blocks, heads, block_size, head_dim)`` —
k/v interleaved on axis 1 so one gather serves both, block id on axis 2
so a session's table indexes one axis.  **Physical block 0 is the null
block**: it is never allocated, stays all-zeros, and pads every block
table out to its bucket width — gathers through it read zeros that the
position-validity mask already excludes, so padding is free instead of
a branch.  ``dtype="int8"`` builds the quantized pool as a
:class:`~apex_tpu.inference.quant.QuantKV` (int8 payload + one fp32
scale per cached position — the same per-position absmax convention as
the contiguous int8 cache, via :func:`~apex_tpu.inference.quant.
absmax_int8`).

**Reference counting + content addressing** (the prefix cache): the
pool is no longer a plain free-list.  Every held block carries a
refcount — the cross-request prefix cache (RadixAttention / vLLM's
automatic prefix caching lineage) lets N sessions whose token chains
share a committed prefix hold the SAME physical blocks.  Full blocks
are *committed* under a rolling content hash of their token chain
(:func:`chain_key` — keyed by the parent block's hash, the block's
tokens, and a tag carrying cache dtype / block size / attention window
/ model weight epoch, so an int8 pool never matches an fp32 chain and
a ``publish_weights`` hot-swap never serves stale KV).  The
``hash → physical block`` index (:meth:`BlockPool.acquire_prefix`)
turns admission into a chain walk: matched blocks are adopted by
refcount, and only the cold suffix is granted from the free list.

Shared blocks are IMMUTABLE — a session that must write into one forks
it copy-on-write (scheduler policy + the paged block-copy program in
serve/kernels.py; the pool only does the id bookkeeping).  A freed
block whose hash entry is still live retires into an LRU **cached
tier** instead of the free list: refcount zero, bytes intact, re-usable
by the next matching chain, evicted (hash entry dropped, id returned
to the free list) only under allocation pressure.  Cached blocks are
headroom, not leaks: ``check_no_leaks`` and ``free_count`` both count
them as reclaimable.

The host side (:class:`BlockPool`) remains deliberately dumb: integer
bookkeeping with leak accounting.  Policy (who gets blocks, who is
preempted, when to fork) lives in the scheduler; device-side index
arithmetic lives in serve/kernels.py.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Sequence

import jax.numpy as jnp

from ..inference.quant import QuantKV
from ..observe import registry as _obs

#: physical id of the all-zeros block every table pads with
NULL_BLOCK = 0


def blocks_for(n_positions: int, block_size: int) -> int:
    """Blocks needed to hold ``n_positions`` KV rows."""
    return -(-max(int(n_positions), 0) // block_size)


def init_pool_buffer(layers, heads, head_dim, num_blocks, block_size,
                     dtype=jnp.float32):
    """The device-side pool array
    ``(layers, 2, num_blocks, heads, block_size, head_dim)`` — zeros, so
    the null block is born valid.  ``dtype="int8"``/``jnp.int8`` builds
    the :class:`QuantKV` pair (scales fp32, one per position)."""
    shape = (layers, 2, num_blocks, heads, block_size, head_dim)
    if jnp.dtype(dtype) == jnp.dtype("int8"):
        return QuantKV(jnp.zeros(shape, jnp.int8),
                       jnp.zeros(shape[:-1] + (1,), jnp.float32))
    return jnp.zeros(shape, dtype)


# ---------------------------------------------------------------------------
# Content hashing: the rolling token-chain key
# ---------------------------------------------------------------------------


def chain_key(parent: str, tokens: Sequence[int], tag: str) -> str:
    """The content hash of ONE full block: rolling over ``parent`` (the
    previous block's key, ``""`` for the chain head), the block's token
    ids, and ``tag`` — the engine's cache-compatibility stamp (dtype,
    block size, window, weight epoch).  Two blocks share a key iff they
    hold the KV of the same token prefix computed under the same cache
    geometry and weights — which is exactly when their bytes are
    interchangeable."""
    h = hashlib.blake2b(digest_size=16)
    h.update(parent.encode("ascii"))
    h.update(b"\x00")
    h.update(tag.encode("utf-8"))
    h.update(b"\x00")
    h.update(",".join(str(int(t)) for t in tokens).encode("ascii"))
    return h.hexdigest()


def chain_keys(tokens: Sequence[int], block_size: int,
               tag: str) -> List[str]:
    """The hash chain over every FULL block of ``tokens`` (partial tail
    blocks are never content-addressed — their rows are still being
    written)."""
    keys: List[str] = []
    prev = ""
    for i in range(len(tokens) // block_size):
        prev = chain_key(prev, tokens[i * block_size:(i + 1) * block_size],
                         tag)
        keys.append(prev)
    return keys


class BlockPool:
    """Host-side refcounted allocator over physical block ids
    ``1 .. num_blocks-1`` (id 0 is :data:`NULL_BLOCK`, never handed
    out).

    ``alloc(n)`` returns ``n`` exclusive ids (refcount 1) or None
    (all-or-nothing — a partial grant would deadlock two half-admitted
    sessions against each other), evicting LRU cached-tier blocks under
    pressure; ``free(ids)`` drops one reference per id — a block
    reaching refcount zero retires to the cached tier when its hash
    entry is live, else returns to the free list.  Freeing more times
    than references held raises (the shared-block double-free).
    ``acquire_prefix(keys)`` walks a request's hash chain and adopts
    the longest matched prefix by refcount; ``commit(id, key)``
    registers a full block under its chain hash.  Every transition
    keeps the ``pool.free`` / ``pool.cached`` / ``pool.active`` gauges
    current, and the churn tests pin ``in_use == 0`` +
    ``free + cached == capacity`` after drain.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 metrics_prefix: str = "serve."):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved null "
                f"block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._prefix = metrics_prefix
        self._lock = threading.Lock()
        # LIFO: recently freed blocks are re-issued first (their pool
        # rows are hottest in cache on CPU runs; on TPU it is a wash)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._refs: Dict[int, int] = {}          # id -> refcount (held)
        # refcount-zero blocks with live hash entries, LRU order
        # (oldest retired first); values are their chain keys
        self._cached: "OrderedDict[int, str]" = OrderedDict()
        self._hash_index: Dict[str, int] = {}    # chain key -> id
        self._block_hash: Dict[int, str] = {}    # id -> chain key
        self.cache_evictions = 0
        self._gauge()

    # -- accounting --------------------------------------------------------

    @property
    def capacity(self) -> int:
        """Allocatable blocks (the null block is not one)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        """Allocatable headroom NOW: free-list blocks plus cached-tier
        blocks (evictable on demand) — what admission and the elastic
        fleet's backpressure should budget against."""
        with self._lock:
            return len(self._free) + len(self._cached)

    @property
    def free_exact(self) -> int:
        """Free-list blocks only (no cached-tier eviction needed)."""
        with self._lock:
            return len(self._free)

    @property
    def cached_count(self) -> int:
        """Cached-tier blocks: refcount zero, hash entry live."""
        with self._lock:
            return len(self._cached)

    @property
    def in_use(self) -> int:
        """Blocks held by at least one live table (refcount >= 1)."""
        with self._lock:
            return len(self._refs)

    @property
    def occupancy(self) -> float:
        """Fraction of allocatable blocks currently held (cached-tier
        blocks are reclaimable headroom, not occupancy)."""
        with self._lock:
            return len(self._refs) / (self.num_blocks - 1)

    def refcount(self, block_id: int) -> int:
        """Live references to ``block_id`` (0 = free or cached)."""
        with self._lock:
            return self._refs.get(block_id, 0)

    def _gauge(self):
        cap = self.num_blocks - 1
        _obs.gauge(self._prefix + "pool_occupancy").set(
            len(self._refs) / cap)
        _obs.gauge(self._prefix + "pool_free_blocks").set(
            len(self._free) + len(self._cached))
        # the split gauges: free conflated with soon-to-be-cached was
        # hiding true headroom from the elastic fleet's shed decisions
        _obs.gauge(self._prefix + "pool.free").set(len(self._free))
        _obs.gauge(self._prefix + "pool.cached").set(len(self._cached))
        _obs.gauge(self._prefix + "pool.active").set(len(self._refs))

    # -- alloc / free ------------------------------------------------------

    def _evict_locked(self) -> None:
        """Drop the LRU cached-tier block's hash entry and return its
        id to the free list (caller holds the lock)."""
        bid, key = self._cached.popitem(last=False)
        del self._hash_index[key]
        del self._block_hash[bid]
        self._free.append(bid)
        self.cache_evictions += 1
        _obs.counter(self._prefix + "cache.evictions").inc()

    def alloc(self, n: int):
        """``n`` exclusive physical block ids (refcount 1), or None if
        the pool cannot cover the whole request (nothing is taken on
        refusal).  Cached-tier blocks are evicted LRU-first when the
        free list alone cannot cover ``n`` — allocation pressure is the
        cached tier's only eviction trigger."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        with self._lock:
            if n > len(self._free) + len(self._cached):
                return None
            while len(self._free) < n:
                self._evict_locked()
            ids = [self._free.pop() for _ in range(n)]
            for b in ids:
                self._refs[b] = 1
            self._gauge()
        return ids

    def free(self, ids) -> None:
        """Drop ONE reference per id.  A block reaching refcount zero
        retires to the cached tier when its hash entry is live (bytes
        stay adoptable), else returns to the free list.  Freeing an id
        with no live reference raises — that is a double free (of an
        exclusive OR a shared block: sharing never grants extra
        frees)."""
        with self._lock:
            for b in ids:
                r = self._refs.get(b)
                if r is None:
                    raise ValueError(
                        f"free of block {b} not held by this pool "
                        f"(double free, foreign id, or more frees than "
                        f"references) — block tables and the refcounts "
                        f"have diverged")
                if r > 1:
                    self._refs[b] = r - 1
                    continue
                del self._refs[b]
                key = self._block_hash.get(b)
                if key is not None:
                    self._cached[b] = key        # MRU end of the LRU
                else:
                    self._free.append(b)
            self._gauge()

    # -- content addressing ------------------------------------------------

    def acquire_prefix(self, keys: Sequence[str]) -> List[int]:
        """Walk a request's hash chain and adopt the longest matched
        prefix: each matched block gains a reference (cached-tier
        blocks are resurrected to refcount 1; held blocks just
        increment).  Returns the matched physical ids, chain order —
        the caller budgets only the cold suffix.  Adopted blocks are
        shared and immutable; release them with :meth:`free`."""
        out: List[int] = []
        with self._lock:
            for key in keys:
                bid = self._hash_index.get(key)
                if bid is None:
                    break
                if bid in self._refs:
                    self._refs[bid] += 1
                else:
                    del self._cached[bid]
                    self._refs[bid] = 1
                out.append(bid)
            if out:
                self._gauge()
        return out

    def commit(self, block_id: int, key: str) -> bool:
        """Register a held, FULL block under its chain hash — from now
        on :meth:`acquire_prefix` can adopt it and :meth:`free` retires
        it to the cached tier instead of the free list.  First writer
        wins: a key already mapped (another session committed the same
        chain first) or a block already hashed is left untouched
        (returns False)."""
        with self._lock:
            if block_id not in self._refs:
                return False                 # freed/evicted underneath
            if key in self._hash_index or block_id in self._block_hash:
                return False
            self._hash_index[key] = block_id
            self._block_hash[block_id] = key
            return True

    def flush_cache(self) -> int:
        """Drop EVERY hash entry and return all cached-tier blocks to
        the free list — the ``publish_weights`` invalidation path: a
        weight hot-swap changes the chain tag, so no stale entry can
        ever match again; flushing reclaims the memory immediately.
        Held blocks stay held (their sessions continue under mixed
        weights, documented in docs/rollout.md) but lose their hash
        entries, so they free to the free list later.  Returns the
        number of cached blocks reclaimed."""
        with self._lock:
            n = len(self._cached)
            for bid in self._cached:
                self._free.append(bid)
            self._cached.clear()
            self._hash_index.clear()
            self._block_hash.clear()
            self._gauge()
            return n

    def check_no_leaks(self) -> None:
        """Raise unless every allocatable block is reclaimable — on the
        free list or in the cached tier (refcount zero, adoptable).
        Cached blocks are NOT leaks: they are the prefix cache
        surviving session churn, evictable on demand.  The post-drain
        invariant of the churn tests."""
        with self._lock:
            if self._refs or \
                    len(self._free) + len(self._cached) \
                    != self.num_blocks - 1:
                raise AssertionError(
                    f"block pool leak: {len(self._refs)} blocks still "
                    f"held (refcounts {dict(self._refs)}), free list "
                    f"{len(self._free)} + cached {len(self._cached)} != "
                    f"{self.num_blocks - 1}")
