"""Paged KV block pool: one preallocated HBM buffer for every session.

The single-session decode paths (inference/session.py, models.gpt
generate) each allocate private ``(B, H, S_max, D)`` caches sized for
their own worst case — at serving concurrency that is the classic
fragmentation failure: a thousand mostly-short sessions reserve a
thousand full-context caches.  vLLM's paged-attention observation is
that KV state is append-only and block-granular, so sessions can share
ONE fixed pool of ``block_size``-position blocks and hold only an
integer block table (logical block i -> physical block id).  HBM for
the serving tier becomes a single static allocation; admission control
is an integer free-list; and — the property the whole serve engine is
built around — the decode program's operand shapes depend only on the
POOL geometry and the bucket dims, never on which sessions are resident,
so session churn cannot force a recompile.

Layout: ``(layers, 2, num_blocks, heads, block_size, head_dim)`` —
k/v interleaved on axis 1 so one gather serves both, block id on axis 2
so a session's table indexes one axis.  **Physical block 0 is the null
block**: it is never allocated, stays all-zeros, and pads every block
table out to its bucket width — gathers through it read zeros that the
position-validity mask already excludes, so padding is free instead of
a branch.  ``dtype="int8"`` builds the quantized pool as a
:class:`~apex_tpu.inference.quant.QuantKV` (int8 payload + one fp32
scale per cached position — the same per-position absmax convention as
the contiguous int8 cache, via :func:`~apex_tpu.inference.quant.
absmax_int8`).

The host side (:class:`BlockPool`) is deliberately dumb: a LIFO
free-list with leak accounting.  Policy (who gets blocks, who is
preempted) lives in the scheduler; device-side index arithmetic lives
in serve/kernels.py.
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

from ..inference.quant import QuantKV
from ..observe import registry as _obs

#: physical id of the all-zeros block every table pads with
NULL_BLOCK = 0


def blocks_for(n_positions: int, block_size: int) -> int:
    """Blocks needed to hold ``n_positions`` KV rows."""
    return -(-max(int(n_positions), 0) // block_size)


def init_pool_buffer(layers, heads, head_dim, num_blocks, block_size,
                     dtype=jnp.float32):
    """The device-side pool array
    ``(layers, 2, num_blocks, heads, block_size, head_dim)`` — zeros, so
    the null block is born valid.  ``dtype="int8"``/``jnp.int8`` builds
    the :class:`QuantKV` pair (scales fp32, one per position)."""
    shape = (layers, 2, num_blocks, heads, block_size, head_dim)
    if jnp.dtype(dtype) == jnp.dtype("int8"):
        return QuantKV(jnp.zeros(shape, jnp.int8),
                       jnp.zeros(shape[:-1] + (1,), jnp.float32))
    return jnp.zeros(shape, dtype)


class BlockPool:
    """Host-side free-list over physical block ids ``1 .. num_blocks-1``
    (id 0 is :data:`NULL_BLOCK`, never handed out).

    ``alloc(n)`` returns ``n`` ids or None (all-or-nothing — a partial
    grant would deadlock two half-admitted sessions against each
    other); ``free(ids)`` returns them.  Every transition keeps the
    ``serve.pool_occupancy`` gauge current and double-free / foreign-id
    frees raise — leaked blocks are the serving analogue of a memory
    leak and the churn tests pin ``in_use == 0`` after drain.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 metrics_prefix: str = "serve."):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved null "
                f"block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._prefix = metrics_prefix
        self._lock = threading.Lock()
        # LIFO: recently freed blocks are re-issued first (their pool
        # rows are hottest in cache on CPU runs; on TPU it is a wash)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._held = set()
        self._gauge()

    # -- accounting --------------------------------------------------------

    @property
    def capacity(self) -> int:
        """Allocatable blocks (the null block is not one)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def in_use(self) -> int:
        with self._lock:
            return len(self._held)

    @property
    def occupancy(self) -> float:
        """Fraction of allocatable blocks currently held."""
        with self._lock:
            return len(self._held) / (self.num_blocks - 1)

    def _gauge(self):
        _obs.gauge(self._prefix + "pool_occupancy").set(
            len(self._held) / (self.num_blocks - 1))
        _obs.gauge(self._prefix + "pool_free_blocks").set(len(self._free))

    # -- alloc / free ------------------------------------------------------

    def alloc(self, n: int):
        """``n`` physical block ids, or None if the pool cannot cover
        the whole request (nothing is taken on refusal)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        with self._lock:
            if n > len(self._free):
                return None
            ids = [self._free.pop() for _ in range(n)]
            self._held.update(ids)
            self._gauge()
        return ids

    def free(self, ids) -> None:
        with self._lock:
            for b in ids:
                if b not in self._held:
                    raise ValueError(
                        f"free of block {b} not held by this pool "
                        f"(double free or foreign id) — block tables "
                        f"and the free list have diverged")
                self._held.discard(b)
                self._free.append(b)
            self._gauge()

    def check_no_leaks(self) -> None:
        """Raise unless every allocatable block is back on the free
        list — the post-drain invariant of the churn tests."""
        with self._lock:
            if self._held or len(self._free) != self.num_blocks - 1:
                raise AssertionError(
                    f"block pool leak: {len(self._held)} blocks still "
                    f"held, free list {len(self._free)}/"
                    f"{self.num_blocks - 1}")
