"""Paged-attention program bodies: the traced code the serve engine
submits through the one-runtime executor.

Everything here is shape-static by construction — operand shapes are
functions of the POOL geometry (layers/heads/block_size/head_dim) and
the BUCKET dims (batch, blocks, chunk) baked into the builder, never of
live request state.  Request state (which sessions, at which positions,
holding which blocks) enters as *traced integer arrays* (tokens,
positions, block tables), so session churn re-dispatches the same
compiled program instead of retracing — the serving analogue of the
step-cache keying discipline, enforced by the SERVE-SHAPE lint rule.

The attention math deliberately reuses the model's own decode pieces —
``GptBlock._chunk_qkv`` (LN1 + interleaved QKV projection),
``GptBlock._attn_mlp_tail`` (out-proj + residual + FFN), the fp32
score einsum + ``-1e30`` mask + softmax of ``GptBlock.decode_chunk``,
and the int8-aware ``gather_rows`` embedding lookup — so the paged
path cannot drift numerically from the contiguous-cache path it is
parity-tested against (tests/test_serve.py).  The only new math is the
index plumbing: block-table gathers into a per-tick linear cache view,
and position→(block, offset) scatters of fresh KV.

Dead batch rows (bucket padding) are encoded as ``position == -1``:
their tables are all-null (gathers read zeros the mask excludes), their
embedding lookups clip to row 0 (outputs discarded), and their KV
scatter targets are redirected past the pool so ``mode="drop"``
discards the write — padding never touches the null block's zeros.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..inference import QuantKV, absmax_int8, gather_rows
from ..nn.modules import Ctx

_f32 = jnp.float32


def _ctx(params, vals):
    return Ctx(env={id(p): v for p, v in zip(params, vals)},
               stats_out={}, training=False)


# ---------------------------------------------------------------------------
# Pool indexing: block-table gather / position scatter
# ---------------------------------------------------------------------------


def gather_pool(pool, tables):
    """Gather each session's blocks into a LINEAR cache view.

    ``tables (B, nb)`` physical ids -> per-layer reader ``read(l)``
    returning ``(k, v)`` of shape ``(B, H, nb*block_size, D)`` fp32,
    where linear slot ``s`` holds the KV of logical position ``s`` (the
    table is logical-block-ordered, so the gather IS the
    logical→physical translation).  Null-padded table entries read the
    zero block — masked out by the caller's position-validity mask.
    QuantKV pools gather int8 payload + scales and dequantize after the
    gather (only the selected blocks' bytes move)."""
    def lin(g):
        # (B, nb, H, bs, D) -> (B, H, nb*bs, D)
        b, nb, h, bs, d = g.shape
        return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(b, h, nb * bs, d)

    if isinstance(pool, QuantKV):
        q = pool.q[:, :, tables]          # (L, 2, B, nb, H, bs, D)
        s = pool.scale[:, :, tables]      # (L, 2, B, nb, H, bs, 1)

        def read(layer):
            return (lin(q[layer, 0]).astype(_f32) * lin(s[layer, 0]),
                    lin(q[layer, 1]).astype(_f32) * lin(s[layer, 1]))
        return read
    g = pool[:, :, tables]                # (L, 2, B, nb, H, bs, D)

    def read(layer):
        return lin(g[layer, 0]).astype(_f32), lin(g[layer, 1]).astype(_f32)
    return read


def scatter_pool(pool, layer, kv, blk_ids, offs, vals):
    """Write ``vals (R, H, D)`` into ``pool[layer, kv]`` at physical
    block ``blk_ids (R,)``, in-block offset ``offs (R,)``.  Rows whose
    ``blk_ids`` point past the pool are dropped (``mode="drop"``) —
    the caller encodes dead/pad rows that way.  QuantKV pools quantize
    per position (absmax over D — identical stored bytes to the
    contiguous int8 cache's write path)."""
    if isinstance(pool, QuantKV):
        q, scale = absmax_int8(vals.astype(_f32), -1, pool.scale.dtype)
        return QuantKV(
            pool.q.at[layer, kv, blk_ids, :, offs, :].set(
                q, mode="drop"),
            pool.scale.at[layer, kv, blk_ids, :, offs, :].set(
                scale, mode="drop"))
    return pool.at[layer, kv, blk_ids, :, offs, :].set(
        vals.astype(pool.dtype), mode="drop")


def insert_row(pool, k_lin, v_lin, k_new, v_new, own):
    """Splice the just-projected KV row(s) into the gathered linear
    view so the current query attends its own fresh keys (the paged
    analogue of decode_chunk's write-then-read).  Through an int8 pool
    the inserted rows take the quantize→dequantize round trip FIRST, so
    attention reads exactly the bytes the scatter will store."""
    if isinstance(pool, QuantKV):
        kq, ks = absmax_int8(k_new.astype(_f32), -1, pool.scale.dtype)
        vq, vs = absmax_int8(v_new.astype(_f32), -1, pool.scale.dtype)
        k_new = kq.astype(_f32) * ks
        v_new = vq.astype(_f32) * vs
    return (jnp.where(own, k_new.astype(_f32), k_lin),
            jnp.where(own, v_new.astype(_f32), v_lin))


def build_block_copy_fn():
    """The copy-on-write fork program body: duplicate ONE physical
    block's bytes — every layer, both k and v, payload AND scales for a
    :class:`QuantKV` pool — from ``src`` to ``dst``.

    ``fn(pool, src, dst) -> pool`` with ``src``/``dst`` traced i32
    scalars, so one compiled program serves every fork (block ids are
    data, not shapes — the SERVE-SHAPE discipline).  The scheduler
    decides WHEN to fork (a session extending into a shared block); the
    destination is a fresh exclusive block, the source keeps serving
    its other holders untouched — the copy is what makes shared blocks
    immutable in practice."""
    def fn(pool, src, dst):
        if isinstance(pool, QuantKV):
            return QuantKV(
                pool.q.at[:, :, dst].set(pool.q[:, :, src]),
                pool.scale.at[:, :, dst].set(pool.scale[:, :, src]))
        return pool.at[:, :, dst].set(pool[:, :, src])
    return fn


# ---------------------------------------------------------------------------
# Program bodies
# ---------------------------------------------------------------------------


def _paged_attend(blk, x, q, k_lin, v_lin, positions, slots, window):
    """decode_chunk's score/mask/softmax/combine against a gathered
    linear cache: ``q (B, H, Q, D)``, per-row query positions
    ``positions (B, Q)``.  ``window`` adds the sliding-window band term
    (rolling.py's mask, generalized to block tables)."""
    scores = jnp.einsum("bhqd,bhsd->bhqs", q.astype(_f32),
                        k_lin) * blk.attn.scaling
    valid = slots[None, None, :] <= positions[:, :, None]   # (B, Q, S)
    if window is not None:
        valid = valid & (slots[None, None, :]
                         > positions[:, :, None] - window)
    scores = jnp.where(valid[:, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bhqs,bhsd->bhqd", probs, v_lin).astype(x.dtype)
    b, h, s_q, d = q.shape
    return jnp.swapaxes(o, 1, 2).reshape(b, s_q, h * d)


def _embed(ctx, model, toks, positions):
    """Token + position embedding with int8-aware row gathers;
    ``positions`` clip to the table (pad rows only — real positions are
    range-checked at admission, where the bound is a host decision, not
    here where a clamp would silently corrupt)."""
    n_pos = model.pos_emb.weight.shape[0]
    pos = jnp.clip(positions, 0, n_pos - 1)
    return gather_rows(ctx, model.tok_emb.weight, toks) \
        + gather_rows(ctx, model.pos_emb.weight, pos)


def _head(ctx, model, x):
    emb = ctx.value(model.tok_emb.weight)
    return model._mask_pad_logits(
        jnp.matmul(x, jnp.swapaxes(emb, 0, 1).astype(x.dtype)))


def build_decode_fn(model, params, block_size, num_blocks, window=None):
    """The decode-tick program body: one token per live session.

    ``fn(vals, pool, tokens, positions, tables) ->
    (next_tokens, logits, pool)`` with ``tokens (B,)`` the last emitted
    token per session, ``positions (B,)`` its ingest position (``-1`` =
    dead pad row), ``tables (B, nb)``.  Greedy sampling happens
    in-program (argmax over the masked logits — the same reduction the
    session path's ``make_sampler(0, ...)`` runs), so the engine's host
    round-trip per tick is one small int array; the logits ride along
    as an un-fetched device array for clients (PagedSession) that
    continue from them."""
    bs = block_size

    def fn(vals, pool, tokens, positions, tables):
        ctx = _ctx(params, vals)
        x = _embed(ctx, model, tokens[:, None], positions[:, None])
        read = gather_pool(pool, tables)
        slots = jnp.arange(tables.shape[1] * bs, dtype=jnp.int32)
        fresh = []
        for layer, blk in enumerate(model.blocks):
            q, k_new, v_new = blk._chunk_qkv(ctx, x)      # (B, H, 1, D)
            k_lin, v_lin = read(layer)
            own = (slots[None, :]
                   == positions[:, None])[:, None, :, None]
            k_lin, v_lin = insert_row(pool, k_lin, v_lin, k_new, v_new,
                                      own)
            o = _paged_attend(blk, x, q, k_lin, v_lin,
                              positions[:, None], slots, window)
            x = blk._attn_mlp_tail(ctx, x, o)
            fresh.append((k_new, v_new))
        x = model.ln_f.forward(ctx, x)
        logits = _head(ctx, model, x)[:, 0]               # (B, V)
        nxt = jnp.argmax(logits, axis=-1).astype(tokens.dtype)
        # position -> (physical block, offset); dead rows drop
        p = jnp.clip(positions, 0)
        tgt = jnp.take_along_axis(
            tables, jnp.minimum(p // bs, tables.shape[1] - 1)[:, None],
            axis=1)[:, 0]
        tgt = jnp.where(positions >= 0, tgt, num_blocks)
        offs = p % bs
        for layer, (k_new, v_new) in enumerate(fresh):
            pool = scatter_pool(pool, layer, 0, tgt, offs,
                                k_new[:, :, 0, :])
            pool = scatter_pool(pool, layer, 1, tgt, offs,
                                v_new[:, :, 0, :])
        return nxt, logits, pool
    return fn


def build_prefill_fn(model, params, block_size, num_blocks,
                     window=None):
    """The prefill-chunk program body: ingest one fixed-width chunk of
    ONE session's prompt per dispatch (long prompts run as several
    chunks, interleaved with decode ticks so they never stall the
    batch).

    ``fn(vals, pool, toks, table, t0, n_real) -> (last_logits, pool)``
    with ``toks (1, chunk)`` zero-padded past ``n_real``, ``table
    (1, nb)``, ``t0`` the chunk's first position, ``n_real`` the live
    prefix length (both traced i32 — the bucketed chunk width, not the
    prompt length, keys compilation).  ``last_logits (1, V)`` is row
    ``n_real - 1`` — the next-token distribution once the final chunk
    lands."""
    bs = block_size

    def fn(vals, pool, toks, table, t0, n_real):
        ctx = _ctx(params, vals)
        chunk = toks.shape[1]
        rows = jnp.arange(chunk, dtype=jnp.int32)
        pos = t0 + rows                                   # (chunk,)
        x = _embed(ctx, model, toks, pos[None, :])
        read = gather_pool(pool, table)
        nb = table.shape[1]
        slots = jnp.arange(nb * bs, dtype=jnp.int32)
        # chunk row d lands in linear slot t0 + d; live rows only
        # (the rolling_kv_write masked-select technique, block-tabled)
        d = slots - t0                                    # (S,)
        own = ((d >= 0) & (d < n_real))[None, None, :, None]
        src = jnp.clip(d, 0, chunk - 1)
        fresh = []
        for layer, blk in enumerate(model.blocks):
            q, k_new, v_new = blk._chunk_qkv(ctx, x)   # (B, H, chunk, D)
            k_lin, v_lin = read(layer)
            k_ins = jnp.take(k_new, src, axis=2)       # (B, H, S, D)
            v_ins = jnp.take(v_new, src, axis=2)
            k_lin, v_lin = insert_row(pool, k_lin, v_lin, k_ins, v_ins,
                                      own)
            o = _paged_attend(blk, x, q, k_lin, v_lin, pos[None, :],
                              slots, window)
            x = blk._attn_mlp_tail(ctx, x, o)
            fresh.append((k_new, v_new))
        x = model.ln_f.forward(ctx, x)
        logits = _head(ctx, model, x)                  # (1, chunk, V)
        last = jax.lax.dynamic_index_in_dim(
            logits, jnp.clip(n_real - 1, 0), axis=1, keepdims=False)
        live = rows < n_real
        tgt = table[0, jnp.minimum(pos // bs, nb - 1)]  # (chunk,)
        tgt = jnp.where(live, tgt, num_blocks)
        offs = pos % bs
        for layer, (k_new, v_new) in enumerate(fresh):
            pool = scatter_pool(pool, layer, 0, tgt, offs,
                                jnp.swapaxes(k_new[0], 0, 1))
            pool = scatter_pool(pool, layer, 1, tgt, offs,
                                jnp.swapaxes(v_new[0], 0, 1))
        return last, pool
    return fn


def _scatter_chunk(pool, fresh, tables, positions, block_size,
                   num_blocks, width):
    """Batched multi-position scatter: write ``fresh`` — per-layer
    ``(k_new, v_new)`` of shape ``(B, H, width, D)`` — so chunk row
    ``j`` of batch row ``b`` lands at logical position
    ``positions[b] + j``.  Dead rows (``positions == -1``) redirect past
    the pool and drop; live rows are distinct (position, table) pairs,
    so the scatter has no write conflicts."""
    bs = block_size
    nb = tables.shape[1]
    p = positions[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
    pc = jnp.clip(p, 0)
    tgt = jnp.take_along_axis(tables, jnp.minimum(pc // bs, nb - 1),
                              axis=1)
    tgt = jnp.where(positions[:, None] >= 0, tgt,
                    num_blocks).reshape(-1)
    offs = (pc % bs).reshape(-1)
    for layer, (k_new, v_new) in enumerate(fresh):
        _, h, _, d_ = k_new.shape
        pool = scatter_pool(pool, layer, 0, tgt, offs,
                            jnp.swapaxes(k_new, 1, 2).reshape(-1, h, d_))
        pool = scatter_pool(pool, layer, 1, tgt, offs,
                            jnp.swapaxes(v_new, 1, 2).reshape(-1, h, d_))
    return pool


def build_spec_verify_fn(target, t_params, draft, d_params, block_size,
                         num_blocks, k):
    """The speculative decode-tick program body: draft-propose + target-
    verify, fused into ONE dispatch per tick.

    ``fn(t_vals, d_vals, t_pool, d_pool, tokens, positions, t_tables,
    d_tables) -> (emitted, n_acc, t_pool, d_pool)`` with ``tokens (B,)``
    each session's pending token, ``positions (B,)`` its ingest position
    (``-1`` = dead pad row), and separate block tables into the target
    and draft pools (same :class:`~apex_tpu.serve.pool.BlockPool`
    free-list, two geometry-matched buffers).

    Inside the program:

    1. the DRAFT runs ``k + 1`` sequential paged decode steps from the
       pending token — the extra step writes the draft KV row for the
       all-accepted case (speculative.py's ``m + k`` cache-coverage
       rule) — proposing greedy tokens ``d_1..d_k``;
    2. the TARGET verifies the chunk ``[x_0, d_1..d_k]`` at positions
       ``p..p+k`` in one batched multi-position paged pass (the prefill
       body's insert mask, per batch row), yielding greedy tokens
       ``g_1..g_{k+1}``;
    3. ragged greedy acceptance PER ROW: ``n_acc[b] - 1`` is the length
       of the longest prefix where ``d_i == g_i``, so row ``b`` commits
       ``emitted[b, :n_acc[b]]`` — between 1 and ``k + 1`` tokens, each
       one exactly what the plain decode program would have emitted.

    Both pools are written through position ``p + k`` every tick; rows
    past the committed point hold KV of rejected continuations and are
    overwritten by the next tick's chunk (positions ``p'..p'+k`` with
    ``p' <= p + k + 1``) before any query's validity mask can reach
    them — the cache-staleness invariant speculative.py documents,
    expressed in block tables.  Shape-static like every serve body: the
    batch bucket, the two table buckets and ``k`` key compilation;
    acceptance lengths are DATA (`n_acc`), never shapes."""
    bs = block_size
    kp1 = k + 1

    def fn(t_vals, d_vals, t_pool, d_pool, tokens, positions,
           t_tables, d_tables):
        t_ctx = _ctx(t_params, t_vals)
        d_ctx = _ctx(d_params, d_vals)
        live = positions >= 0
        # ---- draft proposes: kp1 sequential paged single-token steps
        # against one up-front gather; fresh rows accumulate in the
        # linear view and scatter back once at the end.
        d_read = gather_pool(d_pool, d_tables)
        d_slots = jnp.arange(d_tables.shape[1] * bs, dtype=jnp.int32)
        d_lins = [list(d_read(layer))
                  for layer in range(len(draft.blocks))]
        d_fresh = [[] for _ in draft.blocks]
        chunk_toks = [tokens]
        tok = tokens
        for j in range(kp1):
            pos_j = jnp.where(live, positions + j, -1)
            x = _embed(d_ctx, draft, tok[:, None], pos_j[:, None])
            for layer, blk in enumerate(draft.blocks):
                q, k_new, v_new = blk._chunk_qkv(d_ctx, x)
                own = (d_slots[None, :]
                       == pos_j[:, None])[:, None, :, None]
                k_lin, v_lin = insert_row(
                    d_pool, d_lins[layer][0], d_lins[layer][1],
                    k_new, v_new, own)
                d_lins[layer] = [k_lin, v_lin]
                o = _paged_attend(blk, x, q, k_lin, v_lin,
                                  pos_j[:, None], d_slots, None)
                x = blk._attn_mlp_tail(d_ctx, x, o)
                d_fresh[layer].append((k_new, v_new))
            if j < k:                  # step k only writes its KV row
                x = draft.ln_f.forward(d_ctx, x)
                logits = _head(d_ctx, draft, x)[:, 0]
                tok = jnp.argmax(logits, axis=-1).astype(tokens.dtype)
                chunk_toks.append(tok)
        chunk = jnp.stack(chunk_toks, axis=1)       # (B, kp1)
        d_pool = _scatter_chunk(
            d_pool,
            [(jnp.concatenate([f[0] for f in per], axis=2),
              jnp.concatenate([f[1] for f in per], axis=2))
             for per in d_fresh],
            d_tables, positions, bs, num_blocks, kp1)
        # ---- target verifies the whole chunk in one paged pass
        t_read = gather_pool(t_pool, t_tables)
        slots = jnp.arange(t_tables.shape[1] * bs, dtype=jnp.int32)
        offs_q = jnp.arange(kp1, dtype=jnp.int32)[None, :]
        q_pos = jnp.where(live[:, None], positions[:, None] + offs_q, -1)
        x = _embed(t_ctx, target, chunk, q_pos)
        d = slots[None, :] - positions[:, None]             # (B, S)
        own = ((d >= 0) & (d < kp1)
               & live[:, None])[:, None, :, None]
        src = jnp.clip(d, 0, k)[:, None, :, None]
        t_fresh = []
        for layer, blk in enumerate(target.blocks):
            q, k_new, v_new = blk._chunk_qkv(t_ctx, x)   # (B, H, kp1, D)
            k_lin, v_lin = t_read(layer)
            k_ins = jnp.take_along_axis(k_new, src, axis=2)
            v_ins = jnp.take_along_axis(v_new, src, axis=2)
            k_lin, v_lin = insert_row(t_pool, k_lin, v_lin, k_ins,
                                      v_ins, own)
            o = _paged_attend(blk, x, q, k_lin, v_lin, q_pos, slots,
                              None)
            x = blk._attn_mlp_tail(t_ctx, x, o)
            t_fresh.append((k_new, v_new))
        x = target.ln_f.forward(t_ctx, x)
        logits = _head(t_ctx, target, x)                # (B, kp1, V)
        emitted = jnp.argmax(logits, axis=-1).astype(tokens.dtype)
        agree = chunk[:, 1:] == emitted[:, :k]
        stop = jnp.concatenate(
            [agree, jnp.zeros((agree.shape[0], 1), bool)], axis=1)
        n_acc = jnp.argmin(stop.astype(jnp.int32), axis=1) + 1
        t_pool = _scatter_chunk(t_pool, t_fresh, t_tables, positions,
                                bs, num_blocks, kp1)
        return emitted, n_acc, t_pool, d_pool
    return fn
