"""apex_tpu.serve — continuous-batching + paged-KV serving engine.

The multi-tenant leg of the decode stack: thousands of sessions share
one preallocated HBM block pool (:mod:`pool`), a continuous-batching
scheduler re-packs the live set every tick (:mod:`scheduler`), and the
engine (:mod:`engine`) dispatches a small family of program kinds —
``prefill_step`` / ``decode_step`` / ``draft_prefill_step`` /
``spec_verify_step`` — through the one-runtime executor, inheriting
its step-cache keying, dispatch spans, donation policy and watchdog
heartbeats.  :mod:`disagg` splits the engine into a prefill phase and
a decode phase (optionally speculative, with a draft model served
int8 from its own pool) joined by the schema-3 streamed KV handoff.
:mod:`elastic` replicates the engine into a membership-backed
:class:`ServeFleet` — live session migration on host loss, SLO-aware
shedding, epoch-aware routing.  Shape discipline (bucketed operands,
traced request state) is enforced by the SERVE-SHAPE lint rule; see
docs/serving.md.
"""
from .disagg import DisaggregatedEngine
from .elastic import FleetMember, ServeFleet, StaleEpochError
from .engine import ServeEngine
from .pool import BlockPool, NULL_BLOCK, blocks_for, init_pool_buffer
from .scheduler import Request, SLO_CLASSES, Scheduler, Session, bucket

__all__ = [
    "DisaggregatedEngine", "ServeEngine", "ServeFleet", "FleetMember",
    "StaleEpochError", "SLO_CLASSES", "Request", "Scheduler",
    "Session", "bucket", "BlockPool", "NULL_BLOCK", "blocks_for",
    "init_pool_buffer",
]
