"""ServeEngine: the continuous-batching serving loop.

One engine owns one model, one paged KV pool, one scheduler, and a
small fixed family of compiled programs — two *kinds* (``prefill_step``,
``decode_step``) dispatched through the one-runtime executor
(runtime/executor.py), so serving inherits the whole training-side
runtime for free: step-cache keying (``stats()['by_kind']`` pins
compiles per kind; the bench's ``decode_compiles <= buckets`` bound is
exactly the training side's 1-compile-per-window discipline), dispatch
spans, watchdog heartbeats, and the donation policy (the pool is the
donated carry — on tpu/gpu each tick rewrites KV in place).

The tick loop (:meth:`ServeEngine.step`):

1. **admit** — the scheduler moves queue-head requests into the live
   set while batch slots / blocks / prefill backlog allow;
2. **one prefill chunk** — the oldest prefilling session ingests up to
   ``prefill_chunk`` prompt tokens (ONE chunk per tick, so a long
   prompt interleaves with everyone else's decode instead of stalling
   it); completing prefill emits the first token from the chunk's last
   logits — no decode dispatch spent on it;
3. **one decode tick** — every decoding session advances one token in
   a single bucketed dispatch; sessions that hit ``max_new_tokens`` or
   their ``eos`` free their blocks this same tick.

Per-request lifecycle telemetry (``serve.request`` events with phases
queued→prefill→first_token→done, TTFT/e2e/tick-latency histograms,
queue-depth and pool-occupancy gauges) flows through the observe
registry; ``run()`` can wrap the loop in a stall watchdog — the
executor's per-dispatch heartbeats make a wedged backend fire a typed
``watchdog.stall`` diagnostic instead of hanging silently.

Greedy decoding only, by design: serving parity is pinned bitwise
against ``inference.DecodeSession``, and a sampled path would need
per-session PRNG threading through the bucketed programs — a later
PR's satellite, not this one's.
"""
from __future__ import annotations

import inspect
import itertools
import time
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..kernels.dispatch import decide as _decide
from ..kernels.spec_verify import spec_verify_fp
from ..models.gpt import _sharded_decode_axes
from ..observe import registry as _obs
from ..observe import watchdog as _watchdog
from ..runtime import executor as _executor
from . import kernels as _kernels
from .pool import BlockPool, blocks_for, init_pool_buffer
from .scheduler import DECODE, Request, Scheduler, Session, bucket

#: per-engine token in the serve program static keys — two engines over
#: identically-shaped models must never share a cache entry (their
#: program closures hold different parameter objects)
_SERVE_TOKENS = itertools.count()

#: engine roles in a disaggregated deployment (serve/disagg.py): the
#: phase joins every serve program's static key, so a prefill engine
#: and a decode engine over the same weights never collide in the step
#: cache even when their geometry matches
PHASES = ("unified", "prefill", "decode")


class ServeEngine:
    """Continuous-batching paged-KV serving over a GPT-protocol model.

    ``num_blocks`` sizes the shared pool (one block =
    ``block_size × layers × 2 × heads × head_dim`` KV rows; block 0 is
    the reserved null block).  ``cache_dtype`` follows the session
    convention — default the token-embedding dtype, ``"int8"`` for the
    quantized pool.  ``window`` enables sliding-window attention with
    block-table retirement (rolling.py's band, generalized).
    """

    def __init__(self, model, *, num_blocks, block_size=16, max_batch=8,
                 prefill_chunk=32, cache_dtype=None,
                 max_prefill_backlog=None, window=None, phase="unified",
                 draft=None, spec_k=4, draft_cache_dtype="int8",
                 spec_policy="on", prefix_cache=True):
        self._validate_model(model)
        if phase not in PHASES:
            raise ValueError(f"phase must be one of {PHASES}, got "
                             f"{phase!r}")
        self.model = model
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.window = window
        self._phase = phase
        blk0 = model.blocks[0]
        self._params = list(model.parameters()) + list(model.buffers())
        dtype = cache_dtype if cache_dtype is not None \
            else model.tok_emb.weight.data.dtype
        self._dtype_name = dtype if isinstance(dtype, str) \
            else jnp.dtype(dtype).name
        self.pool = init_pool_buffer(
            len(model.blocks), blk0.attn.num_heads, blk0.attn.head_dim,
            self.num_blocks, self.block_size, dtype)
        self.block_pool = BlockPool(self.num_blocks, self.block_size)
        # -- speculative mode: a draft model served from its OWN pool
        # buffer (int8 by default — weight-only drafts are bandwidth
        # bound) whose block ids come from the SAME BlockPool free-list
        self.spec = draft is not None
        self.draft = draft
        self.spec_k = int(spec_k)
        self._spec_policy = spec_policy
        self._d_params: List = []
        self._d_dtype_name = None
        self.dpool = None
        if self.spec:
            self._validate_spec(model, draft, window, self.spec_k,
                                spec_policy)
            dblk0 = draft.blocks[0]
            self._d_params = list(draft.parameters()) \
                + list(draft.buffers())
            d_dtype = draft_cache_dtype if draft_cache_dtype is not None \
                else draft.tok_emb.weight.data.dtype
            self._d_dtype_name = d_dtype if isinstance(d_dtype, str) \
                else jnp.dtype(d_dtype).name
            self.dpool = init_pool_buffer(
                len(draft.blocks), dblk0.attn.num_heads,
                dblk0.attn.head_dim, self.num_blocks, self.block_size,
                d_dtype)
        if max_prefill_backlog is None:
            max_prefill_backlog = 4 * prefill_chunk
        self.scheduler = Scheduler(
            self.block_pool, max_batch=max_batch,
            prefill_chunk=prefill_chunk,
            max_prefill_backlog=max_prefill_backlog,
            max_positions=model.max_positions,
            spec_tables=self.spec,
            pos_slack=self.spec_k if self.spec else 0,
            prefix_cache=prefix_cache,
            cache_tag=self._cache_tag(epoch=0))
        self._token = next(_SERVE_TOKENS)
        self._donate = _executor.donation.enabled
        self._decode_prog = None
        self._prefill_prog = None
        self._copy_prog = None
        self._draft_prefill_prog = None
        self._spec_prog = None
        self._dispatch_no = itertools.count(1)
        self._tick = 0
        # prefix-cache telemetry (admission-weighted; the pool keeps
        # its own eviction counter)
        self._prefill_tokens_saved = 0
        self._prefix_prompt_tokens = 0
        self._cow_forks = 0
        self._spec_ticks = 0
        self._spec_committed = 0
        self._spec_offered = 0
        self._spec_accepted = 0
        self.results: Dict[str, List[int]] = {}
        # weight hot-swap bookkeeping (apex_tpu.rollout): monotonically
        # growing epoch per weight set; every finished request is
        # attributed to the target epoch it was ADMITTED under (epochs
        # only grow, so that is the oldest weights any token saw)
        self.weight_epochs: Dict[str, int] = {"target": 0, "draft": 0}
        self.result_meta: Dict[str, dict] = {}

    @staticmethod
    def _validate_model(model):
        for a in ("blocks", "tok_emb", "pos_emb", "ln_f",
                  "_mask_pad_logits", "max_positions"):
            if not hasattr(model, a):
                raise ValueError(
                    f"ServeEngine needs model.{a} (the GPT decode "
                    f"protocol)")
        blk = model.blocks[0]
        for a in ("_chunk_qkv", "_attn_mlp_tail"):
            if not hasattr(blk, a):
                raise ValueError(
                    f"ServeEngine needs block.{a} — paged attention "
                    f"reuses the model's own decode projections")
        # Llama's _chunk_qkv(ctx, x, pos) applies RoPE inside the
        # projection — the paged bodies would silently skip it
        if len(inspect.signature(blk._chunk_qkv).parameters) != 2:
            raise NotImplementedError(
                "ServeEngine supports the GPT-family cache protocol "
                "(_chunk_qkv(ctx, x)); rotary-position families need "
                "position-aware paged projections — use the "
                "single-request decode paths for now")
        axes = _sharded_decode_axes(model)
        if axes:
            names = ", ".join(f"{a}='{v}'" for a, v in axes)
            raise NotImplementedError(
                f"ServeEngine runs single-shard; the model was built "
                f"with {names}")

    def _validate_spec(self, model, draft, window, spec_k, spec_policy):
        self._validate_model(draft)
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if window is not None:
            raise NotImplementedError(
                "speculative mode + sliding window: the verify chunk "
                "would need a per-row band mask over retired blocks — "
                "serve one mode or the other")
        if spec_policy not in ("on", "auto"):
            raise ValueError(
                f"spec_policy must be 'on' (always speculate) or "
                f"'auto' (decide() per bucket shape), got "
                f"{spec_policy!r}")
        if draft.tok_emb.weight.shape[0] < model.tok_emb.weight.shape[0]:
            raise ValueError(
                "draft vocabulary is smaller than the target's — "
                "verified tokens could not be re-fed to the draft")
        if draft.max_positions < model.max_positions:
            raise ValueError(
                f"draft.max_positions {draft.max_positions} < target's "
                f"{model.max_positions}: the draft cache must cover "
                f"every position the target can reach")

    # -- programs ----------------------------------------------------------
    # One Program instance per kind: operand shapes (bucketed batch /
    # blocks / chunk) complete the step-cache key through the argument
    # signature, so each bucket compiles once and session churn re-hits.

    def _programs(self):
        if self._decode_prog is None:
            key = (self._token, self._phase, self.block_size,
                   self._dtype_name, self.window, self._donate)
            self._decode_prog = _executor.Program(
                "decode_step", key,
                _kernels.build_decode_fn(
                    self.model, self._params, self.block_size,
                    self.num_blocks, self.window),
                donate_argnums=(1,) if self._donate else ())
            self._prefill_prog = _executor.Program(
                "prefill_step", key,
                _kernels.build_prefill_fn(
                    self.model, self._params, self.block_size,
                    self.num_blocks, self.window),
                donate_argnums=(1,) if self._donate else ())
        return self._prefill_prog, self._decode_prog

    def _spec_programs(self):
        if self._spec_prog is None:
            key = (self._token, self._phase, self.block_size,
                   self._dtype_name, self._d_dtype_name, self.spec_k,
                   self._donate)
            self._draft_prefill_prog = _executor.Program(
                "draft_prefill_step", key,
                _kernels.build_prefill_fn(
                    self.draft, self._d_params, self.block_size,
                    self.num_blocks, None),
                donate_argnums=(1,) if self._donate else ())
            self._spec_prog = _executor.Program(
                "spec_verify_step", key,
                _kernels.build_spec_verify_fn(
                    self.model, self._params, self.draft,
                    self._d_params, self.block_size, self.num_blocks,
                    self.spec_k),
                donate_argnums=(2, 3) if self._donate else ())
        return self._draft_prefill_prog, self._spec_prog

    def _copy_program(self):
        if self._copy_prog is None:
            key = (self._token, self._phase, self.block_size,
                   self._dtype_name, self._donate)
            self._copy_prog = _executor.Program(
                "block_copy", key, _kernels.build_block_copy_fn(),
                donate_argnums=(0,) if self._donate else ())
        return self._copy_prog

    def _vals(self):
        return [p.data for p in self._params]

    def _d_vals(self):
        return [p.data for p in self._d_params]

    # -- prefix cache ------------------------------------------------------

    def _cache_tag(self, epoch=None) -> str:
        """The chain-key compatibility stamp: everything a committed
        block's bytes depend on besides its token chain.  dtype and
        block size fix the stored layout, the window changes every KV
        row's upstream hidden states, and the target weight epoch makes
        ``publish_weights`` an automatic whole-cache invalidation — a
        new epoch means new tags, so stale entries can never match."""
        if epoch is None:
            epoch = self.weight_epochs["target"]
        return (f"{self._dtype_name}:b{self.block_size}:"
                f"w{self.window}:e{int(epoch)}")

    def _dispatch_cow(self, s: Session) -> None:
        """Materialize admission's copy-on-write forks: one paged
        block-copy dispatch per fork, then release the shared source's
        reference (scheduler.complete_cow) — the source was kept
        referenced so the dispatch stream copies its bytes before any
        eviction could recycle them."""
        if not s.cow_pending:
            return
        prog = self._copy_program()
        for _idx, fsrc, fdst in s.cow_pending:
            self.pool = _executor.executor.submit(
                prog, (self.pool, np.int32(fsrc), np.int32(fdst)),
                step=next(self._dispatch_no))
        n = self.scheduler.complete_cow(s)
        self._cow_forks += n
        _obs.counter("serve.prefix.cow_forks").inc(n)

    def _note_commit(self, s: Session) -> None:
        """Chain-commit the session's newly full blocks — unless its
        KV was written under an older target epoch (a mid-swap session
        decodes under mixed weights; hashing its blocks would poison
        the index with bytes no current-epoch chain can reproduce)."""
        if s.weight_epoch == self.weight_epochs["target"]:
            self.scheduler.note_commit(s)

    # -- intake ------------------------------------------------------------

    def submit(self, request: Request) -> None:
        self.scheduler.submit(request)
        sess = self.scheduler.queue[-1]
        sess.t_queued = time.monotonic()
        _obs.event("serve.request", rid=request.rid, phase="queued",
                   tick=self._tick, prompt_len=len(request.prompt),
                   max_new=request.max_new_tokens)

    def submit_recompute(self, request: Request, out) -> None:
        """Queue a request that already generated ``out`` tokens on
        another engine (a session shed or lost during a fleet shrink):
        admission re-prefills ``prompt + out[:-1]`` in recompute mode,
        so the continuation is bitwise the uninterrupted one."""
        self.scheduler.submit_recompute(request, out)
        sess = self.scheduler.queue[-1]
        sess.t_queued = time.monotonic()
        _obs.event("serve.request", rid=request.rid, phase="requeued",
                   tick=self._tick, generated=len(sess.out))

    def evict_session(self, s: Session) -> Session:
        """Shed a live session: free its blocks (both tables) and hand
        it back in recompute mode for the caller — the elastic fleet —
        to re-home on another engine.  Local preemption stays
        ``preempt_for`` (re-queues here); this is the cross-engine
        half."""
        self.scheduler.evict(s)
        _obs.event("serve.request", rid=s.rid, phase="shed",
                   tick=self._tick, generated=len(s.out))
        return s

    # -- weight hot-swap (apex_tpu.rollout) --------------------------------

    def publish_weights(self, leaves, *, which: str = "target",
                        epoch: Optional[int] = None) -> int:
        """Swap the ``which`` model's parameter values between ticks —
        the serve half of the rollout weight-publish path.

        No program is invalidated: the bucketed serve programs pass
        parameter VALUES as traced operands (``_vals()`` reads
        ``p.data`` at every dispatch) and their static keys are
        config-only, so rebinding ``.data`` on the SAME Parameter
        objects changes what the next dispatch computes without a
        recompile.  Shapes and dtypes must match the current values
        exactly — a different shape/dtype is a different engine, not a
        new epoch (and the KV pool dtype was derived from the old
        weights).  Buffers are not swapped.

        Live sessions keep their KV cache: rows written under the old
        weights stay as-is, so a mid-generation swap continues the
        sequence under mixed weights.  That is the documented semantics
        (docs/rollout.md) — each request is attributed to the epoch it
        was ADMITTED under, the oldest weights any of its tokens saw.

        ``epoch`` pins the recorded epoch (checkpoint restore republishes
        at the saved epoch); default bumps the counter by one.  Returns
        the epoch now being served.
        """
        if which not in ("target", "draft"):
            raise ValueError(f"which must be 'target' or 'draft', "
                             f"got {which!r}")
        if which == "draft":
            if not self.spec:
                raise RuntimeError(
                    "publish_weights(which='draft') on a non-speculative "
                    "engine — no draft to publish into")
            params = list(self.draft.parameters())
        else:
            params = list(self.model.parameters())
        leaves = list(leaves)
        if len(leaves) != len(params):
            raise ValueError(
                f"publish_weights({which!r}): {len(leaves)} leaves for "
                f"{len(params)} parameters — different model config")
        for p, v in zip(params, leaves):
            if tuple(getattr(v, "shape", ())) != tuple(p.data.shape):
                raise ValueError(
                    f"publish_weights({which!r}): leaf {p.name or '?'} "
                    f"shape {tuple(getattr(v, 'shape', ()))} != serving "
                    f"shape {tuple(p.data.shape)}")
            if jnp.dtype(getattr(v, "dtype", None)) != \
                    jnp.dtype(p.data.dtype):
                raise ValueError(
                    f"publish_weights({which!r}): leaf {p.name or '?'} "
                    f"dtype {jnp.dtype(v.dtype)} != serving dtype "
                    f"{jnp.dtype(p.data.dtype)} — cast on the publish "
                    f"side (rollout.WeightPublisher casts once)")
        for p, v in zip(params, leaves):
            p.data = v
        ep = self.weight_epochs[which] + 1 if epoch is None else int(epoch)
        self.weight_epochs[which] = ep
        if which == "target":
            # invalidate the prefix cache: the new epoch lands in the
            # chain tag (so future admissions can't match pre-swap
            # chains) and cached-tier blocks holding stale KV go back
            # to the free list rather than waiting out the LRU
            self.scheduler.cache_tag = self._cache_tag()
            self.block_pool.flush_cache()
        _obs.event("serve.weight_swap", which=which, epoch=ep,
                   tick=self._tick, leaves=len(leaves))
        return ep

    # -- the tick ----------------------------------------------------------

    def step(self) -> bool:
        """One engine tick: admit, one prefill (or draft catch-up)
        chunk, one decode/speculative tick.  Returns True while any
        request is live or queued.

        A ``phase="prefill"`` engine stops after the prefill stage —
        sessions that complete prefill wait in DECODE state for the
        disaggregation coordinator (:mod:`apex_tpu.serve.disagg`) to
        stream their KV blocks out.  A ``phase="decode"`` engine runs
        the full tick (its prefill stage serves recompute-mode
        re-admissions after local preemption)."""
        self._tick += 1
        t0 = time.monotonic()
        for s in self.scheduler.admit():
            s.weight_epoch = self.weight_epochs["target"]
            self._dispatch_cow(s)
            self._prefill_tokens_saved += s.prefix_hit_tokens
            self._prefix_prompt_tokens += len(s.prefill_src)
            if s.prefix_hit_tokens:
                _obs.counter("serve.prefix.tokens_saved").inc(
                    s.prefix_hit_tokens)
            if self._prefix_prompt_tokens:
                _obs.gauge("serve.prefix.hit_rate").set(
                    self._prefill_tokens_saved
                    / self._prefix_prompt_tokens)
            _obs.event("serve.request", rid=s.rid, phase="prefill",
                       tick=self._tick, blocks=len(s.table),
                       prefix_hit=s.prefix_hit_tokens,
                       weight_epoch=s.weight_epoch)
        ps = self.scheduler.next_prefill()
        if ps is not None:
            self._prefill_chunk(ps)
        elif self.spec:
            cs = self._next_draft_catchup()
            if cs is not None:
                self._draft_catchup_chunk(cs)
        if self._phase == "prefill":
            _obs.gauge("serve.queue_depth").set(
                len(self.scheduler.queue))
            _obs.gauge("serve.active_sessions").set(
                len(self.scheduler.sessions))
            return self.scheduler.has_work()
        self._ensure_decode_blocks()
        ds = self._decode_ready()
        if ds:
            if self.spec and self._spec_pays(ds):
                self._spec_tick(ds)
            else:
                self._decode_tick(ds)
            _obs.histogram("serve.decode_tick_ms").observe(
                (time.monotonic() - t0) * 1e3)
        _obs.gauge("serve.queue_depth").set(len(self.scheduler.queue))
        _obs.gauge("serve.active_sessions").set(
            len(self.scheduler.sessions))
        return self.scheduler.has_work()

    def run(self, requests: Sequence[Request], arrivals=None,
            watchdog_deadline_s=None, max_ticks=None):
        """Serve ``requests`` to completion; returns ``{rid: tokens}``.

        ``arrivals``: optional per-request tick indices (an open-loop
        trace — request i becomes visible at tick ``arrivals[i]``);
        None submits everything up front.  ``watchdog_deadline_s`` arms
        a stall watchdog over the loop: every dispatch heartbeats, so
        a wedged backend fires ``watchdog.stall`` instead of hanging."""
        pending = sorted(
            zip(arrivals if arrivals is not None else [0] * len(requests),
                range(len(requests))),
            key=lambda p: (p[0], p[1]))
        wd = _watchdog.StallWatchdog(watchdog_deadline_s) \
            if watchdog_deadline_s else None
        if wd is not None:
            wd.start()
        try:
            i = 0
            while True:
                while i < len(pending) and pending[i][0] <= self._tick:
                    self.submit(requests[pending[i][1]])
                    i += 1
                more = self.step()
                if not more and i >= len(pending):
                    break
                if max_ticks is not None and self._tick >= max_ticks:
                    break
        finally:
            if wd is not None:
                wd.stop()
        return dict(self.results)

    # -- internals ---------------------------------------------------------

    def _prefill_chunk(self, s: Session) -> None:
        prefill_prog, _ = self._programs()
        chunk = self.scheduler.prefill_chunk
        n = min(chunk, s.prefill_remaining)
        t0 = s.position
        toks = list(s.prefill_src[t0:t0 + n])
        toks += [0] * (chunk - n)
        nb = bucket(len(s.table))
        table = s.table + [0] * (nb - len(s.table))
        last, self.pool = _executor.executor.submit(
            prefill_prog,
            (self._vals(), self.pool,
             np.asarray([toks], np.int32), np.asarray([table], np.int32),
             np.int32(t0), np.int32(n)),
            step=next(self._dispatch_no))
        if self.spec and s.draft_position == t0:
            # lockstep draft ingest: the draft's cache tracks the
            # target's row for row through prefill (and recompute
            # re-prefill), so a fresh session is spec-ready the tick
            # its prefill completes.  A prefix-hit session starts its
            # target cursor PAST rows the draft never saw — it skips
            # lockstep and repairs through the catch-up path instead.
            draft_prog, _ = self._spec_programs()
            nbd = bucket(len(s.draft_table))
            d_table = s.draft_table + [0] * (nbd - len(s.draft_table))
            _dl, self.dpool = _executor.executor.submit(
                draft_prog,
                (self._d_vals(), self.dpool,
                 np.asarray([toks], np.int32),
                 np.asarray([d_table], np.int32),
                 np.int32(t0), np.int32(n)),
                step=next(self._dispatch_no))
            s.draft_position = t0 + n
        s.position = t0 + n
        if self.window is not None:
            self.scheduler.retire_window_blocks(s, self.window)
        self._note_commit(s)
        if s.prefill_remaining > 0:
            return
        s.state = DECODE
        if s.emit_on_prefill:
            tok = int(jnp.argmax(last[0]))
            s.out.append(tok)
            s.pending_tok = tok
            s.t_first = time.monotonic()
            _obs.histogram("serve.ttft_ms").observe(
                (s.t_first - s.t_queued) * 1e3)
            _obs.event("serve.request", rid=s.rid, phase="first_token",
                       tick=self._tick)
            if s.finished():
                self._finish(s)

    def _next_draft_catchup(self) -> Optional[Session]:
        """Oldest decoding session whose draft cache lags its target
        cache — only handed-off sessions (or plain-decode fallback
        ticks) create the lag; one catch-up chunk per tick repairs it
        in the prefill slot."""
        for s in self.scheduler.sessions:
            if s.state == DECODE and s.draft_position < s.position:
                return s
        return None

    def _draft_catchup_chunk(self, s: Session) -> None:
        draft_prog, _ = self._spec_programs()
        chunk = self.scheduler.prefill_chunk
        fed = s.fed_tokens
        d0 = s.draft_position
        n = min(chunk, s.position - d0)
        toks = list(fed[d0:d0 + n]) + [0] * (chunk - n)
        nbd = bucket(len(s.draft_table))
        d_table = s.draft_table + [0] * (nbd - len(s.draft_table))
        _dl, self.dpool = _executor.executor.submit(
            draft_prog,
            (self._d_vals(), self.dpool,
             np.asarray([toks], np.int32), np.asarray([d_table], np.int32),
             np.int32(d0), np.int32(n)),
            step=next(self._dispatch_no))
        s.draft_position = d0 + n

    def _decode_ready(self) -> List[Session]:
        """Sessions eligible for this tick's decode dispatch: every
        DECODE session, minus (spec mode) those whose draft cache is
        still catching up — including them would verify against stale
        draft rows."""
        ds = self.scheduler.decode_sessions()
        if not self.spec:
            return ds
        return [s for s in ds if s.draft_position == s.position]

    def _spec_pays(self, sessions: List[Session]) -> bool:
        """``spec_policy="on"`` always speculates; ``"auto"`` asks the
        kernel-dispatch ledger (decide(), cached per bucket shape)
        whether the measured verify win covers this shape — below the
        win region the engine falls back to plain decode ticks and the
        catch-up path keeps the draft cache consistent."""
        if self._spec_policy == "on":
            return True
        b = bucket(len(sessions), self.scheduler.max_batch)
        nbt = bucket(max(len(s.table) for s in sessions))
        nbd = bucket(max(len(s.draft_table) for s in sessions))
        fp = spec_verify_fp(b=b, k=self.spec_k,
                            s_t=nbt * self.block_size,
                            s_d=nbd * self.block_size,
                            dtype=self._dtype_name)
        return _decide("spec_verify", fp).tier == "pallas"

    def _ensure_decode_blocks(self) -> None:
        """Every decoding session needs its table to cover the rows
        this tick writes — one row for plain decode, ``spec_k + 1``
        rows across BOTH tables for a speculative tick; a dry pool
        preempts the newest session (recompute mode) until the
        survivors fit."""
        slack = self.spec_k if self.spec else 0
        for s in list(self.scheduler.decode_sessions()):
            if s.state != DECODE:
                continue                     # preempted below us
            if self.spec and s.draft_position < s.position:
                continue                     # catch-up session: no tick
            need = s.position + 1 + slack
            while not (self.scheduler.grow(s, need)
                       and (not self.spec
                            or self.scheduler.grow(s, need,
                                                   draft=True))):
                victim = self.scheduler.preempt_for(s)
                _obs.counter("serve.preemptions").inc()
                _obs.event("serve.request", rid=victim.rid,
                           phase="preempted", tick=self._tick,
                           generated=len(victim.out))
                if victim is s:
                    break

    def _decode_tick(self, sessions: List[Session]) -> None:
        _, decode_prog = self._programs()
        b, nb, tokens, positions, tables = \
            self.scheduler.pack_decode(sessions)
        nxt, _logits, self.pool = _executor.executor.submit(
            decode_prog,
            (self._vals(), self.pool,
             np.asarray(tokens, np.int32), np.asarray(positions, np.int32),
             np.asarray(tables, np.int32)),
            step=next(self._dispatch_no))
        nxt = np.asarray(nxt)
        for i, s in enumerate(sessions):
            s.position += 1
            tok = int(nxt[i])
            s.out.append(tok)
            s.pending_tok = tok
            if self.window is not None:
                self.scheduler.retire_window_blocks(s, self.window)
            self._note_commit(s)
            if s.finished():
                self._finish(s)

    def _spec_tick(self, sessions: List[Session]) -> None:
        """One batched speculative tick: a single ``spec_verify_step``
        dispatch drafts ``spec_k`` proposals and verifies them with one
        (k+1)-wide target pass; the host commits the ragged accepted
        prefix per row.  Commitment rule: row i's emitted tokens are
        the TARGET's argmax at positions p..p+k conditioned on its own
        committed prefix, and ``n_acc`` only ever truncates that stream
        where the draft diverged — so the committed token sequence is
        bitwise the plain-decode sequence, whatever the acceptance
        pattern, eos/max_new truncation, or preemption does to tick
        boundaries."""
        _, spec_prog = self._spec_programs()
        b, nbt, nbd, tokens, positions, t_tables, d_tables = \
            self.scheduler.pack_spec(sessions)
        emitted, n_acc, self.pool, self.dpool = _executor.executor.submit(
            spec_prog,
            (self._vals(), self._d_vals(), self.pool, self.dpool,
             np.asarray(tokens, np.int32), np.asarray(positions, np.int32),
             np.asarray(t_tables, np.int32),
             np.asarray(d_tables, np.int32)),
            step=next(self._dispatch_no))
        emitted = np.asarray(emitted)
        n_acc = np.asarray(n_acc)
        committed_total = 0
        for i, s in enumerate(sessions):
            m = 0
            for j in range(int(n_acc[i])):
                tok = int(emitted[i, j])
                s.out.append(tok)
                s.pending_tok = tok
                s.position += 1
                m += 1
                if s.finished():
                    break
            # rows p..p+m-1 of the draft cache hold exactly the
            # committed tokens (the rejected tail past them is rewritten
            # by the next tick's chunk before any mask can read it)
            s.draft_position = s.position
            # chain-commit only blocks the committed position has fully
            # crossed — every row of such a block holds committed-token
            # KV (any rejected-tail rows were overwritten by later
            # ticks before position could pass them)
            self._note_commit(s)
            committed_total += m
            self._spec_offered += self.spec_k
            self._spec_accepted += max(0, m - 1)
            if s.finished():
                self._finish(s)
        self._spec_ticks += 1
        self._spec_committed += committed_total
        _obs.histogram("serve.spec.accepted_tokens").observe(
            committed_total)
        if self._spec_offered:
            _obs.gauge("serve.spec.accept_rate").set(
                self._spec_accepted / self._spec_offered)

    # -- disaggregation handoff --------------------------------------------

    def harvest_ready(self) -> List[Session]:
        """Prefill-phase engines: sessions whose prefill completed
        (DECODE state, first token emitted) and now wait for the
        coordinator to stream their KV blocks to a decode engine."""
        return [s for s in self.scheduler.decode_sessions()
                if not s.finished()]

    def release_handoff(self, s: Session) -> None:
        """Drop a session whose KV blocks were streamed out: frees its
        blocks and batch slot without recording a result — the decode
        engine owns the request from here."""
        self.scheduler.finish(s)

    def ingest_handoff(self, request: Request, *, out, pending_tok,
                       position, handoff_dir, t_queued=0.0,
                       t_first=None, n_blocks=None, hash_chain=None,
                       weight_epoch=None) -> Optional[Session]:
        """Decode-phase engines: adopt a prefilled session whose KV
        blocks were streamed into ``handoff_dir`` (schema-3 shard
        files, runtime/resilience.py).  Allocates a fresh target table
        and scatters the streamed blocks into this engine's pool
        verbatim — bitwise, no recompute; in spec mode a draft table of
        the same size is allocated but the draft cache starts EMPTY and
        catches up through the prefill slot.  ``n_blocks`` is the
        streamed block count when the source table had grown past the
        admission grant (the elastic fleet passes the snapshot
        manifest's count — a mid-decode session owns
        ``blocks_for(position)`` blocks); None means the
        disaggregation default below.  Returns the new session, or
        None when a batch slot / blocks are not available right now
        (the coordinator retries next tick)."""
        from ..runtime.resilience import load_kv_handoff
        need_pos = len(request.prompt) + request.max_new_tokens \
            + self.scheduler.pos_slack
        if need_pos > self.scheduler.max_positions:
            raise ValueError(
                f"request {request.rid}: {need_pos} positions exceed "
                f"decode engine max_positions "
                f"{self.scheduler.max_positions}")
        if len(self.scheduler.sessions) >= self.scheduler.max_batch:
            return None
        if n_blocks is None:
            # the prefill engine's table is exactly its admission grant
            # — blocks_for(prompt + 1) — because prefill-phase engines
            # never decode, so the streamed block count is deterministic
            n_blocks = blocks_for(len(request.prompt) + 1,
                                  self.block_size)
        have = int(n_blocks)
        ids = self.block_pool.alloc(have)
        if ids is None:
            return None
        draft_ids: List[int] = []
        if self.spec:
            draft_ids = self.block_pool.alloc(have)
            if draft_ids is None:
                self.block_pool.free(ids)
                return None
        try:
            self.pool, _peak = load_kv_handoff(
                handoff_dir, self.pool, ids)
        except Exception:
            self.block_pool.free(ids)
            if draft_ids:
                self.block_pool.free(draft_ids)
            raise
        s = Session(request, self.scheduler._seq)
        self.scheduler._seq += 1
        s.table = ids
        s.draft_table = draft_ids
        s.position = int(position)
        s.draft_position = 0
        s.state = DECODE
        s.prefill_src = ()
        s.emit_on_prefill = False
        s.pending_tok = int(pending_tok)
        s.out = list(out)
        s.t_queued = t_queued
        s.t_first = t_first
        s.weight_epoch = self.weight_epochs["target"]
        if hash_chain and self.scheduler.prefix_cache \
                and weight_epoch == self.weight_epochs["target"]:
            # re-link the migrated chain into THIS pool's index: the
            # streamed blocks are bitwise copies of committed-prefix
            # blocks, so they are valid cache entries here too
            s.hash_chain = list(hash_chain)
            s.committed_blocks = len(s.hash_chain)
            for bid, key in zip(ids, s.hash_chain):
                self.block_pool.commit(bid, key)
        elif hash_chain:
            # the chain was built under a different weight epoch than
            # this engine serves — the KV itself stays valid for THIS
            # session (mixed-epoch semantics, docs/rollout.md) but must
            # never be published for cross-request reuse
            s.cacheable = False
        self.scheduler.sessions.append(s)
        _obs.event("serve.request", rid=s.rid, phase="ingested",
                   tick=self._tick, blocks=have,
                   generated=len(s.out))
        return s

    def _finish(self, s: Session) -> None:
        self.results[s.rid] = list(s.out)
        self.result_meta[s.rid] = {"weight_epoch": s.weight_epoch,
                                   "prompt_len": len(s.request.prompt)}
        s.t_done = time.monotonic()
        _obs.histogram("serve.e2e_ms").observe(
            (s.t_done - s.t_queued) * 1e3)
        _obs.event("serve.request", rid=s.rid, phase="done",
                   tick=self._tick, generated=len(s.out),
                   weight_epoch=s.weight_epoch)
        self.scheduler.finish(s)

    # -- teardown ----------------------------------------------------------

    def close(self) -> None:
        """Tear the engine down: return every live session's blocks —
        target AND draft tables — to the :class:`BlockPool`, drop the
        queue (queued sessions hold no blocks), and assert the pool is
        leak-free.  An engine dropped mid-run without this strands its
        resident sessions' blocks; the elastic fleet also calls it when
        a replica's simulated process dies (the pool's memory dies with
        the process).  Idempotent; no result is recorded for the
        sessions it drops."""
        for s in list(self.scheduler.sessions):
            self.scheduler.finish(s)
        self.scheduler.queue.clear()
        self.block_pool.check_no_leaks()

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False

    # -- introspection -----------------------------------------------------

    @property
    def tick(self) -> int:
        """Ticks executed so far — the loop's logical clock (open-loop
        arrival traces index into it)."""
        return self._tick

    def metrics(self) -> dict:
        """SLO snapshot: compile/dispatch counters per serve kind plus
        the engine's own gauges/histograms."""
        from ..runtime import step_cache as _sc
        snap = _obs.get_registry().snapshot()
        out = {
            "decode": _sc.kind_stats("decode_step"),
            "prefill": _sc.kind_stats("prefill_step"),
            "pool_occupancy": self.block_pool.occupancy,
            "queue_depth": len(self.scheduler.queue),
            "prefix_cache": {
                "hit_rate": (self._prefill_tokens_saved
                             / self._prefix_prompt_tokens
                             if self._prefix_prompt_tokens else 0.0),
                "prefill_tokens_saved": self._prefill_tokens_saved,
                "cached_blocks": self.block_pool.cached_count,
                "cow_forks": self._cow_forks,
                "cache_evictions": self.block_pool.cache_evictions,
            },
            "histograms": {k: v for k, v in snap["histograms"].items()
                           if k.startswith("serve.")},
        }
        if self.spec:
            out["spec_verify"] = _sc.kind_stats("spec_verify_step")
            out["draft_prefill"] = _sc.kind_stats("draft_prefill_step")
            out["spec"] = {
                "ticks": self._spec_ticks,
                "committed_tokens": self._spec_committed,
                "offered": self._spec_offered,
                "accepted": self._spec_accepted,
                "accept_rate": (self._spec_accepted / self._spec_offered
                                if self._spec_offered else 0.0),
                "tokens_per_tick": (self._spec_committed
                                    / self._spec_ticks
                                    if self._spec_ticks else 0.0),
            }
        return out
