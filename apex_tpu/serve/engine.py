"""ServeEngine: the continuous-batching serving loop.

One engine owns one model, one paged KV pool, one scheduler, and a
small fixed family of compiled programs — two *kinds* (``prefill_step``,
``decode_step``) dispatched through the one-runtime executor
(runtime/executor.py), so serving inherits the whole training-side
runtime for free: step-cache keying (``stats()['by_kind']`` pins
compiles per kind; the bench's ``decode_compiles <= buckets`` bound is
exactly the training side's 1-compile-per-window discipline), dispatch
spans, watchdog heartbeats, and the donation policy (the pool is the
donated carry — on tpu/gpu each tick rewrites KV in place).

The tick loop (:meth:`ServeEngine.step`):

1. **admit** — the scheduler moves queue-head requests into the live
   set while batch slots / blocks / prefill backlog allow;
2. **one prefill chunk** — the oldest prefilling session ingests up to
   ``prefill_chunk`` prompt tokens (ONE chunk per tick, so a long
   prompt interleaves with everyone else's decode instead of stalling
   it); completing prefill emits the first token from the chunk's last
   logits — no decode dispatch spent on it;
3. **one decode tick** — every decoding session advances one token in
   a single bucketed dispatch; sessions that hit ``max_new_tokens`` or
   their ``eos`` free their blocks this same tick.

Per-request lifecycle telemetry (``serve.request`` events with phases
queued→prefill→first_token→done, TTFT/e2e/tick-latency histograms,
queue-depth and pool-occupancy gauges) flows through the observe
registry; ``run()`` can wrap the loop in a stall watchdog — the
executor's per-dispatch heartbeats make a wedged backend fire a typed
``watchdog.stall`` diagnostic instead of hanging silently.

Greedy decoding only, by design: serving parity is pinned bitwise
against ``inference.DecodeSession``, and a sampled path would need
per-session PRNG threading through the bucketed programs — a later
PR's satellite, not this one's.
"""
from __future__ import annotations

import inspect
import itertools
import time
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..models.gpt import _sharded_decode_axes
from ..observe import registry as _obs
from ..observe import watchdog as _watchdog
from ..runtime import executor as _executor
from . import kernels as _kernels
from .pool import BlockPool, init_pool_buffer
from .scheduler import DECODE, Request, Scheduler, Session, bucket

#: per-engine token in the serve program static keys — two engines over
#: identically-shaped models must never share a cache entry (their
#: program closures hold different parameter objects)
_SERVE_TOKENS = itertools.count()


class ServeEngine:
    """Continuous-batching paged-KV serving over a GPT-protocol model.

    ``num_blocks`` sizes the shared pool (one block =
    ``block_size × layers × 2 × heads × head_dim`` KV rows; block 0 is
    the reserved null block).  ``cache_dtype`` follows the session
    convention — default the token-embedding dtype, ``"int8"`` for the
    quantized pool.  ``window`` enables sliding-window attention with
    block-table retirement (rolling.py's band, generalized).
    """

    def __init__(self, model, *, num_blocks, block_size=16, max_batch=8,
                 prefill_chunk=32, cache_dtype=None,
                 max_prefill_backlog=None, window=None):
        self._validate_model(model)
        self.model = model
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.window = window
        blk0 = model.blocks[0]
        self._params = list(model.parameters()) + list(model.buffers())
        dtype = cache_dtype if cache_dtype is not None \
            else model.tok_emb.weight.data.dtype
        self._dtype_name = dtype if isinstance(dtype, str) \
            else jnp.dtype(dtype).name
        self.pool = init_pool_buffer(
            len(model.blocks), blk0.attn.num_heads, blk0.attn.head_dim,
            self.num_blocks, self.block_size, dtype)
        self.block_pool = BlockPool(self.num_blocks, self.block_size)
        if max_prefill_backlog is None:
            max_prefill_backlog = 4 * prefill_chunk
        self.scheduler = Scheduler(
            self.block_pool, max_batch=max_batch,
            prefill_chunk=prefill_chunk,
            max_prefill_backlog=max_prefill_backlog,
            max_positions=model.max_positions)
        self._token = next(_SERVE_TOKENS)
        self._donate = _executor.donation.enabled
        self._decode_prog = None
        self._prefill_prog = None
        self._dispatch_no = itertools.count(1)
        self._tick = 0
        self.results: Dict[str, List[int]] = {}

    @staticmethod
    def _validate_model(model):
        for a in ("blocks", "tok_emb", "pos_emb", "ln_f",
                  "_mask_pad_logits", "max_positions"):
            if not hasattr(model, a):
                raise ValueError(
                    f"ServeEngine needs model.{a} (the GPT decode "
                    f"protocol)")
        blk = model.blocks[0]
        for a in ("_chunk_qkv", "_attn_mlp_tail"):
            if not hasattr(blk, a):
                raise ValueError(
                    f"ServeEngine needs block.{a} — paged attention "
                    f"reuses the model's own decode projections")
        # Llama's _chunk_qkv(ctx, x, pos) applies RoPE inside the
        # projection — the paged bodies would silently skip it
        if len(inspect.signature(blk._chunk_qkv).parameters) != 2:
            raise NotImplementedError(
                "ServeEngine supports the GPT-family cache protocol "
                "(_chunk_qkv(ctx, x)); rotary-position families need "
                "position-aware paged projections — use the "
                "single-request decode paths for now")
        axes = _sharded_decode_axes(model)
        if axes:
            names = ", ".join(f"{a}='{v}'" for a, v in axes)
            raise NotImplementedError(
                f"ServeEngine runs single-shard; the model was built "
                f"with {names}")

    # -- programs ----------------------------------------------------------
    # One Program instance per kind: operand shapes (bucketed batch /
    # blocks / chunk) complete the step-cache key through the argument
    # signature, so each bucket compiles once and session churn re-hits.

    def _programs(self):
        if self._decode_prog is None:
            key = (self._token, self.block_size, self._dtype_name,
                   self.window, self._donate)
            self._decode_prog = _executor.Program(
                "decode_step", key,
                _kernels.build_decode_fn(
                    self.model, self._params, self.block_size,
                    self.num_blocks, self.window),
                donate_argnums=(1,) if self._donate else ())
            self._prefill_prog = _executor.Program(
                "prefill_step", key,
                _kernels.build_prefill_fn(
                    self.model, self._params, self.block_size,
                    self.num_blocks, self.window),
                donate_argnums=(1,) if self._donate else ())
        return self._prefill_prog, self._decode_prog

    def _vals(self):
        return [p.data for p in self._params]

    # -- intake ------------------------------------------------------------

    def submit(self, request: Request) -> None:
        self.scheduler.submit(request)
        sess = self.scheduler.queue[-1]
        sess.t_queued = time.monotonic()
        _obs.event("serve.request", rid=request.rid, phase="queued",
                   tick=self._tick, prompt_len=len(request.prompt),
                   max_new=request.max_new_tokens)

    # -- the tick ----------------------------------------------------------

    def step(self) -> bool:
        """One engine tick: admit, one prefill chunk, one decode tick.
        Returns True while any request is live or queued."""
        self._tick += 1
        t0 = time.monotonic()
        for s in self.scheduler.admit():
            _obs.event("serve.request", rid=s.rid, phase="prefill",
                       tick=self._tick, blocks=len(s.table))
        ps = self.scheduler.next_prefill()
        if ps is not None:
            self._prefill_chunk(ps)
        self._ensure_decode_blocks()
        ds = self.scheduler.decode_sessions()
        if ds:
            self._decode_tick(ds)
            _obs.histogram("serve.decode_tick_ms").observe(
                (time.monotonic() - t0) * 1e3)
        _obs.gauge("serve.queue_depth").set(len(self.scheduler.queue))
        _obs.gauge("serve.active_sessions").set(
            len(self.scheduler.sessions))
        return self.scheduler.has_work()

    def run(self, requests: Sequence[Request], arrivals=None,
            watchdog_deadline_s=None, max_ticks=None):
        """Serve ``requests`` to completion; returns ``{rid: tokens}``.

        ``arrivals``: optional per-request tick indices (an open-loop
        trace — request i becomes visible at tick ``arrivals[i]``);
        None submits everything up front.  ``watchdog_deadline_s`` arms
        a stall watchdog over the loop: every dispatch heartbeats, so
        a wedged backend fires ``watchdog.stall`` instead of hanging."""
        pending = sorted(
            zip(arrivals if arrivals is not None else [0] * len(requests),
                range(len(requests))),
            key=lambda p: (p[0], p[1]))
        wd = _watchdog.StallWatchdog(watchdog_deadline_s) \
            if watchdog_deadline_s else None
        if wd is not None:
            wd.start()
        try:
            i = 0
            while True:
                while i < len(pending) and pending[i][0] <= self._tick:
                    self.submit(requests[pending[i][1]])
                    i += 1
                more = self.step()
                if not more and i >= len(pending):
                    break
                if max_ticks is not None and self._tick >= max_ticks:
                    break
        finally:
            if wd is not None:
                wd.stop()
        return dict(self.results)

    # -- internals ---------------------------------------------------------

    def _prefill_chunk(self, s: Session) -> None:
        prefill_prog, _ = self._programs()
        chunk = self.scheduler.prefill_chunk
        n = min(chunk, s.prefill_remaining)
        toks = list(s.prefill_src[s.position:s.position + n])
        toks += [0] * (chunk - n)
        nb = bucket(len(s.table))
        table = s.table + [0] * (nb - len(s.table))
        last, self.pool = _executor.executor.submit(
            prefill_prog,
            (self._vals(), self.pool,
             np.asarray([toks], np.int32), np.asarray([table], np.int32),
             np.int32(s.position), np.int32(n)),
            step=next(self._dispatch_no))
        s.position += n
        if self.window is not None:
            self.scheduler.retire_window_blocks(s, self.window)
        if s.prefill_remaining > 0:
            return
        s.state = DECODE
        if s.emit_on_prefill:
            tok = int(jnp.argmax(last[0]))
            s.out.append(tok)
            s.pending_tok = tok
            s.t_first = time.monotonic()
            _obs.histogram("serve.ttft_ms").observe(
                (s.t_first - s.t_queued) * 1e3)
            _obs.event("serve.request", rid=s.rid, phase="first_token",
                       tick=self._tick)
            if s.finished():
                self._finish(s)

    def _ensure_decode_blocks(self) -> None:
        """Every decoding session needs its table to cover the row this
        tick writes; a dry pool preempts the newest session (recompute
        mode) until the survivors fit."""
        for s in list(self.scheduler.decode_sessions()):
            if s.state != DECODE:
                continue                     # preempted below us
            while not self.scheduler.grow(s, s.position + 1):
                victim = self.scheduler.preempt_for(s)
                _obs.counter("serve.preemptions").inc()
                _obs.event("serve.request", rid=victim.rid,
                           phase="preempted", tick=self._tick,
                           generated=len(victim.out))
                if victim is s:
                    break

    def _decode_tick(self, sessions: List[Session]) -> None:
        _, decode_prog = self._programs()
        b, nb, tokens, positions, tables = \
            self.scheduler.pack_decode(sessions)
        nxt, _logits, self.pool = _executor.executor.submit(
            decode_prog,
            (self._vals(), self.pool,
             np.asarray(tokens, np.int32), np.asarray(positions, np.int32),
             np.asarray(tables, np.int32)),
            step=next(self._dispatch_no))
        nxt = np.asarray(nxt)
        for i, s in enumerate(sessions):
            s.position += 1
            tok = int(nxt[i])
            s.out.append(tok)
            s.pending_tok = tok
            if self.window is not None:
                self.scheduler.retire_window_blocks(s, self.window)
            if s.finished():
                self._finish(s)

    def _finish(self, s: Session) -> None:
        self.results[s.rid] = list(s.out)
        s.t_done = time.monotonic()
        _obs.histogram("serve.e2e_ms").observe(
            (s.t_done - s.t_queued) * 1e3)
        _obs.event("serve.request", rid=s.rid, phase="done",
                   tick=self._tick, generated=len(s.out))
        self.scheduler.finish(s)

    # -- introspection -----------------------------------------------------

    @property
    def tick(self) -> int:
        """Ticks executed so far — the loop's logical clock (open-loop
        arrival traces index into it)."""
        return self._tick

    def metrics(self) -> dict:
        """SLO snapshot: compile/dispatch counters per serve kind plus
        the engine's own gauges/histograms."""
        from ..runtime import step_cache as _sc
        snap = _obs.get_registry().snapshot()
        return {
            "decode": _sc.kind_stats("decode_step"),
            "prefill": _sc.kind_stats("prefill_step"),
            "pool_occupancy": self.block_pool.occupancy,
            "queue_depth": len(self.scheduler.queue),
            "histograms": {k: v for k, v in snap["histograms"].items()
                           if k.startswith("serve.")},
        }
