"""LoRA (low-rank adaptation) as a weight reparameterization:
``w = w0 + (alpha / r) * B @ A`` with ``w0`` frozen and only the rank-r
factors trained.

Built on the same derived-parameter machinery as WeightNorm
(reparameterization.py): the module attribute stays a Parameter whose
value ``Ctx.value`` computes at trace time, so EVERY consumer — the
fused train step, the imperative tape, decode paths — sees the adapted
weight with no forward-code changes, and XLA fuses the rank-r update
into the consuming matmul.  ``Reparameterization.remove`` doubles as
the standard LoRA MERGE: it bakes ``w0 + scale * B A`` back into a
plain parameter for inference.

Init follows the LoRA paper: ``A ~ N(0, 0.02)``, ``B = 0`` — the
adapted model starts exactly at the base model.  Train by giving the
optimizer ONLY :func:`lora_parameters`; everything else is frozen by
the framework's torch-semantics rule (parameters in no optimizer group
receive no update).  Honest cost note: the fused step still computes
gradients for frozen parameters inside the one compiled program (they
feed only the overflow check) and allocates their optimizer slots —
LoRA's win here is update/comm volume and the merge/swap workflow, not
backward FLOPs.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .reparameterization import Reparameterization
from ..nn.parameter import Parameter


class LoRA(Reparameterization):
    """``dim`` carries the rank r (the generic plumbing's one free
    slot); ``alpha`` is a class attribute so :func:`apply_lora` can
    specialize it — default ``2 r``, the common alpha/r = 2 recipe."""

    alpha = None

    def __init__(self, name, dim, module, retain_forward=True):
        if dim is None or dim < 1:
            raise ValueError(f"LoRA rank must be a positive int, "
                             f"got {dim!r}")
        super().__init__(name, dim, module, retain_forward)
        self.r = dim
        self.scale = (self.alpha if self.alpha is not None
                      else 2.0 * dim) / dim

    def compute_weight(self, ctx, module=None, name=None):
        if module is None:
            module = self.module
        if name is None:
            name = self.name
        module, name = Reparameterization.get_module_and_name(module, name)
        w0 = ctx.value(getattr(module, name + "_w0"))
        b = ctx.value(getattr(module, name + "_lora_b"))
        a = ctx.value(getattr(module, name + "_lora_a"))
        delta = self.scale * jnp.matmul(b.astype(jnp.float32),
                                        a.astype(jnp.float32))
        return (w0.astype(jnp.float32)
                + delta.reshape(w0.shape)).astype(w0.dtype)

    def reparameterize(self, name, weight, dim):
        out_f = weight.data.shape[0]
        in_f = int(np.prod(weight.data.shape[1:]))
        if dim > min(out_f, in_f):
            raise ValueError(
                f"LoRA rank {dim} exceeds min(out, in) = "
                f"{min(out_f, in_f)} of '{name}' {tuple(weight.data.shape)}")
        w0 = Parameter(weight.data, requires_grad=False)
        from ..nn.modules import _next_key
        a = Parameter(0.02 * jax.random.normal(
            _next_key(), (dim, in_f), jnp.float32))
        b = Parameter(jnp.zeros((out_f, dim), jnp.float32))
        return ([name + "_w0", name + "_lora_b", name + "_lora_a"],
                [w0, b, a])


def apply_lora(module, name="", r=8, alpha=None, hook_child=True):
    """Adapt ``name`` (or, with no name, every >1-d parameter) with a
    rank-``r`` LoRA.  Returns the module.  ``alpha`` scales the update
    by ``alpha / r`` (default ``2 r``).  Typical fine-tune::

        apply_lora(model, "blocks.0.q_proj.weight", r=8)   # per weight
        apply_lora(model, r=8)                             # everything
        opt = FusedAdam(lora_parameters(model), lr=1e-4)
        step = make_train_step(model, opt, loss_fn)        # w0 frozen

    Merge for inference with
    ``remove_reparameterization(model, LoRA, remove_all=True)`` (or a
    single name) — the adapted value bakes into a plain parameter.
    """
    from . import apply_reparameterization

    cls = LoRA if alpha is None else type(
        "LoRA", (LoRA,), {"alpha": float(alpha)})
    return apply_reparameterization(
        module, reparameterization=cls, name=name, dim=r,
        hook_child=hook_child)


def lora_parameters(module):
    """The trainable LoRA factors (``*_lora_a`` / ``*_lora_b``) — the
    list to hand the optimizer."""
    return [p for n, p in module.named_parameters()
            if n.endswith("_lora_a") or n.endswith("_lora_b")]
