"""Generalized weight reparameterization (reference:
apex/reparameterization/reparameterization.py).

TPU-first restructuring: the reference materializes the reparameterized
weight with a forward-pre-hook and deletes it in a backward hook (a
CUDA-memory bookkeeping dance, reparameterization.py:95-160).  Here the
replaced parameter becomes a *derived parameter*: it stays attached to the
module attribute so forward code is unchanged, but ``Ctx.value`` computes it
from the reparameterization's source parameters at trace time
(nn/parameter.py ``_derived``).  Gradients therefore flow to the source
parameters, XLA fuses the recompute into the consumer op, and there is
nothing to invalidate between steps — the hook machinery disappears while
``apply``/``remove``/``get_module_and_name`` keep the reference contract.
"""
from __future__ import annotations

from ..nn.modules import Embedding, Module
from ..nn.parameter import Parameter


class Reparameterization:
    """Class interface for weight reparameterizations.

    Attributes mirror the reference: ``reparameterization_names`` holds the
    names of the source parameters; ``backward_hook_key`` is kept (always
    None) for API parity — there is no backward hook to manage.
    """

    def __init__(self, name, dim, module, retain_forward=True):
        self.name = name
        self.dim = dim
        self.evaluated = False
        self.retain_forward = retain_forward
        self.reparameterization_names = []
        self.backward_hook_key = None
        self.module = module

    def compute_weight(self, ctx, module=None, name=None):
        """Returns the reparameterized weight value, reading source
        parameters through ``ctx`` (see WeightNorm for an example)."""
        raise NotImplementedError

    def reparameterize(self, name, weight, dim):
        """Returns (names, params) of the source Parameters replacing
        ``name`` (see WeightNorm for an example)."""
        raise NotImplementedError

    @staticmethod
    def apply(module, name, dim, reparameterization=None, hook_child=True,
              strict=True):
        """Applies reparameterization to module's `name` parameter.

        `hook_child` attaches the instance to the direct parent of the
        parameter rather than `module` (naming semantics only here — there
        are no hooks to place).  With ``strict`` (the explicitly-named
        path) a missing or ineligible parameter raises; the bulk ''-name
        sweep passes strict=False and skips ineligible entries silently."""
        if reparameterization is None:
            reparameterization = Reparameterization
        module2use, name2use = Reparameterization.get_module_and_name(
            module, name)
        # does not work on sparse/embedding lookups (reference :66-68)
        if name2use is None or isinstance(module2use, Embedding):
            if strict:
                if name2use is None:
                    raise AttributeError(
                        f"parameter '{name}' not found in "
                        f"{type(module).__name__}")
                raise ValueError(
                    "reparameterization does not support Embedding "
                    f"parameters ('{name}')")
            return

        from ..inference.quant import QuantTensor

        weight = getattr(module2use, name2use, None)
        if not isinstance(weight, Parameter) or weight._derived is not None \
                or isinstance(weight.data, QuantTensor) \
                or weight.data.ndim <= 1:
            if strict:
                if not isinstance(weight, Parameter):
                    raise AttributeError(
                        f"'{name}' of {type(module2use).__name__} is not a "
                        "Parameter")
                if weight._derived is not None:
                    raise ValueError(
                        f"'{name}' is already reparameterized")
                if isinstance(weight.data, QuantTensor):
                    raise ValueError(
                        f"cannot reparameterize int8-quantized weight "
                        f"'{name}' — quantized models are inference-only; "
                        f"reparameterize first, quantize after")
                raise ValueError(
                    f"cannot reparameterize {weight.data.ndim}-d parameter "
                    f"'{name}' (needs ndim > 1)")
            return

        if hook_child:
            fn = reparameterization(name2use, dim, module2use)
        else:
            fn = reparameterization(name, dim, module)

        # build the source parameters BEFORE touching the registry: a
        # reparameterize that rejects this weight (e.g. LoRA's rank
        # bound) must leave the module intact — and under the bulk
        # non-strict sweep it skips the weight instead of aborting
        # half-adapted
        try:
            names, params = fn.reparameterize(name2use, weight, dim)
        except ValueError:
            if strict:
                raise
            return
        # remove weight from the parameter list, register sources
        del module2use._parameters[name2use]
        for n, p in zip(names, params):
            module2use.register_parameter(n, p)
        fn.reparameterization_names = names

        # the attribute keeps a Parameter whose value is computed on read
        derived = Parameter(weight.data, name=weight.name,
                            requires_grad=False)
        derived._derived = lambda ctx: fn.compute_weight(
            ctx, module2use, name2use)
        object.__setattr__(module2use, name2use, derived)

        reparams = getattr(module2use, "_reparameterizations", None)
        if reparams is None:
            reparams = {}
            object.__setattr__(module2use, "_reparameterizations", reparams)
        reparams[name2use] = fn
        return fn

    @staticmethod
    def get_module_and_name(module, name):
        """Recursively fetches the owning (child) module and local name of a
        possibly dotted parameter path."""
        name2use = None
        module2use = None
        names = name.split(".")
        if len(names) == 1 and names[0] != "":
            name2use = names[0]
            module2use = module
        elif len(names) > 1:
            module2use = module
            name2use = names[0]
            for i in range(len(names) - 1):
                module2use = getattr(module2use, name2use)
                name2use = names[i + 1]
        return module2use, name2use

    def get_params(self, module):
        return [getattr(module, n) for n in self.reparameterization_names]

    def remove(self, module=None):
        """Bakes the current reparameterized value back into a plain
        Parameter and drops the sources.  ``self.name`` is relative to
        ``self.module`` (root when hook_child=False, owning child
        otherwise), so resolution starts there, not from the caller's
        module."""
        from ..nn.modules import Ctx
        module2use, name2use = Reparameterization.get_module_and_name(
            self.module, self.name)
        for p in self.get_params(module2use):
            p.requires_grad = False
        weight = self.compute_weight(Ctx(), module2use, name2use)
        for n in self.reparameterization_names:
            del module2use._parameters[n]
            object.__setattr__(module2use, n, None)
        module2use.register_parameter(name2use, Parameter(weight))
        reparams = getattr(module2use, "_reparameterizations", None)
        if reparams:
            reparams.pop(name2use, None)
