"""WeightNorm: w = g * v/||v|| (reference:
apex/reparameterization/weight_norm.py).

The reference routes through the fused CUDA ``Fused_Weight_Norm`` kernel for
fp16/fp32 speed; on TPU the norm+scale is a handful of elementwise/reduce
ops that XLA fuses straight into the consuming GEMM, so the pure-jnp form IS
the fused form.
"""
from __future__ import annotations

import jax.numpy as jnp

from .reparameterization import Reparameterization
from ..nn.parameter import Parameter


def _norm(p, dim):
    """Norm over all dimensions except ``dim``, keepdims (reference
    weight_norm.py:8-18)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(p)))
    axes = tuple(i for i in range(p.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(p), axis=axes, keepdims=True))


class WeightNorm(Reparameterization):
    """Decouples a weight's magnitude (g) from its direction (v); the module
    attribute `name` is recomputed as g * v/||v|| on every read through the
    execution ctx.  With dim=0 the norm is per output channel; dim=None is a
    single norm over the whole tensor."""

    def compute_weight(self, ctx, module=None, name=None):
        if module is None:
            module = self.module
        if name is None:
            name = self.name
        module, name = Reparameterization.get_module_and_name(module, name)
        g = ctx.value(getattr(module, name + "_g"))
        v = ctx.value(getattr(module, name + "_v"))
        vf = v.astype(jnp.float32)
        w = (g.astype(jnp.float32) * (vf / _norm(vf, self.dim)))
        return w.astype(v.dtype)

    def reparameterize(self, name, weight, dim):
        names = [name + "_g", name + "_v"]
        params = [Parameter(_norm(weight.data, dim)), Parameter(weight.data)]
        return names, params
