"""apex_tpu.reparameterization (reference: apex/reparameterization/__init__.py).

apply_weight_norm / remove_weight_norm / apply_reparameterization /
remove_reparameterization with the reference's dotted-name and
apply-to-everything ('' name) semantics."""
from .reparameterization import Reparameterization
from .weight_norm import WeightNorm
from .lora import LoRA, apply_lora, lora_parameters  # noqa: F401


def apply_weight_norm(module, name="", dim=0, hook_child=True):
    """Applies weight normalization (w = g * v/||v||) to `name`, or — with
    no name — to every >1-d parameter in the model."""
    return apply_reparameterization(
        module, reparameterization=WeightNorm, hook_child=hook_child,
        name=name, dim=dim)


def remove_weight_norm(module, name="", remove_all=False):
    return remove_reparameterization(
        module, reparameterization=WeightNorm, name=name,
        remove_all=remove_all)


def apply_reparameterization(module, reparameterization=None, name="",
                             dim=0, hook_child=True):
    assert reparameterization is not None
    if name != "":
        Reparameterization.apply(module, name, dim, reparameterization,
                                 hook_child, strict=True)
    else:
        names = [n for n, _ in module.named_parameters()]
        for name in names:
            Reparameterization.apply(module, name, dim, reparameterization,
                                     hook_child, strict=False)
    return module


def remove_reparameterization(module, reparameterization=Reparameterization,
                              name="", remove_all=False):
    if name != "" or remove_all:
        owner, local = Reparameterization.get_module_and_name(module, name) \
            if name != "" else (None, None)
        removed = False
        for m in module.modules():
            reparams = getattr(m, "_reparameterizations", None)
            if not reparams:
                continue
            for n, fn in list(reparams.items()):
                if isinstance(fn, reparameterization) and (
                        remove_all or (m is owner and n == local)):
                    fn.remove()
                    removed = True
        if not removed and not remove_all:
            raise ValueError(
                f"reparameterization of '{name}' not found in {module}")
        return module
    for m in module.modules():
        remove_reparameterization(m, reparameterization=reparameterization,
                                  remove_all=True)
    return module


__all__ = ["Reparameterization", "WeightNorm", "apply_weight_norm",
           "remove_weight_norm", "apply_reparameterization",
           "remove_reparameterization"]
