"""Multi-process launcher (reference: apex/parallel/multiproc.py:12-35).

The reference spawns one process per GPU appending --rank/--world-size.  The
TPU analogue spawns one process per host-slice for multi-host jax.distributed
runs (or N CPU processes for local testing).  Children call
``apex_tpu.parallel.init_distributed()``, which consumes the
``APEX_TPU_COORDINATOR``/``APEX_TPU_NUM_PROCESSES``/``APEX_TPU_PROCESS_ID``
variables exported here and passes them explicitly to
``jax.distributed.initialize`` (jax reads only the coordinator address from
the environment on its own).

``--cluster-kv DIR`` additionally exports ``APEX_TPU_CLUSTER_KV`` so the
children share a file-backed cluster membership store
(``apex_tpu.cluster.kvstore.FileKV`` — what
``apex_tpu.cluster.kvstore.default_kv`` resolves when no
jax.distributed coordinator is up, e.g. N local CPU processes).

Usage:  python -m apex_tpu.parallel.multiproc [--nproc N]
        [--cluster-kv DIR] script.py args...
"""
from __future__ import annotations

import os
import subprocess
import sys


def _probe_local_device_count() -> int:
    """Count devices in a throwaway child so the parent never initializes
    the backend (libtpu admits one process per chip; a parent that holds it
    would make every spawned worker fail at init)."""
    out = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(jax.local_device_count())"],
        capture_output=True, text=True)
    try:
        return int(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return 1


def main():
    argv = list(sys.argv[1:])
    nproc = None
    cluster_kv = None
    while argv and argv[0] in ("--nproc", "--cluster-kv"):
        if argv[0] == "--nproc":
            nproc = int(argv[1])
        else:
            cluster_kv = os.path.abspath(argv[1])
        argv = argv[2:]
    if not argv:
        print(__doc__)
        sys.exit(1)
    if nproc is None:
        nproc = max(_probe_local_device_count(), 1)

    port = int(os.environ.get("APEX_TPU_COORD_PORT", "12355"))
    coordinator = f"127.0.0.1:{port}"

    procs = []
    for local_rank in range(nproc):
        env = dict(os.environ)
        env["APEX_TPU_COORDINATOR"] = coordinator
        env["APEX_TPU_NUM_PROCESSES"] = str(nproc)
        env["APEX_TPU_PROCESS_ID"] = str(local_rank)
        if cluster_kv is not None:
            env["APEX_TPU_CLUSTER_KV"] = cluster_kv
        cmd = [sys.executable, argv[0], *argv[1:],
               f"--local_rank={local_rank}"]
        procs.append(subprocess.Popen(cmd, env=env))

    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
