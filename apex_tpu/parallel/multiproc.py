"""Multi-process launcher (reference: apex/parallel/multiproc.py:12-35).

The reference spawns one process per GPU appending --rank/--world-size.  The
TPU analogue spawns one process per host-slice for multi-host jax.distributed
runs (or N CPU processes for local testing), exporting the coordinator
address and process ids that ``jax.distributed.initialize`` consumes.

Usage:  python -m apex_tpu.parallel.multiproc [--nproc N] script.py args...
"""
from __future__ import annotations

import os
import subprocess
import sys


def main():
    argv = list(sys.argv[1:])
    nproc = None
    if argv and argv[0] == "--nproc":
        nproc = int(argv[1])
        argv = argv[2:]
    if not argv:
        print(__doc__)
        sys.exit(1)
    if nproc is None:
        import jax
        nproc = max(jax.local_device_count(), 1)

    port = int(os.environ.get("APEX_TPU_COORD_PORT", "12355"))
    coordinator = f"127.0.0.1:{port}"

    procs = []
    for local_rank in range(nproc):
        env = dict(os.environ)
        env["JAX_COORDINATOR_ADDRESS"] = coordinator
        env["JAX_NUM_PROCESSES"] = str(nproc)
        env["JAX_PROCESS_ID"] = str(local_rank)
        cmd = [sys.executable, argv[0], *argv[1:],
               f"--local_rank={local_rank}"]
        procs.append(subprocess.Popen(cmd, env=env))

    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
