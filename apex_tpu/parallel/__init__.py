from .LARC import LARC  # noqa: F401
