"""Distributed layer (reference: apex/parallel/__init__.py).

Exports DistributedDataParallel, Reducer, SyncBatchNorm, LARC, the
convert_syncbn_model module-tree rewrite (reference :21-56) and
create_syncbn_process_group (reference :58-95, returning axis_index_groups
for the data axis instead of a torch process group).
"""
from __future__ import annotations

import jax

from ..nn.modules import _BatchNorm
from .distributed import (  # noqa: F401
    DistributedDataParallel, Reducer, all_reduce_mean, flat_dist_call,
    init_distributed, rank, timed_flat_dist_call, world_size)
from .LARC import LARC  # noqa: F401
from .ring_attention import (  # noqa: F401
    ring_attention, ulysses_attention)
from .sync_batchnorm import SyncBatchNorm  # noqa: F401
from .tensor_parallel import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, column_parallel_linear,
    row_parallel_linear, vocab_parallel_cross_entropy,
    vocab_parallel_embedding, vocab_parallel_logits)
from .pipeline import (PipelinedStack, build_1f1b_schedule,  # noqa: F401
                       make_pipeline_train_step, pipeline_1f1b_grads,
                       pipeline_apply, ring_slots)
from .expert_parallel import switch_moe  # noqa: F401
from .zero import ZeroTrainStep, zero_state_sharding  # noqa: F401
from . import auto  # noqa: F401
from .auto import (  # noqa: F401
    ChipSpec, Fleet, ModelProfile, Plan, PlanReport, ServePhaseSplit,
    chip_spec, parse_fleet, plan_serve_phase_split, plan_training,
    profile_model)


def convert_syncbn_model(module, process_group=None, channel_last=False,
                         axis_name="data"):
    """Recursively replace every BatchNorm module with SyncBatchNorm,
    preserving parameters and running stats (reference
    apex/parallel/__init__.py:21-56).  ``axis_name`` must match the mesh
    axis your shard_map/pmap binds (stats silently stay local otherwise)."""
    mod = module
    if isinstance(module, _BatchNorm) and not isinstance(module,
                                                         SyncBatchNorm):
        mod = SyncBatchNorm(module.num_features, eps=module.eps,
                            momentum=module.momentum, affine=module.affine,
                            track_running_stats=module.track_running_stats,
                            process_group=process_group,
                            channel_last=channel_last,
                            axis_name=axis_name)
        if module.affine:
            mod.weight.data = module.weight.data
            mod.bias.data = module.bias.data
        if module.track_running_stats:
            mod.running_mean.data = module.running_mean.data
            mod.running_var.data = module.running_var.data
            mod.num_batches_tracked.data = module.num_batches_tracked.data
    else:
        for name, child in list(module._modules.items()):
            setattr(module, name,
                    convert_syncbn_model(child, process_group=process_group,
                                         channel_last=channel_last,
                                         axis_name=axis_name))
    return mod


def create_syncbn_process_group(group_size, world_size=None):
    """Partition the data axis into BN stat-sharing groups of ``group_size``
    devices; returns ``axis_index_groups`` for SyncBatchNorm's psum
    (reference :58-95 returns the torch group for the current rank; with
    XLA's axis_index_groups every group is described at once).

    ``world_size`` is the size of the *data mesh axis* the groups index —
    pass it explicitly when training on a sub-mesh; defaults to the global
    device count.  group_size == 0 (or == world size) means global sync
    (None).
    """
    n = world_size if world_size is not None else jax.device_count()
    if group_size == 0 or group_size == n:
        return None
    if group_size < 0:
        raise ValueError(f"group_size must be non-negative, got {group_size}")
    if group_size > n:
        raise ValueError(
            f"group_size {group_size} exceeds data-axis size {n}")
    if n % group_size != 0:
        raise ValueError(
            f"data-axis size {n} must be divisible by group_size "
            f"{group_size}")
    return [list(range(i, i + group_size))
            for i in range(0, n, group_size)]
