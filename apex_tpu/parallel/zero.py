"""ZeRO-style optimizer-state sharding over a mesh axis.

The reference has no analogue (its distributed scope is DDP data
parallelism, SURVEY.md §2.3); this is the TPU-native way to get the
ZeRO-1/2 memory win: instead of hand-written reduce-scatter/all-gather
(DeepSpeed's approach on NCCL), the fused train step is jitted under a
``Mesh`` with the fp32 masters and optimizer slots annotated as sharded
over the data axis and the half model copies replicated.  XLA's GSPMD
partitioner then derives the collectives itself — the gradient reduction
arrives as a reduce-scatter into each device's master shard, the updated
masters all-gather back into the replicated half copies for the next
forward — which is the "annotate shardings, let the compiler insert
collectives" recipe this framework uses everywhere.

Per-device optimizer memory drops from O(P) to O(P / n_shards) for every
tensor whose leading dim divides the axis size (others stay replicated).

``param_shard=True`` is the stage-3 (FSDP-style) extension: the half
model copies are annotated sharded as well, so no device ever holds a
full persistent parameter copy — GSPMD all-gathers each parameter just
ahead of its use in the forward/backward (XLA's latency-hiding
scheduler overlaps the gathers with compute) and the freshly-updated
master shards cast straight into half shards at the end of the step.
Stage-2 (gradient sharding) has no separate switch because the fused
step never holds a persistent gradient buffer: gradients are
intermediates of the one jitted program, and with sharded masters the
partitioner already reduce-scatters them into shards at the update.

Usage::

    step = make_train_step(model, opt, loss_fn, half_dtype=jnp.bfloat16,
                           donate_state=False)     # wrapper jits itself
    mesh = Mesh(np.array(jax.devices()), ("data",))
    zstep = ZeroTrainStep(step, mesh)              # state moves onto mesh
    loss = zstep(x, y)                             # batch auto-sharded

Data parallelism is implicit: the batch is sharded over the axis and the
jitted program is global-view, so the gradient reduction needs no psum /
``axis_name`` in the step (do NOT also pass ``axis_name`` — that is the
explicit shard_map path).  BatchNorm statistics are computed over the
global batch, i.e. SyncBatchNorm semantics for free.
"""
from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: per-wrapper token in the step_cache static key (two ZeroTrainSteps with
#: identical signatures close over different base steps)
_ZERO_TOKENS = itertools.count()


def _leaf_sharding(x, mesh, axis, n):
    if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] >= n \
            and x.shape[0] % n == 0:
        return NamedSharding(mesh, P(axis))
    return NamedSharding(mesh, P())


def zero_state_sharding(state, mesh: Mesh, axis: str = "data",
                        param_shard: bool = False, stage: int = None):
    """A StepState-shaped pytree of ``NamedSharding``s: fp32 masters and
    optimizer slots shard on dim 0 over ``axis`` where divisible; the half
    model copies replicate (stage 1) or shard the same way
    (``param_shard=True``, stage 3); buffers / scaler scalars replicate.
    ``stage=0`` replicates EVERYTHING — only the batch shards, i.e. pure
    GSPMD data parallelism through the same wrapper."""
    if stage is None:
        stage = 3 if param_shard else 1
    n = mesh.shape[axis]
    rep = NamedSharding(mesh, P())
    if stage == 0:
        # tree_map preserves the None placeholders in model_params
        return jax.tree.map(lambda _: rep, state)
    return state._replace(
        master_params=[_leaf_sharding(m, mesh, axis, n)
                       for m in state.master_params],
        model_params=[None if mp is None
                      else (_leaf_sharding(mp, mesh, axis, n)
                            if stage == 3 else rep)
                      for mp in state.model_params],
        opt_state={k: [_leaf_sharding(s, mesh, axis, n) for s in v]
                   for k, v in state.opt_state.items()},
        scaler=jax.tree.map(lambda _: rep, state.scaler),
        stats=[rep for _ in state.stats],
        # telemetry scalars replicate (the global-view program already
        # accumulates global values — no collective needed at drain)
        telem=(None if state.telem is None
               else jax.tree.map(lambda _: rep, state.telem)),
        step=rep)


class ZeroTrainStep:
    """Wrap a :class:`~apex_tpu.training.TrainStep` built WITHOUT
    ``axis_name`` (and with ``donate_state=False`` — this wrapper owns
    donation): jits the step with ZeRO shardings over ``mesh``/``axis``
    and keeps the sharded state.  ``param_shard=True`` additionally
    shards the half model copies (stage 3 / FSDP: parameters are
    all-gathered at use, never stored whole)."""

    def __init__(self, step, mesh: Mesh, axis: str = "data",
                 donate: bool = True, param_shard: bool = False,
                 stage: int = None, plan=None):
        raw = getattr(step, "_raw_step_fn", None)
        if raw is None:
            raise ValueError(
                "ZeroTrainStep needs a TrainStep from make_train_step "
                "(no _raw_step_fn found)")
        if step._step_fn is raw:
            # make_train_step leaves the step un-jitted exactly when it was
            # built with axis_name (the explicit shard_map path): its psum
            # would find no bound axis here (and would double-average)
            raise ValueError(
                "ZeroTrainStep needs a step built WITHOUT axis_name — "
                "data parallelism is implicit in the global-view program")
        if getattr(step, "_donate_state", False):
            # a donating base step invoked directly alongside this wrapper
            # would hand XLA buffers the wrapper still references
            raise ValueError(
                "ZeroTrainStep needs a step built with donate_state=False "
                "— this wrapper owns donation")
        self._base = step
        self.mesh = mesh
        self.axis = axis
        self.stage = (3 if param_shard else 1) if stage is None else stage
        self.param_shard = self.stage == 3
        #: the parallel.auto.Plan that built this step (or None); its
        #: structural key is embedded in the program cache key
        self.plan = plan
        self.shardings = zero_state_sharding(step.state, mesh, axis,
                                             stage=self.stage)
        self.state = jax.device_put(step.state, self.shardings)
        self._rep = NamedSharding(mesh, P())
        self._token = next(_ZERO_TOKENS)
        self._jits = {}
        self._donate = donate
        self.compile_s = None
        self.calls = 0
        self._guard = None
        # telemetry rides the ZeRO carry like any other state leaf (the
        # accumulator scalars replicate); the drain cadence comes from the
        # base step's build flags
        self._telemetry = getattr(step, "_telemetry", False)
        self._drain_every = getattr(step, "_drain_every", 1)

    def _batch_shardings(self, batch):
        """Shard batch elements on dim 0 where the axis divides it;
        scalars / indivisible tail args (per-step constants for loss_fn)
        replicate — mirroring the plain step's broadcast semantics."""
        n = self.mesh.shape[self.axis]
        return tuple(_leaf_sharding(b, self.mesh, self.axis, n)
                     for b in batch)

    def _program(self, batch_shs):
        """The GSPMD window :class:`~apex_tpu.runtime.executor.Program`
        for one batch-sharding signature (memoized: the executor's
        per-Program jit memo makes diagnostics and dispatch share one
        jitted callable) — registered in the runtime step-program cache
        under kind "zero_train_step", so cache stats pin
        compiles/dispatches per window exactly as on the plain fused
        path.  Under accum_steps=K the one dispatch carries the
        boundary-only reduce-scatter / all-gather pair GSPMD derives for
        the window."""
        from ..runtime import executor as _executor
        from ..runtime import step_cache as _step_cache

        prog = self._jits.get(batch_shs)
        if prog is None:
            prog = _executor.Program(
                "zero_train_step",
                (self._token, batch_shs,
                 _step_cache.static_plan_key(self.plan)),
                self._base._raw_step_fn,
                donate_argnums=(0,) if self._donate else (),
                in_shardings=(self.shardings,) + batch_shs,
                out_shardings=(self.shardings, self._rep))
            self._jits[batch_shs] = prog
        return prog

    def _jitted(self, batch_shs):
        """Diagnostic surface: the jitted callable for one batch-sharding
        signature, built without counting a compile or dispatch (tests
        ``.lower()`` the result to inspect collectives / aliasing)."""
        from ..runtime import executor as _executor
        return _executor.executor.jit(self._program(batch_shs))

    def __call__(self, *batch):
        import time
        from ..runtime import executor as _executor
        t0 = time.perf_counter() if self.compile_s is None else None
        shs = self._batch_shardings(batch)
        batch = tuple(jax.device_put(b, s) for b, s in zip(batch, shs))
        args = (self.state,) + batch
        self.calls += 1
        self.state, loss = _executor.executor.submit(
            self._program(shs), args, step=self.calls)
        if t0 is not None:
            self.compile_s = time.perf_counter() - t0
        if self._guard is not None:
            self._guard.observe(self.state.scaler.overflow)
        if self._telemetry and self._drain_every \
                and self.calls % self._drain_every == 0:
            self.drain_telemetry()
        return loss

    def drain_telemetry(self):
        """Host-sync the on-device telemetry accumulator (see
        :func:`apex_tpu.runtime.executor.drain_telemetry`)."""
        from ..runtime import executor as _executor
        return _executor.drain_telemetry(self)

    def sync_to_objects(self):
        """Write the (sharded) device state back into the model objects —
        values are fetched, which gathers the shards."""
        self._base.state = self.state
        self._base.sync_to_objects()

    def load_state(self, host_state):
        """Re-lay a host checkpoint state out under THIS step's ZeRO
        shardings (elastic cross-plan restore: saved arrays are full —
        gathered at save time — and the ``device_put`` inside
        ``reshard_state`` hands each device exactly its shard)."""
        from ..runtime.resilience import reshard_state
        self.state = reshard_state(host_state, self.state)
        return self

    def shard_sizes(self):
        """Per-device byte footprint of masters + optimizer slots + half
        model copies (diagnostic: the ZeRO memory win — ~1/n_shards of
        the replicated footprint for shardable tensors; the half copies
        only shrink under ``param_shard=True``)."""
        total = 0
        halves = [mp for mp in self.state.model_params if mp is not None]
        for leaf in jax.tree.leaves(
                (self.state.master_params, self.state.opt_state, halves)):
            shard = leaf.sharding.shard_shape(leaf.shape)
            total += int(np.prod(shard)) * leaf.dtype.itemsize
        return total
