"""Pipeline parallelism over a mesh axis — GPipe-style microbatch schedule.

The reference has no pipeline parallelism (SURVEY.md §2.3); on TPU the
mesh-native formulation is compact: each device along the ``pp`` axis owns
one STAGE's parameters, activations hop stage-to-stage via
``lax.ppermute``, and the classic fill/drain schedule is a ``lax.scan``
over ``n_micro + n_stages - 1`` ticks.  Because ppermute is differentiable
(its transpose is the reverse permute), ``jax.grad`` through the schedule
yields exact pipeline-parallel gradients with no hand-written backward.

Design notes:

* All devices run the SAME ``stage_fn`` on their own parameter shard —
  the SPMD formulation (stages must share a structure; width can differ
  only via padding).  Each device processes whichever microbatch is
  currently resident; edge ticks process garbage that is masked out of
  the final gather (the pipeline bubble, priced exactly as in GPipe:
  (n_stages - 1) bubble ticks).
* Inputs arrive batch-major ``(n_micro, micro, ...)`` replicated (or
  sharded on a separate data axis — the two composes); outputs are the
  last stage's activations for each microbatch, replicated to all
  stages of the pp axis via the closing gather.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn, stage_params, xs, axis_name):
    """Run ``n_micro`` microbatches through an ``n_stage`` pipeline.

    ``stage_fn(params, x) -> y`` — one stage's computation; activations
    must keep one shape across stages.  ``stage_params`` — this device's
    stage parameters (any pytree).  ``xs`` — ``(n_micro, micro, ...)``,
    same value on every pp device.  Returns ``(n_micro, micro, ...)``:
    stage ``n-1``'s output per microbatch, replicated along the axis.

    Call inside ``shard_map``/``pjit`` with ``axis_name`` bound.
    """
    n = lax.psum(1, axis_name)              # static stage count
    idx = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    ticks = n_micro + n - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    state0 = jnp.zeros_like(xs[0])          # resident activation
    out0 = jnp.zeros_like(xs)               # collected last-stage outputs

    def tick(carry, t):
        state, outs = carry
        # stage 0 ingests microbatch t while t < n_micro (garbage after;
        # masked below by the collection window)
        feed = xs[jnp.minimum(t, n_micro - 1)]
        x_in = jnp.where(idx == 0, feed, state)
        y = stage_fn(stage_params, x_in)
        # the last stage emits microbatch (t - n + 1) at tick t
        m = t - (n - 1)
        emit = jnp.logical_and(idx == n - 1,
                               jnp.logical_and(m >= 0, m < n_micro))
        slot = jnp.clip(m, 0, n_micro - 1)
        # mask the slice VALUE, not a whole-buffer select: keeps the scan
        # carry updated in place (O(micro) per tick, not O(n_micro*micro))
        prev = lax.dynamic_index_in_dim(outs, slot, 0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(emit, y, prev), slot, 0)
        # activations advance one stage per tick
        state = lax.ppermute(y, axis_name, fwd_perm)
        return (state, outs), None

    (_, outs), _ = lax.scan(tick, (state0, out0), jnp.arange(ticks))
    # outs is populated only on the last stage; replicate along the axis
    # (psum of one-hot contribution — every other stage holds zeros)
    return lax.psum(jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
