"""Pipeline parallelism over a mesh axis — GPipe-style microbatch schedule.

The reference has no pipeline parallelism (SURVEY.md §2.3); on TPU the
mesh-native formulation is compact: each device along the ``pp`` axis owns
one STAGE's parameters, activations hop stage-to-stage via
``lax.ppermute``, and the classic fill/drain schedule is a ``lax.scan``
over ``n_micro + n_stages - 1`` ticks.  Because ppermute is differentiable
(its transpose is the reverse permute), ``jax.grad`` through the schedule
yields exact pipeline-parallel gradients with no hand-written backward.

Design notes:

* All devices run the SAME ``stage_fn`` on their own parameter shard —
  the SPMD formulation (stages must share a structure; width can differ
  only via padding).  Each device processes whichever microbatch is
  currently resident; edge ticks process garbage that is masked out of
  the final gather (the pipeline bubble, priced exactly as in GPipe:
  (n_stages - 1) bubble ticks).
* Inputs arrive batch-major ``(n_micro, micro, ...)`` replicated (or
  sharded on a separate data axis — the two composes); outputs are the
  last stage's activations for each microbatch, replicated to all
  stages of the pp axis via the closing gather.
* The planner (``parallel.auto``, planner v3) searches ``pp × micro ×
  remat`` jointly and routes winning plans here through
  ``apply_plan``: ``remat="full"`` → :func:`make_pipeline_train_step`
  (1F1B, recompute by construction), otherwise the GPipe stack wrap of
  ``make_train_step(tp_axis=<pp axis>)``.  Its memory model prices the
  GPipe residuals at ``micro + pp - 1`` in-flight microbatches and the
  1F1B ring at :func:`ring_slots`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def pipeline_apply(stage_fn, stage_params, xs, axis_name,
                   remat_stage=False):
    """Run ``n_micro`` microbatches through an ``n_stage`` pipeline.

    ``stage_fn(params, x) -> y`` — one stage's computation; activations
    must keep ONE shape and dtype across stages (the SPMD formulation —
    every device runs the same program on its own parameter shard; pad
    narrower stages up if widths differ).  A ``stage_fn`` that changes
    the activation shape fails loudly at trace time.  ``stage_params`` —
    this device's stage parameters (any pytree).  ``xs`` —
    ``(n_micro, micro, ...)``, same value on every pp device.  Returns
    ``(n_micro, micro, ...)``: stage ``n-1``'s output per microbatch,
    replicated along the axis by a closing psum (costs one collective of
    the full output).

    ``remat_stage=True`` wraps each tick's stage in ``jax.checkpoint``:
    backward recomputes the stage instead of saving its internals — peak
    activation memory drops from O(ticks · stage_internals) to
    O(ticks · activation) + one stage's internals, the GPipe recipe.

    Under ``jax.grad`` the microbatch axis IS the gradient-accumulation
    unit: each microbatch's backward contribution accumulates through the
    scan transpose, so a mean-reduction loss over all microbatches
    reproduces the full-batch gradients exactly
    (tests/test_pipeline.py::test_pipelined_stack_step_matches_dense_oracle).

    Call inside ``shard_map``/``pjit`` with ``axis_name`` bound.  Bubble
    cost: (n_stages - 1) edge ticks compute garbage that the collection
    window masks out, exactly GPipe's price.
    """
    n = lax.psum(1, axis_name)              # static stage count
    idx = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    ticks = n_micro + n - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    state0 = jnp.zeros_like(xs[0])          # resident activation
    out0 = jnp.zeros_like(xs)               # collected last-stage outputs

    run_stage = jax.checkpoint(stage_fn) if remat_stage else stage_fn

    def tick(carry, t):
        state, outs = carry
        # stage 0 ingests microbatch t while t < n_micro (garbage after;
        # masked below by the collection window)
        feed = xs[jnp.minimum(t, n_micro - 1)]
        x_in = jnp.where(idx == 0, feed, state)
        y = run_stage(stage_params, x_in)
        if y.shape != x_in.shape or y.dtype != x_in.dtype:
            raise ValueError(
                f"pipeline_apply: stage_fn changed the activation from "
                f"{x_in.shape}/{x_in.dtype} to {y.shape}/{y.dtype} — "
                f"pipeline stages must share one activation "
                f"shape/dtype (pad narrower stages)")
        # the last stage emits microbatch (t - n + 1) at tick t
        m = t - (n - 1)
        emit = jnp.logical_and(idx == n - 1,
                               jnp.logical_and(m >= 0, m < n_micro))
        slot = jnp.clip(m, 0, n_micro - 1)
        # mask the slice VALUE, not a whole-buffer select: keeps the scan
        # carry updated in place (O(micro) per tick, not O(n_micro*micro))
        prev = lax.dynamic_index_in_dim(outs, slot, 0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(emit, y, prev), slot, 0)
        # activations advance one stage per tick
        state = lax.ppermute(y, axis_name, fwd_perm)
        return (state, outs), None

    (_, outs), _ = lax.scan(tick, (state0, out0), jnp.arange(ticks))
    # outs is populated only on the last stage; replicate along the axis
    # (psum of one-hot contribution — every other stage holds zeros)
    return lax.psum(jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)


def build_1f1b_schedule(n_stages, n_micro):
    """Static 1F1B tick tables for an ``n_stages`` pipeline over
    ``n_micro`` microbatches.

    Returns ``(fwd, bwd)``, each ``(ticks, n_stages)`` int32: the
    microbatch stage ``s`` forwards (resp. backwards) at tick ``t``, or
    ``-1`` for an idle unit.  The schedule is the lockstep synchronous
    1F1B: ``t_F(s, m) = s + m`` and ``t_B(s, m) = 2(n-1) - s + m`` — in
    steady state every stage does one forward and one backward per tick
    (the 1F1B alternation), the last stage backwards a microbatch on the
    same tick it forwards it, and a stage holds at most
    ``2(n_stages - 1 - s) + 1`` live microbatches.  Total ticks:
    ``n_micro + 2 (n_stages - 1)``.
    """
    t_total = n_micro + 2 * (n_stages - 1)
    fwd = -np.ones((t_total, n_stages), np.int32)
    bwd = -np.ones((t_total, n_stages), np.int32)
    for s in range(n_stages):
        for m in range(n_micro):
            fwd[s + m, s] = m
            bwd[2 * (n_stages - 1) - s + m, s] = m
    return fwd, bwd


def ring_slots(n_stages, n_micro):
    """Residual ring-buffer depth for the 1F1B schedule: a stage's input
    for microbatch ``m`` stays live from its forward tick to its backward
    tick — at most ``2 (n_stages - 1)`` ticks — so ``2 n - 1`` slots
    suffice regardless of ``n_micro``.  This is the 1F1B memory bound:
    the GPipe scan's transpose instead keeps every tick's residual,
    ``n_micro + n_stages - 1`` of them."""
    return min(2 * n_stages - 1, n_micro)


def pipeline_1f1b_grads(stage_fn, stage_params, xs, yrefs, loss_fn,
                        axis_name, cotangent_scale=1.0):
    """Loss and THIS stage's parameter gradients for a 1F1B pipeline.

    One-forward-one-backward interleaves each microbatch's backward into
    the forward stream, so it cannot be phrased as ``jax.grad`` over a
    forward schedule (custom_vjp separates the phases); this function
    computes gradients directly instead.  Backward ticks rebuild the
    stage forward from the stored stage INPUT (activation recomputation,
    the Megatron 1F1B recipe) — the only O(n_micro)-free storage is a
    ring of :func:`ring_slots` microbatch inputs, which is the point:
    GPipe under ``jax.grad`` (:func:`pipeline_apply`) keeps
    ``n_micro + n_stages - 1`` tick residuals live, this path keeps at
    most ``2 n_stages - 1`` regardless of microbatch count, at the price
    of (n_stages - 1) extra bubble ticks and the recompute.

    ``stage_fn(params, x) -> y`` — one stage, same contract as
    :func:`pipeline_apply` (one activation shape/dtype across stages).
    ``xs`` — ``(n_micro, micro, ...)`` inputs, replicated over the axis.
    ``yrefs`` — per-microbatch loss references (labels/targets pytree,
    leading dim ``n_micro``), replicated.  ``loss_fn(y, yref) -> scalar``
    per microbatch; the optimized total is the microbatch mean.
    ``cotangent_scale`` — multiplies the seed cotangent (amp loss
    scaling); the returned loss is unscaled.

    Returns ``(loss, grads)``: the microbatch-mean loss (replicated) and
    this device's stage-parameter gradients (a pytree like
    ``stage_params`` — disjoint per device; psum over the axis assembles
    the full stacked gradient, the ``tp_sharded_params`` pattern).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    slots = ring_slots(n, n_micro)
    fwd_np, bwd_np = build_1f1b_schedule(n, n_micro)
    fwd_tbl, bwd_tbl = jnp.asarray(fwd_np), jnp.asarray(bwd_np)
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]
    is_last = idx == n - 1

    def fwd_loss(params, x, yref):
        y = stage_fn(params, x)
        if y.shape != x.shape or y.dtype != x.dtype:
            raise ValueError(
                f"pipeline_1f1b_grads: stage_fn changed the activation "
                f"from {x.shape}/{x.dtype} to {y.shape}/{y.dtype} — "
                f"pipeline stages must share one activation shape/dtype "
                f"(pad narrower stages)")
        # every stage evaluates loss_fn (the SPMD-uniform program needs
        # one vjp structure); only the last stage's value/cotangent is
        # ever unmasked
        return y, loss_fn(y, yref)

    micro_zero = jnp.zeros_like(xs[0])
    carry0 = (
        micro_zero,                                  # act arriving s-1 -> s
        micro_zero,                                  # ct arriving s+1 -> s
        jnp.zeros((slots,) + xs.shape[1:], xs.dtype),  # input ring
        jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                     stage_params),                  # grad accumulator
        jnp.zeros((), jnp.float32),                  # loss sum (last stage)
    )

    def tick(carry, rows):
        act_in, ct_in, ring, gacc, loss_sum = carry
        row_f, row_b = rows

        # --- forward unit: stage idx forwards microbatch mf (if any) ---
        mf = row_f[idx]
        do_f = mf >= 0
        mf_c = jnp.maximum(mf, 0)
        x_in = jnp.where(idx == 0, xs[jnp.minimum(mf_c, n_micro - 1)],
                         act_in)
        y = stage_fn(stage_params, x_in)
        slot_f = mf_c % slots
        prev = lax.dynamic_index_in_dim(ring, slot_f, 0, keepdims=False)
        ring = lax.dynamic_update_index_in_dim(
            ring, jnp.where(do_f, x_in, prev), slot_f, 0)

        # --- backward unit: stage idx backwards microbatch mb (if any),
        #     recomputing its forward from the stored input ---
        mb = row_b[idx]
        do_b = mb >= 0
        mb_c = jnp.maximum(mb, 0)
        xb = lax.dynamic_index_in_dim(ring, mb_c % slots, 0, keepdims=False)
        yrb = jax.tree.map(lambda a: a[jnp.minimum(mb_c, n_micro - 1)],
                           yrefs)
        (yb, lb), vjp = jax.vjp(fwd_loss, stage_params, xb, yrb)
        ct_y = jnp.where(is_last, jnp.zeros_like(yb), ct_in.astype(yb.dtype))
        ct_l = jnp.where(is_last,
                         jnp.asarray(cotangent_scale / n_micro,
                                     jnp.float32), 0.0).astype(lb.dtype)
        g_params, g_x, _ = vjp((ct_y, ct_l))
        gacc = jax.tree.map(
            lambda a, g: a + jnp.where(do_b, g.astype(jnp.float32), 0.0),
            gacc, g_params)
        loss_sum = loss_sum + jnp.where(
            jnp.logical_and(do_b, is_last), lb.astype(jnp.float32), 0.0)

        # --- hops: activations one stage forward, cotangents one back;
        #     production-to-consumption is exactly one tick in this
        #     schedule, so a single buffer carries each stream ---
        act_in = lax.ppermute(y, axis_name, fwd_perm)
        ct_in = lax.ppermute(g_x, axis_name, bwd_perm)
        return (act_in, ct_in, ring, gacc, loss_sum), None

    (_, _, _, grads, loss_sum), _ = lax.scan(
        tick, carry0, (fwd_tbl, bwd_tbl))
    # only the last stage accumulated real loss values; psum replicates
    loss = lax.psum(jnp.where(is_last, loss_sum, 0.0), axis_name) / n_micro
    return loss, grads


def make_pipeline_train_step(stack, optimizer, loss_fn, *,
                             schedule="1f1b",
                             half_dtype=None,
                             dynamic_loss_scale=True,
                             scale_window=2000,
                             min_loss_scale=None,
                             max_loss_scale=2.0 ** 24,
                             loss_scale="dynamic",
                             lr_schedule=None):
    """Fused amp train step for a :class:`PipelinedStack`.

    ``schedule="gpipe"`` delegates to
    ``make_train_step(stack, ..., tp_axis=stack.axis_name)`` — the
    fill/drain scan differentiated by ``jax.grad`` (all tick residuals
    live through the backward; pair with ``remat_stage=True`` on the
    stack to shrink them).  ``schedule="1f1b"`` uses
    :func:`pipeline_1f1b_grads`: backward interleaved one-forward-one-
    backward with activation recomputation, residual memory bounded by
    :func:`ring_slots` microbatches independent of ``n_micro``.

    ``loss_fn(y, yref) -> scalar`` must be a per-sample mean for the
    microbatch-mean total to equal the full-batch loss (the same
    contract as ``grad_accum_steps``).  Run the returned step's
    ``._step_fn`` under ``shard_map`` over the stack's pp axis with the
    batch replicated — see ``tests/test_pipeline.py`` for the mesh
    setup.  Dynamic loss scaling, the optimizer update and the skip-on-
    overflow path are the same fused machinery as ``make_train_step``.
    """
    from ..training.step import (TrainStep, apply_fused_update,
                                 build_opt_update, init_step_state,
                                 match_param_groups, model_vals_of)

    if schedule == "gpipe":
        from ..training.step import make_train_step
        return make_train_step(
            stack, optimizer, loss_fn, half_dtype=half_dtype,
            dynamic_loss_scale=dynamic_loss_scale,
            scale_window=scale_window, min_loss_scale=min_loss_scale,
            max_loss_scale=max_loss_scale, loss_scale=loss_scale,
            lr_schedule=lr_schedule, tp_axis=stack.axis_name)
    if schedule != "1f1b":
        raise ValueError(
            f"make_pipeline_train_step: schedule must be 'gpipe' or "
            f"'1f1b', got {schedule!r}")
    if stack.remat_stage:
        raise ValueError(
            "make_pipeline_train_step(schedule='1f1b') recomputes each "
            "stage forward by construction; build the PipelinedStack "
            "with remat_stage=False")

    params = stack.parameters()
    group_idxs = match_param_groups(optimizer, params,
                                    caller="make_pipeline_train_step")
    model_dtypes = [p.data.dtype if half_dtype is None
                    else jnp.dtype(half_dtype) for p in params]
    opt_update, opt_init = build_opt_update(
        optimizer, params, group_idxs, caller="make_pipeline_train_step")

    dynamic = loss_scale == "dynamic"
    init_scale = (min(max_loss_scale, 2.0 ** 16) if dynamic
                  else float(loss_scale))
    axis = stack.axis_name
    n_micro = stack.n_micro

    def step_fn(state, x, yref):
        vals = model_vals_of(state)
        stacked = jax.tree.unflatten(stack._treedef, vals)
        i = lax.axis_index(axis)
        local = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            stacked)
        if half_dtype is not None:
            from ..amp.policy import _cast_tree
            x = _cast_tree(x, jnp.dtype(half_dtype))
        b = x.shape[0]
        if b % n_micro:
            raise ValueError(
                f"make_pipeline_train_step: batch {b} does not divide "
                f"into n_micro={n_micro} microbatches")
        micro = b // n_micro
        xs = x.reshape((n_micro, micro) + x.shape[1:])
        yrefs = jax.tree.map(
            lambda a: a.reshape((n_micro, micro) + a.shape[1:]), yref)

        loss, local_grads = pipeline_1f1b_grads(
            stack.stage_fn, local, xs, yrefs, loss_fn, axis,
            cotangent_scale=state.scaler.loss_scale)

        # expand this stage's slice into the stacked layout (disjoint
        # blocks per device) and psum-assemble, as for tp_sharded_params
        stacked_grads = jax.tree.map(
            lambda g, full: lax.psum(
                lax.dynamic_update_index_in_dim(
                    jnp.zeros(full.shape, jnp.float32),
                    g.astype(jnp.float32), i, 0),
                axis),
            local_grads, stacked)
        grads = jax.tree.leaves(stacked_grads)

        new_state = apply_fused_update(
            state, grads, opt_update, model_dtypes,
            dynamic=dynamic, init_scale=init_scale,
            scale_window=scale_window, min_loss_scale=min_loss_scale,
            max_loss_scale=max_loss_scale, lr_schedule=lr_schedule)
        return new_state, loss

    init_state = init_step_state(params, [], model_dtypes, opt_init,
                                 init_scale)
    ts = TrainStep(stack, optimizer, loss_fn, step_fn, params, [],
                   init_state)
    ts._raw_step_fn = step_fn
    ts._donate_state = False
    return ts


class PipelinedStack:
    """An ``nn.Module`` pipelining N structurally-identical stages over a
    mesh axis, integrated with the fused train step.

    Holds the stage parameters STACKED ``(n_stages, ...)`` full-size and
    replicated (the same philosophy as TP/MoE: checkpoints are
    mesh-independent); each device slices its stage at trace time.
    ``forward`` reshapes the batch into ``n_micro`` microbatches and runs
    the GPipe schedule — the microbatch axis is the gradient-accumulation
    unit, so a mean-reduction loss reproduces full-batch gradients.

    Per-device stage gradients are nonzero only in the device's own stage
    slice (disjoint blocks), so the stack exposes them via
    ``tp_sharded_params()`` — build the step with ``tp_axis=<pp axis>``
    and the psum assembly keeps the replicated stacks consistent, exactly
    as for tensor parallelism::

        stack = PipelinedStack(stage_fn, stacked_params, "pp", n_micro=4)
        step = make_train_step(stack, opt, loss_fn, tp_axis="pp")
        # run step._step_fn under shard_map over a ("pp",) mesh with the
        # batch replicated (P()) — or a ("data", "pp") mesh with the
        # batch sharded over "data" and axis_name="data"
    """

    def __init__(self, stage_fn, stacked_params, axis_name, n_micro,
                 remat_stage=False):
        from ..nn.parameter import Parameter

        self.stage_fn = stage_fn
        self.axis_name = axis_name
        self.n_micro = n_micro
        self.remat_stage = remat_stage
        leaves, self._treedef = jax.tree.flatten(stacked_params)
        self._params = [Parameter(jnp.asarray(a)) for a in leaves]
        self.training = True

    def parameters(self):
        return list(self._params)

    def buffers(self):
        return []

    def modules(self):
        return []

    def named_parameters(self):
        return [(f"stage_stack.{i}", p)
                for i, p in enumerate(self._params)]

    def tp_sharded_params(self):
        """Every stacked stage parameter: per-device grads live only in
        the device's stage slice, assembled by the step's tp psum."""
        return list(self._params)

    def train(self):
        self.training = True
        return self

    def eval(self):
        self.training = False
        return self

    def forward(self, ctx, x):
        vals = [ctx.value(p) for p in self._params]
        stacked = jax.tree.unflatten(self._treedef, vals)
        i = lax.axis_index(self.axis_name)
        local = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            stacked)
        b = x.shape[0]
        if b % self.n_micro:
            raise ValueError(
                f"PipelinedStack: batch {b} does not divide into "
                f"n_micro={self.n_micro} microbatches")
        xs = x.reshape((self.n_micro, b // self.n_micro) + x.shape[1:])
        ys = pipeline_apply(self.stage_fn, local, xs, self.axis_name,
                            remat_stage=self.remat_stage)
        return ys.reshape((b,) + ys.shape[2:])
