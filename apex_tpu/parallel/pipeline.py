"""Pipeline parallelism over a mesh axis — GPipe-style microbatch schedule.

The reference has no pipeline parallelism (SURVEY.md §2.3); on TPU the
mesh-native formulation is compact: each device along the ``pp`` axis owns
one STAGE's parameters, activations hop stage-to-stage via
``lax.ppermute``, and the classic fill/drain schedule is a ``lax.scan``
over ``n_micro + n_stages - 1`` ticks.  Because ppermute is differentiable
(its transpose is the reverse permute), ``jax.grad`` through the schedule
yields exact pipeline-parallel gradients with no hand-written backward.

Design notes:

* All devices run the SAME ``stage_fn`` on their own parameter shard —
  the SPMD formulation (stages must share a structure; width can differ
  only via padding).  Each device processes whichever microbatch is
  currently resident; edge ticks process garbage that is masked out of
  the final gather (the pipeline bubble, priced exactly as in GPipe:
  (n_stages - 1) bubble ticks).
* Inputs arrive batch-major ``(n_micro, micro, ...)`` replicated (or
  sharded on a separate data axis — the two composes); outputs are the
  last stage's activations for each microbatch, replicated to all
  stages of the pp axis via the closing gather.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn, stage_params, xs, axis_name,
                   remat_stage=False):
    """Run ``n_micro`` microbatches through an ``n_stage`` pipeline.

    ``stage_fn(params, x) -> y`` — one stage's computation; activations
    must keep ONE shape and dtype across stages (the SPMD formulation —
    every device runs the same program on its own parameter shard; pad
    narrower stages up if widths differ).  A ``stage_fn`` that changes
    the activation shape fails loudly at trace time.  ``stage_params`` —
    this device's stage parameters (any pytree).  ``xs`` —
    ``(n_micro, micro, ...)``, same value on every pp device.  Returns
    ``(n_micro, micro, ...)``: stage ``n-1``'s output per microbatch,
    replicated along the axis by a closing psum (costs one collective of
    the full output).

    ``remat_stage=True`` wraps each tick's stage in ``jax.checkpoint``:
    backward recomputes the stage instead of saving its internals — peak
    activation memory drops from O(ticks · stage_internals) to
    O(ticks · activation) + one stage's internals, the GPipe recipe.

    Under ``jax.grad`` the microbatch axis IS the gradient-accumulation
    unit: each microbatch's backward contribution accumulates through the
    scan transpose, so a mean-reduction loss over all microbatches
    reproduces the full-batch gradients exactly
    (tests/test_pipeline.py::test_pipelined_stack_step_matches_dense_oracle).

    Call inside ``shard_map``/``pjit`` with ``axis_name`` bound.  Bubble
    cost: (n_stages - 1) edge ticks compute garbage that the collection
    window masks out, exactly GPipe's price.
    """
    n = lax.psum(1, axis_name)              # static stage count
    idx = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    ticks = n_micro + n - 1
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    state0 = jnp.zeros_like(xs[0])          # resident activation
    out0 = jnp.zeros_like(xs)               # collected last-stage outputs

    run_stage = jax.checkpoint(stage_fn) if remat_stage else stage_fn

    def tick(carry, t):
        state, outs = carry
        # stage 0 ingests microbatch t while t < n_micro (garbage after;
        # masked below by the collection window)
        feed = xs[jnp.minimum(t, n_micro - 1)]
        x_in = jnp.where(idx == 0, feed, state)
        y = run_stage(stage_params, x_in)
        if y.shape != x_in.shape or y.dtype != x_in.dtype:
            raise ValueError(
                f"pipeline_apply: stage_fn changed the activation from "
                f"{x_in.shape}/{x_in.dtype} to {y.shape}/{y.dtype} — "
                f"pipeline stages must share one activation "
                f"shape/dtype (pad narrower stages)")
        # the last stage emits microbatch (t - n + 1) at tick t
        m = t - (n - 1)
        emit = jnp.logical_and(idx == n - 1,
                               jnp.logical_and(m >= 0, m < n_micro))
        slot = jnp.clip(m, 0, n_micro - 1)
        # mask the slice VALUE, not a whole-buffer select: keeps the scan
        # carry updated in place (O(micro) per tick, not O(n_micro*micro))
        prev = lax.dynamic_index_in_dim(outs, slot, 0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(emit, y, prev), slot, 0)
        # activations advance one stage per tick
        state = lax.ppermute(y, axis_name, fwd_perm)
        return (state, outs), None

    (_, outs), _ = lax.scan(tick, (state0, out0), jnp.arange(ticks))
    # outs is populated only on the last stage; replicate along the axis
    # (psum of one-hot contribution — every other stage holds zeros)
    return lax.psum(jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)


class PipelinedStack:
    """An ``nn.Module`` pipelining N structurally-identical stages over a
    mesh axis, integrated with the fused train step.

    Holds the stage parameters STACKED ``(n_stages, ...)`` full-size and
    replicated (the same philosophy as TP/MoE: checkpoints are
    mesh-independent); each device slices its stage at trace time.
    ``forward`` reshapes the batch into ``n_micro`` microbatches and runs
    the GPipe schedule — the microbatch axis is the gradient-accumulation
    unit, so a mean-reduction loss reproduces full-batch gradients.

    Per-device stage gradients are nonzero only in the device's own stage
    slice (disjoint blocks), so the stack exposes them via
    ``tp_sharded_params()`` — build the step with ``tp_axis=<pp axis>``
    and the psum assembly keeps the replicated stacks consistent, exactly
    as for tensor parallelism::

        stack = PipelinedStack(stage_fn, stacked_params, "pp", n_micro=4)
        step = make_train_step(stack, opt, loss_fn, tp_axis="pp")
        # run step._step_fn under shard_map over a ("pp",) mesh with the
        # batch replicated (P()) — or a ("data", "pp") mesh with the
        # batch sharded over "data" and axis_name="data"
    """

    def __init__(self, stage_fn, stacked_params, axis_name, n_micro,
                 remat_stage=False):
        from ..nn.parameter import Parameter

        self.stage_fn = stage_fn
        self.axis_name = axis_name
        self.n_micro = n_micro
        self.remat_stage = remat_stage
        leaves, self._treedef = jax.tree.flatten(stacked_params)
        self._params = [Parameter(jnp.asarray(a)) for a in leaves]
        self.training = True

    def parameters(self):
        return list(self._params)

    def buffers(self):
        return []

    def modules(self):
        return []

    def named_parameters(self):
        return [(f"stage_stack.{i}", p)
                for i, p in enumerate(self._params)]

    def tp_sharded_params(self):
        """Every stacked stage parameter: per-device grads live only in
        the device's stage slice, assembled by the step's tp psum."""
        return list(self._params)

    def train(self):
        self.training = True
        return self

    def eval(self):
        self.training = False
        return self

    def forward(self, ctx, x):
        vals = [ctx.value(p) for p in self._params]
        stacked = jax.tree.unflatten(self._treedef, vals)
        i = lax.axis_index(self.axis_name)
        local = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            stacked)
        b = x.shape[0]
        if b % self.n_micro:
            raise ValueError(
                f"PipelinedStack: batch {b} does not divide into "
                f"n_micro={self.n_micro} microbatches")
        xs = x.reshape((self.n_micro, b // self.n_micro) + x.shape[1:])
        ys = pipeline_apply(self.stage_fn, local, xs, self.axis_name,
                            remat_stage=self.remat_stage)
        return ys.reshape((b,) + ys.shape[2:])
