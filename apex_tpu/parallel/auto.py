"""``parallel="auto"`` — an analytical parallelism planner + cost model.

Every parallelism primitive in this framework is a manual knob on
:func:`~apex_tpu.training.make_train_step` (``axis_name``, ``tp_axis``,
``zero_sharding``/``zero_stage``, ``accum_steps``) or a model build option
(``tp_axis=``, ``sp_axis=``, the chunked LM loss).  Picking the
configuration is worth double-digit throughput (BENCH_HISTORY round 5:
+13–15% from the chunked vocab chain alone, batch-size plateaus that
invert per model), and the AMP (arXiv:2210.07297) / Galvatron
(arXiv:2504.03662) line of work shows an analytical cost model over
(compute FLOPs, collective bytes, memory footprint) ranks parallel plans
reliably without exhaustive on-device search.  This module is that brain:

1. **enumerate** candidate plans — mesh factorizations dp × sp × tp, ZeRO
   stage 0/1/3, gradient-accumulation K, chunked-loss on/off;
2. **prune** memory-infeasible ones with an explicit HBM model (masters +
   optimizer slots under the chosen ZeRO stage + half model copies +
   gradient carry + activation peak under accumulation + the vocab-logits
   working set vs the chunked-loss lever) — every rejection carries a
   stated reason, nothing is pruned silently;
3. **rank** the survivors with a roofline step-time model: per-device
   FLOPs at the chip's derated peak, HBM bytes at its bandwidth, and
   ring-model ICI time for every collective the plan will emit (psum /
   reduce-scatter / all-gather / ppermute on the candidate mesh axes);
4. **return** a :class:`Plan` whose ``describe()`` prints the predicted
   ms/step, predicted HBM breakdown, the collectives it emits, and — via
   :meth:`PlanReport.describe` — why rejected plans lost.

The planner is pure host-side Python over static shapes.  Its model
constants come from two places: the per-model FLOP/activation profile is
measured from XLA's own cost analysis (``lower().cost_analysis()`` /
``compile().memory_analysis()`` of the unsharded forward+backward at two
probe batch sizes, linearly fitted), and the per-chip constants (peak
FLOP/s, HBM bytes/bandwidth, ICI bandwidth/latency) live in the
:data:`CHIPS` table, checked against ``bench.py --plan``'s
predicted-vs-measured output.

The planner only *drives* primitives that already exist and are tested:
dp/ZeRO plans run through the GSPMD global-view path
(:class:`~apex_tpu.parallel.zero.ZeroTrainStep`, stage 0 = replicated
state / pure data parallelism), tp/sp plans through the
``shard_map``-wrapped explicit-axis path — there are no new execution
paths, and the step-program cache keys carry the plan so cache stats stay
per-plan observables.
"""
from __future__ import annotations

import dataclasses
import itertools
import math
import time
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..observe import registry as _obs

#: per-wrap token in the step-program cache key — two planned steps with
#: identical signatures close over different model/optimizer objects
_PLAN_TOKENS = itertools.count()


# ---------------------------------------------------------------------------
# Chip constants (the calibration table — see docs/auto_parallel.md)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Per-device hardware constants the cost model prices against.

    ``efficiency`` derates the spec-sheet peak to the sustained fraction a
    well-tuned fused step reaches (the bench-measured MFU band, not the
    marketing number).  ``shared_host=True`` marks *virtual* devices
    (``--xla_force_host_platform_device_count``): they split one host's
    cores and memory bus, so spreading work over more of them never buys
    compute time — only memory-model wins — and every collective is a
    host memcpy.  That inversion is deliberate: on the CPU test mesh the
    planner must predict the order a CPU measurement produces.
    """
    name: str
    peak_flops: float        # per device (bf16/fp16 ALU peak, FLOP/s)
    hbm_bytes: float         # per device
    hbm_bw: float            # bytes/s
    ici_bw: float            # bytes/s per link direction
    ici_latency_s: float     # per-hop collective latency
    overhead_s: float        # fixed per-microbatch dispatch/loop overhead
    efficiency: float = 0.45
    shared_host: bool = False
    #: host↔device transfer bandwidth (PCIe/DMA), the prior the offload
    #: term prices against when the executor has no measured H2D rate
    h2d_bw: float = 16e9

    def sustained_flops(self) -> float:
        return self.peak_flops * self.efficiency

    def scaled(self, factor: float) -> "ChipSpec":
        """A speed-scaled copy (compute, HBM and ICI bandwidth all
        multiplied by ``factor``) — the fleet syntax's straggler
        stand-in, e.g. ``"cpu*0.5"`` is a host running at half speed."""
        if factor <= 0:
            raise ValueError(f"scale factor must be > 0, got {factor}")
        if factor == 1.0:
            return self
        return dataclasses.replace(
            self, name=f"{self.name}*{factor:g}",
            peak_flops=self.peak_flops * factor,
            hbm_bw=self.hbm_bw * factor,
            ici_bw=self.ici_bw * factor,
            h2d_bw=self.h2d_bw * factor)


#: bf16 peaks from public spec sheets; HBM/ICI figures are the same
#: per-chip constants bench.py's MFU math uses.  The "cpu" entry models
#: the 8-virtual-device test mesh: one shared host, collectives as
#: memcpys, generous per-collective latency (thread rendezvous).
CHIPS = {
    "v6":  ChipSpec("v6",  918.0e12, 32e9, 1640e9, 180e9, 1e-6, 2e-6,
                    h2d_bw=64e9),
    "v5p": ChipSpec("v5p", 459.0e12, 95e9, 2765e9, 200e9, 1e-6, 2e-6,
                    h2d_bw=64e9),
    "v5e": ChipSpec("v5e", 197.0e12, 16e9,  819e9,  50e9, 1e-6, 2e-6,
                    h2d_bw=32e9),
    "v4":  ChipSpec("v4",  275.0e12, 32e9, 1228e9, 100e9, 1e-6, 2e-6,
                    h2d_bw=32e9),
    "v3":  ChipSpec("v3",  123.0e12, 32e9,  900e9,  70e9, 1e-6, 2e-6,
                    h2d_bw=16e9),
    "cpu": ChipSpec("cpu",   40.0e9,  4e9,   20e9,   4e9, 30e-6, 150e-6,
                    efficiency=1.0, shared_host=True, h2d_bw=20e9),
}


def chip_spec(devices=None) -> ChipSpec:
    """Match the running device kind to the constants table (cpu
    fallback; unknown accelerators borrow the v4 numbers)."""
    devices = list(devices) if devices is not None else jax.devices()
    kind = (getattr(devices[0], "device_kind", "") or
            devices[0].platform or "").lower()
    if "cpu" in kind or devices[0].platform == "cpu":
        return CHIPS["cpu"]
    for key in ("v6", "v5p", "v5e", "v5 lite", "v4", "v3"):
        if key in kind:
            return CHIPS.get(key, CHIPS["v5e"]) if key != "v5 lite" \
                else CHIPS["v5e"]
    return CHIPS["v4"]


# ---------------------------------------------------------------------------
# Fleets — mixed chip types / speed-scaled stragglers (docs/cluster.md)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Fleet:
    """Per-device chip specs for a (possibly mixed) device fleet, in
    planner device order.  A homogeneous fleet prices exactly like the
    single-``ChipSpec`` path; a heterogeneous one switches the planner
    to the slowest-member roofline bound with per-device batch shares
    (see :func:`predict_time_fleet`)."""

    specs: Tuple[ChipSpec, ...]

    def __post_init__(self):
        if not self.specs:
            raise ValueError("a Fleet needs at least one device")

    @property
    def n_devices(self) -> int:
        return len(self.specs)

    @property
    def heterogeneous(self) -> bool:
        return len({s.name for s in self.specs}) > 1

    def slowest(self) -> ChipSpec:
        return min(self.specs, key=lambda s: s.sustained_flops())

    def name(self) -> str:
        """Canonical ``"v5e:4+v4:4"`` rendering (consecutive runs)."""
        parts, i = [], 0
        while i < len(self.specs):
            j = i
            while j < len(self.specs) and \
                    self.specs[j].name == self.specs[i].name:
                j += 1
            parts.append(f"{self.specs[i].name}:{j - i}")
            i = j
        return "+".join(parts)


def parse_fleet(text: str) -> Fleet:
    """Parse the fleet syntax: ``+``-joined members, each
    ``<chip>[*<scale>][:<count>]``.

    ``"v5e:4+v4:4"`` is four v5e chips plus four v4; ``"cpu*0.5:2"`` is
    two CPU virtual devices running at half speed (the straggler
    stand-in the mixed-fleet tier-1 tests use — a declared slowdown the
    cost model must rank correctly against the measured mesh).
    """
    specs = []
    for member in str(text).split("+"):
        member = member.strip()
        if not member:
            raise ValueError(f"empty fleet member in {text!r}")
        count = 1
        if ":" in member:
            member, _, c = member.rpartition(":")
            count = int(c)
        scale = 1.0
        if "*" in member:
            member, _, s = member.partition("*")
            scale = float(s)
        chip = member.strip()
        if chip not in CHIPS:
            raise ValueError(
                f"unknown chip {chip!r} in fleet {text!r} — known: "
                f"{sorted(CHIPS)}")
        if count < 1:
            raise ValueError(f"fleet member count must be >= 1: {text!r}")
        specs.extend([CHIPS[chip].scaled(scale)] * count)
    return Fleet(specs=tuple(specs))


def _fleet_of(fleet) -> Optional[Fleet]:
    """Normalize the ``fleet=`` argument: None, a :class:`Fleet`, the
    string syntax, or a sequence of :class:`ChipSpec`."""
    if fleet is None:
        return None
    if isinstance(fleet, Fleet):
        return fleet
    if isinstance(fleet, str):
        return parse_fleet(fleet)
    return Fleet(specs=tuple(fleet))


def apportion_shares(weights, total: int) -> Tuple[int, ...]:
    """Largest-remainder apportionment of ``total`` integer units
    proportional to ``weights`` — the per-device batch-share rule.  The
    shares sum to ``total`` EXACTLY (the planner never invents or drops
    examples); ties break toward the earlier device for determinism."""
    n = len(weights)
    wsum = float(sum(weights))
    if wsum <= 0:
        weights, wsum = [1.0] * n, float(n)
    quotas = [w / wsum * total for w in weights]
    shares = [int(q) for q in quotas]
    rest = total - sum(shares)
    by_frac = sorted(range(n), key=lambda i: (shares[i] - quotas[i], i))
    for i in by_frac[:rest]:
        shares[i] += 1
    return tuple(shares)


# ---------------------------------------------------------------------------
# Serve phase split — disaggregated prefill/decode placement
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ServePhaseSplit:
    """Device assignment for a disaggregated serving deployment
    (:class:`apex_tpu.serve.DisaggregatedEngine`): ``prefill`` /
    ``decode`` are index tuples into the fleet's device order.  On a
    single device the phases colocate (``colocated=True``, both tuples
    ``(0,)``) — that is the unified engine, not a degenerate split."""

    prefill: Tuple[int, ...]
    decode: Tuple[int, ...]
    colocated: bool
    reason: str

    def name(self) -> str:
        if self.colocated:
            return "colocated"
        return f"prefill:{len(self.prefill)}+decode:{len(self.decode)}"


def plan_serve_phase_split(fleet=None, *, prefill_weight: float = 1.0,
                           decode_weight: float = 1.0) -> ServePhaseSplit:
    """Split a (possibly heterogeneous) fleet between the two serving
    phases.  Phase demands are opposite corners of the roofline:
    prefill is one wide compute-bound matmul over the prompt (ranked by
    ``sustained_flops``), decode re-reads the whole KV cache per token
    (ranked by ``hbm_bw``) — so in a mixed fleet the members with the
    most HBM bandwidth per unit compute go to decode and the
    biggest-MXU members to prefill.  Phase sizes come from
    :func:`apportion_shares` over the declared demand weights (tokens
    of prefill vs decode work per request, roughly prompt length vs
    ``max_new_tokens``), clamped so each phase keeps at least one
    device."""
    flt = _fleet_of(fleet)
    if flt is None:
        flt = Fleet(specs=(chip_spec(),))
    n = flt.n_devices
    if n == 1:
        return ServePhaseSplit(
            prefill=(0,), decode=(0,), colocated=True,
            reason="single device: phases colocated (unified engine)")
    n_pre, n_dec = apportion_shares(
        [float(prefill_weight), float(decode_weight)], n)
    n_pre = max(1, min(n - 1, n_pre))
    n_dec = n - n_pre
    bw_per_flop = [s.hbm_bw / max(s.sustained_flops(), 1.0)
                   for s in flt.specs]
    order = sorted(range(n), key=lambda i: (-bw_per_flop[i], i))
    decode_ids = tuple(sorted(order[:n_dec]))
    prefill_ids = tuple(sorted(order[n_dec:]))
    return ServePhaseSplit(
        prefill=prefill_ids, decode=decode_ids, colocated=False,
        reason=(f"{flt.name()}: decode→{n_dec} member(s) with the "
                f"highest HBM-BW per sustained FLOP, prefill→{n_pre} "
                f"compute-heaviest"))


# ---------------------------------------------------------------------------
# Model profile — XLA-measured FLOPs/activation footprint + capabilities
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ModelProfile:
    """Static-shape profile the cost model scales per plan.

    ``flops_per_example`` / ``act_bytes_per_example`` /
    ``hbm_bytes_per_example`` are linear-fit slopes over the batch dim
    measured from XLA's own cost analysis of the unsharded
    forward+backward at two probe batch sizes (``source="xla"``), or the
    6·N·tokens fallback when the model cannot lower unsharded
    (``source="analytic"``).  The ``*_fixed`` intercepts capture the
    batch-independent part (weights traffic, per-call scratch).
    """
    n_params: int
    param_shapes: tuple
    param_bytes_fp32: int
    half_itemsize: int                 # 0 when params stay fp32
    slots_per_param: int               # fp32 optimizer slot multiplicity
    batch_ref: int                     # global batch the plan prices for
    batch_bytes_per_example: float
    flops_per_example: float
    flops_fixed: float
    act_bytes_per_example: float
    act_bytes_fixed: float
    hbm_bytes_per_example: float
    hbm_bytes_fixed: float
    logits_bytes_per_example: float    # vocab-head working set (chunk lever)
    seq_len: Optional[int]
    vocab: Optional[int]
    hidden: Optional[int]
    layers: Optional[int]
    heads: Optional[int]
    tp_axis: Optional[str]             # model capability (build option)
    sp_axis: Optional[str]
    source: str = "xla"
    # -- planner-v3 capabilities (defaults keep old profiles valid) ----
    pp_axis: Optional[str] = None      # PipelinedStack mesh axis
    remat_capable: bool = False        # model built with remat=True
    moe_axis: Optional[str] = None     # switch-MoE routing axis
    n_experts: int = 0                 # experts per MoE block (E)
    moe_layers: int = 0                # routed blocks in the model
    moe_param_frac: float = 0.0        # fraction of params in experts
    moe_capacity_factor: float = 1.25


def _optimizer_slots(optimizer) -> int:
    from ..optimizers import FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD
    if isinstance(optimizer, (FusedAdam, FusedLAMB)):
        return 2
    if isinstance(optimizer, (FusedSGD, FusedNovoGrad)):
        return 1
    return 2        # unknown: price like Adam, the common case


def _batch_leaves(batch_el):
    return [a for a in jax.tree_util.tree_leaves(batch_el)
            if hasattr(a, "shape")]


def _global_batch_of(example_batch) -> int:
    leaves = _batch_leaves(example_batch[0])
    if not leaves or not leaves[0].shape:
        raise ValueError(
            "example_batch[0] (the model input) has no leading batch "
            "dimension — the planner needs the global batch size")
    return int(leaves[0].shape[0])


def _resize_batch(example_batch, b):
    """ShapeDtypeStruct copy of the batch with splittable elements'
    leading dim set to ``b`` (same broadcast rule as the fused step:
    elements whose every leaf shares the model input's batch dim
    split, anything else is carried whole)."""
    n0 = _global_batch_of(example_batch)

    def splittable(el):
        leaves = _batch_leaves(el)
        return bool(leaves) and all(
            len(a.shape) >= 1 and a.shape[0] == n0 for a in leaves)

    def resize(el, do):
        def leaf(a):
            shape = ((b,) + tuple(a.shape[1:])) if do else tuple(a.shape)
            return jax.ShapeDtypeStruct(shape, jnp.dtype(a.dtype))
        return jax.tree_util.tree_map(leaf, el)

    return tuple(resize(el, i == 0 or splittable(el))
                 for i, el in enumerate(example_batch))


def _introspect(model):
    blocks = getattr(model, "blocks", None)
    layers = len(blocks) if blocks is not None else None
    heads = None
    if blocks is not None and len(blocks):
        for attr in ("heads", "num_heads", "n_heads"):
            heads = getattr(blocks[0], attr, None)
            if heads is None:
                attn = getattr(blocks[0], "attn", None)
                heads = getattr(attn, "heads", None) if attn is not None \
                    else None
            if heads is not None:
                break
    # switch-MoE capability: routed blocks carry num_experts + moe_axis
    # and the stacked expert FFN weights (w1/b1/w2/b2, leading dim E)
    moe_axis, n_experts, moe_layers, expert_bytes = None, 0, 0, 0
    moe_cap = 1.25
    for blk in (blocks or []):
        e = getattr(blk, "num_experts", None)
        if e is None or getattr(blk, "moe_axis", None) is None:
            continue
        moe_axis = blk.moe_axis
        n_experts = int(e)
        moe_layers += 1
        moe_cap = float(getattr(blk, "capacity_factor", moe_cap))
        for attr in ("w1", "b1", "w2", "b2"):
            p = getattr(blk, attr, None)
            if p is not None and hasattr(p, "data"):
                expert_bytes += int(np.prod(p.data.shape)) * 4
    # pipeline capability: a PipelinedStack (stacked stage params sliced
    # over axis_name, microbatch axis = accumulation unit)
    pp_axis = (getattr(model, "axis_name", None)
               if getattr(model, "n_micro", None) is not None and
               getattr(model, "stage_fn", None) is not None else None)
    return dict(
        vocab=getattr(model, "vocab_size", None),
        hidden=getattr(model, "hidden", None),
        layers=layers, heads=heads,
        tp_axis=getattr(model, "tp_axis", None),
        sp_axis=getattr(model, "sp_axis", None),
        pp_axis=pp_axis,
        remat_capable=bool(getattr(model, "remat", False)
                           or getattr(model, "remat_stage", False)),
        moe_axis=moe_axis, n_experts=n_experts, moe_layers=moe_layers,
        moe_capacity_factor=moe_cap,
        _expert_bytes=expert_bytes)


def profile_model(model, optimizer, loss_fn: Callable, example_batch, *,
                  half_dtype=None, keep_batchnorm_fp32: bool = True,
                  rng_seed: int = 0) -> ModelProfile:
    """Measure the model's per-example FLOPs / activation / HBM-traffic
    slopes from XLA's own cost analysis of the unsharded fwd+bwd, at two
    probe batch sizes (pure lower+compile, nothing executes).

    A model built with ``tp_axis=``/``sp_axis=`` cannot trace unsharded
    (its forward psums over mesh axes), so it falls back to the analytic
    6·N FLOP estimate with ``source="analytic"``.
    """
    from ..training.step import _model_dtypes
    from ..nn.modules import Ctx

    params = [p for p in model.parameters() if p is not None]
    buffers = list(model.buffers())
    model_dtypes = _model_dtypes(model, params, half_dtype,
                                 keep_batchnorm_fp32)
    n_params = sum(int(np.prod(p.data.shape)) for p in params)
    param_bytes = n_params * 4
    half_itemsize = 0 if half_dtype is None else jnp.dtype(half_dtype).itemsize
    info = _introspect(model)
    info["moe_param_frac"] = (info.pop("_expert_bytes")
                              / max(param_bytes, 1))
    b_hi = _global_batch_of(example_batch)
    act_itemsize = half_itemsize or 4
    batch_bytes = sum(
        int(np.prod(a.shape)) * jnp.dtype(a.dtype).itemsize
        for el in example_batch for a in _batch_leaves(el)) / max(b_hi, 1)

    leaves0 = _batch_leaves(example_batch[0])
    seq_len = (int(leaves0[0].shape[1])
               if leaves0 and len(leaves0[0].shape) >= 2
               and np.issubdtype(np.dtype(leaves0[0].dtype), np.integer)
               else info["layers"] and getattr(model, "max_positions", None))
    logits_bpe = (float(seq_len) * info["vocab"] * 4.0
                  if seq_len and info["vocab"] else 0.0)

    def fwd(vals, *batch):
        env = {id(p): v for p, v in zip(params, vals)}
        env.update({id(bf): jnp.asarray(bf.data) for bf in buffers})
        ctx = Ctx(env=env, stats_out={}, training=True,
                  key=jax.random.PRNGKey(rng_seed))
        x = batch[0]
        if half_dtype is not None:
            from ..amp.policy import _cast_tree
            x = _cast_tree(x, jnp.dtype(half_dtype))
        out = model.forward(ctx, x)
        loss = loss_fn(out, *batch[1:])
        if ctx.aux_losses:
            loss = loss + sum(ctx.aux_losses)
        return loss.astype(jnp.float32)

    vals_struct = [jax.ShapeDtypeStruct(tuple(p.data.shape), jnp.dtype(d))
                   for p, d in zip(params, model_dtypes)]
    b_lo = max(1, b_hi // 2)
    if b_lo == b_hi:
        b_hi = b_lo + 1

    def probe(b):
        batch = _resize_batch(example_batch, b)
        lowered = jax.jit(jax.value_and_grad(fwd)).lower(
            vals_struct, *batch)
        ca = lowered.cost_analysis()
        if not isinstance(ca, dict):        # older jax returns [dict]
            ca = ca[0]
        ma = lowered.compile().memory_analysis()
        return (float(ca.get("flops", 0.0)),
                float(ca.get("bytes accessed", 0.0)),
                float(ma.temp_size_in_bytes))

    common = dict(
        n_params=n_params,
        param_shapes=tuple(tuple(p.data.shape) for p in params),
        param_bytes_fp32=param_bytes,
        half_itemsize=half_itemsize,
        slots_per_param=_optimizer_slots(optimizer),
        batch_ref=_global_batch_of(example_batch),
        batch_bytes_per_example=batch_bytes,
        logits_bytes_per_example=logits_bpe,
        seq_len=seq_len, **info)

    # models whose forward binds mesh axes (tp/sp psums, MoE routing's
    # axis_index, the pipeline stack's stage slicing) cannot lower
    # unsharded — fall back to the analytic 6·N estimate
    if (info["tp_axis"] is not None or info["sp_axis"] is not None
            or info["moe_axis"] is not None
            or info["pp_axis"] is not None):
        tokens = float(seq_len or 1)
        flops_pe = 6.0 * n_params * tokens
        return ModelProfile(
            flops_per_example=flops_pe, flops_fixed=0.0,
            act_bytes_per_example=12.0 * act_itemsize * (
                (info["layers"] or 1) * (info["hidden"] or n_params ** 0.5)
                * tokens) + logits_bpe,
            act_bytes_fixed=0.0,
            hbm_bytes_per_example=flops_pe / 50.0, hbm_bytes_fixed=0.0,
            source="analytic", **common)

    f_lo, h_lo, a_lo = probe(b_lo)
    f_hi, h_hi, a_hi = probe(b_hi)
    db = b_hi - b_lo

    def fit(lo, hi):
        slope = max((hi - lo) / db, 0.0)
        return slope, max(lo - slope * b_lo, 0.0)

    f_s, f_0 = fit(f_lo, f_hi)
    h_s, h_0 = fit(h_lo, h_hi)
    a_s, a_0 = fit(a_lo, a_hi)
    return ModelProfile(
        flops_per_example=f_s, flops_fixed=f_0,
        act_bytes_per_example=a_s, act_bytes_fixed=a_0,
        hbm_bytes_per_example=h_s, hbm_bytes_fixed=h_0,
        source="xla", **common)


# ---------------------------------------------------------------------------
# Plan
# ---------------------------------------------------------------------------

#: remat policy → (keep_frac, recompute_frac).  ``keep_frac`` scales the
#: HBM model's activation term (what survives to the backward);
#: ``recompute_frac`` is the extra forward work as a fraction of the
#: step's total FLOPs, fed back into the roofline.  "selective" is the
#: checkpoint-every-other-boundary policy; "full" re-runs essentially
#: the whole forward from layer boundaries (the 1F1B stack's policy).
REMAT_POLICIES = {
    "none":      (1.0, 0.0),
    "selective": (0.5, 1.0 / 6.0),
    "full":      (0.15, 1.0 / 3.0),
}

#: deterministic tie-break order for the remat axis (lighter first)
_REMAT_ORDER = {"none": 0, "selective": 1, "full": 2}

#: the (offload_opt, offload_act) rungs the joint enumeration crosses
#: with every mesh/remat point: nothing, full optimizer-state offload,
#: and optimizer state + half the activations
OFFLOAD_LADDER = ((0.0, 0.0), (1.0, 0.0), (1.0, 0.5))

#: fraction of the offload transfer that stays exposed even when the
#: executor's h2d overlap is on (the prologue/epilogue of each window
#: cannot hide under compute)
OFFLOAD_EXPOSED_OVERLAPPED = 0.25


@dataclasses.dataclass(frozen=True)
class Plan:
    """One point in the joint (dp × sp × tp × zero × accum × chunked ×
    pp × remat × offload × ep) space, with the cost model's predictions
    attached.  Hashable — the structural part (:meth:`key`) is embedded
    in step-program cache keys so compiled executables are per-plan
    observables."""
    dp: int = 1
    tp: int = 1
    sp: int = 1
    zero_stage: int = 0
    accum: int = 1
    chunked_loss: bool = False
    #: pipeline stages (devices along the pp axis) and microbatches per
    #: step — the pipeline's accumulation unit (pp plans keep accum=1)
    pp: int = 1
    micro: int = 1
    #: activation-checkpoint policy: a :data:`REMAT_POLICIES` key
    remat: str = "none"
    #: expert-parallel degree — rides the dp axis (ep == dp == E, one
    #: expert per device along the model's moe_axis)
    ep: int = 1
    #: host-offload fractions: optimizer state (masters + slots) and
    #: activations moved to host RAM, priced at the measured H2D rate
    offload_opt: float = 0.0
    offload_act: float = 0.0
    dp_axis: str = "data"
    tp_axis: Optional[str] = None
    sp_axis: Optional[str] = None
    pp_axis: Optional[str] = None
    #: heterogeneous pipelines: layers per stage (apportion_shares over
    #: member speeds) and the chip name hosting each stage.  Empty on a
    #: homogeneous pipeline (uniform layers/pp split).
    stage_layers: tuple = ()
    stage_members: tuple = ()
    n_devices: int = 1                   # devices the planner priced for
    predicted_ms: Optional[float] = None
    predicted_hbm: Optional[int] = None
    breakdown: tuple = ()                # ((name, value), ...) — hashable
    collectives: tuple = ()
    measured_ms: Optional[float] = None
    #: calibration-ledger citations: terms whose roofline prior was
    #: replaced by a measured kernel time (strings, for describe())
    ledger_terms: tuple = ()
    #: heterogeneous fleets only: per-device batch shares (ints summing
    #: EXACTLY to the global batch, device order) — the planner's
    #: replacement for the uniform global_batch/dp split.  Empty on a
    #: homogeneous fleet (uniform split applies).
    device_shares: tuple = ()

    def key(self):
        """The structural identity embedded in program cache keys.

        The first six positions are the historical (dp, tp, sp, zero,
        accum, chunked) tuple — a plan using none of the new axes keys
        exactly as it did before, so old checkpoints/manifests and the
        step cache stay valid.  Each non-default new axis appends one
        tagged STRING segment (``"pp4"``, ``"micro8"``,
        ``"remat=selective"``, ``"ep8"``, ``"offopt=1"``,
        ``"offact=0.5"``) that :func:`plan_from_key` parses back."""
        base = (self.dp, self.tp, self.sp, self.zero_stage, self.accum,
                self.chunked_loss)
        extra = []
        if self.pp != 1:
            extra.append(f"pp{self.pp}")
        if self.micro != 1:
            extra.append(f"micro{self.micro}")
        if self.remat != "none":
            extra.append(f"remat={self.remat}")
        if self.ep != 1:
            extra.append(f"ep{self.ep}")
        if self.offload_opt:
            extra.append(f"offopt={self.offload_opt:g}")
        if self.offload_act:
            extra.append(f"offact={self.offload_act:g}")
        return base + tuple(extra)

    @property
    def n_used(self) -> int:
        return self.dp * self.tp * self.sp * self.pp

    def name(self) -> str:
        parts = [f"dp{self.dp}"]
        if self.sp > 1:
            parts.append(f"sp{self.sp}")
        if self.tp > 1:
            parts.append(f"tp{self.tp}")
        if self.pp > 1:
            parts.append(f"pp{self.pp}")
            if self.micro > 1:
                parts.append(f"m{self.micro}")
        if self.ep > 1:
            parts.append(f"ep{self.ep}")
        if self.remat != "none":
            parts.append(f"remat[{self.remat}]")
        if self.offload_opt or self.offload_act:
            parts.append(f"off[opt{self.offload_opt:g}"
                         f"+act{self.offload_act:g}]")
        if self.zero_stage:
            parts.append(f"zero{self.zero_stage}")
        if self.accum > 1:
            parts.append(f"K{self.accum}")
        if self.chunked_loss:
            parts.append("chunked")
        return "·".join(parts)

    def step_kwargs(self, devices=None) -> dict:
        """The existing entry-point knobs this plan threads — the
        planner drives tested primitives, it adds no execution path.

        dp/ZeRO plans map to the GSPMD ``zero_sharding`` path; tp/sp/ep
        plans to the explicit-axis ``shard_map`` path (an ep plan's data
        axis IS the model's moe_axis); pp plans to the pipeline entry
        points — ``make_pipeline_train_step(schedule="1f1b")`` for
        ``remat="full"``, ``make_train_step(tp_axis=<pp axis>)`` (the
        GPipe stack wrap) otherwise."""
        kw = {}
        if self.pp > 1:
            if self.remat == "full":
                kw["schedule"] = "1f1b"      # make_pipeline_train_step
            else:
                kw["tp_axis"] = self.pp_axis or "pp"
            return kw
        if self.accum > 1:
            kw["accum_steps"] = self.accum
        if self.tp == 1 and self.sp == 1 and self.ep == 1:
            if self.dp > 1:
                kw.update(zero_sharding=True, zero_stage=self.zero_stage,
                          zero_axis=self.dp_axis)
                if devices is not None:
                    kw["zero_mesh"] = Mesh(
                        np.array(list(devices)[:self.dp]), (self.dp_axis,))
        else:
            axes = []
            if self.dp > 1:
                axes.append(self.dp_axis)
            if self.sp > 1:
                axes.append(self.sp_axis)
            if axes:
                kw["axis_name"] = axes[0] if len(axes) == 1 else tuple(axes)
            if self.tp > 1:
                kw["tp_axis"] = self.tp_axis
        return kw

    def _fmt_bytes(self, b):
        return f"{b / 2**30:.2f} GiB" if b >= 2**30 else \
            f"{b / 2**20:.1f} MiB"

    def describe(self) -> str:
        bd = dict(self.breakdown)
        mesh = f"mesh dp={self.dp} sp={self.sp} tp={self.tp}"
        if self.pp > 1:
            mesh += f" pp={self.pp}"
        if self.ep > 1:
            mesh += f" ep={self.ep}"
        lines = [
            f"Plan {self.name()}  ({mesh}, "
            f"{self.n_used} of {self.n_devices} devices, "
            f"ZeRO stage {self.zero_stage}, accum K={self.accum}, "
            f"chunked_loss={'on' if self.chunked_loss else 'off'})"]
        if self.predicted_ms is not None:
            lines.append(f"  predicted {self.predicted_ms:.3f} ms/step"
                         + (f" (measured {self.measured_ms:.3f})"
                            if self.measured_ms is not None else ""))
            lines.append(
                "  time: compute {:.3f} + hbm {:.3f} (roofline max) "
                "+ collectives {:.3f} + overhead {:.3f} ms".format(
                    bd.get("compute_ms", 0.0), bd.get("hbm_ms", 0.0),
                    bd.get("collective_ms", 0.0),
                    bd.get("overhead_ms", 0.0)))
        if self.pp > 1:
            sched = "1F1B" if self.remat == "full" else "GPipe"
            ticks = int(bd.get("pp_ticks",
                               self.micro + 2 * (self.pp - 1)))
            frac = bd.get("bubble_frac",
                          2.0 * (self.pp - 1) / max(ticks, 1))
            lines.append(
                f"  pipeline: {self.pp} stages × {self.micro} "
                f"microbatches ({sched} schedule), {ticks} ticks/step, "
                f"bubble fraction {frac:.1%}")
            if self.stage_layers:
                members = self.stage_members or ("?",) * len(
                    self.stage_layers)
                lines.append("  stage placement: " + "; ".join(
                    f"stage {i} → {m} ({l} layer"
                    + ("s" if l != 1 else "") + ")"
                    for i, (l, m) in enumerate(
                        zip(self.stage_layers, members))))
        if self.remat != "none":
            keep, rec = REMAT_POLICIES[self.remat]
            gf = bd.get("recompute_gflops", 0.0)
            lines.append(
                f"  remat[{self.remat}]: keep {keep:.0%} of activations"
                f", recompute {gf:.2f} GFLOP/step "
                f"(+{rec:.0%} of step FLOPs re-run in the backward)")
        if self.offload_opt or self.offload_act:
            traffic = bd.get("offload_bytes", 0)
            lines.append(
                f"  offload: optimizer state {self.offload_opt:.0%} "
                f"(host {self._fmt_bytes(bd.get('host_opt_bytes', 0))}), "
                f"activations {self.offload_act:.0%} "
                f"(host {self._fmt_bytes(bd.get('host_act_bytes', 0))}) "
                f"— offload bytes {self._fmt_bytes(traffic)}/step over "
                f"H2D/D2H, {bd.get('offload_ms', 0.0):.3f} ms exposed")
        if self.ep > 1:
            lines.append(
                f"  expert parallel: ep={self.ep} (one expert per "
                f"device along {self.dp_axis!r}; dispatch/combine "
                f"all-to-all priced per routed block)")
        if self.device_shares:
            lines.append(
                "  device batch shares: ["
                + ", ".join(str(s) for s in self.device_shares)
                + "] (heterogeneous fleet — slowest-member bound; "
                "shares sum to the global batch)")
        if self.ledger_terms:
            lines.append("  calibration-ledger re-priced terms "
                         "(measured, not roofline priors):")
            for t in self.ledger_terms:
                lines.append(f"    {t}")
        if self.predicted_hbm is not None:
            mem = " + ".join(
                f"{k[4:]} {self._fmt_bytes(v)}"
                for k, v in self.breakdown if k.startswith("mem_"))
            unit = ("per-stage HBM (largest stage)" if self.pp > 1
                    else "predicted HBM")
            lines.append(f"  {unit} "
                         f"{self._fmt_bytes(self.predicted_hbm)}"
                         f"/device = {mem}")
        if self.collectives:
            lines.append("  collectives: " + "; ".join(self.collectives))
        else:
            lines.append("  collectives: none (single-device program)")
        kw = self.step_kwargs()
        if kw:
            lines.append("  knobs: " + ", ".join(
                f"{k}={v!r}" for k, v in kw.items()))
        if self.chunked_loss:
            lines.append(
                "  note: priced with the chunked LM head+loss "
                "(contrib.chunked_lm_loss) — the plan does not swap your "
                "loss_fn; see docs/auto_parallel.md")
        return "\n".join(lines)


def static_plan_key(plan):
    """Hashable normalization used by the step-program cache keys (re-
    exported by runtime.step_cache); None passes through for unplanned
    steps."""
    return None if plan is None else plan.key()


#: tagged plan-key segments: prefix → (Plan field, parser).  The
#: ordering here is the canonical emission order of :meth:`Plan.key`.
_KEY_SEGMENTS = (
    ("pp", "pp", int),
    ("micro", "micro", int),
    ("remat=", "remat", str),
    ("ep", "ep", int),
    ("offopt=", "offload_opt", float),
    ("offact=", "offload_act", float),
)


def plan_from_key(key, n_devices: int = 1) -> Plan:
    """Rebuild a structural :class:`Plan` from a saved manifest key —
    the inverse of :meth:`Plan.key` for the structural fields (cost-model
    predictions are not identity and come back unset).  The elastic
    restore path uses this to describe the plan a schema-2 checkpoint
    was saved under (``manifest["plan"]["key"]``).

    Unknown segments are an ERROR, not silently dropped: a manifest
    written by a newer planner names an axis this build cannot honor,
    and guessing would restore under the wrong plan."""
    key = tuple(key)
    if len(key) < 6:
        raise ValueError(
            f"plan key {key!r} is malformed: the first six segments "
            f"must be (dp, tp, sp, zero_stage, accum, chunked_loss)")
    dp, tp, sp, zero_stage, accum, chunked_loss = key[:6]
    kw = {}
    known = [p for p, _, _ in _KEY_SEGMENTS]
    for seg in key[6:]:
        if not isinstance(seg, str):
            raise ValueError(
                f"unknown plan-key segment {seg!r}: extended segments "
                f"are tagged strings with one of the prefixes {known}")
        for prefix, field, parse in _KEY_SEGMENTS:
            if seg.startswith(prefix):
                if field in kw:
                    raise ValueError(
                        f"plan key {key!r} repeats the {field!r} "
                        f"segment ({seg!r})")
                try:
                    kw[field] = parse(seg[len(prefix):])
                except ValueError:
                    raise ValueError(
                        f"plan-key segment {seg!r}: the {field!r} "
                        f"value {seg[len(prefix):]!r} does not parse "
                        f"as {parse.__name__}")
                break
        else:
            raise ValueError(
                f"unknown plan-key segment {seg!r}: this planner "
                f"recognizes no such field (known segment prefixes: "
                f"{known})")
    if kw.get("remat", "none") not in REMAT_POLICIES:
        raise ValueError(
            f"plan-key segment remat={kw['remat']!r}: unknown remat "
            f"policy (known: {sorted(REMAT_POLICIES)})")
    return Plan(dp=int(dp), tp=int(tp), sp=int(sp),
                zero_stage=int(zero_stage), accum=int(accum),
                chunked_loss=bool(chunked_loss), n_devices=int(n_devices),
                **kw)


# ---------------------------------------------------------------------------
# Cost model: memory feasibility + roofline step time
# ---------------------------------------------------------------------------

#: chunked LM loss default chunk count: the working-set divisor the
#: memory lever is priced at (contrib's default chunking)
CHUNKS = 8

#: fraction of HBM the planner refuses to plan into (XLA scratch,
#: fragmentation, the runtime's own buffers)
HBM_RESERVE = 0.08


def _zero_shard_bytes(prof: ModelProfile, itemsize: int, n: int) -> int:
    """Exact per-tensor ZeRO sharding: dim-0-divisible tensors shard n
    ways, the rest stay replicated (zero.py's `_leaf_sharding` rule)."""
    total = 0
    for shape in prof.param_shapes:
        b = int(np.prod(shape)) * itemsize
        if n > 1 and shape and shape[0] >= n and shape[0] % n == 0:
            b //= n
        total += b
    return total


def _param_scale(plan: Plan, prof: ModelProfile) -> float:
    """Fraction of the parameter state one device holds under the
    plan's pipeline-stage slice and expert sharding (before ZeRO, which
    :func:`_zero_shard_bytes` handles per-tensor)."""
    scale = 1.0
    if plan.pp > 1:
        if plan.stage_layers:
            scale *= max(plan.stage_layers) / max(sum(plan.stage_layers),
                                                  1)
        else:
            scale *= 1.0 / plan.pp
    if plan.ep > 1 and prof.moe_param_frac:
        # expert weights shard one-per-device; the dense remainder is
        # replicated along the (ep == dp) axis
        scale *= ((1.0 - prof.moe_param_frac)
                  + prof.moe_param_frac / plan.ep)
    return scale


def _pp_boundary_bytes(plan: Plan, prof: ModelProfile,
                       micro_b: float) -> float:
    """One microbatch's stage-boundary activation (the tensor ppermute
    hops stage-to-stage): hidden × seq when the profile knows the
    geometry, else one layer's share of the activation slope."""
    act_itemsize = prof.half_itemsize or 4
    if prof.hidden and prof.seq_len:
        return float(prof.hidden) * prof.seq_len * micro_b * act_itemsize
    return (prof.act_bytes_per_example * micro_b
            / max(prof.layers or plan.pp, 1))


def predict_memory(plan: Plan, prof: ModelProfile, spec: ChipSpec,
                   global_batch: int):
    """Per-device steady-state training footprint: returns
    ``(total_bytes, breakdown)`` with one entry per component.

    v3 axes: pipeline plans hold one STAGE's parameter state plus the
    schedule's in-flight microbatch activations (GPipe keeps every
    tick's residuals; 1F1B — ``remat="full"`` — keeps a ring of
    boundary inputs and recomputes internals); ``remat`` scales the
    surviving activation term by its keep-fraction; ``offload`` moves
    optimizer state / activations to host RAM (reported as ``host_*``
    breakdown entries, not HBM); ``ep`` shards the expert slice of the
    parameter state one-per-device."""
    pscale = _param_scale(plan, prof)
    keep_frac, _rec = REMAT_POLICIES[plan.remat]
    shard_n = plan.dp if plan.zero_stage >= 1 else 1
    masters_full = _zero_shard_bytes(prof, 4, shard_n) * pscale
    opt_full = (1 + prof.slots_per_param) * masters_full
    masters = masters_full * (1.0 - plan.offload_opt)
    slots = prof.slots_per_param * masters_full * (1.0 - plan.offload_opt)
    host_opt = opt_full * plan.offload_opt
    half = 0
    if prof.half_itemsize:
        half = _zero_shard_bytes(
            prof, prof.half_itemsize,
            plan.dp if plan.zero_stage == 3 else 1) * pscale
    # gradient carry/working set, per path: the K>1 scan holds a full
    # replicated fp32 accumulator; a K=1 ZeRO program's gradients land
    # reduce-scattered (per-device 1/dp); a stage-0 all-reduce holds
    # grad + collective double buffer; single-device holds one grad set.
    # Gradients are NEVER offloaded: they are produced and consumed
    # inside one step, so a host round-trip would serialize the update.
    if plan.accum > 1:
        # window accumulator + the per-microbatch gradient it adds
        grads = 2 * prof.param_bytes_fp32 * pscale
    elif plan.zero_stage >= 1 and plan.dp > 1:
        # reduce-scattered shards, double-buffered through the collective
        grads = 2 * _zero_shard_bytes(prof, 4, plan.dp) * pscale
    elif plan.dp > 1 or plan.pp > 1:
        # full grads + the collective double buffer (dp all-reduce, or
        # the pipeline's stage-grad assembly psum)
        grads = 2 * prof.param_bytes_fp32 * pscale
    else:
        grads = prof.param_bytes_fp32 * pscale
    micro_b = global_batch / (plan.dp * plan.accum * plan.micro)
    tp_act = (1.0 + 1.0 / plan.tp) / 2.0   # sharded FFN/heads, full residual
    acts = (prof.act_bytes_per_example * micro_b / plan.sp * tp_act
            + prof.act_bytes_fixed)
    if plan.chunked_loss and prof.logits_bytes_per_example:
        acts -= (prof.logits_bytes_per_example * micro_b / plan.sp
                 * (1.0 - 1.0 / CHUNKS))
        acts = max(acts, 0.0)
    if plan.pp > 1:
        stage_frac = (max(plan.stage_layers) / max(sum(plan.stage_layers),
                                                   1)
                      if plan.stage_layers else 1.0 / plan.pp)
        internals = acts * stage_frac * keep_frac
        boundary = _pp_boundary_bytes(plan, prof, micro_b)
        if plan.remat == "full":
            # 1F1B: one microbatch's internals live (recomputed in the
            # backward), boundary inputs in the schedule's ring buffer
            from .pipeline import ring_slots
            acts = internals + boundary * ring_slots(plan.pp, plan.micro)
        else:
            # GPipe scan: the transpose keeps every tick's residuals
            inflight = plan.micro + plan.pp - 1
            acts = (internals + boundary) * inflight
    else:
        acts *= keep_frac
    host_act = acts * plan.offload_act
    acts -= host_act
    batch = prof.batch_bytes_per_example * global_batch / plan.dp / plan.sp
    bd = [("mem_masters", int(masters)), ("mem_slots", int(slots)),
          ("mem_half", int(half)), ("mem_grads", int(grads)),
          ("mem_acts", int(acts)), ("mem_batch", int(batch))]
    if host_opt or host_act:
        # host_* entries are NOT "mem_"-prefixed: they live in host RAM,
        # outside the per-device HBM sum describe() reports
        bd.append(("host_opt_bytes", int(host_opt)))
        bd.append(("host_act_bytes", int(host_act)))
    return (int(masters + slots + half + grads + acts + batch), bd)


def _ring_all_reduce_s(bytes_, n, spec):
    if n <= 1 or bytes_ <= 0:
        return 0.0
    return 2 * (n - 1) / n * bytes_ / spec.ici_bw \
        + 2 * (n - 1) * spec.ici_latency_s


def _ring_half_s(bytes_, n, spec):
    """One reduce-scatter OR all-gather pass."""
    if n <= 1 or bytes_ <= 0:
        return 0.0
    return (n - 1) / n * bytes_ / spec.ici_bw + (n - 1) * spec.ici_latency_s


def _dp_collective_terms(plan: Plan, prof: ModelProfile, spec: ChipSpec,
                         w_itemsize: int, param_scale: float = 1.0):
    """The dp-axis collective terms (stage-0 grad all-reduce, or the
    ZeRO reduce-scatter / param all-gather pair, plus the stage-3
    per-microbatch gather with the executor's prefetch overlap).
    Shared between :func:`predict_time` and :func:`predict_time_fleet`
    — the fleet path hands in a slowest-link spec so every collective
    is priced at the weakest interconnect in the ring.  ``param_scale``
    shrinks the exchanged gradient/parameter bytes for plans whose
    per-device parameter state is a slice (pipeline stage, expert
    shard)."""
    coll_s, colls = 0.0, []
    gbytes = prof.param_bytes_fp32 * param_scale
    if plan.dp > 1:
        if plan.zero_stage == 0:
            coll_s += _ring_all_reduce_s(gbytes, plan.dp, spec)
            colls.append(f"all-reduce fp32 grads ({_mib(gbytes)}) over "
                         f"{plan.dp_axis}({plan.dp}) at the window boundary")
        else:
            coll_s += _ring_half_s(gbytes, plan.dp, spec)
            colls.append(f"reduce-scatter fp32 grads ({_mib(gbytes)}) into "
                         f"master shards over {plan.dp_axis}({plan.dp})")
            ag = prof.n_params * w_itemsize * param_scale
            coll_s += _ring_half_s(ag, plan.dp, spec)
            colls.append(f"all-gather updated params ({_mib(ag)}) over "
                         f"{plan.dp_axis}({plan.dp})")
        if plan.zero_stage == 3:
            from ..runtime import executor as _executor
            ag1 = prof.n_params * w_itemsize * param_scale
            ag3 = plan.accum * ag1
            if plan.accum > 1 and _executor.overlap_enabled("gather"):
                # executor gather prefetch: the scanned window issues
                # microbatch i+1's param gather under microbatch i's
                # compute, so only the prologue gather stays exposed
                coll_s += _ring_half_s(ag1, plan.dp, spec)
                colls.append(
                    f"per-microbatch param all-gather (stage 3, "
                    f"K×{_mib(ag1)} = {_mib(ag3)}/step; prefetch "
                    f"overlaps all but the prologue gather)")
            else:
                coll_s += plan.accum * _ring_half_s(ag1, plan.dp, spec)
                colls.append(f"per-microbatch param all-gather (stage 3, "
                             f"K×{_mib(ag1)} = "
                             f"{_mib(ag3)}/step)")
    return coll_s, colls


def _moe_a2a_terms(plan: Plan, prof: ModelProfile, spec: ChipSpec,
                   micro_b: float, micro_n: int):
    """The expert-parallel dispatch/combine all-to-all: per routed block
    the forward sends each token's hidden vector to its expert's device
    and gathers the result back (2 exchanges), and the backward mirrors
    both (4 total), each moving the (ep-1)/ep off-device fraction of the
    capacity-scaled token buffer."""
    act_itemsize = prof.half_itemsize or 4
    tokens = micro_b * float(prof.seq_len or 1)
    xfer = (tokens * float(prof.hidden or 1) * act_itemsize
            * prof.moe_capacity_factor)
    per_a2a = ((plan.ep - 1) / plan.ep * xfer / spec.ici_bw
               + (plan.ep - 1) * spec.ici_latency_s)
    n_a2a = 4 * prof.moe_layers * micro_n
    coll_s = n_a2a * per_a2a
    desc = (f"MoE dispatch/combine all-to-all ({_mib(xfer)}/exchange × "
            f"{n_a2a}: 4 per routed block × {prof.moe_layers} blocks × "
            f"{micro_n} microbatches) over {plan.dp_axis}({plan.ep})")
    return coll_s, desc


def predict_time(plan: Plan, prof: ModelProfile, spec: ChipSpec,
                 global_batch: int):
    """Roofline step time: ``max(compute, HBM) + collectives + overhead``.
    Returns ``(ms, breakdown, collectives)``.

    v3 axes: ``remat`` adds its recompute FLOPs (and the matching HBM
    re-reads) to the roofline; ``pp`` applies the warmup/drain bubble
    multiplier over the microbatch schedule plus the stage-boundary
    ppermutes and stage-grad assembly; ``offload`` adds the exposed
    fraction of the host round-trip priced at the executor's measured
    H2D bandwidth (``spec.h2d_bw`` prior); ``ep`` adds the MoE
    dispatch/combine all-to-all per routed block."""
    n_used = plan.n_used
    micro_n = plan.accum * plan.micro      # microbatches per step
    micro_b = global_batch / (plan.dp * micro_n)
    act_itemsize = prof.half_itemsize or 4
    w_itemsize = prof.half_itemsize or 4
    keep_frac, rec_frac = REMAT_POLICIES[plan.remat]
    pscale = _param_scale(plan, prof)

    base_flops = (prof.flops_per_example * global_batch / n_used
                  + micro_n * prof.flops_fixed)
    flops = base_flops * (1.0 + rec_frac)
    # virtual devices split one host: per-plan sustained rate is the
    # host's, not n_used × the host's
    sustained = spec.sustained_flops() / (n_used if spec.shared_host else 1)
    compute_s = flops / sustained

    weight_traffic = (micro_n * prof.n_params * w_itemsize * pscale
                      / plan.tp)
    if plan.zero_stage == 3:
        weight_traffic /= plan.dp
    hbm_bytes = ((prof.hbm_bytes_per_example * global_batch / n_used)
                 * (1.0 + rec_frac)
                 + micro_n * prof.hbm_bytes_fixed + weight_traffic)
    if plan.chunked_loss and prof.logits_bytes_per_example:
        hbm_bytes -= (prof.logits_bytes_per_example * global_batch / n_used
                      * (1.0 - 1.0 / CHUNKS))
    hbm_bw = spec.hbm_bw / (n_used if spec.shared_host else 1)
    hbm_s = max(hbm_bytes, 0.0) / hbm_bw

    extra_bd = []
    if plan.remat != "none":
        extra_bd.append(("recompute_gflops",
                         base_flops * rec_frac / 1e9))
    if plan.pp > 1:
        # warmup/drain bubble: (pp-1) fill ticks before the first and
        # after the last full microbatch — both schedules pay it
        bubble_mult = (plan.micro + plan.pp - 1) / plan.micro
        compute_s *= bubble_mult
        hbm_s *= bubble_mult
        ticks = plan.micro + 2 * (plan.pp - 1)
        extra_bd.append(("pp_ticks", float(ticks)))
        extra_bd.append(("bubble_frac",
                         (plan.pp - 1) / (plan.micro + plan.pp - 1)))

    coll_s, colls = _dp_collective_terms(plan, prof, spec, w_itemsize,
                                         param_scale=pscale)
    if plan.pp > 1:
        boundary = _pp_boundary_bytes(plan, prof, micro_b)
        hop_s = boundary / spec.ici_bw + spec.ici_latency_s
        # one fwd send + one bwd send per microbatch per stage boundary
        coll_s += 2 * plan.micro * hop_s
        colls.append(f"stage-boundary ppermute ({_mib(boundary)}/hop, "
                     f"2×{plan.micro} hops/step) over "
                     f"{plan.pp_axis or 'pp'}({plan.pp})")
        gb_stage = prof.param_bytes_fp32 * pscale
        coll_s += _ring_all_reduce_s(prof.param_bytes_fp32, plan.pp, spec)
        colls.append(f"stage-grad assembly psum ({_mib(gb_stage)} live "
                     f"of {_mib(prof.param_bytes_fp32)} stacked) over "
                     f"{plan.pp_axis or 'pp'}({plan.pp})")
    if plan.ep > 1 and prof.moe_layers:
        a2a_s, a2a_desc = _moe_a2a_terms(plan, prof, spec, micro_b,
                                         micro_n)
        coll_s += a2a_s
        colls.append(a2a_desc)
    gbytes = prof.param_bytes_fp32
    if plan.tp > 1:
        if prof.layers and prof.hidden and prof.seq_len:
            per_micro = (4.0 * prof.layers * micro_b * prof.seq_len
                         / plan.sp * prof.hidden * act_itemsize)
        else:
            per_micro = 0.5 * prof.act_bytes_per_example * micro_b
        tp_bytes = plan.accum * per_micro
        coll_s += plan.accum * _ring_all_reduce_s(per_micro, plan.tp, spec)
        colls.append(f"activation all-reduce (row-parallel psum, "
                     f"{_mib(tp_bytes)}/step) over "
                     f"{plan.tp_axis or 'tp'}({plan.tp})")
        shard_grads = 0.66 * gbytes     # head/FFN block fraction
        coll_s += _ring_all_reduce_s(shard_grads, plan.tp, spec)
        colls.append(f"block-sparse grad assembly psum "
                     f"({_mib(shard_grads)}) over "
                     f"{plan.tp_axis or 'tp'}({plan.tp})")
    if plan.sp > 1:
        if prof.layers and prof.hidden and prof.seq_len:
            kv = (2.0 * prof.layers * micro_b * prof.seq_len
                  * prof.hidden * act_itemsize)
        else:
            kv = 0.3 * prof.act_bytes_per_example * micro_b
        coll_s += plan.accum * _ring_all_reduce_s(kv, plan.sp, spec)
        colls.append(f"ring ppermute of K/V blocks ({_mib(kv)}/microbatch) "
                     f"over {plan.sp_axis or 'sp'}({plan.sp})")
        coll_s += _ring_all_reduce_s(gbytes, plan.sp, spec)
        colls.append(f"all-reduce fp32 grads ({_mib(gbytes)}) over "
                     f"{plan.sp_axis or 'sp'}({plan.sp})")

    offload_s = 0.0
    if plan.offload_opt or plan.offload_act:
        from ..runtime import executor as _executor
        _, mem_bd = predict_memory(plan, prof, spec, global_batch)
        md = dict(mem_bd)
        # optimizer state rides host→device and back once per step;
        # activations go device→host in the forward, back in the
        # backward — 2× each component's resident host bytes
        host_traffic = 2 * (md.get("host_opt_bytes", 0)
                            + md.get("host_act_bytes", 0))
        h2d_bw = _executor.measured_h2d_bw() or spec.h2d_bw
        transfer_s = host_traffic / h2d_bw
        exposed = (OFFLOAD_EXPOSED_OVERLAPPED
                   if _executor.overlap_enabled("h2d") else 1.0)
        offload_s = transfer_s * exposed
        extra_bd.append(("offload_bytes", float(host_traffic)))
        extra_bd.append(("offload_ms", offload_s * 1e3))

    overhead_s = micro_n * spec.overhead_s
    total_s = max(compute_s, hbm_s) + coll_s + overhead_s + offload_s
    bd = [("compute_ms", compute_s * 1e3), ("hbm_ms", hbm_s * 1e3),
          ("collective_ms", coll_s * 1e3),
          ("overhead_ms", overhead_s * 1e3)] + extra_bd
    return total_s * 1e3, bd, colls


def predict_time_fleet(plan: Plan, prof: ModelProfile, fleet: Fleet,
                       global_batch: int, shares=None):
    """Slowest-member roofline for a heterogeneous fleet (AMP
    arXiv:2210.07297, Poplar arXiv:2408.12596): every member computes
    its batch SHARE, the step completes when the slowest member does,
    and collectives run at the weakest link in the ring.

    ``shares`` defaults to :func:`apportion_shares` proportional to each
    member's sustained rate; pass an explicit tuple (e.g. a uniform
    split) to price an alternative assignment — the mixed-fleet tier-1
    test prices both and pins that their predicted order matches the
    measured order on the CPU mesh.

    Returns ``(ms, breakdown, collectives, shares)``.  Fleet plans are
    dp-only (``_structural_reject`` enforces it), so only the dp
    collective terms appear.
    """
    n_used = plan.n_used
    specs = fleet.specs[:n_used]
    if len(specs) < n_used:
        raise ValueError(f"plan {plan.name()} needs {n_used} devices, "
                         f"fleet has {fleet.n_devices}")
    if plan.pp > 1:
        return _predict_time_fleet_pp(plan, prof, fleet, global_batch)
    if shares is None:
        shares = apportion_shares(
            [s.sustained_flops() for s in specs], global_batch)
    shares = tuple(int(s) for s in shares)
    if len(shares) != n_used or sum(shares) != global_batch:
        raise ValueError(
            f"device shares {shares} must have {n_used} entries summing "
            f"to the global batch {global_batch}")
    w_itemsize = prof.half_itemsize or 4

    # each member's roofline at its share; the step is bound by the
    # slowest member (max over members), not the mean
    bound_s, bound_i, bound_compute, bound_hbm = 0.0, 0, 0.0, 0.0
    for i, (spec, share) in enumerate(zip(specs, shares)):
        div = n_used if spec.shared_host else 1
        flops = (prof.flops_per_example * share
                 + plan.accum * prof.flops_fixed)
        compute_s = flops / (spec.sustained_flops() / div)
        weight_traffic = plan.accum * prof.n_params * w_itemsize
        if plan.zero_stage == 3:
            weight_traffic /= plan.dp
        hbm_bytes = (prof.hbm_bytes_per_example * share
                     + plan.accum * prof.hbm_bytes_fixed + weight_traffic)
        if plan.chunked_loss and prof.logits_bytes_per_example:
            hbm_bytes -= (prof.logits_bytes_per_example * share
                          * (1.0 - 1.0 / CHUNKS))
        hbm_s = max(hbm_bytes, 0.0) / (spec.hbm_bw / div)
        member_s = max(compute_s, hbm_s)
        if member_s > bound_s:
            bound_s, bound_i = member_s, i
            bound_compute, bound_hbm = compute_s, hbm_s

    # collectives at the slowest link: min bandwidth, max latency
    link = dataclasses.replace(
        fleet.slowest(),
        ici_bw=min(s.ici_bw for s in specs),
        ici_latency_s=max(s.ici_latency_s for s in specs))
    coll_s, colls = _dp_collective_terms(plan, prof, link, w_itemsize)
    if fleet.heterogeneous and coll_s > 0:
        colls.append(f"(all collectives priced at the slowest link: "
                     f"{link.ici_bw / 1e9:.1f} GB/s, "
                     f"{link.ici_latency_s * 1e6:.0f} us/hop)")

    overhead_s = plan.accum * max(s.overhead_s for s in specs)
    total_s = bound_s + coll_s + overhead_s
    bd = [("compute_ms", bound_compute * 1e3), ("hbm_ms", bound_hbm * 1e3),
          ("collective_ms", coll_s * 1e3),
          ("overhead_ms", overhead_s * 1e3),
          ("bound_member", float(bound_i))]
    return total_s * 1e3, bd, colls, shares


def _predict_time_fleet_pp(plan: Plan, prof: ModelProfile, fleet: Fleet,
                           global_batch: int):
    """Heterogeneous pipeline pricing: stage ``i`` lives on fleet member
    ``i`` with :attr:`Plan.stage_layers` layers (apportioned to member
    speed), every microbatch visits every stage, and the steady-state
    tick rate is set by the SLOWEST member's stage time — the pipeline
    analogue of the slowest-member roofline."""
    pp = plan.pp
    specs = fleet.specs[:pp]
    layers = (plan.stage_layers if plan.stage_layers
              else (1,) * pp)
    total_layers = max(sum(layers), 1)
    micro_n = plan.micro
    micro_b = global_batch / max(micro_n, 1)
    w_itemsize = prof.half_itemsize or 4
    _keep, rec_frac = REMAT_POLICIES[plan.remat]

    bound_s, bound_i, bound_compute, bound_hbm = 0.0, 0, 0.0, 0.0
    for i, spec_i in enumerate(specs):
        frac = layers[i] / total_layers
        div = pp if spec_i.shared_host else 1
        flops = ((prof.flops_per_example * global_batch
                  + micro_n * prof.flops_fixed) * frac
                 * (1.0 + rec_frac))
        compute_s = flops / (spec_i.sustained_flops() / div)
        weight_traffic = (micro_n * prof.n_params * w_itemsize * frac)
        hbm_bytes = ((prof.hbm_bytes_per_example * global_batch
                      * (1.0 + rec_frac)
                      + micro_n * prof.hbm_bytes_fixed) * frac
                     + weight_traffic)
        hbm_s = max(hbm_bytes, 0.0) / (spec_i.hbm_bw / div)
        member_s = max(compute_s, hbm_s)
        if member_s > bound_s:
            bound_s, bound_i = member_s, i
            bound_compute, bound_hbm = compute_s, hbm_s

    # the slowest stage paces every tick; warmup/drain bubbles add
    # (pp-1) of its tick times on top of the micro_n steady ticks
    bubble_mult = (micro_n + pp - 1) / max(micro_n, 1)
    step_s = bound_s * bubble_mult

    link = dataclasses.replace(
        fleet.slowest(),
        ici_bw=min(s.ici_bw for s in specs),
        ici_latency_s=max(s.ici_latency_s for s in specs))
    boundary = _pp_boundary_bytes(plan, prof, micro_b)
    hop_s = boundary / link.ici_bw + link.ici_latency_s
    coll_s = 2 * micro_n * hop_s
    colls = [f"stage-boundary ppermute ({_mib(boundary)}/hop, "
             f"2×{micro_n} hops/step) over {plan.pp_axis or 'pp'}({pp}) "
             f"at the slowest link"]
    coll_s += _ring_all_reduce_s(prof.param_bytes_fp32, pp, link)
    colls.append(f"stage-grad assembly psum "
                 f"({_mib(prof.param_bytes_fp32)} stacked) over "
                 f"{plan.pp_axis or 'pp'}({pp})")

    overhead_s = micro_n * max(s.overhead_s for s in specs)
    total_s = step_s + coll_s + overhead_s
    bd = [("compute_ms", bound_compute * bubble_mult * 1e3),
          ("hbm_ms", bound_hbm * bubble_mult * 1e3),
          ("collective_ms", coll_s * 1e3),
          ("overhead_ms", overhead_s * 1e3),
          ("bound_member", float(bound_i)),
          ("stage_ms_bound", bound_s * 1e3),
          ("pp_ticks", float(micro_n + 2 * (pp - 1))),
          ("bubble_frac", (pp - 1) / (micro_n + pp - 1))]
    return total_s * 1e3, bd, colls, ()


def _mib(b):
    return f"{b / 2**20:.1f} MiB"


# ---------------------------------------------------------------------------
# Calibration-ledger re-pricing (apex_tpu.kernels.ledger)
# ---------------------------------------------------------------------------


def model_fp(prof: ModelProfile, global_batch: int) -> str:
    """The ledger's model-shape fingerprint: what makes two training
    runs "the same workload" for plan-measurement reuse.  Built with the
    same :func:`~apex_tpu.kernels.dispatch.shape_fp` helper the kernel
    probes use, so one canonicalization serves both ledger sections."""
    from ..kernels.dispatch import shape_fp
    return shape_fp(params=int(prof.n_params),
                    layers=int(prof.layers or 0),
                    hidden=int(prof.hidden or 0),
                    heads=int(prof.heads or 0),
                    seq=int(prof.seq_len or 0),
                    vocab=int(prof.vocab or 0),
                    batch=int(global_batch))


def _opt_kernel_name(optimizer) -> Optional[str]:
    """Which registered multi-tensor kernel prices this optimizer's
    update step (None: no registered kernel — priors keep deciding)."""
    try:
        from ..optimizers import FusedAdam, FusedSGD
    except Exception:
        return None
    if isinstance(optimizer, FusedAdam):
        return "multi_tensor_adam"
    if isinstance(optimizer, FusedSGD):
        return "multi_tensor_sgd"
    return None


def _plan_attention_fp(plan: Plan, prof: ModelProfile,
                       global_batch: int) -> Optional[str]:
    """The per-device attention-call fingerprint this plan would hand to
    ``decide("flash_attention", ...)``: micro-batch rows, heads, the
    sp-sharded query chunk against full keys, head dim."""
    if not (prof.layers and prof.heads and prof.hidden and prof.seq_len):
        return None
    if prof.hidden % prof.heads:
        return None
    from ..kernels.dispatch import attention_fp
    micro_b = max(int(global_batch // (plan.dp * plan.accum)), 1)
    dt = "bfloat16" if prof.half_itemsize == 2 else "float32"
    return attention_fp(micro_b, prof.heads,
                        prof.seq_len // max(plan.sp, 1), prof.seq_len,
                        prof.hidden // prof.heads, dtype=dt, causal=True)


def _ledger_reprice(plan: Plan, prof: ModelProfile, spec: ChipSpec,
                    global_batch: int, chip: str,
                    opt_kernel: Optional[str]) -> Plan:
    """Swap the roofline's attention and optimizer terms for
    ledger-measured kernel times when the calibration ledger holds an
    entry for this chip and the plan's exact shapes.

    The adjustment is a delta — ``predicted_ms += measured − prior`` —
    against the analytic estimate of the same term (attention FLOPs at
    the sustained rate; the optimizer's read/modify/write HBM traffic at
    bandwidth), so an empty ledger changes nothing and a measurement
    shifts only the term it covers.  Citations land in
    :attr:`Plan.ledger_terms` for ``describe()``.
    """
    try:
        from ..kernels import ledger as _kl
        from ..kernels.dispatch import multi_tensor_fp
        led = _kl.get_ledger()
    except Exception:
        return plan
    terms, delta_ms = [], 0.0
    n_used = plan.n_used
    sustained = spec.sustained_flops() / (n_used if spec.shared_host else 1)
    hbm_bw = spec.hbm_bw / (n_used if spec.shared_host else 1)
    micro_b = max(int(global_batch // (plan.dp * plan.accum)), 1)

    afp = _plan_attention_fp(plan, prof, global_batch)
    if afp is not None:
        rec = led.lookup_kernel(chip, "flash_attention", afp)
        if rec is not None:
            tier = "pallas" if rec["win"] >= 1.0 else "xla"
            per_call_us = rec["pallas_us" if tier == "pallas" else "xla_us"]
            calls = prof.layers * plan.accum
            measured_ms = per_call_us * 1e-3 * calls
            sq = prof.seq_len // max(plan.sp, 1)
            d = prof.hidden // prof.heads
            # fwd 2 matmuls of 2·b·h·sq·sk·d each, bwd ≈ 2× fwd
            attn_flops = (12.0 * calls * micro_b * prof.heads * sq
                          * prof.seq_len * d)
            prior_ms = attn_flops / sustained * 1e3
            delta_ms += measured_ms - prior_ms
            terms.append(
                f"attention {measured_ms:.3f} ms/step ledger-measured "
                f"(flash_attention[{afp}] {per_call_us:.1f}us/call, "
                f"{tier} tier, win {rec['win']:.2f}x, x{calls} calls; "
                f"roofline prior {prior_ms:.3f} ms)")
    if opt_kernel is not None:
        ofp = multi_tensor_fp(opt_kernel.replace("multi_tensor_", ""),
                              prof.n_params, len(prof.param_shapes))
        rec = led.lookup_kernel(chip, opt_kernel, ofp)
        if rec is not None:
            tier = "pallas" if rec["win"] >= 1.0 else "xla"
            per_us = rec["pallas_us" if tier == "pallas" else "xla_us"]
            shard = plan.dp if (plan.zero_stage >= 1 and plan.dp > 1) else 1
            measured_ms = per_us * 1e-3 / shard
            # read masters+slots+grads, write masters+slots — the
            # bandwidth-bound analytic estimate of the update sweep
            opt_bytes = ((3 + 2 * prof.slots_per_param)
                         * prof.param_bytes_fp32 / shard)
            prior_ms = opt_bytes / hbm_bw * 1e3
            delta_ms += measured_ms - prior_ms
            terms.append(
                f"optimizer {measured_ms:.3f} ms/step ledger-measured "
                f"({opt_kernel}[{ofp}] {per_us:.1f}us, {tier} tier, "
                f"win {rec['win']:.2f}x"
                + (f", /{shard} ZeRO shards" if shard > 1 else "")
                + f"; roofline prior {prior_ms:.3f} ms)")
    if not terms:
        return plan
    return dataclasses.replace(
        plan, predicted_ms=max(plan.predicted_ms + delta_ms, 1e-3),
        ledger_terms=tuple(terms))


# ---------------------------------------------------------------------------
# Enumeration + ranking
# ---------------------------------------------------------------------------


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_plans(n_devices: int, *, chunked_loss=False,
                    accum_max: int = 32, global_batch: int):
    """Yield the raw candidate space as a JOINT enumeration (not a
    per-axis sweep): every mesh/zero/accum/chunk point is crossed with
    the remat ladder × offload ladder, dp-only meshes additionally
    carry an expert-parallel twin (``ep == dp`` — one expert per
    device), and pure-pipeline meshes (``pp`` stages × ``micro``
    microbatches) join the space crossed with the same remat × offload
    rungs.  Infeasible combinations are NOT filtered here — the
    planner's structural/memory pruning rejects them with stated
    reasons, so the candidate space stays auditable."""
    meshes = set()
    for dp in _divisors(n_devices):
        rest = n_devices // dp
        for sp in _divisors(rest):
            meshes.add((dp, sp, rest // sp))
        meshes.add((dp, 1, 1))       # partial mesh: idle devices allowed
    chunk_opts = (False, True) if chunked_loss is None else (chunked_loss,)
    variants = [(r, oo, oa) for r in REMAT_POLICIES
                for (oo, oa) in OFFLOAD_LADDER]
    for dp, sp, tp in sorted(meshes):
        zero_opts = (0, 1, 3) if (dp > 1 and sp == 1 and tp == 1) else (0,)
        local = global_batch // dp if dp and global_batch % dp == 0 else 1
        ks = [k for k in _divisors(max(local, 1))
              if k <= accum_max and (k & (k - 1)) == 0]
        for zero in zero_opts:
            for k in ks or [1]:
                for ch in chunk_opts:
                    for remat, oo, oa in variants:
                        yield Plan(dp=dp, sp=sp, tp=tp, zero_stage=zero,
                                   accum=k, chunked_loss=ch,
                                   remat=remat, offload_opt=oo,
                                   offload_act=oa, n_devices=n_devices)
                        if dp > 1 and sp == 1 and tp == 1 and zero == 0:
                            # expert-parallel twin: ep rides the dp axis
                            yield Plan(dp=dp, sp=sp, tp=tp,
                                       zero_stage=zero, accum=k,
                                       chunked_loss=ch, ep=dp,
                                       remat=remat, offload_opt=oo,
                                       offload_act=oa,
                                       n_devices=n_devices)
    # pure-pipeline meshes: pp stages over the device axis, micro
    # power-of-two microbatches (the pipeline's accumulation unit)
    for pp in _divisors(n_devices):
        if pp == 1:
            continue
        micros = [m for m in _divisors(max(global_batch, 1))
                  if (m & (m - 1)) == 0 and pp <= m <= accum_max]
        for micro in micros:
            for ch in chunk_opts:
                for remat, oo, oa in variants:
                    yield Plan(pp=pp, micro=micro, chunked_loss=ch,
                               remat=remat, offload_opt=oo,
                               offload_act=oa, n_devices=n_devices)


@dataclasses.dataclass
class PlanReport:
    """Planner output: the ranked feasible plans, and every rejected
    plan with its stated reason — nothing is pruned silently."""
    best: Optional[Plan]
    ranked: list
    rejected: list                      # [(Plan, reason)]
    profile: ModelProfile
    chip: ChipSpec
    global_batch: int
    hbm_cap: float
    fleet: Optional[Fleet] = None
    search_ms: float = 0.0              # wall-clock of the joint search
    explored: int = 0                   # plans enumerated (incl. rejected)
    pruned_oom: int = 0                 # rejected by the HBM model

    def describe(self, top: int = 5) -> str:
        chip_desc = (f"fleet {self.fleet.name()}"
                     if self.fleet is not None and self.fleet.heterogeneous
                     else self.chip.name)
        out = [f"auto-parallel plan report — {chip_desc}, "
               f"global batch {self.global_batch}, HBM cap "
               f"{self.hbm_cap / 2**30:.2f} GiB/device, model "
               f"{self.profile.n_params / 1e6:.2f}M params "
               f"(profile: {self.profile.source})"]
        if self.explored:
            out.append(f"search: {self.explored} plans explored, "
                       f"{self.pruned_oom} pruned by the HBM model, "
                       f"{self.search_ms:.1f} ms")
        if self.best is None:
            out.append("NO FEASIBLE PLAN — every candidate was rejected:")
        else:
            out.append(f"chosen: {self.best.name()}")
            out.append(self.best.describe())
            out.append(f"runners-up (of {len(self.ranked)} feasible):")
            for p in self.ranked[1:top]:
                why = (f"+{p.predicted_ms - self.best.predicted_ms:.3f} ms "
                       f"predicted vs chosen"
                       if p.predicted_ms is not None else "")
                out.append(f"  {p.name():<24} {p.predicted_ms:9.3f} ms  "
                           f"{(p.predicted_hbm or 0) / 2**20:9.1f} MiB  "
                           f"{why}")
        shown = self.rejected[:max(top * 3, 12)]
        if shown:
            out.append(f"rejected ({len(self.rejected)}):")
            for p, reason in shown:
                out.append(f"  {p.name():<24} {reason}")
            if len(self.rejected) > len(shown):
                out.append(f"  ... {len(self.rejected) - len(shown)} more "
                           f"(same reason classes)")
        return "\n".join(out)


def plan_training(model, optimizer, loss_fn: Callable, example_batch, *,
                  devices=None, half_dtype=None,
                  keep_batchnorm_fp32: bool = True,
                  chip: Optional[ChipSpec] = None,
                  hbm_cap_bytes: Optional[float] = None,
                  hbm_reserve: float = HBM_RESERVE,
                  accum_max: int = 32,
                  chunked_loss=False,
                  profile: Optional[ModelProfile] = None,
                  fleet=None) -> PlanReport:
    """Enumerate → prune (memory, capability) → rank (roofline).

    ``chunked_loss``: what the caller's ``loss_fn`` actually is (the
    planner cannot swap it) — pass ``None`` to enumerate both and see
    the lever's predicted effect in the report.

    ``fleet``: a :class:`Fleet`, the ``"v5e:4+v4:4"`` string syntax, or
    a sequence of :class:`ChipSpec` — one per device, planner order.  A
    heterogeneous fleet switches pricing to the slowest-member bound
    with per-device batch shares (:func:`predict_time_fleet`); memory
    feasibility is then checked for the LARGEST share against the
    SMALLEST member's HBM (conservative on both axes).
    """
    flt = _fleet_of(fleet)
    devices = list(devices) if devices is not None else jax.devices()
    spec = chip or (flt.slowest() if flt is not None else
                    chip_spec(devices))
    prof = profile or profile_model(
        model, optimizer, loss_fn, example_batch, half_dtype=half_dtype,
        keep_batchnorm_fp32=keep_batchnorm_fp32)
    global_batch = _global_batch_of(example_batch)
    if hbm_cap_bytes is not None:
        cap = hbm_cap_bytes
    elif flt is not None:
        cap = min(s.hbm_bytes for s in flt.specs) * (1.0 - hbm_reserve)
    else:
        cap = spec.hbm_bytes * (1.0 - hbm_reserve)
    n_plan_devices = flt.n_devices if flt is not None else len(devices)

    chip_key, mfp = None, None
    try:
        from ..kernels import ledger as _kl
        chip_key = _kl.chip_name(devices)
        mfp = model_fp(prof, global_batch)
    except Exception:
        _kl = None
    opt_kernel = _opt_kernel_name(optimizer)

    hetero = flt is not None and flt.heterogeneous
    feasible, rejected = [], []
    explored = 0
    t_search = time.perf_counter()
    for plan in enumerate_plans(n_plan_devices, chunked_loss=chunked_loss,
                                accum_max=accum_max,
                                global_batch=global_batch):
        explored += 1
        reason = _structural_reject(plan, prof, global_batch, fleet=flt)
        if reason is not None:
            rejected.append((plan, reason))
            continue
        plan = dataclasses.replace(
            plan,
            tp_axis=prof.tp_axis if plan.tp > 1 else None,
            sp_axis=prof.sp_axis if plan.sp > 1 else None,
            pp_axis=prof.pp_axis if plan.pp > 1 else None,
            dp_axis=(prof.moe_axis if plan.ep > 1 and prof.moe_axis
                     else plan.dp_axis))
        if hetero and plan.pp > 1:
            # heterogeneous pipeline: stages apportioned to member
            # speed (faster chips take more layers); the batch is NOT
            # split — every microbatch visits every stage
            members = flt.specs[:plan.pp]
            n_layers = prof.layers or plan.pp
            plan = dataclasses.replace(
                plan,
                stage_layers=apportion_shares(
                    [s.sustained_flops() for s in members], n_layers),
                stage_members=tuple(s.name for s in members))
            shares, mem_batch = None, global_batch
        elif hetero:
            # memory for the binding member: the largest share on the
            # smallest HBM — price the uniform formula at an effective
            # global batch of max_share × dp so micro_b == max_share
            shares = apportion_shares(
                [s.sustained_flops() for s in flt.specs[:plan.n_used]],
                global_batch)
            mem_batch = max(shares) * plan.dp
        else:
            shares, mem_batch = None, global_batch
        mem, mem_bd = predict_memory(plan, prof, spec, mem_batch)
        if mem > cap:
            over = dict(mem_bd)
            reason = (
                f"memory-infeasible: needs {mem / 2**20:.1f} MiB/device > "
                f"cap {cap / 2**20:.1f} MiB (masters "
                f"{over['mem_masters'] / 2**20:.1f} + slots "
                f"{over['mem_slots'] / 2**20:.1f} + half "
                f"{over['mem_half'] / 2**20:.1f} + grads "
                f"{over['mem_grads'] / 2**20:.1f} + acts "
                f"{over['mem_acts'] / 2**20:.1f} + batch "
                f"{over['mem_batch'] / 2**20:.1f})")
            rejected.append((dataclasses.replace(
                plan, predicted_hbm=mem, breakdown=tuple(mem_bd)), reason))
            continue
        if hetero:
            ms, time_bd, colls, shares = predict_time_fleet(
                plan, prof, flt, global_batch, shares=shares)
        else:
            ms, time_bd, colls = predict_time(plan, prof, spec,
                                              global_batch)
        plan = dataclasses.replace(
            plan, predicted_ms=ms, predicted_hbm=mem,
            breakdown=tuple(time_bd + mem_bd), collectives=tuple(colls),
            device_shares=tuple(shares) if shares is not None else ())
        if chip_key is not None:
            plan = _ledger_reprice(plan, prof, spec, global_batch,
                                   chip_key, opt_kernel)
        feasible.append(plan)

    # deterministic rank: predicted time, then fewer devices, lower
    # stage, smaller K, simpler v3 levers (simpler plans win ties)
    def _rank(p):
        return (p.predicted_ms, p.n_used, p.zero_stage, p.accum, p.tp,
                p.sp, p.pp, p.micro, _REMAT_ORDER.get(p.remat, 9),
                p.offload_opt, p.offload_act, p.ep)

    feasible.sort(key=_rank)
    # measured plan trials from previous runs of this same (chip, model
    # shape) re-rank repeated runs from data — measurement outranks any
    # prediction, exactly as a fresh auto_tune pass would
    if chip_key is not None and mfp is not None:
        try:
            meas = _kl.get_ledger().plan_measurements(chip_key, mfp)
        except Exception:
            meas = {}
        if meas:
            from ..kernels.ledger import _plan_key_str
            feasible = [
                dataclasses.replace(p, measured_ms=float(
                    meas[_plan_key_str(p.key())]["measured_ms"]))
                if (p.measured_ms is None
                    and _plan_key_str(p.key()) in meas) else p
                for p in feasible]
            feasible.sort(key=lambda p: (
                p.measured_ms is None,
                p.measured_ms if p.measured_ms is not None
                else p.predicted_ms) + _rank(p)[1:])
    search_ms = (time.perf_counter() - t_search) * 1e3
    pruned_oom = sum(1 for _, r in rejected
                     if r.startswith("memory-infeasible"))
    best = feasible[0] if feasible else None
    _obs.gauge("plan.search_ms").set(search_ms)
    _obs.gauge("plan.explored").set(float(explored))
    _obs.gauge("plan.pruned_oom").set(float(pruned_oom))
    if best is not None and best.pp > 1:
        bf = dict(best.breakdown).get("bubble_frac")
        if bf is not None:
            _obs.gauge("plan.bubble_frac").set(float(bf))
    return PlanReport(best=best,
                      ranked=feasible, rejected=rejected, profile=prof,
                      chip=spec, global_batch=global_batch, hbm_cap=cap,
                      fleet=flt, search_ms=search_ms, explored=explored,
                      pruned_oom=pruned_oom)


def _structural_reject(plan: Plan, prof: ModelProfile,
                       global_batch: int,
                       fleet: Optional[Fleet] = None) -> Optional[str]:
    if fleet is not None and fleet.heterogeneous and \
            (plan.tp > 1 or plan.sp > 1):
        return (f"tp={plan.tp}/sp={plan.sp} across the mixed fleet "
                f"{fleet.name()}: tensor/sequence parallelism needs "
                f"identical per-shard throughput (lockstep layer math), "
                f"so heterogeneous fleets are dp-only — stragglers are "
                f"absorbed by batch shares, not layer shards")
    if plan.dp > 1 and global_batch % plan.dp:
        return (f"global batch {global_batch} not divisible by "
                f"dp={plan.dp}")
    if plan.tp > 1:
        if prof.tp_axis is None:
            return (f"tp={plan.tp} needs a model built with tp_axis= "
                    f"(this one was built unsharded — rebuild with "
                    f"tp_axis='tp' to enable tensor parallelism)")
        if prof.heads and prof.heads % plan.tp:
            return (f"tp={plan.tp} does not divide the model's "
                    f"{prof.heads} attention heads")
    if plan.sp > 1:
        if prof.sp_axis is None:
            return (f"sp={plan.sp} needs a model built with sp_axis= "
                    f"(ring attention) — rebuild to enable sequence "
                    f"parallelism")
        if prof.seq_len and prof.seq_len % plan.sp:
            return (f"sp={plan.sp} does not divide sequence length "
                    f"{prof.seq_len}")
    if plan.chunked_loss and not prof.logits_bytes_per_example:
        return ("chunked_loss priced but the model exposes no vocab head "
                "(no logits working set to chunk)")
    if plan.micro > 1 and plan.pp == 1:
        return (f"micro={plan.micro} without pipeline stages — the "
                f"microbatch axis is the pipeline's accumulation unit "
                f"(use accum=K for non-pipelined accumulation)")
    if plan.pp > 1:
        if prof.pp_axis is None:
            return (f"pp={plan.pp} needs a PipelinedStack model (build "
                    f"one with parallel.pipeline.PipelinedStack to "
                    f"enable pipeline parallelism)")
        if plan.tp > 1 or plan.sp > 1 or plan.zero_stage:
            return (f"pp={plan.pp} composes with neither tp/sp shard "
                    f"axes nor ZeRO in this planner — pipeline plans "
                    f"run pure pp")
        if plan.micro < plan.pp:
            return (f"micro={plan.micro} < pp={plan.pp}: the pipeline "
                    f"never fills (every tick would carry a bubble)")
        if global_batch % (plan.dp * plan.micro):
            return (f"global batch {global_batch} not divisible by "
                    f"dp×micro = {plan.dp * plan.micro}")
        hetero = fleet is not None and fleet.heterogeneous
        if hetero and plan.dp > 1:
            return (f"dp×pp across the mixed fleet {fleet.name()}: "
                    f"heterogeneous pipelines absorb stragglers via "
                    f"stage apportionment, dp replicas would need "
                    f"identical stage sets")
        if not hetero and prof.layers and prof.layers % plan.pp:
            return (f"pp={plan.pp} does not divide the model's "
                    f"{prof.layers} layers (homogeneous stages)")
    if plan.remat != "none" and plan.pp == 1 and not prof.remat_capable:
        return (f"remat={plan.remat} needs a model built with "
                f"remat=True (activation checkpointing) — rebuild to "
                f"enable it")
    if plan.ep > 1:
        if prof.moe_axis is None:
            return (f"ep={plan.ep} needs a switch-MoE model (build with "
                    f"moe_axis=/moe_num_experts= to enable expert "
                    f"parallelism)")
        if plan.ep != plan.dp:
            return (f"ep={plan.ep} must equal dp={plan.dp}: expert "
                    f"parallelism rides the data axis (one expert per "
                    f"dp member)")
        if prof.n_experts and plan.ep != prof.n_experts:
            return (f"ep={plan.ep} != the model's {prof.n_experts} "
                    f"experts — switch_moe routes one expert per "
                    f"device along the axis")
        if plan.zero_stage or plan.tp > 1 or plan.sp > 1 or plan.pp > 1:
            return (f"ep={plan.ep} runs the explicit-axis MoE path: no "
                    f"ZeRO/tp/sp/pp composition in this planner")
        if fleet is not None and fleet.heterogeneous:
            return (f"ep={plan.ep} across the mixed fleet "
                    f"{fleet.name()}: expert dispatch needs lockstep "
                    f"all-to-all throughput")
    return None


# ---------------------------------------------------------------------------
# Applying a plan: thread the existing knobs / wrap the explicit-axis path
# ---------------------------------------------------------------------------


def _resolve_devices(devices):
    if devices is None:
        return list(jax.devices())
    if isinstance(devices, int):
        ds = list(jax.devices())
        if devices > len(ds):
            raise ValueError(f"asked to plan for {devices} devices, "
                             f"have {len(ds)}")
        return ds[:devices]
    return list(devices)


def apply_plan(plan: Plan, model, optimizer, loss_fn, devices=None,
               **base_kwargs):
    """Build the train step a plan describes by threading the existing
    make_train_step knobs (dp/ZeRO plans run the GSPMD global-view path,
    tp/sp plans the explicit shard_map path).  The returned step carries
    ``.plan``."""
    from ..training.step import make_train_step
    devices = _resolve_devices(devices)
    if plan.n_used > len(devices):
        raise ValueError(f"plan {plan.name()} needs {plan.n_used} devices, "
                         f"have {len(devices)}")
    kw = dict(base_kwargs)
    kw.pop("parallel", None)
    for knob in ("axis_name", "tp_axis", "zero_sharding", "zero_mesh"):
        if kw.pop(knob, None):
            raise ValueError(
                f"parallel= owns the {knob} knob — pass one or the other")
    if plan.pp > 1:
        return _apply_pp_plan(plan, model, optimizer, loss_fn, devices, kw)
    kw.update(plan.step_kwargs(devices))

    if plan.tp == 1 and plan.sp == 1 and plan.ep == 1:
        step = make_train_step(model, optimizer, loss_fn, _plan=plan, **kw)
        step.plan = plan
        return step

    # explicit-axis path: the tested shard_map wrap (tp / sp / dp×tp / ep)
    if plan.ep > 1 and \
            getattr(model, "moe_axis", None) != plan.dp_axis:
        raise ValueError(
            f"plan {plan.name()} routes {plan.ep} experts over axis "
            f"{plan.dp_axis!r} but the model's moe_axis is "
            f"{getattr(model, 'moe_axis', None)!r} — build the model "
            f"with moe_axis={plan.dp_axis!r} (expert dispatch rides the "
            f"data axis)")
    if plan.tp > 1 and getattr(model, "tp_axis", None) is None:
        raise ValueError(
            f"plan {plan.name()} uses tensor parallelism but the model "
            f"was built without tp_axis= — rebuild the model with "
            f"tp_axis={plan.tp_axis or 'tp'!r}")
    if plan.sp > 1 and getattr(model, "sp_axis", None) is None:
        raise ValueError(
            f"plan {plan.name()} uses sequence parallelism but the model "
            f"was built without sp_axis= — rebuild the model with "
            f"sp_axis={plan.sp_axis or 'sp'!r}")
    donate = bool(kw.get("donate_state", True))
    step = make_train_step(model, optimizer, loss_fn, _plan=plan, **kw)
    axis_dims = [(plan.dp_axis, plan.dp)]
    if plan.sp > 1:
        axis_dims.append((model.sp_axis, plan.sp))
    if plan.tp > 1:
        axis_dims.append((model.tp_axis, plan.tp))
    axis_dims = [(n, s) for n, s in axis_dims if s > 1] or \
        [(plan.dp_axis, 1)]
    names = tuple(n for n, _ in axis_dims)
    shape = tuple(s for _, s in axis_dims)
    mesh = Mesh(np.array(devices[:plan.n_used]).reshape(shape), names)
    mean_axes = tuple(n for n, s in axis_dims
                      if s > 1 and n != (model.tp_axis if plan.tp > 1
                                         else None))

    from .. import compat
    from ..runtime import executor as _executor

    raw = step._raw_step_fn
    plan_key = plan.key()
    token = next(_PLAN_TOKENS)
    dispatch_no = itertools.count(1)
    programs = {}

    def _batch_spec(el):
        def leaf(a):
            dims = []
            if plan.dp > 1 and getattr(a, "ndim", 0) >= 1:
                dims.append(plan.dp_axis)
            else:
                dims.append(None)
            if plan.sp > 1 and getattr(a, "ndim", 0) >= 2:
                dims.append(model.sp_axis)
            return P(*dims)
        return jax.tree_util.tree_map(leaf, el)

    def _program(specs):
        prog = programs.get(specs)
        if prog is not None:
            return prog

        def run(state, *b):
            new_state, loss = raw(state, *b)
            if mean_axes:
                # the in-step loss is one shard's local mean; make
                # the reported number the global mean (grads are
                # already psum-exchanged inside the step)
                loss = jax.lax.pmean(
                    loss, mean_axes if len(mean_axes) > 1
                    else mean_axes[0])
            return new_state, loss

        def wrap(f):
            return compat.shard_map(f, mesh=mesh,
                                    in_specs=(P(),) + specs,
                                    out_specs=(P(), P()), check_vma=False)

        prog = _executor.Program(
            "train_step", (token, plan_key, specs, donate), run,
            donate_argnums=(0,) if donate else (), wrap=wrap)
        programs[specs] = prog
        return prog

    def dispatch(state, *batch):
        specs = tuple(_batch_spec(b) for b in batch)
        return _executor.executor.submit(
            _program(specs), (state,) + batch, step=next(dispatch_no))

    step._step_fn = dispatch
    step._via_executor = True
    step.plan = plan
    return step


_PIPELINE_STEP_KNOBS = ("half_dtype", "dynamic_loss_scale", "scale_window",
                        "min_loss_scale", "max_loss_scale", "loss_scale",
                        "lr_schedule")


def _apply_pp_plan(plan: Plan, model, optimizer, loss_fn, devices, kw):
    """Pipeline plans: route to the tested pipeline entry points
    (make_pipeline_train_step for 1F1B, the GPipe stack wrap of
    make_train_step otherwise) and dispatch the sharded step through the
    executor over a 1-D pp mesh with the batch replicated — the same
    wrap tests/test_pipeline.py drives by hand."""
    from ..training.step import make_train_step
    from .pipeline import make_pipeline_train_step
    from .. import compat
    from ..runtime import executor as _executor

    if getattr(model, "n_micro", None) is None or \
            getattr(model, "stage_fn", None) is None:
        raise ValueError(
            f"plan {plan.name()} pipelines {plan.pp} stages but the model "
            f"is not a PipelinedStack — build one with "
            f"PipelinedStack(stage_fn, stacked_params, axis_name, "
            f"n_micro={plan.micro})")
    if plan.dp > 1 or plan.tp > 1 or plan.sp > 1 or plan.ep > 1:
        raise ValueError(
            f"plan {plan.name()}: the planner schedules pure pipelines "
            f"only — no dp/tp/sp/ep composition with pp")
    if model.n_micro != plan.micro:
        raise ValueError(
            f"plan {plan.name()} schedules micro={plan.micro} microbatches "
            f"but the stack was built with n_micro={model.n_micro} — "
            f"rebuild the stack to match the plan")
    axis = plan.pp_axis or model.axis_name
    if model.axis_name != axis:
        raise ValueError(
            f"plan {plan.name()} pipelines over axis {axis!r} but the "
            f"stack's axis_name is {model.axis_name!r}")
    step_kw = {k: v for k, v in kw.items() if k in _PIPELINE_STEP_KNOBS}
    unknown = {k for k in kw if k not in _PIPELINE_STEP_KNOBS
               and k not in ("donate_state",)}
    if unknown:
        raise ValueError(
            f"plan {plan.name()}: pipeline steps do not accept "
            f"{sorted(unknown)} — supported knobs: "
            f"{sorted(_PIPELINE_STEP_KNOBS)}")

    if plan.remat == "full":
        # 1F1B recomputes stage forwards by construction
        step = make_pipeline_train_step(model, optimizer, loss_fn,
                                        schedule="1f1b", **step_kw)
    else:
        if plan.remat == "selective" and not model.remat_stage:
            raise ValueError(
                f"plan {plan.name()} checkpoints stage internals "
                f"(remat=selective) but the stack was built with "
                f"remat_stage=False — rebuild with remat_stage=True")
        if plan.remat == "none" and model.remat_stage:
            raise ValueError(
                f"plan {plan.name()} keeps all activations (remat=none) "
                f"but the stack was built with remat_stage=True — the "
                f"run would not match the plan's memory model")
        step = make_train_step(model, optimizer, loss_fn, _plan=plan,
                               tp_axis=axis, **step_kw)

    donate = bool(kw.get("donate_state", True)) and plan.remat != "full"
    mesh = Mesh(np.array(devices[:plan.pp]), (axis,))
    raw = step._raw_step_fn
    plan_key = plan.key()
    token = next(_PLAN_TOKENS)
    dispatch_no = itertools.count(1)
    programs = {}

    def _program(nbatch):
        prog = programs.get(nbatch)
        if prog is not None:
            return prog

        def wrap(f):
            # batch replicated: every stage sees the full batch; the
            # scan/1f1b schedule slices its own microbatches
            return compat.shard_map(
                f, mesh=mesh, in_specs=(P(),) * (1 + nbatch),
                out_specs=(P(), P()), check_vma=False)

        prog = _executor.Program(
            "train_step", (token, plan_key, nbatch, donate), raw,
            donate_argnums=(0,) if donate else (), wrap=wrap)
        programs[nbatch] = prog
        return prog

    def dispatch(state, *batch):
        return _executor.executor.submit(
            _program(len(batch)), (state,) + batch,
            step=next(dispatch_no))

    step._step_fn = dispatch
    step._via_executor = True
    step.plan = plan
    return step


# ---------------------------------------------------------------------------
# Measured refinement (auto_tune) + the make_train_step entry point
# ---------------------------------------------------------------------------


def _concrete_batch(example_batch):
    """Concrete arrays for trial runs: the example's own arrays where
    concrete, zeros of the right shape/dtype where abstract."""
    def leaf(a):
        if isinstance(a, jax.ShapeDtypeStruct):
            return jnp.zeros(a.shape, a.dtype)
        return jnp.asarray(a)
    return tuple(jax.tree_util.tree_map(leaf, el) for el in example_batch)


def measure_plan(plan: Plan, model, optimizer, loss_fn, example_batch,
                 devices=None, steps: int = 3, **base_kwargs):
    """Compile + time a plan through the real step (the step-program
    cache does the compiling).  Returns min ms/step over ``steps`` timed
    calls, or None with the failure recorded on the exception."""
    batch = _concrete_batch(example_batch)
    step = apply_plan(plan, model, optimizer, loss_fn, devices=devices,
                      **base_kwargs)
    float(step(*batch))              # compile + warm
    best = math.inf
    for _ in range(max(steps, 1)):
        t0 = time.perf_counter()
        float(step(*batch))          # scalar fetch = device sync
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def auto_tune_report(report: PlanReport, model, optimizer, loss_fn,
                     example_batch, devices=None, k: int = 3,
                     steps: int = 3, **base_kwargs) -> PlanReport:
    """Measured refinement: compile and time the top-k predicted plans
    and re-rank by measurement (prediction breaks ties / fills gaps)."""
    chip_key, mfp, led = None, None, None
    try:
        from ..kernels import ledger as _kl
        chip_key = _kl.chip_name(devices)
        mfp = model_fp(report.profile, report.global_batch)
        led = _kl.get_ledger()
    except Exception:
        pass
    measured = []
    for plan in report.ranked[:max(k, 1)]:
        try:
            ms = measure_plan(plan, model, optimizer, loss_fn,
                              example_batch, devices=devices, steps=steps,
                              **base_kwargs)
            measured.append(dataclasses.replace(plan, measured_ms=ms))
            # each trial measurement is a calibration-ledger entry —
            # stamped with (chip, model_fp) so ledger.ingest_events can
            # fold the event stream back in, and written through to the
            # ledger directly so the NEXT plan_training on this shape
            # re-ranks from measurement without an ingest pass
            _obs.event("plan.auto_tune", plan=plan.name(),
                       plan_key=plan.key(), measured_ms=ms,
                       predicted_ms=plan.predicted_ms,
                       chip=chip_key, model_fp=mfp)
            if led is not None:
                led.record_plan(chip_key, mfp, plan.key(),
                                measured_ms=ms,
                                predicted_ms=plan.predicted_ms,
                                plan=plan.name(), source="auto_tune")
        except Exception as e:        # a plan that fails to run loses
            report.rejected.append(
                (plan, f"auto_tune trial failed: {type(e).__name__}: {e}"))
            _obs.event("plan.auto_tune", plan=plan.name(),
                       plan_key=plan.key(), measured_ms=None,
                       chip=chip_key, model_fp=mfp,
                       error=f"{type(e).__name__}: {e}")
    measured.sort(key=lambda p: (p.measured_ms, p.predicted_ms))
    ranked = measured + [p for p in report.ranked
                         if p.key() not in {m.key() for m in measured}]
    return dataclasses.replace(
        report, best=ranked[0] if ranked else None, ranked=ranked)


def build_planned_step(model, optimizer, loss_fn, parallel, *,
                       example_batch=None, devices=None, auto_tune: int = 0,
                       plan_options=None, **base_kwargs):
    """The ``make_train_step(parallel=...)`` entry point: resolve
    "auto" (or a Plan) into knobs and build the step.  The returned step
    carries ``.plan`` and (for "auto") ``.plan_report``."""
    devices = _resolve_devices(devices)
    report = None
    if isinstance(parallel, str):
        if parallel != "auto":
            raise ValueError(
                f"parallel= accepts 'auto' or a parallel.auto.Plan, "
                f"got {parallel!r}")
        if example_batch is None:
            raise ValueError(
                "parallel='auto' needs example_batch=(x, y, ...) — a "
                "tuple of arrays (or ShapeDtypeStructs) shaped like one "
                "global training batch, so the planner knows the batch "
                "and sequence geometry")
        opts = dict(plan_options or {})
        report = plan_training(
            model, optimizer, loss_fn, example_batch, devices=devices,
            half_dtype=base_kwargs.get("half_dtype"),
            keep_batchnorm_fp32=base_kwargs.get("keep_batchnorm_fp32",
                                                True),
            **opts)
        if report.best is None:
            raise RuntimeError(
                "parallel='auto': no feasible plan\n" + report.describe())
        if auto_tune:
            report = auto_tune_report(
                report, model, optimizer, loss_fn, example_batch,
                devices=devices, k=auto_tune, **base_kwargs)
            if report.best is None:
                raise RuntimeError(
                    "parallel='auto': every auto_tune trial failed\n"
                    + report.describe())
        plan = report.best
    elif isinstance(parallel, Plan):
        plan = parallel
    else:
        raise TypeError(
            f"parallel= accepts 'auto' or a parallel.auto.Plan, got "
            f"{type(parallel).__name__}")
    chip_key, mfp = None, None
    try:
        from ..kernels import ledger as _kl
        chip_key = _kl.chip_name(devices)
        if report is not None:
            mfp = model_fp(report.profile, report.global_batch)
    except Exception:
        _kl = None
    _obs.event("plan.decision", plan=plan.name(), plan_key=plan.key(),
               source="auto" if report is not None else "explicit",
               n_devices=len(devices),
               predicted_ms=plan.predicted_ms,
               measured_ms=plan.measured_ms,
               chip=chip_key, model_fp=mfp,
               feasible=len(report.ranked) if report is not None else None,
               rejected=len(report.rejected) if report is not None else None)
    if mfp is not None:
        # the decision itself is ledger data: record_plan keeps any
        # prior measured_ms when this decision carries none
        try:
            _kl.get_ledger().record_plan(
                chip_key, mfp, plan.key(), measured_ms=plan.measured_ms,
                predicted_ms=plan.predicted_ms, plan=plan.name(),
                source="decision")
        except Exception:
            pass
    step = apply_plan(plan, model, optimizer, loss_fn, devices=devices,
                      **base_kwargs)
    step.plan_report = report
    return step


def measured_step_memory(compiled) -> int:
    """Per-device footprint of a compiled step program, donation-aware:
    arguments + outputs + temps − aliased (donated buffers counted
    once).  The validation target for :func:`predict_memory`.

    Compile the program with :func:`compile_uncached`: when jax 0.4.x's
    persistent compilation cache is enabled, executables that pass
    through its (de)serialization layer report ``alias_size_in_bytes=0``
    — a donated program then measures its outputs double, and whether a
    given compile passes through the layer depends on the
    ``min_compile_time_secs`` threshold, i.e. on machine load.
    """
    ma = compiled.memory_analysis()
    return int(ma.argument_size_in_bytes + ma.output_size_in_bytes
               + ma.temp_size_in_bytes - ma.alias_size_in_bytes)


def compile_uncached(lowered):
    """``lowered.compile()`` with the persistent compilation cache
    disabled for the duration — the donation-aware companion of
    :func:`measured_step_memory` (see its note on alias metadata)."""
    try:
        prev = jax.config.jax_compilation_cache_dir
    except AttributeError:
        prev = None
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:       # knob absent on this jax: nothing to bypass
        return lowered.compile()
    try:
        return lowered.compile()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
