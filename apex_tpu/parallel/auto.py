"""``parallel="auto"`` — an analytical parallelism planner + cost model.

Every parallelism primitive in this framework is a manual knob on
:func:`~apex_tpu.training.make_train_step` (``axis_name``, ``tp_axis``,
``zero_sharding``/``zero_stage``, ``accum_steps``) or a model build option
(``tp_axis=``, ``sp_axis=``, the chunked LM loss).  Picking the
configuration is worth double-digit throughput (BENCH_HISTORY round 5:
+13–15% from the chunked vocab chain alone, batch-size plateaus that
invert per model), and the AMP (arXiv:2210.07297) / Galvatron
(arXiv:2504.03662) line of work shows an analytical cost model over
(compute FLOPs, collective bytes, memory footprint) ranks parallel plans
reliably without exhaustive on-device search.  This module is that brain:

1. **enumerate** candidate plans — mesh factorizations dp × sp × tp, ZeRO
   stage 0/1/3, gradient-accumulation K, chunked-loss on/off;
2. **prune** memory-infeasible ones with an explicit HBM model (masters +
   optimizer slots under the chosen ZeRO stage + half model copies +
   gradient carry + activation peak under accumulation + the vocab-logits
   working set vs the chunked-loss lever) — every rejection carries a
   stated reason, nothing is pruned silently;
3. **rank** the survivors with a roofline step-time model: per-device
   FLOPs at the chip's derated peak, HBM bytes at its bandwidth, and
   ring-model ICI time for every collective the plan will emit (psum /
   reduce-scatter / all-gather / ppermute on the candidate mesh axes);
4. **return** a :class:`Plan` whose ``describe()`` prints the predicted
   ms/step, predicted HBM breakdown, the collectives it emits, and — via
   :meth:`PlanReport.describe` — why rejected plans lost.

The planner is pure host-side Python over static shapes.  Its model
constants come from two places: the per-model FLOP/activation profile is
measured from XLA's own cost analysis (``lower().cost_analysis()`` /
``compile().memory_analysis()`` of the unsharded forward+backward at two
probe batch sizes, linearly fitted), and the per-chip constants (peak
FLOP/s, HBM bytes/bandwidth, ICI bandwidth/latency) live in the
:data:`CHIPS` table, checked against ``bench.py --plan``'s
predicted-vs-measured output.

The planner only *drives* primitives that already exist and are tested:
dp/ZeRO plans run through the GSPMD global-view path
(:class:`~apex_tpu.parallel.zero.ZeroTrainStep`, stage 0 = replicated
state / pure data parallelism), tp/sp plans through the
``shard_map``-wrapped explicit-axis path — there are no new execution
paths, and the step-program cache keys carry the plan so cache stats stay
per-plan observables.
"""
from __future__ import annotations

import dataclasses
import itertools
import math
import time
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..observe import registry as _obs

#: per-wrap token in the step-program cache key — two planned steps with
#: identical signatures close over different model/optimizer objects
_PLAN_TOKENS = itertools.count()


# ---------------------------------------------------------------------------
# Chip constants (the calibration table — see docs/auto_parallel.md)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Per-device hardware constants the cost model prices against.

    ``efficiency`` derates the spec-sheet peak to the sustained fraction a
    well-tuned fused step reaches (the bench-measured MFU band, not the
    marketing number).  ``shared_host=True`` marks *virtual* devices
    (``--xla_force_host_platform_device_count``): they split one host's
    cores and memory bus, so spreading work over more of them never buys
    compute time — only memory-model wins — and every collective is a
    host memcpy.  That inversion is deliberate: on the CPU test mesh the
    planner must predict the order a CPU measurement produces.
    """
    name: str
    peak_flops: float        # per device (bf16/fp16 ALU peak, FLOP/s)
    hbm_bytes: float         # per device
    hbm_bw: float            # bytes/s
    ici_bw: float            # bytes/s per link direction
    ici_latency_s: float     # per-hop collective latency
    overhead_s: float        # fixed per-microbatch dispatch/loop overhead
    efficiency: float = 0.45
    shared_host: bool = False

    def sustained_flops(self) -> float:
        return self.peak_flops * self.efficiency

    def scaled(self, factor: float) -> "ChipSpec":
        """A speed-scaled copy (compute, HBM and ICI bandwidth all
        multiplied by ``factor``) — the fleet syntax's straggler
        stand-in, e.g. ``"cpu*0.5"`` is a host running at half speed."""
        if factor <= 0:
            raise ValueError(f"scale factor must be > 0, got {factor}")
        if factor == 1.0:
            return self
        return dataclasses.replace(
            self, name=f"{self.name}*{factor:g}",
            peak_flops=self.peak_flops * factor,
            hbm_bw=self.hbm_bw * factor,
            ici_bw=self.ici_bw * factor)


#: bf16 peaks from public spec sheets; HBM/ICI figures are the same
#: per-chip constants bench.py's MFU math uses.  The "cpu" entry models
#: the 8-virtual-device test mesh: one shared host, collectives as
#: memcpys, generous per-collective latency (thread rendezvous).
CHIPS = {
    "v6":  ChipSpec("v6",  918.0e12, 32e9, 1640e9, 180e9, 1e-6, 2e-6),
    "v5p": ChipSpec("v5p", 459.0e12, 95e9, 2765e9, 200e9, 1e-6, 2e-6),
    "v5e": ChipSpec("v5e", 197.0e12, 16e9,  819e9,  50e9, 1e-6, 2e-6),
    "v4":  ChipSpec("v4",  275.0e12, 32e9, 1228e9, 100e9, 1e-6, 2e-6),
    "v3":  ChipSpec("v3",  123.0e12, 32e9,  900e9,  70e9, 1e-6, 2e-6),
    "cpu": ChipSpec("cpu",   40.0e9,  4e9,   20e9,   4e9, 30e-6, 150e-6,
                    efficiency=1.0, shared_host=True),
}


def chip_spec(devices=None) -> ChipSpec:
    """Match the running device kind to the constants table (cpu
    fallback; unknown accelerators borrow the v4 numbers)."""
    devices = list(devices) if devices is not None else jax.devices()
    kind = (getattr(devices[0], "device_kind", "") or
            devices[0].platform or "").lower()
    if "cpu" in kind or devices[0].platform == "cpu":
        return CHIPS["cpu"]
    for key in ("v6", "v5p", "v5e", "v5 lite", "v4", "v3"):
        if key in kind:
            return CHIPS.get(key, CHIPS["v5e"]) if key != "v5 lite" \
                else CHIPS["v5e"]
    return CHIPS["v4"]


# ---------------------------------------------------------------------------
# Fleets — mixed chip types / speed-scaled stragglers (docs/cluster.md)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Fleet:
    """Per-device chip specs for a (possibly mixed) device fleet, in
    planner device order.  A homogeneous fleet prices exactly like the
    single-``ChipSpec`` path; a heterogeneous one switches the planner
    to the slowest-member roofline bound with per-device batch shares
    (see :func:`predict_time_fleet`)."""

    specs: Tuple[ChipSpec, ...]

    def __post_init__(self):
        if not self.specs:
            raise ValueError("a Fleet needs at least one device")

    @property
    def n_devices(self) -> int:
        return len(self.specs)

    @property
    def heterogeneous(self) -> bool:
        return len({s.name for s in self.specs}) > 1

    def slowest(self) -> ChipSpec:
        return min(self.specs, key=lambda s: s.sustained_flops())

    def name(self) -> str:
        """Canonical ``"v5e:4+v4:4"`` rendering (consecutive runs)."""
        parts, i = [], 0
        while i < len(self.specs):
            j = i
            while j < len(self.specs) and \
                    self.specs[j].name == self.specs[i].name:
                j += 1
            parts.append(f"{self.specs[i].name}:{j - i}")
            i = j
        return "+".join(parts)


def parse_fleet(text: str) -> Fleet:
    """Parse the fleet syntax: ``+``-joined members, each
    ``<chip>[*<scale>][:<count>]``.

    ``"v5e:4+v4:4"`` is four v5e chips plus four v4; ``"cpu*0.5:2"`` is
    two CPU virtual devices running at half speed (the straggler
    stand-in the mixed-fleet tier-1 tests use — a declared slowdown the
    cost model must rank correctly against the measured mesh).
    """
    specs = []
    for member in str(text).split("+"):
        member = member.strip()
        if not member:
            raise ValueError(f"empty fleet member in {text!r}")
        count = 1
        if ":" in member:
            member, _, c = member.rpartition(":")
            count = int(c)
        scale = 1.0
        if "*" in member:
            member, _, s = member.partition("*")
            scale = float(s)
        chip = member.strip()
        if chip not in CHIPS:
            raise ValueError(
                f"unknown chip {chip!r} in fleet {text!r} — known: "
                f"{sorted(CHIPS)}")
        if count < 1:
            raise ValueError(f"fleet member count must be >= 1: {text!r}")
        specs.extend([CHIPS[chip].scaled(scale)] * count)
    return Fleet(specs=tuple(specs))


def _fleet_of(fleet) -> Optional[Fleet]:
    """Normalize the ``fleet=`` argument: None, a :class:`Fleet`, the
    string syntax, or a sequence of :class:`ChipSpec`."""
    if fleet is None:
        return None
    if isinstance(fleet, Fleet):
        return fleet
    if isinstance(fleet, str):
        return parse_fleet(fleet)
    return Fleet(specs=tuple(fleet))


def apportion_shares(weights, total: int) -> Tuple[int, ...]:
    """Largest-remainder apportionment of ``total`` integer units
    proportional to ``weights`` — the per-device batch-share rule.  The
    shares sum to ``total`` EXACTLY (the planner never invents or drops
    examples); ties break toward the earlier device for determinism."""
    n = len(weights)
    wsum = float(sum(weights))
    if wsum <= 0:
        weights, wsum = [1.0] * n, float(n)
    quotas = [w / wsum * total for w in weights]
    shares = [int(q) for q in quotas]
    rest = total - sum(shares)
    by_frac = sorted(range(n), key=lambda i: (shares[i] - quotas[i], i))
    for i in by_frac[:rest]:
        shares[i] += 1
    return tuple(shares)


# ---------------------------------------------------------------------------
# Serve phase split — disaggregated prefill/decode placement
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ServePhaseSplit:
    """Device assignment for a disaggregated serving deployment
    (:class:`apex_tpu.serve.DisaggregatedEngine`): ``prefill`` /
    ``decode`` are index tuples into the fleet's device order.  On a
    single device the phases colocate (``colocated=True``, both tuples
    ``(0,)``) — that is the unified engine, not a degenerate split."""

    prefill: Tuple[int, ...]
    decode: Tuple[int, ...]
    colocated: bool
    reason: str

    def name(self) -> str:
        if self.colocated:
            return "colocated"
        return f"prefill:{len(self.prefill)}+decode:{len(self.decode)}"


def plan_serve_phase_split(fleet=None, *, prefill_weight: float = 1.0,
                           decode_weight: float = 1.0) -> ServePhaseSplit:
    """Split a (possibly heterogeneous) fleet between the two serving
    phases.  Phase demands are opposite corners of the roofline:
    prefill is one wide compute-bound matmul over the prompt (ranked by
    ``sustained_flops``), decode re-reads the whole KV cache per token
    (ranked by ``hbm_bw``) — so in a mixed fleet the members with the
    most HBM bandwidth per unit compute go to decode and the
    biggest-MXU members to prefill.  Phase sizes come from
    :func:`apportion_shares` over the declared demand weights (tokens
    of prefill vs decode work per request, roughly prompt length vs
    ``max_new_tokens``), clamped so each phase keeps at least one
    device."""
    flt = _fleet_of(fleet)
    if flt is None:
        flt = Fleet(specs=(chip_spec(),))
    n = flt.n_devices
    if n == 1:
        return ServePhaseSplit(
            prefill=(0,), decode=(0,), colocated=True,
            reason="single device: phases colocated (unified engine)")
    n_pre, n_dec = apportion_shares(
        [float(prefill_weight), float(decode_weight)], n)
    n_pre = max(1, min(n - 1, n_pre))
    n_dec = n - n_pre
    bw_per_flop = [s.hbm_bw / max(s.sustained_flops(), 1.0)
                   for s in flt.specs]
    order = sorted(range(n), key=lambda i: (-bw_per_flop[i], i))
    decode_ids = tuple(sorted(order[:n_dec]))
    prefill_ids = tuple(sorted(order[n_dec:]))
    return ServePhaseSplit(
        prefill=prefill_ids, decode=decode_ids, colocated=False,
        reason=(f"{flt.name()}: decode→{n_dec} member(s) with the "
                f"highest HBM-BW per sustained FLOP, prefill→{n_pre} "
                f"compute-heaviest"))


# ---------------------------------------------------------------------------
# Model profile — XLA-measured FLOPs/activation footprint + capabilities
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ModelProfile:
    """Static-shape profile the cost model scales per plan.

    ``flops_per_example`` / ``act_bytes_per_example`` /
    ``hbm_bytes_per_example`` are linear-fit slopes over the batch dim
    measured from XLA's own cost analysis of the unsharded
    forward+backward at two probe batch sizes (``source="xla"``), or the
    6·N·tokens fallback when the model cannot lower unsharded
    (``source="analytic"``).  The ``*_fixed`` intercepts capture the
    batch-independent part (weights traffic, per-call scratch).
    """
    n_params: int
    param_shapes: tuple
    param_bytes_fp32: int
    half_itemsize: int                 # 0 when params stay fp32
    slots_per_param: int               # fp32 optimizer slot multiplicity
    batch_ref: int                     # global batch the plan prices for
    batch_bytes_per_example: float
    flops_per_example: float
    flops_fixed: float
    act_bytes_per_example: float
    act_bytes_fixed: float
    hbm_bytes_per_example: float
    hbm_bytes_fixed: float
    logits_bytes_per_example: float    # vocab-head working set (chunk lever)
    seq_len: Optional[int]
    vocab: Optional[int]
    hidden: Optional[int]
    layers: Optional[int]
    heads: Optional[int]
    tp_axis: Optional[str]             # model capability (build option)
    sp_axis: Optional[str]
    source: str = "xla"


def _optimizer_slots(optimizer) -> int:
    from ..optimizers import FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD
    if isinstance(optimizer, (FusedAdam, FusedLAMB)):
        return 2
    if isinstance(optimizer, (FusedSGD, FusedNovoGrad)):
        return 1
    return 2        # unknown: price like Adam, the common case


def _batch_leaves(batch_el):
    return [a for a in jax.tree_util.tree_leaves(batch_el)
            if hasattr(a, "shape")]


def _global_batch_of(example_batch) -> int:
    leaves = _batch_leaves(example_batch[0])
    if not leaves or not leaves[0].shape:
        raise ValueError(
            "example_batch[0] (the model input) has no leading batch "
            "dimension — the planner needs the global batch size")
    return int(leaves[0].shape[0])


def _resize_batch(example_batch, b):
    """ShapeDtypeStruct copy of the batch with splittable elements'
    leading dim set to ``b`` (same broadcast rule as the fused step:
    elements whose every leaf shares the model input's batch dim
    split, anything else is carried whole)."""
    n0 = _global_batch_of(example_batch)

    def splittable(el):
        leaves = _batch_leaves(el)
        return bool(leaves) and all(
            len(a.shape) >= 1 and a.shape[0] == n0 for a in leaves)

    def resize(el, do):
        def leaf(a):
            shape = ((b,) + tuple(a.shape[1:])) if do else tuple(a.shape)
            return jax.ShapeDtypeStruct(shape, jnp.dtype(a.dtype))
        return jax.tree_util.tree_map(leaf, el)

    return tuple(resize(el, i == 0 or splittable(el))
                 for i, el in enumerate(example_batch))


def _introspect(model):
    blocks = getattr(model, "blocks", None)
    layers = len(blocks) if blocks is not None else None
    heads = None
    if blocks is not None and len(blocks):
        for attr in ("heads", "num_heads", "n_heads"):
            heads = getattr(blocks[0], attr, None)
            if heads is None:
                attn = getattr(blocks[0], "attn", None)
                heads = getattr(attn, "heads", None) if attn is not None \
                    else None
            if heads is not None:
                break
    return dict(
        vocab=getattr(model, "vocab_size", None),
        hidden=getattr(model, "hidden", None),
        layers=layers, heads=heads,
        tp_axis=getattr(model, "tp_axis", None),
        sp_axis=getattr(model, "sp_axis", None))


def profile_model(model, optimizer, loss_fn: Callable, example_batch, *,
                  half_dtype=None, keep_batchnorm_fp32: bool = True,
                  rng_seed: int = 0) -> ModelProfile:
    """Measure the model's per-example FLOPs / activation / HBM-traffic
    slopes from XLA's own cost analysis of the unsharded fwd+bwd, at two
    probe batch sizes (pure lower+compile, nothing executes).

    A model built with ``tp_axis=``/``sp_axis=`` cannot trace unsharded
    (its forward psums over mesh axes), so it falls back to the analytic
    6·N FLOP estimate with ``source="analytic"``.
    """
    from ..training.step import _model_dtypes
    from ..nn.modules import Ctx

    params = [p for p in model.parameters() if p is not None]
    buffers = list(model.buffers())
    model_dtypes = _model_dtypes(model, params, half_dtype,
                                 keep_batchnorm_fp32)
    n_params = sum(int(np.prod(p.data.shape)) for p in params)
    param_bytes = n_params * 4
    half_itemsize = 0 if half_dtype is None else jnp.dtype(half_dtype).itemsize
    info = _introspect(model)
    b_hi = _global_batch_of(example_batch)
    act_itemsize = half_itemsize or 4
    batch_bytes = sum(
        int(np.prod(a.shape)) * jnp.dtype(a.dtype).itemsize
        for el in example_batch for a in _batch_leaves(el)) / max(b_hi, 1)

    leaves0 = _batch_leaves(example_batch[0])
    seq_len = (int(leaves0[0].shape[1])
               if leaves0 and len(leaves0[0].shape) >= 2
               and np.issubdtype(np.dtype(leaves0[0].dtype), np.integer)
               else info["layers"] and getattr(model, "max_positions", None))
    logits_bpe = (float(seq_len) * info["vocab"] * 4.0
                  if seq_len and info["vocab"] else 0.0)

    def fwd(vals, *batch):
        env = {id(p): v for p, v in zip(params, vals)}
        env.update({id(bf): jnp.asarray(bf.data) for bf in buffers})
        ctx = Ctx(env=env, stats_out={}, training=True,
                  key=jax.random.PRNGKey(rng_seed))
        x = batch[0]
        if half_dtype is not None:
            from ..amp.policy import _cast_tree
            x = _cast_tree(x, jnp.dtype(half_dtype))
        out = model.forward(ctx, x)
        loss = loss_fn(out, *batch[1:])
        if ctx.aux_losses:
            loss = loss + sum(ctx.aux_losses)
        return loss.astype(jnp.float32)

    vals_struct = [jax.ShapeDtypeStruct(tuple(p.data.shape), jnp.dtype(d))
                   for p, d in zip(params, model_dtypes)]
    b_lo = max(1, b_hi // 2)
    if b_lo == b_hi:
        b_hi = b_lo + 1

    def probe(b):
        batch = _resize_batch(example_batch, b)
        lowered = jax.jit(jax.value_and_grad(fwd)).lower(
            vals_struct, *batch)
        ca = lowered.cost_analysis()
        if not isinstance(ca, dict):        # older jax returns [dict]
            ca = ca[0]
        ma = lowered.compile().memory_analysis()
        return (float(ca.get("flops", 0.0)),
                float(ca.get("bytes accessed", 0.0)),
                float(ma.temp_size_in_bytes))

    common = dict(
        n_params=n_params,
        param_shapes=tuple(tuple(p.data.shape) for p in params),
        param_bytes_fp32=param_bytes,
        half_itemsize=half_itemsize,
        slots_per_param=_optimizer_slots(optimizer),
        batch_ref=_global_batch_of(example_batch),
        batch_bytes_per_example=batch_bytes,
        logits_bytes_per_example=logits_bpe,
        seq_len=seq_len, **info)

    if info["tp_axis"] is not None or info["sp_axis"] is not None:
        tokens = float(seq_len or 1)
        flops_pe = 6.0 * n_params * tokens
        return ModelProfile(
            flops_per_example=flops_pe, flops_fixed=0.0,
            act_bytes_per_example=12.0 * act_itemsize * (
                (info["layers"] or 1) * (info["hidden"] or n_params ** 0.5)
                * tokens) + logits_bpe,
            act_bytes_fixed=0.0,
            hbm_bytes_per_example=flops_pe / 50.0, hbm_bytes_fixed=0.0,
            source="analytic", **common)

    f_lo, h_lo, a_lo = probe(b_lo)
    f_hi, h_hi, a_hi = probe(b_hi)
    db = b_hi - b_lo

    def fit(lo, hi):
        slope = max((hi - lo) / db, 0.0)
        return slope, max(lo - slope * b_lo, 0.0)

    f_s, f_0 = fit(f_lo, f_hi)
    h_s, h_0 = fit(h_lo, h_hi)
    a_s, a_0 = fit(a_lo, a_hi)
    return ModelProfile(
        flops_per_example=f_s, flops_fixed=f_0,
        act_bytes_per_example=a_s, act_bytes_fixed=a_0,
        hbm_bytes_per_example=h_s, hbm_bytes_fixed=h_0,
        source="xla", **common)


# ---------------------------------------------------------------------------
# Plan
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Plan:
    """One point in the (dp × sp × tp × zero × accum × chunked) space,
    with the cost model's predictions attached.  Hashable — the
    structural part (:meth:`key`) is embedded in step-program cache keys
    so compiled executables are per-plan observables."""
    dp: int = 1
    tp: int = 1
    sp: int = 1
    zero_stage: int = 0
    accum: int = 1
    chunked_loss: bool = False
    dp_axis: str = "data"
    tp_axis: Optional[str] = None
    sp_axis: Optional[str] = None
    n_devices: int = 1                   # devices the planner priced for
    predicted_ms: Optional[float] = None
    predicted_hbm: Optional[int] = None
    breakdown: tuple = ()                # ((name, value), ...) — hashable
    collectives: tuple = ()
    measured_ms: Optional[float] = None
    #: calibration-ledger citations: terms whose roofline prior was
    #: replaced by a measured kernel time (strings, for describe())
    ledger_terms: tuple = ()
    #: heterogeneous fleets only: per-device batch shares (ints summing
    #: EXACTLY to the global batch, device order) — the planner's
    #: replacement for the uniform global_batch/dp split.  Empty on a
    #: homogeneous fleet (uniform split applies).
    device_shares: tuple = ()

    def key(self):
        """The structural identity embedded in program cache keys."""
        return (self.dp, self.tp, self.sp, self.zero_stage, self.accum,
                self.chunked_loss)

    @property
    def n_used(self) -> int:
        return self.dp * self.tp * self.sp

    def name(self) -> str:
        parts = [f"dp{self.dp}"]
        if self.sp > 1:
            parts.append(f"sp{self.sp}")
        if self.tp > 1:
            parts.append(f"tp{self.tp}")
        if self.zero_stage:
            parts.append(f"zero{self.zero_stage}")
        if self.accum > 1:
            parts.append(f"K{self.accum}")
        if self.chunked_loss:
            parts.append("chunked")
        return "·".join(parts)

    def step_kwargs(self, devices=None) -> dict:
        """The existing make_train_step knobs this plan threads — the
        planner drives tested primitives, it adds no execution path."""
        kw = {}
        if self.accum > 1:
            kw["accum_steps"] = self.accum
        if self.tp == 1 and self.sp == 1:
            if self.dp > 1:
                kw.update(zero_sharding=True, zero_stage=self.zero_stage,
                          zero_axis=self.dp_axis)
                if devices is not None:
                    kw["zero_mesh"] = Mesh(
                        np.array(list(devices)[:self.dp]), (self.dp_axis,))
        else:
            axes = []
            if self.dp > 1:
                axes.append(self.dp_axis)
            if self.sp > 1:
                axes.append(self.sp_axis)
            if axes:
                kw["axis_name"] = axes[0] if len(axes) == 1 else tuple(axes)
            if self.tp > 1:
                kw["tp_axis"] = self.tp_axis
        return kw

    def _fmt_bytes(self, b):
        return f"{b / 2**30:.2f} GiB" if b >= 2**30 else \
            f"{b / 2**20:.1f} MiB"

    def describe(self) -> str:
        bd = dict(self.breakdown)
        lines = [
            f"Plan {self.name()}  (mesh dp={self.dp} sp={self.sp} "
            f"tp={self.tp}, {self.n_used} of {self.n_devices} devices, "
            f"ZeRO stage {self.zero_stage}, accum K={self.accum}, "
            f"chunked_loss={'on' if self.chunked_loss else 'off'})"]
        if self.predicted_ms is not None:
            lines.append(f"  predicted {self.predicted_ms:.3f} ms/step"
                         + (f" (measured {self.measured_ms:.3f})"
                            if self.measured_ms is not None else ""))
            lines.append(
                "  time: compute {:.3f} + hbm {:.3f} (roofline max) "
                "+ collectives {:.3f} + overhead {:.3f} ms".format(
                    bd.get("compute_ms", 0.0), bd.get("hbm_ms", 0.0),
                    bd.get("collective_ms", 0.0),
                    bd.get("overhead_ms", 0.0)))
        if self.device_shares:
            lines.append(
                "  device batch shares: ["
                + ", ".join(str(s) for s in self.device_shares)
                + "] (heterogeneous fleet — slowest-member bound; "
                "shares sum to the global batch)")
        if self.ledger_terms:
            lines.append("  calibration-ledger re-priced terms "
                         "(measured, not roofline priors):")
            for t in self.ledger_terms:
                lines.append(f"    {t}")
        if self.predicted_hbm is not None:
            mem = " + ".join(
                f"{k[4:]} {self._fmt_bytes(v)}"
                for k, v in self.breakdown if k.startswith("mem_"))
            lines.append(f"  predicted HBM {self._fmt_bytes(self.predicted_hbm)}"
                         f"/device = {mem}")
        if self.collectives:
            lines.append("  collectives: " + "; ".join(self.collectives))
        else:
            lines.append("  collectives: none (single-device program)")
        kw = self.step_kwargs()
        if kw:
            lines.append("  knobs: " + ", ".join(
                f"{k}={v!r}" for k, v in kw.items()))
        if self.chunked_loss:
            lines.append(
                "  note: priced with the chunked LM head+loss "
                "(contrib.chunked_lm_loss) — the plan does not swap your "
                "loss_fn; see docs/auto_parallel.md")
        return "\n".join(lines)


def static_plan_key(plan):
    """Hashable normalization used by the step-program cache keys (re-
    exported by runtime.step_cache); None passes through for unplanned
    steps."""
    return None if plan is None else plan.key()


def plan_from_key(key, n_devices: int = 1) -> Plan:
    """Rebuild a structural :class:`Plan` from a saved manifest key —
    the inverse of :meth:`Plan.key` for the structural fields (cost-model
    predictions are not identity and come back unset).  The elastic
    restore path uses this to describe the plan a schema-2 checkpoint
    was saved under (``manifest["plan"]["key"]``)."""
    dp, tp, sp, zero_stage, accum, chunked_loss = key
    return Plan(dp=int(dp), tp=int(tp), sp=int(sp),
                zero_stage=int(zero_stage), accum=int(accum),
                chunked_loss=bool(chunked_loss), n_devices=int(n_devices))


# ---------------------------------------------------------------------------
# Cost model: memory feasibility + roofline step time
# ---------------------------------------------------------------------------

#: chunked LM loss default chunk count: the working-set divisor the
#: memory lever is priced at (contrib's default chunking)
CHUNKS = 8

#: fraction of HBM the planner refuses to plan into (XLA scratch,
#: fragmentation, the runtime's own buffers)
HBM_RESERVE = 0.08


def _zero_shard_bytes(prof: ModelProfile, itemsize: int, n: int) -> int:
    """Exact per-tensor ZeRO sharding: dim-0-divisible tensors shard n
    ways, the rest stay replicated (zero.py's `_leaf_sharding` rule)."""
    total = 0
    for shape in prof.param_shapes:
        b = int(np.prod(shape)) * itemsize
        if n > 1 and shape and shape[0] >= n and shape[0] % n == 0:
            b //= n
        total += b
    return total


def predict_memory(plan: Plan, prof: ModelProfile, spec: ChipSpec,
                   global_batch: int):
    """Per-device steady-state training footprint: returns
    ``(total_bytes, breakdown)`` with one entry per component."""
    shard_n = plan.dp if plan.zero_stage >= 1 else 1
    masters = _zero_shard_bytes(prof, 4, shard_n)
    slots = prof.slots_per_param * masters
    half = 0
    if prof.half_itemsize:
        half = _zero_shard_bytes(
            prof, prof.half_itemsize,
            plan.dp if plan.zero_stage == 3 else 1)
    # gradient carry/working set, per path: the K>1 scan holds a full
    # replicated fp32 accumulator; a K=1 ZeRO program's gradients land
    # reduce-scattered (per-device 1/dp); a stage-0 all-reduce holds
    # grad + collective double buffer; single-device holds one grad set
    if plan.accum > 1:
        # window accumulator + the per-microbatch gradient it adds
        grads = 2 * prof.param_bytes_fp32
    elif plan.zero_stage >= 1 and plan.dp > 1:
        # reduce-scattered shards, double-buffered through the collective
        grads = 2 * _zero_shard_bytes(prof, 4, plan.dp)
    elif plan.dp > 1:
        # full grads + the all-reduce double buffer
        grads = 2 * prof.param_bytes_fp32
    else:
        grads = prof.param_bytes_fp32
    micro_b = global_batch / (plan.dp * plan.accum)
    tp_act = (1.0 + 1.0 / plan.tp) / 2.0   # sharded FFN/heads, full residual
    acts = (prof.act_bytes_per_example * micro_b / plan.sp * tp_act
            + prof.act_bytes_fixed)
    if plan.chunked_loss and prof.logits_bytes_per_example:
        acts -= (prof.logits_bytes_per_example * micro_b / plan.sp
                 * (1.0 - 1.0 / CHUNKS))
        acts = max(acts, 0.0)
    batch = prof.batch_bytes_per_example * global_batch / plan.dp / plan.sp
    bd = [("mem_masters", masters), ("mem_slots", slots),
          ("mem_half", half), ("mem_grads", grads),
          ("mem_acts", int(acts)), ("mem_batch", int(batch))]
    return int(masters + slots + half + grads + acts + batch), bd


def _ring_all_reduce_s(bytes_, n, spec):
    if n <= 1 or bytes_ <= 0:
        return 0.0
    return 2 * (n - 1) / n * bytes_ / spec.ici_bw \
        + 2 * (n - 1) * spec.ici_latency_s


def _ring_half_s(bytes_, n, spec):
    """One reduce-scatter OR all-gather pass."""
    if n <= 1 or bytes_ <= 0:
        return 0.0
    return (n - 1) / n * bytes_ / spec.ici_bw + (n - 1) * spec.ici_latency_s


def _dp_collective_terms(plan: Plan, prof: ModelProfile, spec: ChipSpec,
                         w_itemsize: int):
    """The dp-axis collective terms (stage-0 grad all-reduce, or the
    ZeRO reduce-scatter / param all-gather pair, plus the stage-3
    per-microbatch gather with the executor's prefetch overlap).
    Shared between :func:`predict_time` and :func:`predict_time_fleet`
    — the fleet path hands in a slowest-link spec so every collective
    is priced at the weakest interconnect in the ring."""
    coll_s, colls = 0.0, []
    gbytes = prof.param_bytes_fp32
    if plan.dp > 1:
        if plan.zero_stage == 0:
            coll_s += _ring_all_reduce_s(gbytes, plan.dp, spec)
            colls.append(f"all-reduce fp32 grads ({_mib(gbytes)}) over "
                         f"{plan.dp_axis}({plan.dp}) at the window boundary")
        else:
            coll_s += _ring_half_s(gbytes, plan.dp, spec)
            colls.append(f"reduce-scatter fp32 grads ({_mib(gbytes)}) into "
                         f"master shards over {plan.dp_axis}({plan.dp})")
            ag = prof.n_params * w_itemsize
            coll_s += _ring_half_s(ag, plan.dp, spec)
            colls.append(f"all-gather updated params ({_mib(ag)}) over "
                         f"{plan.dp_axis}({plan.dp})")
        if plan.zero_stage == 3:
            from ..runtime import executor as _executor
            ag1 = prof.n_params * w_itemsize
            ag3 = plan.accum * ag1
            if plan.accum > 1 and _executor.overlap_enabled("gather"):
                # executor gather prefetch: the scanned window issues
                # microbatch i+1's param gather under microbatch i's
                # compute, so only the prologue gather stays exposed
                coll_s += _ring_half_s(ag1, plan.dp, spec)
                colls.append(
                    f"per-microbatch param all-gather (stage 3, "
                    f"K×{_mib(ag1)} = {_mib(ag3)}/step; prefetch "
                    f"overlaps all but the prologue gather)")
            else:
                coll_s += plan.accum * _ring_half_s(ag1, plan.dp, spec)
                colls.append(f"per-microbatch param all-gather (stage 3, "
                             f"K×{_mib(ag1)} = "
                             f"{_mib(ag3)}/step)")
    return coll_s, colls


def predict_time(plan: Plan, prof: ModelProfile, spec: ChipSpec,
                 global_batch: int):
    """Roofline step time: ``max(compute, HBM) + collectives + overhead``.
    Returns ``(ms, breakdown, collectives)``."""
    n_used = plan.n_used
    micro_b = global_batch / (plan.dp * plan.accum)
    act_itemsize = prof.half_itemsize or 4
    w_itemsize = prof.half_itemsize or 4

    flops = (prof.flops_per_example * global_batch / n_used
             + plan.accum * prof.flops_fixed)
    # virtual devices split one host: per-plan sustained rate is the
    # host's, not n_used × the host's
    sustained = spec.sustained_flops() / (n_used if spec.shared_host else 1)
    compute_s = flops / sustained

    weight_traffic = plan.accum * prof.n_params * w_itemsize / plan.tp
    if plan.zero_stage == 3:
        weight_traffic /= plan.dp
    hbm_bytes = (prof.hbm_bytes_per_example * global_batch / n_used
                 + plan.accum * prof.hbm_bytes_fixed + weight_traffic)
    if plan.chunked_loss and prof.logits_bytes_per_example:
        hbm_bytes -= (prof.logits_bytes_per_example * global_batch / n_used
                      * (1.0 - 1.0 / CHUNKS))
    hbm_bw = spec.hbm_bw / (n_used if spec.shared_host else 1)
    hbm_s = max(hbm_bytes, 0.0) / hbm_bw

    coll_s, colls = _dp_collective_terms(plan, prof, spec, w_itemsize)
    gbytes = prof.param_bytes_fp32
    if plan.tp > 1:
        if prof.layers and prof.hidden and prof.seq_len:
            per_micro = (4.0 * prof.layers * micro_b * prof.seq_len
                         / plan.sp * prof.hidden * act_itemsize)
        else:
            per_micro = 0.5 * prof.act_bytes_per_example * micro_b
        tp_bytes = plan.accum * per_micro
        coll_s += plan.accum * _ring_all_reduce_s(per_micro, plan.tp, spec)
        colls.append(f"activation all-reduce (row-parallel psum, "
                     f"{_mib(tp_bytes)}/step) over "
                     f"{plan.tp_axis or 'tp'}({plan.tp})")
        shard_grads = 0.66 * gbytes     # head/FFN block fraction
        coll_s += _ring_all_reduce_s(shard_grads, plan.tp, spec)
        colls.append(f"block-sparse grad assembly psum "
                     f"({_mib(shard_grads)}) over "
                     f"{plan.tp_axis or 'tp'}({plan.tp})")
    if plan.sp > 1:
        if prof.layers and prof.hidden and prof.seq_len:
            kv = (2.0 * prof.layers * micro_b * prof.seq_len
                  * prof.hidden * act_itemsize)
        else:
            kv = 0.3 * prof.act_bytes_per_example * micro_b
        coll_s += plan.accum * _ring_all_reduce_s(kv, plan.sp, spec)
        colls.append(f"ring ppermute of K/V blocks ({_mib(kv)}/microbatch) "
                     f"over {plan.sp_axis or 'sp'}({plan.sp})")
        coll_s += _ring_all_reduce_s(gbytes, plan.sp, spec)
        colls.append(f"all-reduce fp32 grads ({_mib(gbytes)}) over "
                     f"{plan.sp_axis or 'sp'}({plan.sp})")

    overhead_s = plan.accum * spec.overhead_s
    total_s = max(compute_s, hbm_s) + coll_s + overhead_s
    bd = [("compute_ms", compute_s * 1e3), ("hbm_ms", hbm_s * 1e3),
          ("collective_ms", coll_s * 1e3), ("overhead_ms", overhead_s * 1e3)]
    return total_s * 1e3, bd, colls


def predict_time_fleet(plan: Plan, prof: ModelProfile, fleet: Fleet,
                       global_batch: int, shares=None):
    """Slowest-member roofline for a heterogeneous fleet (AMP
    arXiv:2210.07297, Poplar arXiv:2408.12596): every member computes
    its batch SHARE, the step completes when the slowest member does,
    and collectives run at the weakest link in the ring.

    ``shares`` defaults to :func:`apportion_shares` proportional to each
    member's sustained rate; pass an explicit tuple (e.g. a uniform
    split) to price an alternative assignment — the mixed-fleet tier-1
    test prices both and pins that their predicted order matches the
    measured order on the CPU mesh.

    Returns ``(ms, breakdown, collectives, shares)``.  Fleet plans are
    dp-only (``_structural_reject`` enforces it), so only the dp
    collective terms appear.
    """
    n_used = plan.n_used
    specs = fleet.specs[:n_used]
    if len(specs) < n_used:
        raise ValueError(f"plan {plan.name()} needs {n_used} devices, "
                         f"fleet has {fleet.n_devices}")
    if shares is None:
        shares = apportion_shares(
            [s.sustained_flops() for s in specs], global_batch)
    shares = tuple(int(s) for s in shares)
    if len(shares) != n_used or sum(shares) != global_batch:
        raise ValueError(
            f"device shares {shares} must have {n_used} entries summing "
            f"to the global batch {global_batch}")
    w_itemsize = prof.half_itemsize or 4

    # each member's roofline at its share; the step is bound by the
    # slowest member (max over members), not the mean
    bound_s, bound_i, bound_compute, bound_hbm = 0.0, 0, 0.0, 0.0
    for i, (spec, share) in enumerate(zip(specs, shares)):
        div = n_used if spec.shared_host else 1
        flops = (prof.flops_per_example * share
                 + plan.accum * prof.flops_fixed)
        compute_s = flops / (spec.sustained_flops() / div)
        weight_traffic = plan.accum * prof.n_params * w_itemsize
        if plan.zero_stage == 3:
            weight_traffic /= plan.dp
        hbm_bytes = (prof.hbm_bytes_per_example * share
                     + plan.accum * prof.hbm_bytes_fixed + weight_traffic)
        if plan.chunked_loss and prof.logits_bytes_per_example:
            hbm_bytes -= (prof.logits_bytes_per_example * share
                          * (1.0 - 1.0 / CHUNKS))
        hbm_s = max(hbm_bytes, 0.0) / (spec.hbm_bw / div)
        member_s = max(compute_s, hbm_s)
        if member_s > bound_s:
            bound_s, bound_i = member_s, i
            bound_compute, bound_hbm = compute_s, hbm_s

    # collectives at the slowest link: min bandwidth, max latency
    link = dataclasses.replace(
        fleet.slowest(),
        ici_bw=min(s.ici_bw for s in specs),
        ici_latency_s=max(s.ici_latency_s for s in specs))
    coll_s, colls = _dp_collective_terms(plan, prof, link, w_itemsize)
    if fleet.heterogeneous and coll_s > 0:
        colls.append(f"(all collectives priced at the slowest link: "
                     f"{link.ici_bw / 1e9:.1f} GB/s, "
                     f"{link.ici_latency_s * 1e6:.0f} us/hop)")

    overhead_s = plan.accum * max(s.overhead_s for s in specs)
    total_s = bound_s + coll_s + overhead_s
    bd = [("compute_ms", bound_compute * 1e3), ("hbm_ms", bound_hbm * 1e3),
          ("collective_ms", coll_s * 1e3),
          ("overhead_ms", overhead_s * 1e3),
          ("bound_member", float(bound_i))]
    return total_s * 1e3, bd, colls, shares


def _mib(b):
    return f"{b / 2**20:.1f} MiB"


# ---------------------------------------------------------------------------
# Calibration-ledger re-pricing (apex_tpu.kernels.ledger)
# ---------------------------------------------------------------------------


def model_fp(prof: ModelProfile, global_batch: int) -> str:
    """The ledger's model-shape fingerprint: what makes two training
    runs "the same workload" for plan-measurement reuse.  Built with the
    same :func:`~apex_tpu.kernels.dispatch.shape_fp` helper the kernel
    probes use, so one canonicalization serves both ledger sections."""
    from ..kernels.dispatch import shape_fp
    return shape_fp(params=int(prof.n_params),
                    layers=int(prof.layers or 0),
                    hidden=int(prof.hidden or 0),
                    heads=int(prof.heads or 0),
                    seq=int(prof.seq_len or 0),
                    vocab=int(prof.vocab or 0),
                    batch=int(global_batch))


def _opt_kernel_name(optimizer) -> Optional[str]:
    """Which registered multi-tensor kernel prices this optimizer's
    update step (None: no registered kernel — priors keep deciding)."""
    try:
        from ..optimizers import FusedAdam, FusedSGD
    except Exception:
        return None
    if isinstance(optimizer, FusedAdam):
        return "multi_tensor_adam"
    if isinstance(optimizer, FusedSGD):
        return "multi_tensor_sgd"
    return None


def _plan_attention_fp(plan: Plan, prof: ModelProfile,
                       global_batch: int) -> Optional[str]:
    """The per-device attention-call fingerprint this plan would hand to
    ``decide("flash_attention", ...)``: micro-batch rows, heads, the
    sp-sharded query chunk against full keys, head dim."""
    if not (prof.layers and prof.heads and prof.hidden and prof.seq_len):
        return None
    if prof.hidden % prof.heads:
        return None
    from ..kernels.dispatch import attention_fp
    micro_b = max(int(global_batch // (plan.dp * plan.accum)), 1)
    dt = "bfloat16" if prof.half_itemsize == 2 else "float32"
    return attention_fp(micro_b, prof.heads,
                        prof.seq_len // max(plan.sp, 1), prof.seq_len,
                        prof.hidden // prof.heads, dtype=dt, causal=True)


def _ledger_reprice(plan: Plan, prof: ModelProfile, spec: ChipSpec,
                    global_batch: int, chip: str,
                    opt_kernel: Optional[str]) -> Plan:
    """Swap the roofline's attention and optimizer terms for
    ledger-measured kernel times when the calibration ledger holds an
    entry for this chip and the plan's exact shapes.

    The adjustment is a delta — ``predicted_ms += measured − prior`` —
    against the analytic estimate of the same term (attention FLOPs at
    the sustained rate; the optimizer's read/modify/write HBM traffic at
    bandwidth), so an empty ledger changes nothing and a measurement
    shifts only the term it covers.  Citations land in
    :attr:`Plan.ledger_terms` for ``describe()``.
    """
    try:
        from ..kernels import ledger as _kl
        from ..kernels.dispatch import multi_tensor_fp
        led = _kl.get_ledger()
    except Exception:
        return plan
    terms, delta_ms = [], 0.0
    n_used = plan.n_used
    sustained = spec.sustained_flops() / (n_used if spec.shared_host else 1)
    hbm_bw = spec.hbm_bw / (n_used if spec.shared_host else 1)
    micro_b = max(int(global_batch // (plan.dp * plan.accum)), 1)

    afp = _plan_attention_fp(plan, prof, global_batch)
    if afp is not None:
        rec = led.lookup_kernel(chip, "flash_attention", afp)
        if rec is not None:
            tier = "pallas" if rec["win"] >= 1.0 else "xla"
            per_call_us = rec["pallas_us" if tier == "pallas" else "xla_us"]
            calls = prof.layers * plan.accum
            measured_ms = per_call_us * 1e-3 * calls
            sq = prof.seq_len // max(plan.sp, 1)
            d = prof.hidden // prof.heads
            # fwd 2 matmuls of 2·b·h·sq·sk·d each, bwd ≈ 2× fwd
            attn_flops = (12.0 * calls * micro_b * prof.heads * sq
                          * prof.seq_len * d)
            prior_ms = attn_flops / sustained * 1e3
            delta_ms += measured_ms - prior_ms
            terms.append(
                f"attention {measured_ms:.3f} ms/step ledger-measured "
                f"(flash_attention[{afp}] {per_call_us:.1f}us/call, "
                f"{tier} tier, win {rec['win']:.2f}x, x{calls} calls; "
                f"roofline prior {prior_ms:.3f} ms)")
    if opt_kernel is not None:
        ofp = multi_tensor_fp(opt_kernel.replace("multi_tensor_", ""),
                              prof.n_params, len(prof.param_shapes))
        rec = led.lookup_kernel(chip, opt_kernel, ofp)
        if rec is not None:
            tier = "pallas" if rec["win"] >= 1.0 else "xla"
            per_us = rec["pallas_us" if tier == "pallas" else "xla_us"]
            shard = plan.dp if (plan.zero_stage >= 1 and plan.dp > 1) else 1
            measured_ms = per_us * 1e-3 / shard
            # read masters+slots+grads, write masters+slots — the
            # bandwidth-bound analytic estimate of the update sweep
            opt_bytes = ((3 + 2 * prof.slots_per_param)
                         * prof.param_bytes_fp32 / shard)
            prior_ms = opt_bytes / hbm_bw * 1e3
            delta_ms += measured_ms - prior_ms
            terms.append(
                f"optimizer {measured_ms:.3f} ms/step ledger-measured "
                f"({opt_kernel}[{ofp}] {per_us:.1f}us, {tier} tier, "
                f"win {rec['win']:.2f}x"
                + (f", /{shard} ZeRO shards" if shard > 1 else "")
                + f"; roofline prior {prior_ms:.3f} ms)")
    if not terms:
        return plan
    return dataclasses.replace(
        plan, predicted_ms=max(plan.predicted_ms + delta_ms, 1e-3),
        ledger_terms=tuple(terms))


# ---------------------------------------------------------------------------
# Enumeration + ranking
# ---------------------------------------------------------------------------


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_plans(n_devices: int, *, chunked_loss=False,
                    accum_max: int = 32, global_batch: int):
    """Yield the raw candidate space: full-mesh dp×sp×tp factorizations
    plus partial pure-dp meshes (for batch-divisibility limits), ZeRO
    stages where the framework supports them (dp-only meshes — the
    GSPMD ZeRO path excludes explicit tp/sp axes), accumulation K over
    divisors of the local batch, and the chunked-loss lever."""
    meshes = set()
    for dp in _divisors(n_devices):
        rest = n_devices // dp
        for sp in _divisors(rest):
            meshes.add((dp, sp, rest // sp))
        meshes.add((dp, 1, 1))       # partial mesh: idle devices allowed
    chunk_opts = (False, True) if chunked_loss is None else (chunked_loss,)
    for dp, sp, tp in sorted(meshes):
        zero_opts = (0, 1, 3) if (dp > 1 and sp == 1 and tp == 1) else (0,)
        local = global_batch // dp if dp and global_batch % dp == 0 else 1
        ks = [k for k in _divisors(max(local, 1))
              if k <= accum_max and (k & (k - 1)) == 0]
        for zero in zero_opts:
            for k in ks or [1]:
                for ch in chunk_opts:
                    yield Plan(dp=dp, sp=sp, tp=tp, zero_stage=zero,
                               accum=k, chunked_loss=ch,
                               n_devices=n_devices)


@dataclasses.dataclass
class PlanReport:
    """Planner output: the ranked feasible plans, and every rejected
    plan with its stated reason — nothing is pruned silently."""
    best: Optional[Plan]
    ranked: list
    rejected: list                      # [(Plan, reason)]
    profile: ModelProfile
    chip: ChipSpec
    global_batch: int
    hbm_cap: float
    fleet: Optional[Fleet] = None

    def describe(self, top: int = 5) -> str:
        chip_desc = (f"fleet {self.fleet.name()}"
                     if self.fleet is not None and self.fleet.heterogeneous
                     else self.chip.name)
        out = [f"auto-parallel plan report — {chip_desc}, "
               f"global batch {self.global_batch}, HBM cap "
               f"{self.hbm_cap / 2**30:.2f} GiB/device, model "
               f"{self.profile.n_params / 1e6:.2f}M params "
               f"(profile: {self.profile.source})"]
        if self.best is None:
            out.append("NO FEASIBLE PLAN — every candidate was rejected:")
        else:
            out.append(f"chosen: {self.best.name()}")
            out.append(self.best.describe())
            out.append(f"runners-up (of {len(self.ranked)} feasible):")
            for p in self.ranked[1:top]:
                why = (f"+{p.predicted_ms - self.best.predicted_ms:.3f} ms "
                       f"predicted vs chosen"
                       if p.predicted_ms is not None else "")
                out.append(f"  {p.name():<24} {p.predicted_ms:9.3f} ms  "
                           f"{(p.predicted_hbm or 0) / 2**20:9.1f} MiB  "
                           f"{why}")
        shown = self.rejected[:max(top * 3, 12)]
        if shown:
            out.append(f"rejected ({len(self.rejected)}):")
            for p, reason in shown:
                out.append(f"  {p.name():<24} {reason}")
            if len(self.rejected) > len(shown):
                out.append(f"  ... {len(self.rejected) - len(shown)} more "
                           f"(same reason classes)")
        return "\n".join(out)


def plan_training(model, optimizer, loss_fn: Callable, example_batch, *,
                  devices=None, half_dtype=None,
                  keep_batchnorm_fp32: bool = True,
                  chip: Optional[ChipSpec] = None,
                  hbm_cap_bytes: Optional[float] = None,
                  hbm_reserve: float = HBM_RESERVE,
                  accum_max: int = 32,
                  chunked_loss=False,
                  profile: Optional[ModelProfile] = None,
                  fleet=None) -> PlanReport:
    """Enumerate → prune (memory, capability) → rank (roofline).

    ``chunked_loss``: what the caller's ``loss_fn`` actually is (the
    planner cannot swap it) — pass ``None`` to enumerate both and see
    the lever's predicted effect in the report.

    ``fleet``: a :class:`Fleet`, the ``"v5e:4+v4:4"`` string syntax, or
    a sequence of :class:`ChipSpec` — one per device, planner order.  A
    heterogeneous fleet switches pricing to the slowest-member bound
    with per-device batch shares (:func:`predict_time_fleet`); memory
    feasibility is then checked for the LARGEST share against the
    SMALLEST member's HBM (conservative on both axes).
    """
    flt = _fleet_of(fleet)
    devices = list(devices) if devices is not None else jax.devices()
    spec = chip or (flt.slowest() if flt is not None else
                    chip_spec(devices))
    prof = profile or profile_model(
        model, optimizer, loss_fn, example_batch, half_dtype=half_dtype,
        keep_batchnorm_fp32=keep_batchnorm_fp32)
    global_batch = _global_batch_of(example_batch)
    if hbm_cap_bytes is not None:
        cap = hbm_cap_bytes
    elif flt is not None:
        cap = min(s.hbm_bytes for s in flt.specs) * (1.0 - hbm_reserve)
    else:
        cap = spec.hbm_bytes * (1.0 - hbm_reserve)
    n_plan_devices = flt.n_devices if flt is not None else len(devices)

    chip_key, mfp = None, None
    try:
        from ..kernels import ledger as _kl
        chip_key = _kl.chip_name(devices)
        mfp = model_fp(prof, global_batch)
    except Exception:
        _kl = None
    opt_kernel = _opt_kernel_name(optimizer)

    hetero = flt is not None and flt.heterogeneous
    feasible, rejected = [], []
    for plan in enumerate_plans(n_plan_devices, chunked_loss=chunked_loss,
                                accum_max=accum_max,
                                global_batch=global_batch):
        reason = _structural_reject(plan, prof, global_batch, fleet=flt)
        if reason is not None:
            rejected.append((plan, reason))
            continue
        plan = dataclasses.replace(
            plan,
            tp_axis=prof.tp_axis if plan.tp > 1 else None,
            sp_axis=prof.sp_axis if plan.sp > 1 else None)
        if hetero:
            # memory for the binding member: the largest share on the
            # smallest HBM — price the uniform formula at an effective
            # global batch of max_share × dp so micro_b == max_share
            shares = apportion_shares(
                [s.sustained_flops() for s in flt.specs[:plan.n_used]],
                global_batch)
            mem_batch = max(shares) * plan.dp
        else:
            shares, mem_batch = None, global_batch
        mem, mem_bd = predict_memory(plan, prof, spec, mem_batch)
        if mem > cap:
            over = dict(mem_bd)
            reason = (
                f"memory-infeasible: needs {mem / 2**20:.1f} MiB/device > "
                f"cap {cap / 2**20:.1f} MiB (masters "
                f"{over['mem_masters'] / 2**20:.1f} + slots "
                f"{over['mem_slots'] / 2**20:.1f} + half "
                f"{over['mem_half'] / 2**20:.1f} + grads "
                f"{over['mem_grads'] / 2**20:.1f} + acts "
                f"{over['mem_acts'] / 2**20:.1f} + batch "
                f"{over['mem_batch'] / 2**20:.1f})")
            rejected.append((dataclasses.replace(
                plan, predicted_hbm=mem, breakdown=tuple(mem_bd)), reason))
            continue
        if hetero:
            ms, time_bd, colls, shares = predict_time_fleet(
                plan, prof, flt, global_batch, shares=shares)
        else:
            ms, time_bd, colls = predict_time(plan, prof, spec,
                                              global_batch)
        plan = dataclasses.replace(
            plan, predicted_ms=ms, predicted_hbm=mem,
            breakdown=tuple(time_bd + mem_bd), collectives=tuple(colls),
            device_shares=tuple(shares) if shares is not None else ())
        if chip_key is not None:
            plan = _ledger_reprice(plan, prof, spec, global_batch,
                                   chip_key, opt_kernel)
        feasible.append(plan)

    # deterministic rank: predicted time, then fewer devices, lower
    # stage, smaller K (simpler plans win ties)
    feasible.sort(key=lambda p: (p.predicted_ms, p.n_used, p.zero_stage,
                                 p.accum, p.tp, p.sp))
    # measured plan trials from previous runs of this same (chip, model
    # shape) re-rank repeated runs from data — measurement outranks any
    # prediction, exactly as a fresh auto_tune pass would
    if chip_key is not None and mfp is not None:
        try:
            meas = _kl.get_ledger().plan_measurements(chip_key, mfp)
        except Exception:
            meas = {}
        if meas:
            from ..kernels.ledger import _plan_key_str
            feasible = [
                dataclasses.replace(p, measured_ms=float(
                    meas[_plan_key_str(p.key())]["measured_ms"]))
                if (p.measured_ms is None
                    and _plan_key_str(p.key()) in meas) else p
                for p in feasible]
            feasible.sort(key=lambda p: (
                p.measured_ms is None,
                p.measured_ms if p.measured_ms is not None
                else p.predicted_ms,
                p.n_used, p.zero_stage, p.accum, p.tp, p.sp))
    return PlanReport(best=feasible[0] if feasible else None,
                      ranked=feasible, rejected=rejected, profile=prof,
                      chip=spec, global_batch=global_batch, hbm_cap=cap,
                      fleet=flt)


def _structural_reject(plan: Plan, prof: ModelProfile,
                       global_batch: int,
                       fleet: Optional[Fleet] = None) -> Optional[str]:
    if fleet is not None and fleet.heterogeneous and \
            (plan.tp > 1 or plan.sp > 1):
        return (f"tp={plan.tp}/sp={plan.sp} across the mixed fleet "
                f"{fleet.name()}: tensor/sequence parallelism needs "
                f"identical per-shard throughput (lockstep layer math), "
                f"so heterogeneous fleets are dp-only — stragglers are "
                f"absorbed by batch shares, not layer shards")
    if plan.dp > 1 and global_batch % plan.dp:
        return (f"global batch {global_batch} not divisible by "
                f"dp={plan.dp}")
    if plan.tp > 1:
        if prof.tp_axis is None:
            return (f"tp={plan.tp} needs a model built with tp_axis= "
                    f"(this one was built unsharded — rebuild with "
                    f"tp_axis='tp' to enable tensor parallelism)")
        if prof.heads and prof.heads % plan.tp:
            return (f"tp={plan.tp} does not divide the model's "
                    f"{prof.heads} attention heads")
    if plan.sp > 1:
        if prof.sp_axis is None:
            return (f"sp={plan.sp} needs a model built with sp_axis= "
                    f"(ring attention) — rebuild to enable sequence "
                    f"parallelism")
        if prof.seq_len and prof.seq_len % plan.sp:
            return (f"sp={plan.sp} does not divide sequence length "
                    f"{prof.seq_len}")
    if plan.chunked_loss and not prof.logits_bytes_per_example:
        return ("chunked_loss priced but the model exposes no vocab head "
                "(no logits working set to chunk)")
    return None


# ---------------------------------------------------------------------------
# Applying a plan: thread the existing knobs / wrap the explicit-axis path
# ---------------------------------------------------------------------------


def _resolve_devices(devices):
    if devices is None:
        return list(jax.devices())
    if isinstance(devices, int):
        ds = list(jax.devices())
        if devices > len(ds):
            raise ValueError(f"asked to plan for {devices} devices, "
                             f"have {len(ds)}")
        return ds[:devices]
    return list(devices)


def apply_plan(plan: Plan, model, optimizer, loss_fn, devices=None,
               **base_kwargs):
    """Build the train step a plan describes by threading the existing
    make_train_step knobs (dp/ZeRO plans run the GSPMD global-view path,
    tp/sp plans the explicit shard_map path).  The returned step carries
    ``.plan``."""
    from ..training.step import make_train_step
    devices = _resolve_devices(devices)
    if plan.n_used > len(devices):
        raise ValueError(f"plan {plan.name()} needs {plan.n_used} devices, "
                         f"have {len(devices)}")
    kw = dict(base_kwargs)
    kw.pop("parallel", None)
    for knob in ("axis_name", "tp_axis", "zero_sharding", "zero_mesh"):
        if kw.pop(knob, None):
            raise ValueError(
                f"parallel= owns the {knob} knob — pass one or the other")
    kw.update(plan.step_kwargs(devices))

    if plan.tp == 1 and plan.sp == 1:
        step = make_train_step(model, optimizer, loss_fn, _plan=plan, **kw)
        step.plan = plan
        return step

    # explicit-axis path: the tested shard_map wrap (tp / sp / dp×tp)
    if plan.tp > 1 and getattr(model, "tp_axis", None) is None:
        raise ValueError(
            f"plan {plan.name()} uses tensor parallelism but the model "
            f"was built without tp_axis= — rebuild the model with "
            f"tp_axis={plan.tp_axis or 'tp'!r}")
    if plan.sp > 1 and getattr(model, "sp_axis", None) is None:
        raise ValueError(
            f"plan {plan.name()} uses sequence parallelism but the model "
            f"was built without sp_axis= — rebuild the model with "
            f"sp_axis={plan.sp_axis or 'sp'!r}")
    donate = bool(kw.get("donate_state", True))
    step = make_train_step(model, optimizer, loss_fn, _plan=plan, **kw)
    axis_dims = [(plan.dp_axis, plan.dp)]
    if plan.sp > 1:
        axis_dims.append((model.sp_axis, plan.sp))
    if plan.tp > 1:
        axis_dims.append((model.tp_axis, plan.tp))
    axis_dims = [(n, s) for n, s in axis_dims if s > 1] or \
        [(plan.dp_axis, 1)]
    names = tuple(n for n, _ in axis_dims)
    shape = tuple(s for _, s in axis_dims)
    mesh = Mesh(np.array(devices[:plan.n_used]).reshape(shape), names)
    mean_axes = tuple(n for n, s in axis_dims
                      if s > 1 and n != (model.tp_axis if plan.tp > 1
                                         else None))

    from .. import compat
    from ..runtime import executor as _executor

    raw = step._raw_step_fn
    plan_key = plan.key()
    token = next(_PLAN_TOKENS)
    dispatch_no = itertools.count(1)
    programs = {}

    def _batch_spec(el):
        def leaf(a):
            dims = []
            if plan.dp > 1 and getattr(a, "ndim", 0) >= 1:
                dims.append(plan.dp_axis)
            else:
                dims.append(None)
            if plan.sp > 1 and getattr(a, "ndim", 0) >= 2:
                dims.append(model.sp_axis)
            return P(*dims)
        return jax.tree_util.tree_map(leaf, el)

    def _program(specs):
        prog = programs.get(specs)
        if prog is not None:
            return prog

        def run(state, *b):
            new_state, loss = raw(state, *b)
            if mean_axes:
                # the in-step loss is one shard's local mean; make
                # the reported number the global mean (grads are
                # already psum-exchanged inside the step)
                loss = jax.lax.pmean(
                    loss, mean_axes if len(mean_axes) > 1
                    else mean_axes[0])
            return new_state, loss

        def wrap(f):
            return compat.shard_map(f, mesh=mesh,
                                    in_specs=(P(),) + specs,
                                    out_specs=(P(), P()), check_vma=False)

        prog = _executor.Program(
            "train_step", (token, plan_key, specs, donate), run,
            donate_argnums=(0,) if donate else (), wrap=wrap)
        programs[specs] = prog
        return prog

    def dispatch(state, *batch):
        specs = tuple(_batch_spec(b) for b in batch)
        return _executor.executor.submit(
            _program(specs), (state,) + batch, step=next(dispatch_no))

    step._step_fn = dispatch
    step._via_executor = True
    step.plan = plan
    return step


# ---------------------------------------------------------------------------
# Measured refinement (auto_tune) + the make_train_step entry point
# ---------------------------------------------------------------------------


def _concrete_batch(example_batch):
    """Concrete arrays for trial runs: the example's own arrays where
    concrete, zeros of the right shape/dtype where abstract."""
    def leaf(a):
        if isinstance(a, jax.ShapeDtypeStruct):
            return jnp.zeros(a.shape, a.dtype)
        return jnp.asarray(a)
    return tuple(jax.tree_util.tree_map(leaf, el) for el in example_batch)


def measure_plan(plan: Plan, model, optimizer, loss_fn, example_batch,
                 devices=None, steps: int = 3, **base_kwargs):
    """Compile + time a plan through the real step (the step-program
    cache does the compiling).  Returns min ms/step over ``steps`` timed
    calls, or None with the failure recorded on the exception."""
    batch = _concrete_batch(example_batch)
    step = apply_plan(plan, model, optimizer, loss_fn, devices=devices,
                      **base_kwargs)
    float(step(*batch))              # compile + warm
    best = math.inf
    for _ in range(max(steps, 1)):
        t0 = time.perf_counter()
        float(step(*batch))          # scalar fetch = device sync
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def auto_tune_report(report: PlanReport, model, optimizer, loss_fn,
                     example_batch, devices=None, k: int = 3,
                     steps: int = 3, **base_kwargs) -> PlanReport:
    """Measured refinement: compile and time the top-k predicted plans
    and re-rank by measurement (prediction breaks ties / fills gaps)."""
    chip_key, mfp, led = None, None, None
    try:
        from ..kernels import ledger as _kl
        chip_key = _kl.chip_name(devices)
        mfp = model_fp(report.profile, report.global_batch)
        led = _kl.get_ledger()
    except Exception:
        pass
    measured = []
    for plan in report.ranked[:max(k, 1)]:
        try:
            ms = measure_plan(plan, model, optimizer, loss_fn,
                              example_batch, devices=devices, steps=steps,
                              **base_kwargs)
            measured.append(dataclasses.replace(plan, measured_ms=ms))
            # each trial measurement is a calibration-ledger entry —
            # stamped with (chip, model_fp) so ledger.ingest_events can
            # fold the event stream back in, and written through to the
            # ledger directly so the NEXT plan_training on this shape
            # re-ranks from measurement without an ingest pass
            _obs.event("plan.auto_tune", plan=plan.name(),
                       plan_key=plan.key(), measured_ms=ms,
                       predicted_ms=plan.predicted_ms,
                       chip=chip_key, model_fp=mfp)
            if led is not None:
                led.record_plan(chip_key, mfp, plan.key(),
                                measured_ms=ms,
                                predicted_ms=plan.predicted_ms,
                                plan=plan.name(), source="auto_tune")
        except Exception as e:        # a plan that fails to run loses
            report.rejected.append(
                (plan, f"auto_tune trial failed: {type(e).__name__}: {e}"))
            _obs.event("plan.auto_tune", plan=plan.name(),
                       plan_key=plan.key(), measured_ms=None,
                       chip=chip_key, model_fp=mfp,
                       error=f"{type(e).__name__}: {e}")
    measured.sort(key=lambda p: (p.measured_ms, p.predicted_ms))
    ranked = measured + [p for p in report.ranked
                         if p.key() not in {m.key() for m in measured}]
    return dataclasses.replace(
        report, best=ranked[0] if ranked else None, ranked=ranked)


def build_planned_step(model, optimizer, loss_fn, parallel, *,
                       example_batch=None, devices=None, auto_tune: int = 0,
                       plan_options=None, **base_kwargs):
    """The ``make_train_step(parallel=...)`` entry point: resolve
    "auto" (or a Plan) into knobs and build the step.  The returned step
    carries ``.plan`` and (for "auto") ``.plan_report``."""
    devices = _resolve_devices(devices)
    report = None
    if isinstance(parallel, str):
        if parallel != "auto":
            raise ValueError(
                f"parallel= accepts 'auto' or a parallel.auto.Plan, "
                f"got {parallel!r}")
        if example_batch is None:
            raise ValueError(
                "parallel='auto' needs example_batch=(x, y, ...) — a "
                "tuple of arrays (or ShapeDtypeStructs) shaped like one "
                "global training batch, so the planner knows the batch "
                "and sequence geometry")
        opts = dict(plan_options or {})
        report = plan_training(
            model, optimizer, loss_fn, example_batch, devices=devices,
            half_dtype=base_kwargs.get("half_dtype"),
            keep_batchnorm_fp32=base_kwargs.get("keep_batchnorm_fp32",
                                                True),
            **opts)
        if report.best is None:
            raise RuntimeError(
                "parallel='auto': no feasible plan\n" + report.describe())
        if auto_tune:
            report = auto_tune_report(
                report, model, optimizer, loss_fn, example_batch,
                devices=devices, k=auto_tune, **base_kwargs)
            if report.best is None:
                raise RuntimeError(
                    "parallel='auto': every auto_tune trial failed\n"
                    + report.describe())
        plan = report.best
    elif isinstance(parallel, Plan):
        plan = parallel
    else:
        raise TypeError(
            f"parallel= accepts 'auto' or a parallel.auto.Plan, got "
            f"{type(parallel).__name__}")
    chip_key, mfp = None, None
    try:
        from ..kernels import ledger as _kl
        chip_key = _kl.chip_name(devices)
        if report is not None:
            mfp = model_fp(report.profile, report.global_batch)
    except Exception:
        _kl = None
    _obs.event("plan.decision", plan=plan.name(), plan_key=plan.key(),
               source="auto" if report is not None else "explicit",
               n_devices=len(devices),
               predicted_ms=plan.predicted_ms,
               measured_ms=plan.measured_ms,
               chip=chip_key, model_fp=mfp,
               feasible=len(report.ranked) if report is not None else None,
               rejected=len(report.rejected) if report is not None else None)
    if mfp is not None:
        # the decision itself is ledger data: record_plan keeps any
        # prior measured_ms when this decision carries none
        try:
            _kl.get_ledger().record_plan(
                chip_key, mfp, plan.key(), measured_ms=plan.measured_ms,
                predicted_ms=plan.predicted_ms, plan=plan.name(),
                source="decision")
        except Exception:
            pass
    step = apply_plan(plan, model, optimizer, loss_fn, devices=devices,
                      **base_kwargs)
    step.plan_report = report
    return step


def measured_step_memory(compiled) -> int:
    """Per-device footprint of a compiled step program, donation-aware:
    arguments + outputs + temps − aliased (donated buffers counted
    once).  The validation target for :func:`predict_memory`.

    Compile the program with :func:`compile_uncached`: when jax 0.4.x's
    persistent compilation cache is enabled, executables that pass
    through its (de)serialization layer report ``alias_size_in_bytes=0``
    — a donated program then measures its outputs double, and whether a
    given compile passes through the layer depends on the
    ``min_compile_time_secs`` threshold, i.e. on machine load.
    """
    ma = compiled.memory_analysis()
    return int(ma.argument_size_in_bytes + ma.output_size_in_bytes
               + ma.temp_size_in_bytes - ma.alias_size_in_bytes)


def compile_uncached(lowered):
    """``lowered.compile()`` with the persistent compilation cache
    disabled for the duration — the donation-aware companion of
    :func:`measured_step_memory` (see its note on alias metadata)."""
    try:
        prev = jax.config.jax_compilation_cache_dir
    except AttributeError:
        prev = None
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:       # knob absent on this jax: nothing to bypass
        return lowered.compile()
    try:
        return lowered.compile()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
