"""Data-parallel layer (reference: apex/parallel/distributed.py).

TPU-native stance: the reference's DDP is ~640 lines of bucket management,
grad-arrival hooks and NCCL stream choreography.  Under XLA the same job —
exchange gradients, overlapped with backward — is the compiler's: params are
replicated over a device mesh, the batch is sharded, and the partitioner
inserts (and schedules) the all-reduces.  What remains API-surface:

* ``DistributedDataParallel`` — wraps a module; shards incoming batches over
  the mesh's data axis and keeps parameters replicated, so the tape's
  compiled backward produces exchanged (replicated) gradients.  Knob parity
  with the reference: ``message_size``/``delay_allreduce`` (bucketing hints —
  accepted, validated, and recorded; XLA's all-reduce combiner plays the
  bucket role), ``allreduce_always_fp32`` and ``gradient_predivide_factor``
  (honored in the explicit shard_map path, apex_tpu.training.make_train_step),
  ``num_allreduce_streams`` etc. validated like the reference
  (distributed.py:176-213).
* ``Reducer`` — the manual "allreduce on demand" helper (reference :89-126).
* ``flat_dist_call``/``apply_flat_dist_call`` — coalesced collective
  application (reference :36-70), expressed over jax arrays.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn.modules import Module
from ..nn.parameter import Parameter


def _default_mesh(devices=None, axis: str = "data") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def world_size() -> int:
    return jax.device_count()


def rank() -> int:
    return jax.process_index()


def num_processes() -> int:
    """Process count, from the one sanctioned home for topology
    queries (the CLUSTER-ASSUME lint rule points everything else
    here or to ``apex_tpu.cluster``'s membership views)."""
    return jax.process_count()


def apply_flat_dist_call(bucket, call, extra_args=None):
    """Apply a collective to a coalesced bucket (reference
    distributed.py:36-49).  XLA fuses the concatenation/split, so this is a
    semantic no-copy."""
    flat = jnp.concatenate([jnp.ravel(t) for t in bucket])
    flat = call(flat) if extra_args is None else call(flat, *extra_args)
    out, offset = [], 0
    for t in bucket:
        n = t.size
        out.append(flat[offset:offset + n].reshape(t.shape))
        offset += n
    return out


def split_by_type(tensors):
    """Bucket tensors by dtype (reference split_half_float_double,
    distributed.py:27-34 — extended with bfloat16)."""
    buckets = {}
    for t in tensors:
        buckets.setdefault(jnp.dtype(t.dtype), []).append(t)
    return list(buckets.values())


def flat_dist_call(tensors, call, extra_args=None):
    out = []
    for bucket in split_by_type(tensors):
        out.extend(apply_flat_dist_call(bucket, call, extra_args))
    return out


def _is_replicated(x) -> bool:
    sh = getattr(x, "sharding", None)
    return sh is None or sh.is_fully_replicated


def all_reduce_mean(tensors, mesh: Optional[Mesh] = None,
                    always_fp32: bool = False,
                    predivide_factor: float = 1.0,
                    average: bool = True):
    """Mean-all-reduce over the mesh's data axis, honoring the DDP
    dtype/predivide knobs.

    In the single-controller SPMD model a *replicated* array is by
    definition already identical on every device — the exchange the
    reference's NCCL allreduce performs happened inside the compiled
    backward — so replicated inputs pass through unchanged.  Arrays sharded
    on their leading dim over the data axis (one value per replica) are
    psum-mean-combined via shard_map, which is the explicit-collective path.
    """
    mesh = mesh or _default_mesh()
    axis = mesh.axis_names[0]
    n = mesh.devices.size

    def exchange(g):
        gc = g.astype(jnp.float32) if always_fp32 else g
        if predivide_factor != 1.0:
            # unconditional predivide before the collective: bounds the
            # summed magnitude, which is what keeps low-precision grads
            # finite; only the post-multiply is gated on gradient_average
            # (reference distributed.py:445-454)
            gc = gc / predivide_factor
        gc = jax.lax.psum(gc, axis)
        if average:
            gc = gc * (predivide_factor / n)
        return gc.astype(g.dtype) if always_fp32 else gc

    out = list(tensors)
    todo = [i for i, t in enumerate(tensors) if not _is_replicated(t)]
    if todo:
        # one shard_map over the whole list: a single dispatch whose
        # collectives XLA's combiner can coalesce (the reference's bucketing,
        # distributed.py:425-475, done by the compiler)
        from ..compat import shard_map as _shard_map
        fn = _shard_map(
            lambda ts: [exchange(g) for g in ts], mesh=mesh,
            in_specs=P(axis), out_specs=P(axis), check_vma=False)
        for i, r in zip(todo, fn([tensors[i] for i in todo])):
            out[i] = r
    return out


#: the presence registry IS the cluster membership layer's member table:
#: each rank joins as an ``apex_tpu.cluster`` Member over the
#: jax.distributed coordinator's KV store after a successful init, so a
#: later collective timeout can NAME the ranks that never arrived (or
#: died) — and a cluster Coordinator watching the same table sees the
#: very same registrations (one registry, two consumers).
#: Test seam: when set, a callable returning the list of missing rank
#: ids (production queries the coordinator KV store).
_PRESENCE_PROBE = None


def _kv_client():
    from ..cluster.kvstore import JaxCoordinatorKV
    return JaxCoordinatorKV.client()


def announce_presence():
    """Join this process into the cluster membership registry
    (best-effort; no-op single-process).  ``init_distributed`` calls it
    after a successful initialize; the member id is the rank, the
    registration record the hostname."""
    client = _kv_client()
    if client is None:
        return
    import socket
    try:
        from ..cluster.kvstore import JaxCoordinatorKV
        from ..cluster.membership import Member
        Member(JaxCoordinatorKV(client), str(jax.process_index()),
               spec=socket.gethostname()).join()
    except Exception:
        pass


def missing_ranks() -> Optional[list]:
    """Ranks with no membership registration, or None when
    undeterminable (single process / no coordinator client)."""
    if _PRESENCE_PROBE is not None:
        return _PRESENCE_PROBE()
    client = _kv_client()
    if client is None:
        return None
    try:
        from ..cluster.kvstore import JaxCoordinatorKV
        from ..cluster.membership import PREFIX
        kv = JaxCoordinatorKV(client)
        n = len(f"{PREFIX}members/")
        present = {k[n:] for k in kv.scan(f"{PREFIX}members/")}
    except Exception:
        return None
    return [r for r in range(jax.process_count())
            if str(r) not in present]


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout_s: Optional[float] = None,
                     max_retries: Optional[int] = None,
                     backoff_s: float = 1.0,
                     backoff_factor: float = 2.0,
                     max_backoff_s: float = 30.0,
                     _initialize=None):
    """Initialize ``jax.distributed`` from explicit args or the environment
    the ``apex_tpu.parallel.multiproc`` launcher exports — with a bounded
    retry loop instead of the bare ``jax.distributed.initialize``'s
    block-forever default.

    jax itself consumes only ``JAX_COORDINATOR_ADDRESS`` from the
    environment (jax/_src/distributed.py); the process count/id must be
    passed explicitly, which is what this helper does with the launcher's
    ``APEX_TPU_NUM_PROCESSES``/``APEX_TPU_PROCESS_ID``.

    Robustness contract (pods preempt; coordinators restart slowly):
    attempts are retried with exponential backoff (``backoff_s`` doubling
    by ``backoff_factor`` up to ``max_backoff_s``) until either
    ``max_retries`` attempts (env ``APEX_TPU_INIT_RETRIES``, default 4) or
    the overall ``timeout_s`` deadline (env ``APEX_TPU_INIT_TIMEOUT``,
    default 300s) is exhausted, whichever comes first; each attempt's own
    ``initialization_timeout`` is capped by the remaining deadline.  On
    exhaustion a :class:`~apex_tpu.runtime.resilience.DistributedInitError`
    names the coordinator, the rank, the attempt count, and the last
    underlying error — the diagnostic a 2am page needs, not a hung
    process.  Chaos hook ``dist.init`` fires before every attempt
    (``"fail"`` exercises the retry path; ``"kill"`` is preemption and
    propagates).  ``_initialize`` is a test seam defaulting to
    ``jax.distributed.initialize``.
    """
    import os
    import time as _time

    from ..runtime import chaos as _chaos
    from ..runtime.resilience import DistributedInitError

    coordinator_address = coordinator_address or \
        os.environ.get("APEX_TPU_COORDINATOR")
    if num_processes is None and "APEX_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["APEX_TPU_NUM_PROCESSES"])
    if process_id is None and "APEX_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["APEX_TPU_PROCESS_ID"])
    if timeout_s is None:
        timeout_s = float(os.environ.get("APEX_TPU_INIT_TIMEOUT", 300.0))
    if max_retries is None:
        max_retries = int(os.environ.get("APEX_TPU_INIT_RETRIES", 4))
    if _initialize is None:
        _initialize = jax.distributed.initialize

    deadline = _time.monotonic() + timeout_s
    delay = backoff_s
    last_exc = None
    attempt = -1
    for attempt in range(max_retries + 1):
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            break
        try:
            if _chaos.active():
                _chaos.hook("dist.init", attempt=attempt)
            _initialize(coordinator_address=coordinator_address,
                        num_processes=num_processes,
                        process_id=process_id,
                        initialization_timeout=max(1, int(remaining)))
            announce_presence()
            return
        except _chaos.ChaosKilled:
            raise           # simulated preemption: die like the real thing
        except Exception as e:  # noqa: BLE001 — every init failure retries
            last_exc = e
            sleep = min(delay, max_backoff_s, max(deadline - _time.monotonic(),
                                                  0.0))
            if sleep > 0 and attempt < max_retries:
                _time.sleep(sleep)
            delay *= backoff_factor
    raise DistributedInitError(
        f"init_distributed gave up after {attempt + 1} attempt(s) / "
        f"{timeout_s:.0f}s deadline (coordinator="
        f"{coordinator_address!r}, process_id={process_id}, "
        f"num_processes={num_processes}): {last_exc}") from last_exc


def timed_flat_dist_call(tensors, call, extra_args=None,
                         timeout_s: float = 60.0):
    """:func:`flat_dist_call` with a deadline and a *named-suspect*
    diagnostic.

    A collective against a dead/slow peer blocks forever with no
    indication of WHICH rank is missing.  This wrapper runs the collective
    on a worker thread, and on deadline raises
    :class:`~apex_tpu.runtime.resilience.CollectiveTimeoutError` naming
    this rank, the world size, and — when the coordinator's presence
    registry (:func:`announce_presence`) can identify them — the ranks
    that never checked in.  Chaos hook ``dist.collective`` fires inside
    the worker (``"delay"`` simulates the slow peer the timeout exists
    for).

    The abandoned worker thread is daemonic: if the collective later
    completes its result is discarded; if it never does, process exit is
    not held up — the caller is expected to checkpoint-and-die or
    re-init, not to retry the wedged collective in place.
    """
    import threading

    from ..runtime import chaos as _chaos
    from ..runtime.resilience import CollectiveTimeoutError

    box = {}

    def worker():
        try:
            if _chaos.active():
                _chaos.hook("dist.collective")
            box["out"] = flat_dist_call(tensors, call, extra_args)
        except BaseException as e:  # surfaced below
            box["exc"] = e

    t = threading.Thread(target=worker, daemon=True,
                         name="apex-tpu-collective")
    t.start()
    t.join(timeout_s)
    if "exc" in box:
        raise box["exc"]
    if "out" in box:
        return box["out"]
    missing = missing_ranks()
    suspect = (f"ranks never present in the coordinator registry: "
               f"{missing}" if missing
               else "missing rank unknown (no coordinator presence "
                    "registry — single process or init_distributed not "
                    "used)")
    raise CollectiveTimeoutError(
        f"collective did not complete within {timeout_s:g}s on rank "
        f"{rank()} of {jax.process_count()} process(es); {suspect}")


class Reducer:
    """Manual gradient/param averaging helper (reference
    apex/parallel/distributed.py:89-126): call ``reduce()`` whenever you want
    the wrapped module's gradients averaged across replicas."""

    def __init__(self, module_or_grads_list, mesh: Optional[Mesh] = None,
                 allreduce_always_fp32: bool = False,
                 gradient_predivide_factor: float = 1.0):
        self.mesh = mesh or _default_mesh()
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_predivide_factor = gradient_predivide_factor
        if isinstance(module_or_grads_list, Module):
            self.module = module_or_grads_list
            # parameter broadcast at construction (reference :253): in
            # single-controller SPMD params are already identical; multihost
            # sync happens through the jit replication below.
        else:
            self.module = None
            self.grads = list(module_or_grads_list)

    def reduce(self):
        if self.module is not None:
            params = [p for p in self.module.parameters()
                      if p is not None and p.grad is not None]
            grads = [p.grad for p in params]
            new = all_reduce_mean(
                grads, self.mesh,
                always_fp32=self.allreduce_always_fp32,
                predivide_factor=self.gradient_predivide_factor)
            for p, g in zip(params, new):
                p.grad = g
        else:
            self.grads[:] = all_reduce_mean(
                self.grads, self.mesh,
                always_fp32=self.allreduce_always_fp32,
                predivide_factor=self.gradient_predivide_factor)


class DistributedDataParallel(Module):
    """Module wrapper for data-parallel training (reference
    apex/parallel/distributed.py:129).

    On TPU the wrapper's job is placement: incoming batches are sharded over
    the mesh's data axis and parameters kept replicated; XLA's partitioner
    then inserts the gradient all-reduce into the compiled backward and
    overlaps it with computation (the latency-hiding scheduler replaces the
    reference's hand-rolled bucket/stream machinery, :363-475).
    """

    def __init__(self, module: Module, message_size: int = 10000000,
                 delay_allreduce: bool = False,
                 shared_param: Optional[bool] = None,
                 allreduce_trigger_params=None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 num_allreduce_streams: int = 1,
                 allreduce_communicators=None,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 gradient_average_split_factor=None,
                 prof: bool = False,
                 mesh: Optional[Mesh] = None):
        super().__init__()
        # ---- option validation, mirroring distributed.py:145-213 ----
        if shared_param is not None:
            raise ValueError(
                "shared_param is no longer supported as an option.  It was "
                "misleadingly named and didn't do what it claimed to do.  "
                "The new behavior is shared_param=True.")
        if allreduce_communicators is not None:
            if len(allreduce_communicators[0]) != num_allreduce_streams or \
                    not isinstance(allreduce_communicators[1], (list, tuple)):
                raise ValueError("allreduce_communicators must be a tuple "
                                 "(groups, streams) matching "
                                 "num_allreduce_streams")
        if delay_allreduce and num_allreduce_streams > 1:
            raise ValueError("Setting delay_allreduce=True makes "
                             "num_allreduce_streams irrelevant.")
        if allreduce_trigger_params is not None and delay_allreduce:
            raise ValueError("Setting allreduce_trigger_params is only valid "
                             "if delay_allreduce=False.")

        self.module = module
        self.message_size = message_size
        self.delay_allreduce = delay_allreduce
        self.allreduce_trigger_params = (
            [id(p) for p in allreduce_trigger_params]
            if allreduce_trigger_params is not None else None)
        self.retain_allreduce_buffers = retain_allreduce_buffers
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.num_allreduce_streams = num_allreduce_streams
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.prof = prof
        self.mesh = mesh or _default_mesh()
        self._data_axis = self.mesh.axis_names[0]
        self._batch_sharding = NamedSharding(self.mesh, P(self._data_axis))

        # DDP is applied AFTER amp.initialize (reference order, simple/
        # distributed example): the amp cast/policy tags live on the wrapped
        # module, but calls enter through this wrapper — mirror them here so
        # the tape applies the casts exactly once (inner module.forward is
        # invoked directly, bypassing the inner tags).
        for attr in ("_amp_input_cast_dtype", "_amp_output_cast_dtype",
                     "_amp_policy"):
            if hasattr(module, attr):
                setattr(self, attr, getattr(module, attr))

        # parameter broadcast from rank 0 (reference :253): replicate every
        # param over the mesh so XLA sees them as shared across the data axis
        self._replicate_params()

    def _replicate_params(self):
        rep = NamedSharding(self.mesh, P())
        for p in self.module.parameters():
            if p is not None:
                p.data = jax.device_put(p.data, rep)
        for b in self.module.buffers():
            b.data = jax.device_put(b.data, rep)

    def shard_batch(self, x):
        """Place a global batch sharded over the data axis."""
        return jax.device_put(x, self._batch_sharding)

    def allreduce_gradients(self):
        """Explicitly exchange the wrapped module's ``.grad``s, honoring the
        wrapper's knobs (``allreduce_always_fp32``,
        ``gradient_predivide_factor``, ``gradient_average``) — the analogue
        of the reference's end-of-backward fallback allreduce
        (apex/parallel/distributed.py:491-510).

        In the normal SPMD path grads come out of the compiled backward
        already exchanged; this is for grads produced per-replica (sharded
        on their leading axis), e.g. by a manual per-device loop.
        """
        params = [p for p in self.module.parameters()
                  if p is not None and getattr(p, "grad", None) is not None]
        new = all_reduce_mean(
            [p.grad for p in params], self.mesh,
            always_fp32=self.allreduce_always_fp32,
            predivide_factor=self.gradient_predivide_factor,
            average=self.gradient_average)
        for p, g in zip(params, new):
            p.grad = g

    def attach_optimizer(self, optimizer):
        """Wire the deferred gradient exchange into ``optimizer.step()``.

        Requires ``delay_allreduce=True`` — the knob whose reference
        meaning is "one exchange at the end of backward, no per-bucket
        overlap" (apex/parallel/distributed.py:363-380).  Here the
        boundary moves one step further, to the optimizer step: each
        ``step()`` first runs ONE :meth:`allreduce_gradients` over the
        accumulated ``.grad``s, then updates.  Under K-microbatch gradient
        accumulation (``amp.scale_loss(delay_unscale=True)`` × K, one
        ``step()``) that is exactly one exchange per window instead of
        one per microbatch — gradient-exchange bytes drop by K×.  The
        wrapper composes with amp's step patching (amp wraps first, DDP
        attaches after, as in the examples): an amp overflow-skip replaces
        ``optimizer.step`` for that one call, so a skipped window also
        skips its exchange.  Returns the optimizer.
        """
        if not self.delay_allreduce:
            raise ValueError(
                "attach_optimizer requires delay_allreduce=True — with "
                "eager per-backward exchange semantics a step-boundary "
                "allreduce would exchange the same gradients twice")
        if getattr(optimizer, "_ddp_attached", None) is self:
            return optimizer
        inner_step = optimizer.step

        def step_with_exchange(closure=None):
            self.allreduce_gradients()
            return inner_step() if closure is None else inner_step(closure)

        optimizer.step = step_with_exchange
        optimizer._ddp_attached = self
        return optimizer

    # DDP delegates module protocol (parameters/state_dict/etc. come from
    # Module via the registered child)
    def forward(self, ctx, *inputs):
        return self.module.forward(ctx, *inputs)

    def __call__(self, *inputs):
        placed = tuple(
            self.shard_batch(x) if hasattr(x, "shape") and getattr(
                x, "ndim", 0) > 0 else x
            for x in inputs)
        return super().__call__(*placed)

    def train(self, mode=True):
        self.module.train(mode)
        return super().train(mode)
