"""LARC — layer-wise adaptive rate control optimizer wrapper
(reference: apex/parallel/LARC.py:5-107).

Computes a per-param trust ratio ``tc * ||p|| / (||g|| + wd*||p|| + eps)``,
in 'clip' mode capped so the effective lr is ``min(adaptive_lr, lr)``,
modifies grads in place, then delegates to the wrapped optimizer with its
weight decay absorbed.
"""
from __future__ import annotations

import jax.numpy as jnp


class LARC:
    def __init__(self, optimizer, trust_coefficient=0.02, clip=True,
                 eps=1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.eps = eps
        self.clip = clip

    def __getstate__(self):
        return self.optim.__getstate__()

    def __setstate__(self, state):
        self.optim.__setstate__(state)

    @property
    def state(self):
        return self.optim.state

    def __repr__(self):
        return self.optim.__repr__()

    @property
    def param_groups(self):
        return self.optim.param_groups

    @param_groups.setter
    def param_groups(self, value):
        self.optim.param_groups = value

    def state_dict(self):
        return self.optim.state_dict()

    def load_state_dict(self, state_dict):
        self.optim.load_state_dict(state_dict)

    def zero_grad(self, *args, **kwargs):
        self.optim.zero_grad(*args, **kwargs)

    def add_param_group(self, param_group):
        self.optim.add_param_group(param_group)

    def step(self):
        from .. import ops

        weight_decays = []
        for group in self.optim.param_groups:
            weight_decay = group.get("weight_decay", 0)
            weight_decays.append(weight_decay)
            group["weight_decay"] = 0
            params = [p for p in group["params"] if p.grad is not None]
            if not params:
                continue
            # batched per-tensor norms via the fused op (one program each for
            # params and grads instead of 2N eager reductions)
            _, _, p_norms = ops.multi_tensor_l2norm(
                ops.zero_flag(), [[p.data for p in params]], per_tensor=True)
            _, _, g_norms = ops.multi_tensor_l2norm(
                ops.zero_flag(), [[p.grad for p in params]], per_tensor=True)
            for i, p in enumerate(params):
                param_norm, grad_norm = p_norms[i], g_norms[i]
                adaptive_lr = self.trust_coefficient * param_norm / (
                    grad_norm + param_norm * weight_decay + self.eps)
                if self.clip:
                    adaptive_lr = jnp.minimum(adaptive_lr / group["lr"], 1.0)
                # zero param or grad norm -> leave the grad untouched
                # (reference LARC.py:92)
                active = (param_norm != 0) & (grad_norm != 0)
                adaptive_lr = jnp.where(active, adaptive_lr, 1.0)
                wd_term = jnp.where(active, weight_decay, 0.0)
                gd = p.grad.astype(jnp.float32)
                new_grad = (gd + wd_term * p.data.astype(jnp.float32)) \
                    * adaptive_lr
                p.grad = new_grad.astype(p.grad.dtype)

        self.optim.step()
        for i, group in enumerate(self.optim.param_groups):
            group["weight_decay"] = weight_decays[i]
