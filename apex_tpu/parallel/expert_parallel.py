"""Expert parallelism over a mesh axis — Switch-style top-1 MoE.

The reference has no MoE/expert parallelism (SURVEY.md §2.3); the
TPU-native formulation is the canonical one: one expert per device along
the ``ep`` axis, tokens exchanged with their expert's owner by a pair of
``lax.all_to_all``s around the expert computation.

Routing math (Switch Transformer):

* top-1 expert per token from a replicated router, gate = that expert's
  softmax probability;
* per (source device, expert) capacity ``C = ceil(T_local/E *
  capacity_factor)``; tokens beyond capacity are DROPPED (contribute
  zero output — the standard Switch overflow behavior, callers keep the
  residual path);
* dispatch/combine are einsums against a (T, E, C) one-hot, so the whole
  layer is differentiable — gradients flow through the gate (router
  learns) and through the expert weights; the all_to_alls transpose to
  themselves.

``expert_fn(params, x)`` runs THIS device's expert on ``(n*C, d)`` — its
own expert's bucket gathered from every source device.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def switch_moe(x, router_w, expert_params, expert_fn, axis_name,
               capacity_factor=1.25):
    """x (T_local, d); router_w (d, E) replicated; expert_params — this
    device's expert (any pytree).  E must equal the axis size (one expert
    per device).  Returns (T_local, d): gated expert outputs, zeros for
    dropped tokens.
    """
    n = lax.psum(1, axis_name)              # static: devices == experts
    t_loc, d = x.shape
    logits = x @ router_w                   # (T, E)
    e = logits.shape[-1]
    if e != n:
        raise ValueError(
            f"switch_moe: router has {e} experts but the '{axis_name}' "
            f"axis has {n} devices; expert parallelism is one expert per "
            f"device")
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)             # (T,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    cap = max(1, math.ceil(t_loc / e * capacity_factor))
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)      # (T, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1                # (T, E)
    pos_t = jnp.max(pos, axis=-1)                        # position, (T,)
    keep = pos_t < cap
    # (T, E, C) dispatch one-hot; dropped tokens are all-zero rows
    disp = (onehot.astype(jnp.float32)[:, :, None]
            * jax.nn.one_hot(jnp.clip(pos_t, 0, cap - 1), cap,
                             dtype=jnp.float32)[:, None, :]
            * keep[:, None, None].astype(jnp.float32))

    buckets = jnp.einsum("tec,td->ecd", disp, x.astype(jnp.float32))
    # ship bucket e to device e; receive my expert's bucket from every
    # source: (E, C, d) -> (n_src, C, d), slot i = source device i
    recv = lax.all_to_all(buckets, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    out = expert_fn(expert_params,
                    recv.reshape(n * cap, d).astype(x.dtype))
    out = out.astype(jnp.float32).reshape(n, cap, d)
    # return results to their sources: slot e = my tokens' outputs from
    # expert e, aligned with disp's expert axis
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    y = jnp.einsum("tec,ecd->td", disp, back)
    return (y * gate[:, None].astype(jnp.float32)).astype(x.dtype)
