"""Expert parallelism over a mesh axis — Switch/GShard-style top-k MoE.

The reference has no MoE/expert parallelism (SURVEY.md §2.3); the
TPU-native formulation is the canonical one: one expert per device along
the ``ep`` axis, tokens exchanged with their expert's owner by a pair of
``lax.all_to_all``s around the expert computation.

Routing math:

* top-1 (Switch Transformer, arXiv:2101.03961) or top-2 (GShard,
  arXiv:2006.16668) experts per token from a replicated router; gates are
  the selected experts' softmax probabilities, normalized over the
  selection for top-2;
* per (source device, expert) capacity ``C = ceil(T_local/E *
  capacity_factor)``; tokens beyond capacity are DROPPED (contribute
  zero output — the standard Switch overflow behavior, callers keep the
  residual path).  For top-2 the capacity is counted jointly: first
  choices claim slots before second choices (GShard's ordering);
* dispatch/combine are einsums against a (T, E, C) tensor, so the whole
  layer is differentiable — gradients flow through the gate (router
  learns) and through the expert weights; the all_to_alls transpose to
  themselves (exact per-device gradients, no conjugate operators
  needed — unlike the TP psum pair, parallel/tensor_parallel.py);
* the load-balancing auxiliary loss (Switch eq. 4): ``aux = E * Σ_e
  f_e · P_e`` with ``f_e`` the fraction of tokens whose FIRST choice is
  expert ``e`` and ``P_e`` the mean router probability, both averaged
  over the axis (global batch).  Minimized at uniform routing (aux = 1);
  without it a learned top-1 router collapses onto one expert.  Callers
  add ``aux_weight * aux`` to their loss — the model families route it
  through ``Ctx.add_aux_loss`` (models/gpt.py MoE blocks).

``expert_fn(params, x)`` runs THIS device's expert on ``(n*C, d)`` — its
own expert's bucket gathered from every source device.

Switch-MoE models are *plannable* since planner v3: ``parallel.auto``
enumerates an ``ep == dp == n_experts`` twin for every dp-only mesh
(the data axis IS the expert axis), prices the dispatch/combine
all-to-alls per routed block, and shards the expert slice of the
parameter state one-per-device in its HBM model — see
docs/auto_parallel.md.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def switch_moe(x, router_w, expert_params, expert_fn, axis_name,
               capacity_factor=1.25, top_k=1):
    """x (T_local, d); router_w (d, E) replicated; expert_params — this
    device's expert (any pytree).  E must equal the axis size (one expert
    per device).  ``top_k`` in (1, 2): experts consulted per token.

    Returns ``(y, aux)``: ``y (T_local, d)`` gated expert outputs (zeros
    for dropped tokens) and ``aux`` — the scalar load-balancing loss,
    replicated over the axis.
    """
    if top_k not in (1, 2):
        raise ValueError(f"switch_moe: top_k must be 1 or 2, got {top_k}")
    n = lax.psum(1, axis_name)              # static: devices == experts
    t_loc, d = x.shape
    logits = x @ router_w                   # (T, E)
    e = logits.shape[-1]
    if e != n:
        raise ValueError(
            f"switch_moe: router has {e} experts but the '{axis_name}' "
            f"axis has {n} devices; expert parallelism is one expert per "
            f"device")
    if top_k > e:
        raise ValueError(
            f"switch_moe: top_k={top_k} exceeds the expert count {e}")
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    cap = max(1, math.ceil(t_loc / e * capacity_factor))

    # k-th choice per token, k = 0..top_k-1 (argsort of -probs)
    top_idx = jnp.argsort(-probs, axis=-1)[:, :top_k]      # (T, K)
    top_gate = jnp.take_along_axis(probs, top_idx, axis=-1)  # (T, K)
    if top_k == 2:
        # GShard gate normalization over the selected pair
        top_gate = top_gate / jnp.maximum(
            jnp.sum(top_gate, axis=-1, keepdims=True), 1e-9)

    # joint capacity counting, first choices before second (GShard):
    # running per-expert occupancy carries across the k sweep.  Only the
    # gate-weighted combine tensor is accumulated; the 0/1 dispatch mask
    # derives from it below (gates are strictly positive), halving the
    # (T, E, C) routing memory held for backward
    counts = jnp.zeros((e,), jnp.int32)
    comb = jnp.zeros((t_loc, e, cap), jnp.float32)
    for k in range(top_k):
        oh = jax.nn.one_hot(top_idx[:, k], e, dtype=jnp.int32)   # (T, E)
        pos = (jnp.cumsum(oh, axis=0) - oh) + counts[None, :]    # (T, E)
        pos_t = jnp.sum(pos * oh, axis=-1)                       # (T,)
        keep = pos_t < cap
        d_k = (oh.astype(jnp.float32)[:, :, None]
               * jax.nn.one_hot(jnp.clip(pos_t, 0, cap - 1), cap,
                                dtype=jnp.float32)[:, None, :]
               * keep[:, None, None].astype(jnp.float32))
        comb = comb + d_k * top_gate[:, k, None, None]
        counts = counts + jnp.sum(oh * keep[:, None].astype(jnp.int32),
                                  axis=0)
    # softmax probs are > 0, so comb > 0 exactly where a token occupies a
    # slot; stop_gradient pins the dispatch mask as routing data (the old
    # one-hot was equally gradient-free)
    disp = jax.lax.stop_gradient((comb > 0).astype(jnp.float32))

    buckets = jnp.einsum("tec,td->ecd", disp, x.astype(jnp.float32))
    # ship bucket e to device e; receive my expert's bucket from every
    # source: (E, C, d) -> (n_src, C, d), slot i = source device i
    recv = lax.all_to_all(buckets, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    out = expert_fn(expert_params,
                    recv.reshape(n * cap, d).astype(x.dtype))
    out = out.astype(jnp.float32).reshape(n, cap, d)
    # return results to their sources: slot e = my tokens' outputs from
    # expert e, aligned with disp's expert axis
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    y = jnp.einsum("tec,ecd->td", comb, back).astype(x.dtype)

    # load-balancing aux (Switch eq. 4), over the GLOBAL batch: f_e from
    # first choices (pre-drop — the assignment the router asked for),
    # P_e the mean router probability; pmean makes both global and the
    # scalar replicated
    f_e = lax.pmean(jnp.mean(
        jax.nn.one_hot(top_idx[:, 0], e, dtype=jnp.float32), axis=0),
        axis_name)
    p_e = lax.pmean(jnp.mean(probs, axis=0), axis_name)
    aux = e * jnp.sum(f_e * p_e)
    return y, aux
