"""SyncBatchNorm (reference: apex/parallel/sync_batchnorm.py +
optimized_sync_batchnorm*.py).

The reference computes local Welford stats, all-gathers per-rank mean/var and
merges them (optimized_sync_batchnorm_kernel.py:20-45).  The TPU-native
equivalent is one ``lax.psum`` of (sum, sqsum, count) over the mesh's data
axis — mathematically identical to the Welford merge, and fused by XLA into
the surrounding step.  ``process_group`` maps to ``axis_index_groups``
(sub-groups of the data axis, reference create_syncbn_process_group,
apex/parallel/__init__.py:58-95).

Semantics notes, matching the reference:
* under explicit per-shard execution (shard_map — the make_train_step path),
  the psum is what synchronizes statistics;
* under automatic SPMD (jit + sharded batch), a plain BatchNorm already has
  global-batch semantics, so SyncBatchNorm degrades gracefully: if the axis
  name is unbound at trace time, stats are computed over the (global) batch —
  same observable result;
* eval mode uses running stats with no collective
  (reference sync_batchnorm.py:85-88).
"""
from __future__ import annotations

from ..nn.modules import _BatchNorm


class SyncBatchNorm(_BatchNorm):
    """Cross-replica BatchNorm.  ``channel_last`` matches the reference
    API (optimized_sync_batchnorm.py:58) and feeds _BatchNorm's native
    channel-axis path (stats over NHWC's minor axis directly — no
    transpose sandwich, so the channels-last layout survives through
    the norm)."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True, process_group=None,
                 channel_last=False, fuse_relu=False,
                 axis_name: str = "data"):
        super().__init__(num_features, eps=eps, momentum=momentum,
                         affine=affine,
                         track_running_stats=track_running_stats)
        self.process_group = process_group  # axis_index_groups
        self.channel_last = channel_last    # property -> channels_last
        self.fuse_relu = fuse_relu
        self.axis_name = axis_name

    # one flag, two spellings: the reference API says channel_last,
    # _BatchNorm's layout switch (nn.to_channels_last) says channels_last
    @property
    def channel_last(self):
        return self.channels_last

    @channel_last.setter
    def channel_last(self, v):
        self.channels_last = v

    def _stats_args(self):
        return dict(axis_name=self.axis_name,
                    axis_index_groups=self.process_group)

    def forward(self, ctx, x):
        y = super().forward(ctx, x)
        if self.fuse_relu:
            from ..nn import functional as F
            y = F.relu(y)
        return y
