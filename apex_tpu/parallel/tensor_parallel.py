"""Tensor (model) parallelism over a mesh axis — Megatron-style sharded
linears.

The reference has no model parallelism (SURVEY.md §2.3 — data parallelism
is its only strategy); on TPU the pattern is a first-class citizen of the
mesh, so the framework provides the two canonical building blocks.  Both
are meant to run inside ``shard_map``/``pjit`` with the weight shards
resident per device:

* ``column_parallel_linear`` — W is split along the OUTPUT features: each
  device computes ``x @ W_i^T`` for its slice, producing the output's
  feature shard.  No communication on the forward; an optional
  ``all_gather`` returns the full output.
* ``row_parallel_linear`` — W is split along the INPUT features: each
  device contracts its input shard against its weight slice and the
  partial products are ``psum``'d.  The bias is added once, after the
  reduction.

Chained column→row (the transformer MLP/attention pattern) needs exactly
one collective per pair: the column layer's sharded output feeds the row
layer's sharded input directly, and only the row layer reduces.

Gradient convention: differentiation happens INSIDE shard_map (per-device
AD — how the fused train step computes grads, training/step.py), with the
Megatron conjugate pair pinning the collective transposes explicitly:
``copy_to_tp_region`` (identity fwd / psum bwd) enters a region,
``reduce_from_tp_region`` (psum fwd / identity bwd) exits it.  Sharded
parameters then carry disjoint per-device gradient blocks (psum
assembles the full gradient — ``make_train_step(tp_axis=...)`` does
this), and replicated parameters carry full identical gradients.
Differentiating *through* an outer ``shard_map`` instead relies on
JAX's default collective-transpose chain and is not supported for these
ops.

Module forms (``ColumnParallelLinear`` / ``RowParallelLinear``) hold the
LOCAL shard as their parameter, constructed from a deterministic full-size
init so the sharded pair reproduces the unsharded ``nn.Linear`` with the
same seed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..nn.parameter import Parameter


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp_region(x, axis_name):
    """Megatron's ``f`` operator: identity forward, psum backward.

    A replicated activation entering a column-parallel region is consumed
    by a different weight shard on each device, so each device's backward
    computes only its own shard's contribution to ``d loss / d x``.  The
    psum on the backward pass assembles the full input gradient — without
    it every parameter UPSTREAM of the region (embeddings, LayerNorms,
    previous layers) silently gets a per-device partial gradient.  The
    conjugate ``g`` operator (psum forward, identity backward) is the
    row-parallel layer's reduction, which psum's own VJP already
    provides."""
    return x


def _copy_to_tp_fwd(x, axis_name):
    return x, None


def _copy_to_tp_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


copy_to_tp_region.defvjp(_copy_to_tp_fwd, _copy_to_tp_bwd)


@_partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tp_region(x, axis_name):
    """Megatron's ``g`` operator: psum forward, IDENTITY backward.

    The backward must be pinned explicitly: under shard_map the default
    transpose of ``psum`` applied to an already-replicated cotangent is
    another psum — an ×n_shards overcount per region traversed (verified
    against the unsharded oracle in tests/test_tp_models.py).  With ``f``
    (identity fwd / psum bwd) at region entry and this ``g`` at region
    exit, gradients of replicated parameters come out exactly full and
    identical on every device, and sharded parameters' gradients stay
    disjoint blocks."""
    return lax.psum(x, axis_name)


def _reduce_from_tp_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_from_tp_bwd(axis_name, _, g):
    return (g,)


reduce_from_tp_region.defvjp(_reduce_from_tp_fwd, _reduce_from_tp_bwd)


def column_parallel_linear(x, weight_shard, bias_shard=None,
                           axis_name=None, gather_output=False):
    """x (..., in); weight_shard (out/n, in); bias_shard (out/n,).
    Returns (..., out/n), or (..., out) when ``gather_output``."""
    y = jnp.matmul(x, weight_shard.T)
    if bias_shard is not None:
        y = y + bias_shard
    if gather_output:
        y = lax.all_gather(y, axis_name, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_linear(x_shard, weight_shard, bias=None, axis_name=None):
    """x_shard (..., in/n); weight_shard (out, in/n); bias (out,), added
    once after the reduction.  Returns the full (..., out), replicated.
    The reduction is the ``g`` operator (psum fwd, identity bwd) so the
    replicated cotangent passes through unscaled — see
    ``reduce_from_tp_region``."""
    y = reduce_from_tp_region(jnp.matmul(x_shard, weight_shard.T),
                              axis_name)
    if bias is not None:
        y = y + bias
    return y


def _shard_dim(full, axis_name, dim):
    n = lax.psum(1, axis_name)           # static mesh-axis size
    if full.shape[dim] % n:
        # dynamic_slice would silently clamp, dropping trailing features
        raise ValueError(
            f"tensor-parallel shard: dimension {dim} of size "
            f"{full.shape[dim]} is not divisible by the '{axis_name}' "
            f"axis size {n}")
    i = lax.axis_index(axis_name)
    size = full.shape[dim] // n
    return lax.dynamic_slice_in_dim(full, i * size, size, axis=dim)


def _shard_rows(full, axis_name):
    return _shard_dim(full, axis_name, 0)


def _shard_cols(full, axis_name):
    return _shard_dim(full, axis_name, 1)


def vocab_parallel_embedding(ids, emb_full, axis_name):
    """Megatron vocab-parallel embedding lookup: the ``(V, E)`` table is
    row-sharded over ``axis_name`` (full replicated parameter, sliced at
    trace time like every TP weight here); each device gathers only ids
    in its vocab range and the partial rows combine through the g
    operator.  The gradient is a scatter into the device's own vocab
    block — disjoint per device, so the table belongs in
    ``tp_sharded_params()``."""
    shard = _shard_rows(emb_full, axis_name)   # validates divisibility
    v_loc = shard.shape[0]
    off = lax.axis_index(axis_name) * v_loc
    local = ids - off
    valid = (local >= 0) & (local < v_loc)
    rows = jnp.take(shard, jnp.clip(local, 0, v_loc - 1), axis=0)
    rows = jnp.where(valid[..., None], rows, jnp.zeros_like(rows))
    return reduce_from_tp_region(rows, axis_name)


def vocab_parallel_logits(x, emb_full, axis_name):
    """The tied LM head under vocab parallelism: ``x (..., E)`` against
    the row-sharded table gives VOCAB-SHARDED logits ``(..., V/n)`` —
    the full ``(..., V)`` logits tensor (usually the largest activation
    in an LM step) never materializes on any device.  Feed the result to
    :func:`vocab_parallel_cross_entropy`.  ``x`` passes the f operator
    (each device consumes it against a different weight block)."""
    x = copy_to_tp_region(x, axis_name)
    shard = _shard_rows(emb_full, axis_name)
    return jnp.matmul(x, jnp.swapaxes(shard, 0, 1).astype(x.dtype))


def vocab_parallel_cross_entropy(logits_shard, targets, axis_name,
                                 reduction="mean"):
    """Cross entropy over vocab-sharded logits (Megatron's parallel
    cross-entropy): per-device max → pmax for stability, per-device
    sum-exp and target-logit partials combined through g operators, so
    the backward is exactly ``softmax_local - onehot_local`` on each
    device with no full-vocab gather in either direction.

    ``logits_shard (..., V/n)``, integer ``targets (...)`` GLOBAL ids.
    """
    v_loc = logits_shard.shape[-1]
    off = lax.axis_index(axis_name) * v_loc
    lf = logits_shard.astype(jnp.float32)
    # global max, constant w.r.t. the grad (standard LSE stabilization);
    # stop_gradient BEFORE the collective — pmax has no differentiation
    # rule, so it must only ever see a non-tangent-carrying value
    m = lax.pmax(lax.stop_gradient(jnp.max(lf, axis=-1)), axis_name)
    sumexp = reduce_from_tp_region(
        jnp.sum(jnp.exp(lf - m[..., None]), axis=-1), axis_name)
    lse = jnp.log(sumexp) + m
    local = targets - off
    valid = (local >= 0) & (local < v_loc)
    tl = jnp.take_along_axis(
        lf, jnp.clip(local, 0, v_loc - 1)[..., None], axis=-1)[..., 0]
    tl = reduce_from_tp_region(
        jnp.where(valid, tl, jnp.zeros_like(tl)), axis_name)
    losses = lse - tl
    if reduction == "mean":
        return jnp.mean(losses)
    if reduction == "sum":
        return jnp.sum(losses)
    return losses


def tp_attn_begin(axis_name, heads, inputs, row_weights, col_weights):
    """Shared TP entry protocol for the attention functionals
    (contrib/multihead_attn/attn_funcs.py) — one place for the
    f-operator application to every input stream, the head divisibility
    check, and the weight-block slicing, so the self and encdec paths
    cannot desynchronize.

    Returns ``(inputs, heads_local, row_shards, col_shards)`` where
    ``row_weights`` slice dim 0 (head-major projection rows) and
    ``col_weights`` slice dim 1 (the row-parallel output projections);
    exit is ``reduce_from_tp_region`` on the projected output.

    Attention dropout IS supported under TP: the in-kernel hash mask's
    seed is folded with ``lax.axis_index`` at the call site
    (attn_funcs), so each head-shard draws a decorrelated stream — the
    TPU analogue of the reference's per-rank Philox streams (multi-GPU
    dropout there is not bit-identical to single-GPU either).  The
    flip side, same as the reference: a TP run's dropped positions
    differ from the single-shard run's, so dropped-path tp-vs-unsharded
    comparisons are statistical, not bitwise."""
    inputs = [copy_to_tp_region(x, axis_name) for x in inputs]
    n = lax.psum(1, axis_name)
    if heads % n:
        raise ValueError(
            f"tensor parallelism: heads ({heads}) not divisible by "
            f"the '{axis_name}' axis size ({n})")
    rows = [_shard_dim(w, axis_name, 0) for w in row_weights]
    cols = [_shard_dim(w, axis_name, 1) for w in col_weights]
    return inputs, heads // n, rows, cols


def tp_ffn(x, w1, b1, w2, b2, axis_name, activation=None):
    """Column→row feed-forward over FULL (replicated) weights: each device
    slices its shard at trace time (XLA folds the static slice into the
    weight layout), applies ``activation`` on the feature-sharded hidden,
    and the row layer's psum is the pair's single collective.  This is the
    building block the model families (models/gpt.py, models/bert.py) use
    for their ``tp_axis`` MLPs — weights stay full-size so checkpoints
    and init are shard-count-independent."""
    x = copy_to_tp_region(x, axis_name)
    h = column_parallel_linear(
        x, _shard_rows(w1, axis_name),
        None if b1 is None else _shard_rows(b1, axis_name))
    if activation is not None:
        h = activation(h)
    return row_parallel_linear(h, _shard_cols(w2, axis_name), b2, axis_name)


class ColumnParallelLinear(nn.Module):
    """nn.Linear with the weight split along output features.  Holds the
    FULL parameter (so init/checkpoints match the unsharded layer) and
    slices its own shard per device at forward time; under jit the slice
    is a static gather XLA folds into the weight layout."""

    def __init__(self, in_features, out_features, axis_name,
                 bias=True, gather_output=False):
        super().__init__()
        ref = nn.Linear(in_features, out_features, bias=bias)
        self.weight = Parameter(ref.weight.data)
        if bias:
            self.bias = Parameter(ref.bias.data)
        else:
            self.register_parameter("bias", None)
        self.axis_name = axis_name
        self.gather_output = gather_output

    def forward(self, ctx, x):
        w = _shard_rows(ctx.value(self.weight), self.axis_name)
        b = None
        if self.bias is not None:
            b = _shard_rows(ctx.value(self.bias), self.axis_name)
        return column_parallel_linear(x, w, b, self.axis_name,
                                      self.gather_output)


class RowParallelLinear(nn.Module):
    """nn.Linear with the weight split along input features; expects its
    input already feature-sharded (a column layer's output)."""

    def __init__(self, in_features, out_features, axis_name, bias=True):
        super().__init__()
        ref = nn.Linear(in_features, out_features, bias=bias)
        self.weight = Parameter(ref.weight.data)
        if bias:
            self.bias = Parameter(ref.bias.data)
        else:
            self.register_parameter("bias", None)
        self.axis_name = axis_name

    def forward(self, ctx, x_shard):
        w = _shard_cols(ctx.value(self.weight), self.axis_name)
        b = ctx.value(self.bias) if self.bias is not None else None
        return row_parallel_linear(x_shard, w, b, self.axis_name)
