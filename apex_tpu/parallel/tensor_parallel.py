"""Tensor (model) parallelism over a mesh axis — Megatron-style sharded
linears.

The reference has no model parallelism (SURVEY.md §2.3 — data parallelism
is its only strategy); on TPU the pattern is a first-class citizen of the
mesh, so the framework provides the two canonical building blocks.  Both
are meant to run inside ``shard_map``/``pjit`` with the weight shards
resident per device:

* ``column_parallel_linear`` — W is split along the OUTPUT features: each
  device computes ``x @ W_i^T`` for its slice, producing the output's
  feature shard.  No communication on the forward; an optional
  ``all_gather`` returns the full output.
* ``row_parallel_linear`` — W is split along the INPUT features: each
  device contracts its input shard against its weight slice and the
  partial products are ``psum``'d.  The bias is added once, after the
  reduction.

Chained column→row (the transformer MLP/attention pattern) needs exactly
one collective per pair: the column layer's sharded output feeds the row
layer's sharded input directly, and only the row layer reduces.  Gradients
need no extra hand-written collectives — ``psum``/``all_gather`` are
differentiable and the transpose collectives are inserted by JAX.

Module forms (``ColumnParallelLinear`` / ``RowParallelLinear``) hold the
LOCAL shard as their parameter, constructed from a deterministic full-size
init so the sharded pair reproduces the unsharded ``nn.Linear`` with the
same seed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..nn.parameter import Parameter


def column_parallel_linear(x, weight_shard, bias_shard=None,
                           axis_name=None, gather_output=False):
    """x (..., in); weight_shard (out/n, in); bias_shard (out/n,).
    Returns (..., out/n), or (..., out) when ``gather_output``."""
    y = jnp.matmul(x, weight_shard.T)
    if bias_shard is not None:
        y = y + bias_shard
    if gather_output:
        y = lax.all_gather(y, axis_name, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_linear(x_shard, weight_shard, bias=None, axis_name=None):
    """x_shard (..., in/n); weight_shard (out, in/n); bias (out,), added
    once after the psum.  Returns the full (..., out), replicated."""
    y = lax.psum(jnp.matmul(x_shard, weight_shard.T), axis_name)
    if bias is not None:
        y = y + bias
    return y


def _shard_dim(full, axis_name, dim):
    n = lax.psum(1, axis_name)           # static mesh-axis size
    if full.shape[dim] % n:
        # dynamic_slice would silently clamp, dropping trailing features
        raise ValueError(
            f"tensor-parallel shard: dimension {dim} of size "
            f"{full.shape[dim]} is not divisible by the '{axis_name}' "
            f"axis size {n}")
    i = lax.axis_index(axis_name)
    size = full.shape[dim] // n
    return lax.dynamic_slice_in_dim(full, i * size, size, axis=dim)


def _shard_rows(full, axis_name):
    return _shard_dim(full, axis_name, 0)


def _shard_cols(full, axis_name):
    return _shard_dim(full, axis_name, 1)


class ColumnParallelLinear(nn.Module):
    """nn.Linear with the weight split along output features.  Holds the
    FULL parameter (so init/checkpoints match the unsharded layer) and
    slices its own shard per device at forward time; under jit the slice
    is a static gather XLA folds into the weight layout."""

    def __init__(self, in_features, out_features, axis_name,
                 bias=True, gather_output=False):
        super().__init__()
        ref = nn.Linear(in_features, out_features, bias=bias)
        self.weight = Parameter(ref.weight.data)
        if bias:
            self.bias = Parameter(ref.bias.data)
        else:
            self.register_parameter("bias", None)
        self.axis_name = axis_name
        self.gather_output = gather_output

    def forward(self, ctx, x):
        w = _shard_rows(ctx.value(self.weight), self.axis_name)
        b = None
        if self.bias is not None:
            b = _shard_rows(ctx.value(self.bias), self.axis_name)
        return column_parallel_linear(x, w, b, self.axis_name,
                                      self.gather_output)


class RowParallelLinear(nn.Module):
    """nn.Linear with the weight split along input features; expects its
    input already feature-sharded (a column layer's output)."""

    def __init__(self, in_features, out_features, axis_name, bias=True):
        super().__init__()
        ref = nn.Linear(in_features, out_features, bias=bias)
        self.weight = Parameter(ref.weight.data)
        if bias:
            self.bias = Parameter(ref.bias.data)
        else:
            self.register_parameter("bias", None)
        self.axis_name = axis_name

    def forward(self, ctx, x_shard):
        w = _shard_cols(ctx.value(self.weight), self.axis_name)
        b = ctx.value(self.bias) if self.bias is not None else None
        return row_parallel_linear(x_shard, w, b, self.axis_name)
