"""Sequence-parallel (context-parallel) cached decode.

Training-side sequence parallelism (parallel/ring_attention.py) shards
the TIME axis of activations; its decode-side mirror shards the TIME
axis of the KV cache: each device along ``sp_axis`` owns one contiguous
block of cache positions, so per-device cache HBM shrinks with the mesh
and the servable context length scales past one chip's memory — the
serving analogue of the training long-context recipe.  (The reference,
apex/contrib/multihead_attn/, is single-device and training-only; this
subsystem has no reference counterpart.)

The protocol per decoded chunk, run inside ``shard_map`` over the axis
(models/gpt.py ``generate(mesh=...)`` wraps it):

1. every device computes the chunk's q/k/v (replicated — per-token
   projection work is tiny next to the O(S) cache sweep);
2. each device writes ONLY the chunk rows whose global positions fall in
   its cache block (:func:`sp_kv_write` — a windowed masked write, O(S_c)
   traffic, no full-cache rewrite);
3. each device computes partial attention scores against its LOCAL cache
   block, masked by global validity, and the partials merge with the
   streaming-softmax identity over the axis (:func:`sp_softmax_combine`):
   ``m = pmax(m_i)``, ``o = Σ_i e^{s_i - m} v_i / Σ_i e^{s_i - m}`` —
   two psums + one pmax per layer, the same lse-merge flash attention
   uses across blocks, here across devices.

Score compute — the O(S) part of decode — is therefore SHARDED n ways,
and the result is bit-comparable to single-shard decode (same f32
softmax math, reassociated only across the device partition).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def sp_axis_size(axis):
    """Static size of a shard_map axis, with a decode-shaped error when
    called outside shard_map (mirrors models' init_caches contract)."""
    try:
        return jax.lax.psum(1, axis)
    except NameError:
        raise ValueError(
            f"sequence-parallel decode on sp_axis='{axis}' must run "
            f"inside shard_map over a mesh with that axis — "
            f"generate(..., mesh=...) wraps the whole decode; direct "
            f"callers must shard_map themselves") from None


def sp_slot_positions(s_local, axis):
    """Global position of each LOCAL cache slot: device ``i`` owns the
    contiguous block ``[i*s_local, (i+1)*s_local)``."""
    off = jax.lax.axis_index(axis) * s_local
    return off + jnp.arange(s_local, dtype=jnp.int32)


def _masked_window_write(arr, src, t0, off):
    """Write the rows of ``src (B, H, S_c, Dx)`` whose global positions
    ``t0+i`` fall inside this device's block ``[off, off+S_local)`` into
    ``arr (B, H, S_local, Dx)``.

    One S_c-wide window at ``clip(t0-off, 0, S_local-S_c)`` covers any
    contiguous overlap (chunks may straddle two devices' blocks): rows
    outside the overlap are re-written with their own current values.
    O(S_c) traffic — the cache is never rewritten wholesale.  Requires
    ``S_c <= S_local`` (callers chunk prompts accordingly).
    """
    s_local, s_c = arr.shape[2], src.shape[2]
    j0 = jnp.clip(t0 - off, 0, s_local - s_c)
    old = jax.lax.dynamic_slice(
        arr, (0, 0, j0, 0), arr.shape[:2] + (s_c, arr.shape[3]))
    slot_pos = off + j0 + jnp.arange(s_c, dtype=jnp.int32)
    cand = jnp.take(src, jnp.clip(slot_pos - t0, 0, s_c - 1), axis=2)
    own = ((slot_pos >= t0) & (slot_pos < t0 + s_c))[None, None, :, None]
    return jax.lax.dynamic_update_slice(
        arr, jnp.where(own, cand, old), (0, 0, j0, 0))


def sp_kv_write(cache, new, t0, axis):
    """Sequence-sharded counterpart of inference.quant.kv_write: write
    chunk ``new (B, H, S_c, D)`` at global positions ``t0..`` into this
    device's block of the cache.  QuantKV caches quantize the chunk
    per-position first (identical values to the single-shard write, so
    int8 decode stays bit-comparable across shardings)."""
    from ..inference.quant import QuantKV, _absmax_int8

    s_local, s_c = cache.shape[2], new.shape[2]
    if s_c > s_local:
        raise ValueError(
            f"sp_kv_write: chunk length {s_c} exceeds the per-device "
            f"cache block {s_local} — chunk the write (prefill does)")
    off = jax.lax.axis_index(axis) * s_local
    if isinstance(cache, QuantKV):
        q, scale = _absmax_int8(new.astype(jnp.float32), -1,
                                cache.scale.dtype)
        return QuantKV(_masked_window_write(cache.q, q, t0, off),
                       _masked_window_write(cache.scale, scale, t0, off))
    return _masked_window_write(cache, new.astype(cache.dtype), t0, off)


def sp_softmax_combine(scores, axis, weighted_v):
    """Merge per-device partial attention over ``axis``: ``scores``
    (..., S_c, S_local) are this device's f32 masked scores (invalid
    slots at -1e30); ``weighted_v(p)`` contracts probabilities-shaped
    weights with the LOCAL values (caller owns the einsum — GPT and GQA
    layouts differ).  Fully-masked local blocks contribute exactly 0
    (``e^{-1e30 - m} == 0``); some device always holds the query's own
    position, so the global row is never empty."""
    m = jax.lax.pmax(jnp.max(scores, axis=-1, keepdims=True), axis)
    p = jnp.exp(scores - m)
    l = jax.lax.psum(jnp.sum(p, axis=-1, keepdims=True), axis)
    return jax.lax.psum(weighted_v(p), axis) / l


def sp_chunked_prefill(model, ctx, toks, caches, chunk=512,
                       bound_by_cache=True):
    """Prompt consumption through ``model.decode_chunk`` in chunks —
    the cache-mediated prefill loop shared by sequence-parallel decode
    (chunks bounded by the per-device cache block so every KV row lands
    on its owning device; cross-chunk attention rides the lse merge)
    and the rolling sliding-window cache (``bound_by_cache=False``:
    rolling decode_chunk takes any chunk length, so chunks stay large
    and the unroll count small).  Returns ``(logits (B, S_p, V),
    caches)`` — the non-chunked prefill contract."""
    s_p = toks.shape[1]
    c = min(s_p, chunk)
    if bound_by_cache:
        c = min(caches[0][0].shape[2], c)
    outs = []
    t = 0
    while t < s_p:
        s_c = min(c, s_p - t)
        logits, caches = model.decode_chunk(ctx, toks[:, t:t + s_c],
                                            caches, t)
        outs.append(logits)
        t += s_c
    return jnp.concatenate(outs, axis=1), caches
