"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no long-context parallelism (SURVEY.md §5 — its only
attention is the single-device fused MHA in apex/contrib/multihead_attn/);
on TPU long-context is first-class, so this module provides the two standard
sequence-parallel schemes over a mesh axis, both designed around ICI:

* ``ring_attention`` — the sequence stays sharded; K/V blocks rotate around
  the ring via ``lax.ppermute`` while each device folds one block per step
  into a numerically-stable online-softmax accumulator (running logsumexp
  merge, the same math as the Pallas flash kernel's k-sweep in
  apex_tpu/ops/pallas/attention.py, lifted one level up to the mesh).  The
  loop is unrolled over the (static) axis size for rings up to
  ``UNROLL_LIMIT`` (env ``APEX_TPU_RING_UNROLL_LIMIT``, default 8) so XLA's
  latency-hiding scheduler overlaps each step's ppermute with the previous
  step's block compute — the ring-attention trick, no hand-rolled double
  buffering.  Larger rings fall back to ``lax.fori_loop`` to keep the HLO
  O(1) per pass (an unrolled 256-ring would emit O(n^2) comm ops).
  Memory per device is O(S_local); sequence length scales linearly with the
  ring size.  The backward is a second ring pass in which dK/dV accumulators
  travel *with* their K/V blocks; after a full cycle each lands back on the
  block's owner.

* ``ulysses_attention`` — all-to-all sequence parallelism: heads are
  scattered over the axis while the sequence is gathered
  (``lax.all_to_all``), each device runs ordinary full-sequence attention on
  H/n heads (the Pallas flash kernel when enabled), and a second all-to-all
  restores the sequence sharding.  Differentiable for free (all_to_all has a
  transpose); preferred when H ≥ axis size and the per-device full sequence
  fits.

Both are meant to be called *inside* ``shard_map``/``pjit`` with q/k/v
sharded on the sequence axis, layout (B, H, S_local, D); both consume the
per-chunk kernels of ops/pallas/attention.py under the same
``pallas_mode()`` dispatch (compiled on TPU, interpret for kernel tests,
jnp fallback otherwise).
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from ..kernels.dispatch import pallas_mode
from ..kernels import attention as _k

_f32 = jnp.float32
_NEG = -1e30


def _chunk_bias(sq, sk, q_off, k_off, causal):
    """Additive (1, sq, sk) bias masking global-causal order for a K/V chunk
    at global key offset ``k_off`` against queries at ``q_off``."""
    if not causal:
        return None
    rows = q_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    cols = k_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return jnp.where(rows >= cols, 0.0, _NEG).astype(_f32)[None]


def _chunk_fwd(q3, k3, v3, bias, scale, mode, dropout_p=0.0, seed=None,
               q_off=0, k_off=0):
    """One attention block → (normalized out, logsumexp).  Finite masking
    (-1e30) keeps every lse finite, which the merge relies on.

    Dropout uses the kernel's counter-based hash mask at GLOBAL
    coordinates (``q_off``/``k_off`` shift this chunk's rows/cols): the
    chunk's softmax sum ``l`` stays undropped, so the lse-merge across
    chunks reconstructs exactly dropout(P_global) @ V — bit-consistent
    masking with the single-device kernel."""
    if mode is not None:
        return _k.flash_attention_fwd(q3, k3, v3, bias, scale, False,
                                      interpret=(mode == "interpret"),
                                      dropout_p=dropout_p,
                                      dropout_seed=seed,
                                      dropout_row_off=q_off,
                                      dropout_col_off=k_off)
    s = jnp.einsum("bqd,bkd->bqk", q3.astype(_f32),
                   k3.astype(_f32)) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)   # undropped: full softmax sum
    pn = p
    if dropout_p > 0.0:
        pn = p * _k.dropout_keep_reference(
            q3.shape[0], q3.shape[1], k3.shape[1], seed, dropout_p,
            row_off=q_off, col_off=k_off)
    out = jnp.einsum("bqk,bkd->bqd", pn, v3.astype(_f32)) / l
    return out.astype(q3.dtype), (m + jnp.log(l))[..., 0]


def _chunk_bwd(q3, k3, v3, bias, out, lse, g, scale, mode,
               dropout_p=0.0, seed=None, q_off=0, k_off=0):
    """Block gradients against the *global* (out, lse): p = exp(s - lse)
    already carries the full-softmax normalization, so per-chunk calls sum
    to the exact full-attention gradient.  With dropout, delta already
    includes the mask (it derives from the dropped ``out``); dv sees the
    dropped probs and dp routes through the multiplier — same regenerated
    global-coordinate mask as the forward."""
    if mode is not None:
        return _k.flash_attention_bwd(q3, k3, v3, bias, out, lse, g, scale,
                                      False, interpret=(mode == "interpret"),
                                      dropout_p=dropout_p,
                                      dropout_seed=seed,
                                      dropout_row_off=q_off,
                                      dropout_col_off=k_off)
    s = jnp.einsum("bqd,bkd->bqk", q3.astype(_f32),
                   k3.astype(_f32)) * scale
    if bias is not None:
        s = s + bias
    p = jnp.exp(s - lse[..., None])
    gf = g.astype(_f32)
    delta = jnp.sum(gf * out.astype(_f32), axis=-1, keepdims=True)
    if dropout_p > 0.0:
        mult = _k.dropout_keep_reference(
            q3.shape[0], q3.shape[1], k3.shape[1], seed, dropout_p,
            row_off=q_off, col_off=k_off)
        dv = jnp.einsum("bqk,bqd->bkd", p * mult, gf)
        dp = mult * jnp.einsum("bqd,bkd->bqk", gf, v3.astype(_f32))
    else:
        dv = jnp.einsum("bqk,bqd->bkd", p, gf)
        dp = jnp.einsum("bqd,bkd->bqk", gf, v3.astype(_f32))
    ds = p * (dp - delta)
    dq = jnp.einsum("bqk,bkd->bqd", ds, k3.astype(_f32)) * scale
    dk = jnp.einsum("bqk,bqd->bkd", ds, q3.astype(_f32)) * scale
    return dq, dk, dv


def _merge(out, lse, o_r, lse_r):
    """Fold a block's (normalized out, lse) into the running pair."""
    lse_new = jnp.logaddexp(lse, lse_r)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_new = jnp.exp(lse_r - lse_new)[..., None]
    return out * w_old + o_r.astype(_f32) * w_new, lse_new


# Up to this ring size the loops are Python-unrolled: each step is separate
# HLO, letting XLA's latency-hiding scheduler overlap each ppermute hop with
# the previous block's compute.  Above it, a lax.fori_loop bounds the HLO
# size at O(1) per pass (an unrolled 256-ring would emit O(n^2)
# communication ops across fwd+bwd traces and blow up compile time).
UNROLL_LIMIT = int(os.environ.get("APEX_TPU_RING_UNROLL_LIMIT", "8"))


def _jaxlib_version():
    try:
        import jaxlib.version
        return tuple(int(p) for p in
                     jaxlib.version.__version__.split(".")[:2])
    except Exception:
        return (0, 0)


_JAXLIB = _jaxlib_version()


def _must_unroll(causal: bool, dropout_p: float) -> bool:
    """jaxlib 0.4.x workaround: with ``causal=False`` and no dropout,
    nothing in the ring body consumes ``lax.axis_index`` — but the
    fori_loop lowering still materializes it as a PartitionId
    instruction, which that jaxlib's SPMD partitioner rejects inside the
    loop body ("PartitionId is not supported").  The unrolled path
    computes the identical math (the fori body is the same ``step``
    closure), so route these cases there regardless of ring size; fixed
    upstream in jaxlib >= 0.5."""
    return (not causal) and dropout_p == 0.0 and _JAXLIB < (0, 5)


def _expand_kv(kv3, groups, batch):
    """(B*KVH, Sk, D) -> (B*H, Sk, D): repeat each KV head over its
    query group (kv-major, groups consecutive — the GQA head order the
    Llama family uses).  groups == 1 is the MHA no-op."""
    if groups == 1:
        return kv3
    bkv, sk, d = kv3.shape
    kv4 = kv3.reshape(batch, bkv // batch, sk, d)
    return jnp.repeat(kv4, groups, axis=1).reshape(bkv * groups, sk, d)


def _reduce_kv_grad(g3, groups, batch):
    """Transpose of :func:`_expand_kv`: sum each query group's gradient
    back onto its shared KV head."""
    if groups == 1:
        return g3
    bh, sk, d = g3.shape
    g5 = g3.reshape(batch, bh // batch // groups, groups, sk, d)
    return jnp.sum(g5, axis=2).reshape(bh // groups, sk, d)


def _ring_fwd_math(q3, k3, v3, seed, axis_name, causal, scale, mode,
                   groups, batch, dropout_p=0.0):
    n = lax.psum(1, axis_name)          # static mesh-axis size
    idx = lax.axis_index(axis_name)
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    out = jnp.zeros((bh, sq, d), _f32)
    lse = jnp.full((bh, sq), -jnp.inf, _f32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(r, out, lse, k_cur, v_cur, rotate):
        """One ring step, shared by the unrolled and fori paths; ``rotate``
        controls the trailing hop (the unrolled path elides the last one).
        GQA: the ring carries KVH-wide chunks (groups x fewer ICI bytes
        per hop) and expands at the point of use."""
        src = (idx - r) % n             # which global chunk we hold now
        bias = _chunk_bias(sq, sk, idx * sq, src * sk, causal)
        o_r, lse_r = _chunk_fwd(q3, _expand_kv(k_cur, groups, batch),
                                _expand_kv(v_cur, groups, batch), bias,
                                scale, mode, dropout_p, seed,
                                q_off=idx * sq, k_off=src * sk)
        out, lse = _merge(out, lse, o_r, lse_r)
        if rotate:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        return out, lse, k_cur, v_cur

    if n <= UNROLL_LIMIT or _must_unroll(causal, dropout_p):
        k_cur, v_cur = k3, v3
        for r in range(n):
            out, lse, k_cur, v_cur = step(r, out, lse, k_cur, v_cur,
                                          rotate=(r != n - 1))
        return out, lse

    # fori body rotates unconditionally (one extra hop total vs the
    # unrolled path; n hops return k/v to their owners, so the carry
    # stays consistent)
    out, lse, _, _ = lax.fori_loop(
        0, n, lambda r, c: step(r, *c, rotate=True), (out, lse, k3, v3))
    return out, lse


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _ring(q3, k3, v3, seed, axis_name, causal, scale, mode, groups, batch,
          dropout_p):
    out, _ = _ring_fwd_math(q3, k3, v3, seed, axis_name, causal, scale,
                            mode, groups, batch, dropout_p)
    return out


def _ring_vjp_fwd(q3, k3, v3, seed, axis_name, causal, scale, mode, groups,
                  batch, dropout_p):
    out, lse = _ring_fwd_math(q3, k3, v3, seed, axis_name, causal, scale,
                              mode, groups, batch, dropout_p)
    return out, (q3, k3, v3, seed, out, lse)


def _ring_vjp_bwd(axis_name, causal, scale, mode, groups, batch, dropout_p,
                  res, g):
    q3, k3, v3, seed, out, lse = res
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    sq, sk = q3.shape[1], k3.shape[1]
    out_c = out.astype(q3.dtype)
    g_c = g.astype(q3.dtype)
    perm = [(i, (i + 1) % n) for i in range(n)]
    dq = jnp.zeros(q3.shape, _f32)
    dk_cur = jnp.zeros(k3.shape, _f32)
    dv_cur = jnp.zeros(v3.shape, _f32)

    def step(r, dq, dk_cur, dv_cur, k_cur, v_cur, rotate_kv):
        """One backward ring step (shared unrolled/fori).  dK/dV
        accumulators rotate WITH their chunk; n single-hop permutes return
        every accumulator to the chunk's owner.  K/V themselves are dead
        after the last compute — only the accumulators must take that hop,
        so the unrolled path elides the final K/V rotate (``rotate_kv``)."""
        src = (idx - r) % n
        bias = _chunk_bias(sq, sk, idx * sq, src * sk, causal)
        dq_r, dk_r, dv_r = _chunk_bwd(
            q3, _expand_kv(k_cur, groups, batch),
            _expand_kv(v_cur, groups, batch), bias, out_c, lse,
            g_c, scale, mode, dropout_p, seed,
            q_off=idx * sq, k_off=src * sk)
        dq = dq + dq_r.astype(_f32)
        dk_cur = dk_cur + _reduce_kv_grad(dk_r, groups, batch).astype(_f32)
        dv_cur = dv_cur + _reduce_kv_grad(dv_r, groups, batch).astype(_f32)
        if rotate_kv:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
        return dq, dk_cur, dv_cur, k_cur, v_cur

    if n <= UNROLL_LIMIT or _must_unroll(causal, dropout_p):
        k_cur, v_cur = k3, v3
        for r in range(n):
            dq, dk_cur, dv_cur, k_cur, v_cur = step(
                r, dq, dk_cur, dv_cur, k_cur, v_cur,
                rotate_kv=(r != n - 1))
    else:
        dq, dk_cur, dv_cur, _, _ = lax.fori_loop(
            0, n, lambda r, c: step(r, *c, rotate_kv=True),
            (dq, dk_cur, dv_cur, k3, v3))
    dseed = None if seed is None else _np.zeros(_np.shape(seed),
                                                jax.dtypes.float0)
    return (dq.astype(q3.dtype), dk_cur.astype(k3.dtype),
            dv_cur.astype(v3.dtype), dseed)


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   dropout_p=0.0, dropout_seed=None):
    """Ring self/cross attention over a sequence-sharded mesh axis.

    q (B, H, Sq_local, D); k/v (B, KVH, Sk_local, D) with KVH dividing H
    (GQA: the ring carries KVH-wide chunks — H/KVH x fewer ICI bytes per
    hop — and expands each chunk at the point of use; KVH == H is plain
    MHA).  All sharded on the same ``axis_name`` in rank-contiguous order
    (device i holds global rows [i*S_local, (i+1)*S_local)).  Call inside
    shard_map/pjit.  Returns the local output shard (B, H, Sq_local, D)
    in q's dtype.

    ``dropout_p`` > 0 drops attention probabilities with the counter-based
    hash mask at GLOBAL coordinates: ``dropout_seed`` (an int32 scalar)
    must be REPLICATED across the axis, and the dropped ring result is
    then bit-consistent with the single-device flash kernel under the
    same seed — sequence parallelism does not change which positions
    drop (each chunk's softmax sum stays undropped, so the lse-merge
    reconstructs exactly dropout(P_global) @ V).
    """
    if dropout_p:
        if not 0.0 <= dropout_p < 1.0:
            raise ValueError(f"dropout_p must be in [0, 1), got {dropout_p}")
        if dropout_seed is None:
            raise ValueError("dropout_p > 0 requires dropout_seed "
                             "(replicated across the axis)")
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    if h % h_kv:
        raise ValueError(
            f"ring_attention: q heads ({h}) not divisible by kv heads "
            f"({h_kv})")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    mode = pallas_mode()
    if mode is not None:
        # the ring's per-chunk flash step goes through the same dispatch
        # policy as single-device attention: the ledger (or the probe's
        # measured min-sk prior) decides at the LOCAL chunk shape, so an
        # sp plan whose chunks sit below the win region falls back to
        # the jnp chunk math instead of running a losing kernel n times
        from ..kernels.dispatch import attention_fp, decide
        tier = decide("flash_attention",
                      attention_fp(b, h, s, k.shape[2], d, q.dtype,
                                   causal)).tier
        if tier == "xla":
            mode = None
    q3 = q.reshape(b * h, s, d)
    k3 = k.reshape(b * h_kv, k.shape[2], d)
    v3 = v.reshape(b * h_kv, v.shape[2], d)
    seed = None if not dropout_p else dropout_seed
    out = _ring(q3, k3, v3, seed, axis_name, causal, scale, mode,
                h // h_kv, b, dropout_p)
    return out.reshape(b, h, s, d).astype(q.dtype)


def _sp_seed_fold(seed, idx):
    """Fold a sequence-parallel shard index into a dropout seed.

    Multiply-then-avalanche, deliberately NOT the bare idx*0x9E3779B1
    xor that ``_dropout_seed`` uses for the TP axis: if a
    shard-replicated base seed reaches both folds on a TP×SP mesh
    (direct API use — the make_train_step path pre-folds its keys), two
    linear xors with the SAME constant are symmetric under (tp, sp)
    index swap, so devices (a, b) and (b, a) would draw identical mask
    streams.  The shift makes this fold non-linear; no index pair
    collides."""
    h = (idx.astype(jnp.uint32) + jnp.uint32(1)) * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 15)
    return (jnp.asarray(seed).astype(jnp.uint32) ^ h).astype(jnp.int32)


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None,
                      bias=None, dropout_p=0.0, dropout_seed=None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    q/k/v (B, H, S_local, D) sequence-sharded on ``axis_name``; H must be
    divisible by the axis size.  Two tiled all-to-alls re-shard
    heads↔sequence around an ordinary full-sequence attention (Pallas flash
    kernel under ``pallas_mode()``), so each device computes H/n complete
    heads.  Differentiable end-to-end (all_to_all transposes to itself).

    ``bias`` applies to the gathered sequence, so it must be *global*-shape
    (B|1, Sq_global|1, Sk_global) and replicated across the axis — a
    sequence-local bias shard would silently mask out non-local keys.

    ``dropout_p`` > 0: each device attends full-sequence over its OWN
    head block, so the hash-mask batch·head index is local — the seed
    folds with ``axis_index`` for decorrelated per-shard streams (the
    TP semantics, NOT the ring's bit-consistency; heads are what is
    sharded here).
    """
    from ..contrib.multihead_attn.attn_funcs import flash_attention
    n = lax.psum(1, axis_name)
    if q.shape[1] % n:
        raise ValueError(
            f"ulysses_attention: heads ({q.shape[1]}) not divisible by "
            f"sequence-parallel axis size ({n})")
    if bias is not None:
        if bias.shape[-1] != k.shape[2] * n:
            raise ValueError(
                f"ulysses_attention: bias key dim ({bias.shape[-1]}) must "
                f"equal the GLOBAL key length ({k.shape[2] * n}); pass the "
                "replicated global-shape bias, not a sequence-local shard")
        if bias.ndim >= 2 and bias.shape[-2] not in (1, q.shape[2] * n):
            raise ValueError(
                f"ulysses_attention: bias query dim ({bias.shape[-2]}) must "
                f"be 1 or the GLOBAL query length ({q.shape[2] * n})")
    # (B, H, S_loc, D) → (B, H/n, S_global, D)
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    seed = dropout_seed
    if dropout_p and seed is not None:
        seed = _sp_seed_fold(seed, lax.axis_index(axis_name))
    out = flash_attention(qh, kh, vh, bias=bias, causal=causal, scale=scale,
                          dropout_p=dropout_p, dropout_seed=seed)
    # back to (B, H, S_loc, D)
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
