"""ImageNet training with apex_tpu amp + DDP (reference:
examples/imagenet/main_amp.py, 542 LoC — same argparse surface:
opt-level / loss-scale / keep-batchnorm-fp32 / sync_bn / prof, checkpoint
resume, prefetcher, throughput meter printing
world_size*batch/avg_step_time every --print-freq, reference :390-397).

TPU differences: the data prefetcher is the native-runtime thread +
device_put pipeline (apex_tpu/runtime/data.py) instead of a side CUDA
stream; DDP places the batch over the mesh's data axis and XLA inserts the
gradient all-reduce.  ``--synthetic`` trains on generated data so the
example runs anywhere (no ImageFolder requirement).

Usage (mirrors the reference README):
    python main_amp.py -a resnet50 --b 224 --opt-level O2 --synthetic
"""
import argparse
import os
import time

import numpy as np


def parse_args():
    p = argparse.ArgumentParser(description="ImageNet + apex_tpu amp")
    p.add_argument("data", nargs="?", default=None,
                   help="path to dataset (omit with --synthetic)")
    p.add_argument("--arch", "-a", default="resnet50",
                   choices=["resnet18", "resnet34", "resnet50", "resnet101"])
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--iters-per-epoch", type=int, default=20)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--accum-steps", type=int, default=1,
                   help="gradient accumulation: treat every K consecutive "
                        "batches as one effective batch — K delayed "
                        "backwards (amp.scale_loss(delay_unscale=True)), "
                        "ONE optimizer step / gradient exchange / scale "
                        "update per window (docs/accumulation.md)")
    p.add_argument("--resume", default="", help="checkpoint to resume from")
    p.add_argument("--load-torch", default="",
                   help="initialize from a torch/torchvision ResNet "
                        "checkpoint (.pth state dict or the reference "
                        "example's resume format)")
    p.add_argument("--checkpoint", default="checkpoint.pkl")
    p.add_argument("--ckpt-dir", default="",
                   help="rolling checkpoint directory (CheckpointManager): "
                        "atomic async per-epoch saves + automatic resume "
                        "from the newest valid checkpoint after preemption")
    p.add_argument("--keep-n", type=int, default=3,
                   help="checkpoints retained in --ckpt-dir")
    p.add_argument("--opt-level", default="O2",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--loss-scale", default=None)
    p.add_argument("--keep-batchnorm-fp32", default=None)
    p.add_argument("--sync_bn", action="store_true",
                   help="convert BatchNorm to SyncBatchNorm")
    p.add_argument("--prof", action="store_true",
                   help="pyprof op capture + analysis for one iteration")
    p.add_argument("--synthetic", action="store_true",
                   help="generated data instead of an ImageFolder tree")
    p.add_argument("--channels-last", action="store_true",
                   help="NHWC execution (nn.to_channels_last): convs/BN/"
                        "pools compute channels-minor, and the input "
                        "pipeline skips its layout transpose — the TPU "
                        "conv-layout lever (docs/performance.md)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--parallel", default=None, choices=["auto"],
                   help="auto: let the analytical parallelism planner "
                        "(apex_tpu.parallel.auto) pick the fastest "
                        "feasible dp x zero x accum plan for the visible "
                        "devices and train through the fused step it "
                        "configures; prints the chosen Plan.describe() "
                        "(docs/auto_parallel.md)")
    p.add_argument("--auto-tune", type=int, default=0,
                   help="with --parallel auto: compile+time the top-K "
                        "predicted plans and re-rank by measurement")
    return p.parse_args()


class AverageMeter:
    """(reference main_amp.py AverageMeter)"""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = self.sum = self.count = 0.0
        self.avg = 0.0

    def update(self, val, n=1):
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / self.count


def synthetic_loader(args, n_classes=1000):
    rng = np.random.default_rng(1234)
    for _ in range(args.iters_per_epoch):
        yield (rng.integers(0, 256,
                            (args.batch_size, args.image_size,
                             args.image_size, 3), dtype=np.uint8),
               rng.integers(0, n_classes, (args.batch_size,)))


def adjust_learning_rate(optimizer, epoch, args):
    """The reference recipe (examples/imagenet/main_amp.py there): /10
    every 30 epochs.  Eager-path lr mutation is free — group["lr"] is read
    live by the imperative optimizer.step(); the fused path uses
    make_train_step(lr_schedule=step_decay(...)) instead."""
    lr = args.lr * (0.1 ** (epoch // 30))
    for group in optimizer.param_groups:
        group["lr"] = lr


def train_auto(args):
    """--parallel auto: the planner configures the fused train step
    (ZeRO/dp/accum knobs threaded from the chosen plan); the eager
    amp/DDP objects are not used — the fused step IS the amp-O2 path."""
    import jax
    import jax.numpy as jnp

    import apex_tpu.nn as nn
    from apex_tpu import models
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedSGD

    nn.manual_seed(0)
    model = getattr(models, args.arch)(num_classes=1000)
    if args.channels_last:
        model = nn.to_channels_last(model)
    optimizer = FusedSGD(list(model.parameters()), lr=args.lr,
                         momentum=args.momentum,
                         weight_decay=args.weight_decay)
    half = jnp.bfloat16 if args.opt_level in ("O2", "O3") else None
    loader = list(synthetic_loader(args))
    x0 = jnp.asarray(loader[0][0], jnp.float32) / 255.0
    if not args.channels_last:
        x0 = jnp.transpose(x0, (0, 3, 1, 2))
    y0 = jnp.asarray(loader[0][1])
    from apex_tpu.training import make_train_step
    step = make_train_step(
        model, optimizer, lambda o, t: F.cross_entropy(o, t),
        half_dtype=half, loss_scale="dynamic" if half else 1.0,
        parallel="auto", example_batch=(x0, y0),
        auto_tune=args.auto_tune)
    print(step.plan_report.describe() if step.plan_report is not None
          else step.plan.describe())
    batch_time, losses = AverageMeter(), AverageMeter()
    for epoch in range(args.epochs):
        end = time.time()
        for i, (inp, target) in enumerate(loader):
            x = jnp.asarray(inp, jnp.float32) / 255.0
            if not args.channels_last:
                x = jnp.transpose(x, (0, 3, 1, 2))
            loss = step(x, jnp.asarray(target))
            losses.update(float(loss), n=args.batch_size)
            batch_time.update(time.time() - end)
            end = time.time()
            if i % args.print_freq == 0:
                ips = args.batch_size / max(batch_time.avg, 1e-9)
                print(f"Epoch [{epoch}][{i}] loss {losses.val:.4f} "
                      f"({losses.avg:.4f})  {ips:.1f} img/s  "
                      f"[plan {step.plan.name()}]")
    step.sync_to_objects()


def main():
    args = parse_args()
    if args.parallel == "auto":
        if not args.synthetic:
            raise SystemExit("--parallel auto currently pairs with "
                             "--synthetic (the fused-step demo path)")
        return train_auto(args)
    import jax
    import jax.numpy as jnp

    import apex_tpu.nn as nn
    from apex_tpu import amp, models, parallel, runtime
    from apex_tpu.optimizers import FusedSGD

    nn.manual_seed(0)
    if args.load_torch:
        # torch checkpoint interop (mirror of the reference's --resume,
        # main_amp.py:180-195): geometry comes from the tensors
        import torch
        model = models.resnet_from_torch(
            torch.load(args.load_torch, map_location="cpu",
                       weights_only=True))
        model.train()    # the loader returns eval(); this script trains
        n_cls = model.fc.weight.shape[0]
        if n_cls != 1000:
            # out-of-range labels contribute 0 loss under jit (see
            # nn/functional.cross_entropy) — a class-count mismatch
            # would train with silent near-zero loss, so refuse here
            raise SystemExit(
                f"--load-torch checkpoint has {n_cls} classes; this "
                f"script's loaders produce 1000-class ImageNet labels")
        print(f"=> loaded torch weights from {args.load_torch}")
    else:
        model = getattr(models, args.arch)(num_classes=1000)
    if args.sync_bn:
        model = parallel.convert_syncbn_model(
            model, channel_last=args.channels_last)
    if args.channels_last:
        model = nn.to_channels_last(model)
    optimizer = FusedSGD(list(model.parameters()), lr=args.lr,
                         momentum=args.momentum,
                         weight_decay=args.weight_decay)
    loss_scale = args.loss_scale
    if loss_scale not in (None, "dynamic"):
        loss_scale = float(loss_scale)
    kbf = args.keep_batchnorm_fp32
    if isinstance(kbf, str):
        kbf = {"True": True, "False": False}.get(kbf, None)
    model, optimizer = amp.initialize(
        model, optimizer, opt_level=args.opt_level, loss_scale=loss_scale,
        keep_batchnorm_fp32=kbf)
    # under accumulation the explicit per-replica gradient exchange (if
    # any) belongs at the step boundary: one allreduce per K-microbatch
    # window, not one per backward
    model = parallel.DistributedDataParallel(
        model, delay_allreduce=(args.accum_steps > 1))
    if args.accum_steps > 1:
        model.attach_optimizer(optimizer)
    criterion = nn.CrossEntropyLoss()

    def load_ck(ck, source):
        for p, d in zip(model.parameters(), ck["model"]):
            p.data = jnp.asarray(d, p.data.dtype)
        for b, d in zip(model.buffers(), ck["buffers"]):
            b.data = jnp.asarray(d, b.data.dtype)
        optimizer.load_state_dict(ck["optimizer"])
        amp.load_state_dict(ck["amp"])
        print(f"=> resumed from {source} (epoch {ck['epoch']})")
        # elastic sanity: a preempted job can come back on a different
        # slice.  These torch-style state_dicts re-replicate on load, so
        # resume still works — but say so, and point at the full
        # re-plan + reshard path for sharded fused-step state.
        saved_n = ck.get("n_devices")
        n_now = len(runtime.elastic.current_devices())
        if saved_n is not None and saved_n != n_now:
            print(f"=> elastic: checkpoint was written on {saved_n} "
                  f"devices, now running on {n_now}; state_dicts "
                  f"re-replicate so this resume is fine — for sharded "
                  f"(ZeRO/tp) step state use runtime.ElasticTrainer, "
                  f"which re-plans and reshards")
        return ck["epoch"]

    # preemption-safe auto-resume: every epoch lands atomically in the
    # rolling --ckpt-dir, and restore_or_initialize() scans back past any
    # save a preemption interrupted — rerunning the same command after a
    # kill continues from the newest VALID epoch with no flags needed.
    manager = runtime.CheckpointManager(args.ckpt_dir, keep_n=args.keep_n) \
        if args.ckpt_dir else None
    start_epoch = 0
    if args.resume and os.path.exists(args.resume):
        # --resume reads one explicit file (legacy pickles still load,
        # with a warning; corrupt manifested files fail typed)
        from apex_tpu.utils import load_checkpoint
        start_epoch = load_ck(load_checkpoint(args.resume), args.resume)
    elif manager is not None:
        epoch, ck = manager.restore_or_initialize()
        if ck is not None:
            start_epoch = load_ck(ck, manager.path_for(epoch))

    if args.prof:
        from apex_tpu import pyprof
        pyprof.nvtx.init()

    half = jnp.bfloat16 if args.opt_level in ("O2", "O3") else None
    for epoch in range(start_epoch, args.epochs):
        adjust_learning_rate(optimizer, epoch, args)
        batch_time, losses = AverageMeter(), AverageMeter()
        loader = synthetic_loader(args) if args.synthetic else \
            folder_loader(args)
        prefetcher = runtime.DataPrefetcher(
            loader, half_dtype=half, channels_last=args.channels_last)
        end = time.time()
        i = 0
        inp, target = prefetcher.next()
        while inp is not None:
            if args.prof and i == 1:
                from apex_tpu import pyprof
                cap = pyprof.capture()
                cap.__enter__()
            out = model(inp)
            loss = criterion(out, target)
            if args.accum_steps > 1:
                # sum of K (loss/K)-gradients == the effective-batch mean
                loss = loss / args.accum_steps
            window_end = (i + 1) % args.accum_steps == 0
            # delayed backwards accumulate scaled grads in the one
            # compiled backward; the window-closing scale_loss unscales
            # once and step() applies one update (docs/accumulation.md)
            with amp.scale_loss(loss, optimizer,
                                delay_unscale=not window_end) as scaled_loss:
                scaled_loss.backward()
            if window_end:
                optimizer.step()
                optimizer.zero_grad()
            if args.prof and i == 1:
                cap.__exit__(None, None, None)
                rows = pyprof.analyze()
                rows.sort(key=lambda r: -r["est_us"])
                print("pyprof: top-5 ops by est time:")
                for r in rows[:5]:
                    print(f"  {r['dir']:>3} {r['op']:<12} "
                          f"{r['flops'] / 1e9:8.2f} GFLOP  "
                          f"{r['est_us']:8.1f} us  {r['scope']}")
            losses.update(float(loss), n=args.batch_size)
            batch_time.update(time.time() - end)
            end = time.time()
            if i % args.print_freq == 0:
                ips = jax.device_count() * args.batch_size / \
                    max(batch_time.avg, 1e-9)
                print(f"Epoch [{epoch}][{i}] loss {losses.val:.4f} "
                      f"({losses.avg:.4f})  {ips:.1f} img/s")
            i += 1
            inp, target = prefetcher.next()

        ck = {
            "epoch": epoch + 1,
            "n_devices": jax.device_count(),   # elastic-resume check
            "model": [np.asarray(p.data, np.float32)
                      for p in model.parameters()],
            "buffers": [np.asarray(b.data) for b in model.buffers()],
            "optimizer": optimizer.state_dict(),
            "amp": amp.state_dict(),
        }
        if manager is not None:
            # async: pickling/IO overlap the next epoch; atomic + rolling
            manager.save_async(epoch + 1, **ck)
            print(f"=> checkpointing epoch {epoch + 1} to {args.ckpt_dir} "
                  f"(async)")
        else:
            from apex_tpu.utils import save_checkpoint
            save_checkpoint(args.checkpoint, **ck)   # atomic tmp+rename
            print(f"=> saved {args.checkpoint}")
    if manager is not None:
        manager.close()     # block until the last write is durable


def folder_loader(args):
    """Minimal ImageFolder reader (uint8 NHWC), mirroring the reference's
    torchvision loader role without torchvision."""
    import glob

    from PIL import Image
    classes = sorted(os.listdir(args.data))
    files = [(f, ci) for ci, c in enumerate(classes)
             for f in glob.glob(os.path.join(args.data, c, "*"))]
    rng = np.random.default_rng(0)
    rng.shuffle(files)
    batch, labels = [], []
    for f, ci in files:
        img = Image.open(f).convert("RGB").resize(
            (args.image_size, args.image_size))
        batch.append(np.asarray(img, np.uint8))
        labels.append(ci)
        if len(batch) == args.batch_size:
            yield np.stack(batch), np.asarray(labels)
            batch, labels = [], []


if __name__ == "__main__":
    main()
