"""GPT causal-LM pretraining example — the autoregressive counterpart of
examples/bert (the reference ships no language models; these demonstrate
the framework's transformer path on the fused step).

Run: ``python main_amp.py --steps 50 --batch 16 --seq-len 256``
(synthetic token streams).
"""
import argparse
import contextlib
import sys
import time

import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu import observe
from apex_tpu.models import GptModel
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam
from apex_tpu.training import make_train_step

VOCAB = 50257


def parse_args():
    p = argparse.ArgumentParser(description="GPT pretrain + apex_tpu amp")
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--lr", type=float, default=6e-4)
    p.add_argument("--weight-decay", type=float, default=0.1)
    p.add_argument("--half-dtype", default="bfloat16",
                   choices=["bfloat16", "float16", "none"])
    p.add_argument("--loss-scale", default="1.0")
    p.add_argument("--layers", type=int, default=12)
    p.add_argument("--hidden", type=int, default=768)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--remat", action="store_true",
                   help="rematerialize block activations in backward "
                        "(long-sequence HBM saver)")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="microbatch accumulation steps inside the "
                        "compiled step")
    p.add_argument("--warmup-steps", type=int, default=0,
                   help="with --total-steps: on-device warmup+cosine lr")
    p.add_argument("--total-steps", type=int, default=0)
    p.add_argument("--materialized-loss", action="store_true",
                   help="materialize full (B,S,V) logits + "
                        "F.cross_entropy instead of the default "
                        "chunked vocab-chain loss (docs/performance.md "
                        "'The LM vocab chain': +13%% step throughput "
                        "at this geometry on v5e)")
    p.add_argument("--telemetry", action="store_true",
                   help="accumulate loss/grad-norm/overflows ON DEVICE "
                        "in the step's donated carry and drain every "
                        "--drain-every steps (docs/observability.md); "
                        "the print loop then reads the drained gauges "
                        "instead of forcing a device sync per print")
    p.add_argument("--drain-every", type=int, default=16)
    p.add_argument("--events-jsonl", default=None,
                   help="append the observe event log (telemetry "
                        "drains, spans, stalls) to this JSONL file")
    p.add_argument("--watchdog-s", type=float, default=0.0,
                   help="fire a stall diagnostic if no step completes "
                        "for this many seconds (0 = off)")
    return p.parse_args()


def lm_loss(logits, ids):
    flat = logits[:, :-1].reshape((-1, VOCAB))
    tgt = ids[:, 1:].reshape((-1,))
    return F.cross_entropy(flat, tgt)


def main():
    args = parse_args()
    nn.manual_seed(0)
    model = GptModel(vocab_size=VOCAB, hidden=args.hidden,
                     layers=args.layers, heads=args.heads,
                     max_positions=args.seq_len,
                     attn_dropout=0.0,  # flash path; LM recipes skip it
                     remat=args.remat,
                     # chunked loss owns the vocab chain: forward
                     # returns (hidden, table), (B,S,V) never exists
                     output_hidden=not args.materialized_loss)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    print(f"model: {args.layers}L/{args.hidden}H "
          f"({n_params / 1e6:.1f}M params)")

    opt = FusedAdam(list(model.parameters()), lr=args.lr,
                    weight_decay=args.weight_decay)
    half = None if args.half_dtype == "none" else \
        jnp.dtype(args.half_dtype).type
    loss_scale = args.loss_scale if args.loss_scale == "dynamic" \
        else float(args.loss_scale)
    sched = None
    if args.warmup_steps and args.total_steps:
        from apex_tpu.optimizers import warmup_cosine
        sched = warmup_cosine(args.warmup_steps, args.total_steps)
    if args.materialized_loss:
        loss_fn = lm_loss
    else:
        from apex_tpu.contrib.xentropy import make_chunked_lm_loss
        loss_fn = make_chunked_lm_loss(padding_idx=-1)
    step = make_train_step(model, opt, loss_fn, half_dtype=half,
                           loss_scale=loss_scale,
                           grad_accum_steps=args.grad_accum,
                           lr_schedule=sched,
                           telemetry=args.telemetry,
                           drain_every=args.drain_every)

    if args.events_jsonl:
        observe.get_registry().add_jsonl_sink(args.events_jsonl)
    watchdog = observe.StallWatchdog(args.watchdog_s) \
        if args.watchdog_s > 0 else contextlib.nullcontext()

    rng = np.random.default_rng(0)

    def batch():
        return jnp.asarray(rng.integers(0, VOCAB,
                                        (args.batch, args.seq_len)))

    with watchdog:
        ids = batch()
        t0 = time.perf_counter()
        loss = step(ids, ids)
        print(f"compile+first step: {time.perf_counter() - t0:.1f}s "
              f"loss {float(loss):.4f}")

        seen, t_mark = 0, time.perf_counter()
        final = loss
        for i in range(1, args.steps):
            ids = batch()
            final = step(ids, ids)
            seen += args.batch
            if i % args.print_freq == 0:
                if args.telemetry:
                    # the drained gauge: no device sync, K steps stale
                    lv = observe.gauge("train.loss").value or float("nan")
                else:
                    lv = float(final)  # fetch = device sync here
                dt = time.perf_counter() - t_mark
                print(f"step {i}: loss {lv:.4f}  {seen / dt:.1f} seq/s")
                seen, t_mark = 0, time.perf_counter()
    step.drain_telemetry()             # flush the partial last window
    print("final loss:", float(final))


if __name__ == "__main__":
    sys.exit(main())
