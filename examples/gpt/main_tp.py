"""GPT pretraining with data × tensor parallelism — the Megatron recipe
on a 2-D mesh: the batch shards over 'data', attention heads and the MLP
hidden width shard over 'tp' (models/gpt.py ``tp_axis``; one psum per
column→row pair via the f/g conjugate operators,
parallel/tensor_parallel.py).  Weights stay full-size and replicated —
each device slices its head/feature block at trace time — so checkpoints
are shard-count-independent.

The reference has no model parallelism (SURVEY.md §2.3 — its distributed
scope is DDP); this is the TPU-native equivalent of what Megatron-LM
layers on top of it.  Runs anywhere: with fewer real devices than
``--dp * --tp`` it builds a virtual CPU mesh (the test harness trick).

Run: ``python main_tp.py --dp 2 --tp 4 --steps 20``
"""
import argparse
import os
import sys
import time


def parse_args():
    p = argparse.ArgumentParser(
        description="data x tensor parallel GPT pretrain + apex_tpu")
    p.add_argument("--dp", type=int, default=2, help="data-parallel width")
    p.add_argument("--tp", type=int, default=4,
                   help="tensor-parallel width (must divide --heads)")
    p.add_argument("--batch", type=int, default=4,
                   help="GLOBAL batch (shards over --dp)")
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=6e-4)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=50257)
    p.add_argument("--print-freq", type=int, default=5)
    return p.parse_args()


def main():
    args = parse_args()
    n_dev = args.dp * args.tp

    import jax
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_dev}"
        ).strip()
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import apex_tpu.nn as nn
    from apex_tpu.models import GptModel
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    devices = jax.devices()[:n_dev]
    if len(devices) < n_dev:
        raise SystemExit(f"need {n_dev} devices, have {len(devices)}")
    if args.heads % args.tp:
        raise SystemExit("--heads must divide by --tp")
    if args.batch % args.dp:
        raise SystemExit("--batch must divide by --dp")
    mesh = Mesh(np.array(devices).reshape(args.dp, args.tp),
                ("data", "tp"))

    nn.manual_seed(0)
    # attn_dropout composes with tp_axis since the in-kernel hash-mask
    # dropout (per-shard seed streams) — 0.1 here exercises it
    model = GptModel(vocab_size=args.vocab, hidden=args.hidden,
                     layers=args.layers, heads=args.heads,
                     max_positions=args.seq_len, attn_dropout=0.1,
                     tp_axis="tp")
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    print(f"model: {args.layers}L/{args.hidden}H "
          f"({n_params / 1e6:.1f}M params), mesh {args.dp}x{args.tp} "
          f"(data x tp), heads {args.heads} -> "
          f"{args.heads // args.tp}/device")

    opt = FusedAdam(list(model.parameters()), lr=args.lr)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, args.vocab)),
                               tgt.reshape((-1,)))

    step = make_train_step(model, opt, lm_loss,
                           half_dtype=jnp.bfloat16, loss_scale=1.0,
                           axis_name="data", tp_axis="tp")

    def global_loss_step(state, ids, tgt):
        # the in-step loss is one data-shard's mean (replicated over tp);
        # pmean over 'data' makes the printed number the global mean
        state, loss = step._step_fn(state, ids, tgt)
        return state, jax.lax.pmean(loss, "data")

    sharded = jax.jit(jax.shard_map(
        global_loss_step, mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))

    rng = np.random.default_rng(0)

    def batch():
        ids = rng.integers(0, args.vocab, (args.batch, args.seq_len))
        tgt = np.roll(ids, -1, axis=1)
        return jnp.asarray(ids), jnp.asarray(tgt)

    ids, tgt = batch()
    t0 = time.perf_counter()
    state, loss = sharded(step.state, ids, tgt)
    print(f"compile+first step: {time.perf_counter() - t0:.1f}s "
          f"loss {float(loss):.4f}")

    seen, t_mark = 0, time.perf_counter()
    for i in range(1, args.steps):
        ids, tgt = batch()
        state, loss = sharded(state, ids, tgt)
        seen += args.batch * args.seq_len
        if i % args.print_freq == 0:
            lv = float(loss)               # fetch = device sync
            dt = time.perf_counter() - t_mark
            print(f"step {i}: loss {lv:.4f}  {seen / dt:.0f} tok/s")
            seen, t_mark = 0, time.perf_counter()
    print("final loss:", float(loss))
    return 0


if __name__ == "__main__":
    sys.exit(main())
