"""Long-context GPT pretraining with sequence parallelism — the sequence
dimension shards over a mesh axis, attention rides the ring
(parallel/ring_attention.py), and each block rematerializes in backward:
per-device activation memory is O(S / n_devices) at block boundaries, so
global context length scales linearly with the ring size.

The reference has no long-context story (SURVEY.md §5); this is the
TPU-native recipe.  Runs anywhere: with fewer real devices than
``--devices`` it builds a virtual CPU mesh (the same trick the test
harness uses).

Run: ``python main_sp.py --devices 8 --seq-len 1024 --steps 20``
"""
import argparse
import os
import sys
import time


def parse_args():
    p = argparse.ArgumentParser(
        description="sequence-parallel GPT pretrain + apex_tpu")
    p.add_argument("--devices", type=int, default=8,
                   help="ring size (mesh axis length)")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--seq-len", type=int, default=1024,
                   help="GLOBAL sequence length (shards over the ring)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=6e-4)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=50257)
    p.add_argument("--no-remat", action="store_true")
    p.add_argument("--print-freq", type=int, default=5)
    return p.parse_args()


def main():
    args = parse_args()

    # pin a virtual CPU mesh when the attached platform cannot provide
    # the requested ring (single-chip or laptop runs)
    import jax
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import apex_tpu.nn as nn
    from apex_tpu.models import GptModel
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    devices = jax.devices()[:args.devices]
    if len(devices) < args.devices:
        raise SystemExit(f"need {args.devices} devices, have {len(devices)}")
    if args.seq_len % args.devices:
        raise SystemExit("--seq-len must divide by --devices")
    mesh = Mesh(np.array(devices), ("sp",))

    nn.manual_seed(0)
    model = GptModel(vocab_size=args.vocab, hidden=args.hidden,
                     layers=args.layers, heads=args.heads,
                     max_positions=args.seq_len, attn_dropout=0.0,
                     remat=not args.no_remat, sp_axis="sp")
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    print(f"model: {args.layers}L/{args.hidden}H "
          f"({n_params / 1e6:.1f}M params), ring of {args.devices}, "
          f"global seq {args.seq_len} "
          f"({args.seq_len // args.devices}/device)")

    opt = FusedAdam(list(model.parameters()), lr=args.lr)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, args.vocab)),
                               tgt.reshape((-1,)))

    step = make_train_step(model, opt, lm_loss,
                           half_dtype=jnp.bfloat16, loss_scale=1.0,
                           axis_name="sp")
    def global_loss_step(state, ids, tgt):
        # each shard's loss covers its local sequence slice; pmean makes
        # the printed number the global mean (grads are already
        # psum-averaged inside the step, so this only fixes monitoring)
        state, loss = step._step_fn(state, ids, tgt)
        return state, jax.lax.pmean(loss, "sp")

    sharded = jax.jit(jax.shard_map(
        global_loss_step, mesh=mesh,
        in_specs=(P(), P(None, "sp"), P(None, "sp")),
        out_specs=(P(), P()), check_vma=False))

    rng = np.random.default_rng(0)

    def batch():
        ids = rng.integers(0, args.vocab, (args.batch, args.seq_len))
        tgt = np.roll(ids, -1, axis=1)      # global next-token shift
        return jnp.asarray(ids), jnp.asarray(tgt)

    ids, tgt = batch()
    t0 = time.perf_counter()
    state, loss = sharded(step.state, ids, tgt)
    print(f"compile+first step: {time.perf_counter() - t0:.1f}s "
          f"loss {float(loss):.4f}")

    seen, t_mark = 0, time.perf_counter()
    for i in range(1, args.steps):
        ids, tgt = batch()
        state, loss = sharded(state, ids, tgt)
        seen += args.batch * args.seq_len
        if i % args.print_freq == 0:
            lv = float(loss)               # fetch = device sync
            dt = time.perf_counter() - t_mark
            print(f"step {i}: loss {lv:.4f}  {seen / dt:.0f} tok/s")
            seen, t_mark = 0, time.perf_counter()
    print("final loss:", float(loss))


if __name__ == "__main__":
    sys.exit(main())
