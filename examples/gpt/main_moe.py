"""Switch-MoE GPT pretraining — every second block routes its FFN over
one expert per device along the data axis (models/gpt.py ``moe_axis``;
parallel/expert_parallel.py carries the all_to_all dispatch/combine and
the load-balancing aux loss, which flows through ``Ctx.add_aux_loss``
into the fused step's optimized loss).

The canonical Switch layout: experts ride the SAME mesh axis the batch
shards over, so expert-parallel capacity grows with data parallelism and
the ordinary psum-mean of the step yields exact expert gradients.  The
reference has no MoE (SURVEY.md §2.3).  Runs anywhere: with fewer real
devices than ``--devices`` it builds a virtual CPU mesh.

Run: ``python main_moe.py --devices 4 --steps 20 --top-k 1``
"""
import argparse
import os
import sys
import time


def parse_args():
    p = argparse.ArgumentParser(description="Switch-MoE GPT + apex_tpu")
    p.add_argument("--devices", type=int, default=4,
                   help="data-axis width = expert count")
    p.add_argument("--batch", type=int, default=8,
                   help="GLOBAL batch (shards over the axis)")
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=6e-4)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=50257)
    p.add_argument("--top-k", type=int, default=1, choices=(1, 2))
    p.add_argument("--capacity-factor", type=float, default=1.25)
    p.add_argument("--aux-weight", type=float, default=0.01)
    p.add_argument("--print-freq", type=int, default=5)
    return p.parse_args()


def main():
    args = parse_args()

    import jax
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import apex_tpu.nn as nn
    from apex_tpu.models import GptModel
    from apex_tpu.models.gpt import MoeGptBlock
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    devices = jax.devices()[:args.devices]
    if len(devices) < args.devices:
        raise SystemExit(f"need {args.devices} devices, have {len(devices)}")
    if args.batch % args.devices:
        raise SystemExit("--batch must divide by --devices")
    mesh = Mesh(np.array(devices), ("data",))

    nn.manual_seed(0)
    model = GptModel(vocab_size=args.vocab, hidden=args.hidden,
                     layers=args.layers, heads=args.heads,
                     max_positions=args.seq_len, attn_dropout=0.0,
                     moe_axis="data", moe_num_experts=args.devices,
                     moe_top_k=args.top_k,
                     moe_capacity_factor=args.capacity_factor,
                     moe_aux_weight=args.aux_weight)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    n_moe = sum(1 for blk in model.blocks
                if isinstance(blk, MoeGptBlock))
    print(f"model: {args.layers}L/{args.hidden}H "
          f"({n_params / 1e6:.1f}M params incl. {args.devices} experts "
          f"x {n_moe} MoE blocks, top-{args.top_k})")

    opt = FusedAdam(list(model.parameters()), lr=args.lr)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, args.vocab)),
                               tgt.reshape((-1,)))

    step = make_train_step(model, opt, lm_loss,
                           half_dtype=jnp.bfloat16, loss_scale=1.0,
                           axis_name="data")

    def global_loss_step(state, ids, tgt):
        state, loss = step._step_fn(state, ids, tgt)
        return state, jax.lax.pmean(loss, "data")

    sharded = jax.jit(jax.shard_map(
        global_loss_step, mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))

    rng = np.random.default_rng(0)

    def batch():
        ids = rng.integers(0, args.vocab, (args.batch, args.seq_len))
        tgt = np.roll(ids, -1, axis=1)
        return jnp.asarray(ids), jnp.asarray(tgt)

    ids, tgt = batch()
    t0 = time.perf_counter()
    state, loss = sharded(step.state, ids, tgt)
    print(f"compile+first step: {time.perf_counter() - t0:.1f}s "
          f"loss {float(loss):.4f} (incl. aux)")

    seen, t_mark = 0, time.perf_counter()
    for i in range(1, args.steps):
        ids, tgt = batch()
        state, loss = sharded(state, ids, tgt)
        seen += args.batch * args.seq_len
        if i % args.print_freq == 0:
            lv = float(loss)
            dt = time.perf_counter() - t_mark
            print(f"step {i}: loss {lv:.4f}  {seen / dt:.0f} tok/s")
            seen, t_mark = 0, time.perf_counter()
    print("final loss:", float(loss))
    return 0


if __name__ == "__main__":
    sys.exit(main())
