"""Multi-turn serving session demo: persistent KV caches across
append/generate turns (inference/session.py) — the chat pattern
without re-prefilling the history each turn.

Demonstrates, on one int8-quantized GPT:
  1. system prompt + three user turns, each model reply generated from
     the live caches;
  2. exactness: the final reply equals one-shot ``generate`` on the
     concatenated history;
  3. a sampled turn with temperature/top-k/top-p on the same session.

Run (CPU or TPU):
    python main_session.py --turns 3 --reply-tokens 12

The reference repo has no inference path (SURVEY.md §2); this example
exercises the framework's own serving-session layer end to end.
"""
import argparse


def parse_args():
    p = argparse.ArgumentParser(description="decode-session demo")
    p.add_argument("--turns", type=int, default=3)
    p.add_argument("--reply-tokens", type=int, default=12)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=512)
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import jax.numpy as jnp
    import numpy as np

    import apex_tpu.nn as nn
    from apex_tpu.inference import DecodeSession, quantize_int8
    from apex_tpu.models import GptModel, generate

    SYSTEM_LEN, USER_LEN = 16, 6
    cap = SYSTEM_LEN + args.turns * (USER_LEN + args.reply_tokens) \
        + args.reply_tokens
    nn.manual_seed(0)
    model = GptModel(vocab_size=args.vocab, hidden=args.hidden,
                     layers=args.layers, heads=args.heads,
                     max_positions=cap, dropout=0.0, attn_dropout=0.0)
    model.eval()
    quantize_int8(model, min_size=1024)

    rng = np.random.default_rng(0)
    session = DecodeSession(model, cache_dtype="int8")
    system = jnp.asarray(rng.integers(0, args.vocab, (1, SYSTEM_LEN)))
    session.append(system)
    history = [system]
    for turn in range(args.turns):
        user = jnp.asarray(rng.integers(0, args.vocab, (1, USER_LEN)))
        session.append(user)
        reply = session.generate(args.reply_tokens)
        history += [user, reply]
        print(f"turn {turn}: cursor={session.position}, "
              f"reply={np.asarray(reply)[0, :6]}...")

    full = jnp.concatenate(history[:-1], axis=1)
    want = np.asarray(generate(model, full, args.reply_tokens,
                               cache_dtype="int8"))[:, full.shape[1]:]
    exact = bool((np.asarray(history[-1]) == want).all())
    print(f"final reply equals one-shot decode of the history: {exact}")
    assert exact

    sampled = session.generate(args.reply_tokens, temperature=0.8,
                               top_k=50, top_p=0.95,
                               key=jax.random.PRNGKey(1))
    print(f"sampled turn: {np.asarray(sampled)[0, :6]}... "
          f"(cursor {session.position})")


if __name__ == "__main__":
    main()
