"""BERT pretraining example — BASELINE config 4: BERT-base masked-LM with
FusedLAMB + FusedLayerNorm under mixed precision.

The reference repo has no BERT example of its own (its FusedLAMB/
FusedLayerNorm/fast-MHA pieces were consumed by NVIDIA's external BERT
scripts); this is the standalone equivalent on the TPU-first fused step.
Argparse surface follows the other examples (opt-level/loss-scale knobs).

Run: ``python main_amp.py --steps 50 --batch 32 --seq-len 128``
(synthetic data; there is no dataset plumbing in the reference baseline
configs either).
"""
import argparse
import sys
import time

import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu.models import BertForMaskedLM
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedLAMB
from apex_tpu.training import make_train_step

VOCAB = 30522


def parse_args():
    p = argparse.ArgumentParser(description="BERT pretrain + apex_tpu amp")
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--weight-decay", type=float, default=0.01)
    p.add_argument("--loss-scale", default="1.0",
                   help="'dynamic' or a float; bf16 default needs none")
    p.add_argument("--half-dtype", default="bfloat16",
                   choices=["bfloat16", "float16", "none"])
    p.add_argument("--layers", type=int, default=12)
    p.add_argument("--hidden", type=int, default=768)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--mask-prob", type=float, default=0.15)
    p.add_argument("--remat", action="store_true",
                   help="rematerialize layer activations in backward")
    p.add_argument("--grad-accum", type=int, default=1)
    p.add_argument("--warmup-steps", type=int, default=0,
                   help="with --total-steps: on-device warmup+linear lr "
                        "(the BERT pretraining shape)")
    p.add_argument("--total-steps", type=int, default=0)
    return p.parse_args()


def mlm_batch(rng, batch, seq_len, mask_prob):
    """Synthetic MLM batch: random token ids, ~mask_prob positions carry
    labels (-100 = ignore, matching the usual MLM convention)."""
    ids = rng.integers(0, VOCAB, (batch, seq_len))
    labels = np.full((batch, seq_len), -100, np.int64)
    pick = rng.random((batch, seq_len)) < mask_prob
    labels[pick] = ids[pick]          # predict the original token
    ids = ids.copy()
    ids[pick] = 103                   # [MASK]
    return jnp.asarray(ids), jnp.asarray(labels)


def mlm_loss(logits, labels):
    flat = logits.reshape((-1, VOCAB))
    lab = labels.reshape((-1,))
    mask = (lab >= 0).astype(jnp.float32)
    losses = F.cross_entropy(flat, jnp.maximum(lab, 0), reduction="none")
    return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def main():
    args = parse_args()
    nn.manual_seed(0)
    model = BertForMaskedLM(
        vocab_size=VOCAB, hidden=args.hidden, layers=args.layers,
        heads=args.heads, intermediate=4 * args.hidden,
        max_positions=args.seq_len, remat=args.remat)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    print(f"model: {args.layers}L/{args.hidden}H "
          f"({n_params / 1e6:.1f}M params)")

    opt = FusedLAMB(list(model.parameters()), lr=args.lr,
                    weight_decay=args.weight_decay)
    half = None if args.half_dtype == "none" else \
        jnp.dtype(args.half_dtype).type
    loss_scale = args.loss_scale if args.loss_scale == "dynamic" \
        else float(args.loss_scale)
    sched = None
    if args.warmup_steps and args.total_steps:
        from apex_tpu.optimizers import warmup_linear
        sched = warmup_linear(args.warmup_steps, args.total_steps)
    step = make_train_step(model, opt, mlm_loss, half_dtype=half,
                           loss_scale=loss_scale,
                           grad_accum_steps=args.grad_accum,
                           lr_schedule=sched)

    rng = np.random.default_rng(0)
    ids, labels = mlm_batch(rng, args.batch, args.seq_len, args.mask_prob)

    t0 = time.perf_counter()
    loss = step(ids, labels)
    print(f"compile+first step: {time.perf_counter() - t0:.1f}s "
          f"loss {float(loss):.4f}")

    seen, t_mark = 0, time.perf_counter()
    final = None
    for i in range(1, args.steps):
        ids, labels = mlm_batch(rng, args.batch, args.seq_len,
                                args.mask_prob)
        loss = step(ids, labels)
        seen += args.batch
        if i % args.print_freq == 0:
            lv = float(loss)   # fetch = device sync on this platform
            dt = time.perf_counter() - t_mark
            print(f"step {i}: loss {lv:.4f}  {seen / dt:.1f} seq/s")
            seen, t_mark = 0, time.perf_counter()
        final = loss
    print("final loss:", float(final if final is not None else loss))


if __name__ == "__main__":
    sys.exit(main())
