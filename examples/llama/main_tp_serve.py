"""Tensor-parallel Llama serving example: one set of weights, decoded
across a TP mesh with head-sharded KV caches — the configuration that
lets a model too large for one chip's HBM (e.g. the ``llama_7b``
preset at bf16 + cache) serve across chips.

Demonstrates, on the same weights:
  1. plain TP greedy decode (``generate(..., mesh=...)``) and its
     bit-identity with single-shard decode,
  2. int8 weight-only quantization under TP,
  3. TP-target + replicated-draft speculative decoding
     (``speculative_generate(..., mesh=...)``), greedy-exact,
  4. beam search under the same mesh (``beam_generate(..., mesh=...)``),
     bit-identical to single-shard beam search.

Run (any host; uses a virtual CPU mesh unless real devices exist):
    python main_tp_serve.py --tp 2 --new-tokens 32

The reference repo has no inference path (SURVEY.md §2 — it is a
training-side library); this example exercises the framework's own
serving story end to end.
"""
import argparse
import os
import sys


def parse_args():
    p = argparse.ArgumentParser(description="TP Llama serving demo")
    p.add_argument("--tp", type=int, default=2, help="TP mesh size")
    p.add_argument("--new-tokens", type=int, default=32)
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--kv-heads", type=int, default=4)
    return p.parse_args()


def main():
    args = parse_args()
    # a virtual device mesh when the host lacks args.tp real devices
    # (set BEFORE jax import; harmless if real devices exist)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.tp}"
        ).strip()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    import apex_tpu.nn as nn
    from apex_tpu.inference import (beam_generate, quantize_int8,
                                    speculative_generate)
    from apex_tpu.models import LlamaModel, generate

    devs = jax.devices()
    if len(devs) < args.tp:
        sys.exit(f"need {args.tp} devices, have {len(devs)}")
    mesh = Mesh(np.array(devs)[:args.tp].reshape(args.tp), ("tp",))
    print(f"mesh: {args.tp} x {devs[0].platform}")

    vocab = 2048
    max_pos = args.prompt_len + args.new_tokens + 8

    def build(**kw):
        nn.manual_seed(0)
        return LlamaModel(vocab_size=vocab, hidden=args.hidden,
                          layers=args.layers, heads=args.heads,
                          kv_heads=args.kv_heads, max_positions=max_pos,
                          **kw)

    # in production: llama_from_hf(...) then set tp_axis at build time
    # and load the same checkpoint into both — weights are FULL
    # (replicated, sliced at trace time), so checkpoints are
    # mesh-independent
    single = build()
    single.eval()
    tp = build(tp_axis="tp")
    tp.eval()
    for ps, pd in zip(single.parameters(), tp.parameters()):
        pd.data = ps.data

    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, vocab,
                                      (1, args.prompt_len)))

    # 1. TP greedy decode, bit-identical to single-shard
    want = np.asarray(generate(single, prompt, args.new_tokens))
    got = np.asarray(generate(tp, prompt, args.new_tokens, mesh=mesh))
    assert (want == got).all(), "TP decode diverged from single-shard"
    print(f"tp greedy decode: {got.shape[1]} tokens, "
          f"bit-identical to single-shard: True")

    # 2. int8 weight-only under TP (per-device cache already KVH/n-wide;
    #    int8 halves the weight reads on top)
    quantize_int8(tp, min_size=1)
    out8 = np.asarray(generate(tp, prompt, args.new_tokens, mesh=mesh))
    print(f"tp int8 decode: {out8.shape[1]} tokens")

    # 3. speculative decoding: TP target + small replicated draft
    nn.manual_seed(1)
    draft = LlamaModel(vocab_size=vocab, hidden=64, layers=1, heads=2,
                       max_positions=max_pos)
    draft.eval()
    spec = np.asarray(speculative_generate(
        tp, draft, prompt, args.new_tokens, k=4, mesh=mesh))
    assert (spec == out8).all(), \
        "speculative decode broke the greedy exactness guarantee"
    print(f"tp speculative decode: exact match with tp int8 decode: True")

    # 4. beam search under the same mesh (int8 weights already applied
    #    to tp; compare against single-shard int8 beams)
    quantize_int8(single, min_size=1)
    bwant = np.asarray(beam_generate(single, prompt, args.new_tokens,
                                     num_beams=3))
    bgot = np.asarray(beam_generate(tp, prompt, args.new_tokens,
                                    num_beams=3, mesh=mesh))
    assert (bwant == bgot).all(), "TP beam search diverged"
    print(f"tp beam search (3 beams): bit-identical to single-shard: "
          f"True")


if __name__ == "__main__":
    main()
