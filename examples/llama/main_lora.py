"""LoRA fine-tuning example: adapt a (random-init stand-in for an HF)
Llama checkpoint with rank-r factors only, then merge and decode.

Run: ``python main_lora.py --steps 40 --rank 8``
(synthetic token streams; with network access, replace the model build
with ``llama_from_hf(LlamaForCausalLM.from_pretrained(...))`` — the
rest is identical).
"""
import argparse
import sys
import time

import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu.models import LlamaModel, generate
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam
from apex_tpu.reparameterization import (LoRA, apply_lora,
                                         lora_parameters,
                                         remove_reparameterization)
from apex_tpu.training import make_train_step

VOCAB = 2048


def parse_args():
    p = argparse.ArgumentParser(description="LoRA fine-tune + merge")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--rank", type=int, default=8)
    p.add_argument("--alpha", type=float, default=16.0)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--print-freq", type=int, default=10)
    return p.parse_args()


def main():
    args = parse_args()
    nn.manual_seed(0)
    model = LlamaModel(vocab_size=VOCAB, hidden=args.hidden,
                       layers=args.layers, heads=8, kv_heads=4,
                       max_positions=args.seq_len + 16)

    # adapt the attention projections; everything else stays frozen
    for blk in model.blocks:
        apply_lora(blk, "q_proj.weight", r=args.rank, alpha=args.alpha)
        apply_lora(blk, "v_proj.weight", r=args.rank, alpha=args.alpha)
    factors = lora_parameters(model)
    total = sum(int(np.prod(p.shape)) for p in model.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in factors)
    print(f"trainable: {trainable:,} of {total:,} parameters "
          f"({100 * trainable / total:.2f}%)")

    opt = FusedAdam(factors, lr=args.lr, weight_decay=0.0)

    def lm_loss(logits, ids):
        flat = logits[:, :-1].reshape((-1, VOCAB))
        return F.cross_entropy(flat, ids[:, 1:].reshape((-1,)))

    step = make_train_step(model, opt, lm_loss,
                           half_dtype=jnp.bfloat16)

    rng = np.random.default_rng(0)
    phase = rng.integers(0, 97, (args.batch, 1))
    ids = jnp.asarray((phase + np.arange(args.seq_len)[None, :]) % 97)
    t0 = time.time()
    for i in range(args.steps):
        loss = step(ids, ids)
        if i % args.print_freq == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}  "
                  f"({time.time() - t0:.1f}s)")
    step.sync_to_objects()
    model.eval()

    pre = generate(model, ids[:1, :8], 8)
    remove_reparameterization(model, LoRA, remove_all=True)  # merge
    post = generate(model, ids[:1, :8], 8)
    assert np.array_equal(np.asarray(pre), np.asarray(post)), \
        "merged decode must equal the adapted decode"
    names = [n for n, _ in model.named_parameters()]
    assert not any("lora" in n for n in names)
    print("merged: decode identical, LoRA machinery gone")
    return 0


if __name__ == "__main__":
    sys.exit(main())
