"""Llama-family end-to-end example: pretrain a small Llama-style model
(RoPE + RMSNorm + SwiGLU + GQA) on the fused amp step, then run the
inference stack on the trained weights — flash-path prefill generate,
weight-only int8 quantization, and draft-verified speculative decoding.

Run: ``python main.py --steps 40 --batch 16 --seq-len 128``
(synthetic token streams; load real weights with
``apex_tpu.models.llama_from_hf`` instead of the random init).
"""
import argparse
import sys
import time

import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu.inference import quantize_int8, speculative_generate
from apex_tpu.models import LlamaModel, generate
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam
from apex_tpu.training import make_train_step

VOCAB = 4096


def parse_args():
    p = argparse.ArgumentParser(description="Llama pretrain + inference")
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--lr", type=float, default=6e-4)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--kv-heads", type=int, default=4)
    p.add_argument("--half-dtype", default="bfloat16",
                   choices=["bfloat16", "none"])
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--gen-tokens", type=int, default=32)
    p.add_argument("--spec-k", type=int, default=4)
    return p.parse_args()


def lm_loss(logits, ids):
    flat = logits[:, :-1].reshape((-1, VOCAB))
    tgt = ids[:, 1:].reshape((-1,))
    return F.cross_entropy(flat, tgt)


def main():
    args = parse_args()
    nn.manual_seed(0)
    max_pos = args.seq_len + args.gen_tokens + args.spec_k + 1
    model = LlamaModel(vocab_size=VOCAB, hidden=args.hidden,
                       layers=args.layers, heads=args.heads,
                       kv_heads=args.kv_heads, max_positions=max_pos)
    opt = FusedAdam(list(model.parameters()), lr=args.lr,
                    weight_decay=0.1)
    half = None if args.half_dtype == "none" else jnp.bfloat16
    step = make_train_step(model, opt, lm_loss, half_dtype=half,
                           loss_scale="dynamic" if half else 1.0)

    # synthetic corpus with learnable structure (periodic token streams)
    rng = np.random.default_rng(0)
    phase = rng.integers(0, 97, (args.batch, 1))
    ids = jnp.asarray(
        (phase + np.arange(args.seq_len)[None, :]) % 97 +
        rng.integers(0, 3, (args.batch, args.seq_len)) * 97)

    t0 = time.time()
    for i in range(args.steps):
        loss = step(ids, ids)
        if i % args.print_freq == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}  "
                  f"({time.time() - t0:.1f}s)")
    step.sync_to_objects()

    # inference on the trained weights: prefill generate, then the same
    # continuation via an int8-quantized copy of the model as its own
    # speculative draft (self-speculation: the int8 copy agrees with the
    # full-precision target on most argmax positions)
    model.eval()
    prompt = ids[:2, :16]
    out = generate(model, prompt, args.gen_tokens)
    print("greedy continuation:", np.asarray(out[0, 16:16 + 8]))

    draft = LlamaModel(vocab_size=VOCAB, hidden=args.hidden,
                       layers=args.layers, heads=args.heads,
                       kv_heads=args.kv_heads, max_positions=max_pos)
    for p_d, p_t in zip(draft.parameters(), model.parameters()):
        p_d.data = p_t.data
    quantize_int8(draft)
    spec = speculative_generate(model, draft, prompt, args.gen_tokens,
                                k=args.spec_k)
    assert np.array_equal(np.asarray(spec), np.asarray(out)), \
        "speculative output must match the target's greedy decode"
    print(f"speculative decode (int8 self-draft, k={args.spec_k}) "
          f"matches greedy exactly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
